"""The verdict service: batched device models behind the wire seam.

The standalone-process analog of the reference's verdict library: where
the reference loads ``libcilium.so`` into Envoy and parses per request
(reference: envoy/cilium_proxylib.cc:125 OnIO -> proxylib OnData), this
service accepts per-connection byte batches from datapath shims over a
unix socket, aggregates them across shims with the adaptive
fill-vs-deadline dispatcher, renders verdicts with the batched TPU
models, and returns FilterOp lists.

Verdict paths, fastest first:

1. **Vectorized fast path** — request-direction entries that carry
   exactly one complete frame for a flow with no buffered remainder are
   lifted straight into a ``[n, width]`` device batch with O(1) numpy
   gathers (no per-flow Python state), and ops are emitted from the
   verdict arrays.  This is the steady-state hot loop.
2. **Engine slow path** — stateful flows (partial frames, pipelined
   frames, carried NFA state) go through the per-protocol batch engines
   (runtime/batch.py, runtime/engines.py), still device-batched.
3. **Oracle path** — protocols without a device model, and all reply
   direction traffic, run the in-process streaming parsers
   (proxylib/) — the same code that defines bit-exactness.

Access logs on the fast path are recorded columnarly (verdict counters +
the standard logger on a sampled subset is NOT used — every request is
logged, but via one appended batch record) to keep host Python off the
per-request critical path.
"""

from __future__ import annotations

import base64
import binascii
import functools
import json
import logging
import os
import queue
import re
import socket
import struct
import threading
import time
from collections import deque as _deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FuturesTimeout

import numpy as np

from ..flowlog import (
    CODE_DENIED,
    CODE_ERROR,
    CODE_FORWARDED,
    CODE_SHED,
    FlowLog,
)
from ..models.base import ConstVerdict
from ..proxylib import instance as pl
from ..analysis.protocols import (
    CACHE_ARMED,
    CACHE_DECLINED,
    CACHE_UNARMED,
    EPOCH_SWAP_PROTOCOL,
    FLOW_CACHE_PROTOCOL,
    MESH_FALLBACK,
    MESH_FULL,
    MESH_LADDER_PROTOCOL,
    MESH_RESHAPED,
    SWAP_COMMITTED,
    SWAP_REJECTED,
    SWAP_STAGED,
)
from ..proxylib.accesslog import EntryType, LogEntry
from ..proxylib.npds import policy_from_dict
from ..proxylib.types import DROP, ERROR, MORE, PASS, FilterResult, OpError
from ..runtime.batch import R2d2BatchEngine
from ..utils import flowdebug, metrics
from ..utils.option import DaemonConfig
from ..utils.sockutil import shutdown_close
from . import blackbox, wire
from . import ledger as ledger_mod
from .dispatch import BatchDispatcher
from .guard import DeviceGuard
from .reasm import (
    FRAMING_CRLF,
    FRAMINGS,
    ByteArena,
    Reassembler,
    gather_segments,
)
from .shm import GenerationMismatch, RingError, sweep_stale_segments
from .trace import (
    PATH_CACHED,
    PATH_HOST,
    PATH_ORACLE,
    PATH_SHED,
    PATH_VEC,
    VerdictTracer,
)
from .transport import (
    CREDIT_FLAG_QUARANTINED,
    DEATH_ABRUPT,
    DEATH_CLOSED,
    DEATH_SEND_TIMEOUT,
    DEATH_WRITE_FAILED,
    QUARANTINE_FLOOD,
    QUARANTINE_RECONNECT_STORM,
    SESSION_DEAD,
    REASON_ATTACH_REJECTED,
    REASON_DISABLED,
    REASON_GENERATION,
    REASON_OVERSIZE,
    REASON_OVERSIZE_SPREE,
    REASON_PEER_DEATH,
    REASON_TORN_SLOT,
    REASON_VERDICT_RING_FULL,
    SHED_FENCED,
    SHED_SESSION_QUARANTINED,
    SHED_SESSION_QUOTA,
    TRANSPORT_SOCKET,
    SessionState,
    ShmPeer,
)

log = logging.getLogger(__name__)
# Per-flow debug stream, flowdebug-gated (one boolean when disabled).
_flow_log = logging.getLogger("cilium_tpu.sidecar.flow")

# Protocols served by a device batch engine (everything else rides the
# in-process oracle), and the subset whose single-frame payloads may
# take the vectorized fast path (engines framing whole requests the
# model can judge from one row: r2d2 on CRLF, DNS on its length
# prefix — the per-framing gate is reasm.FRAMINGS).
ENGINE_PROTOS = ("r2d2", "cassandra", "memcache", "http", "dns")
FAST_PROTOS = ("r2d2", "dns")


def _engine_framing(engine):
    """The reasm Framing an engine's declared ``reasm_spec`` resolves
    to, or None when the engine (or its framing) is not columnar-
    capable — THE per-framing dispatch gate (ISSUE 13): the columnar
    lane, the vec/matrix whole-frame checks and the verdict-cache
    alignment tiers all route through this one lookup."""
    if engine is None or not getattr(engine, "reasm_columnar", False):
        return None
    spec = getattr(engine, "reasm_spec", None)
    if spec is None:
        return None
    return FRAMINGS.get(spec())


# In-process executable-cache handoff (keyed by socket path): a
# surrendering service deposits its shape-keyed prewarm ledger here so a
# same-process successor rebuilding the restored rule sources skips its
# warm launches entirely.  jax's jit executable cache is process-global
# and shape-keyed (the module-level _call_model trace twins), so
# unchanged tables recompile NOTHING across a graceful handoff — this
# ledger carries the "which shape signatures are fully warmed" half
# that would otherwise die with the instance.  A cross-process
# successor simply finds no deposit (cold prewarm; correct either way).
_HANDOFF_SHAPE_CACHE: dict[str, dict] = {}


def _gather_model(model, blob, offs, lens, remotes, width: int,
                  attr: bool = False):
    """On-device row build: gather each entry's bytes from the flat
    payload blob into the [n, width] layout the batch models consume,
    masking the padding tail to zero.  ``attr`` routes through the
    model's attributed variant (verdict + deciding-rule argmax in the
    same fused executable)."""
    import jax.numpy as jnp

    col = jnp.arange(width, dtype=jnp.int32)[None, :]
    g = jnp.clip(offs[:, None] + col, 0, blob.shape[0] - 1)
    rows = jnp.where(col < lens[:, None], blob[g], 0)
    if attr:
        return model.verdicts_attr(rows, lens, remotes)
    return model(rows, lens, remotes)


def _call_model(model, data, lens, remotes):
    """Model-as-argument trace twin of ``model(...)`` for the shape-
    keyed dispatch cache: the model's tables are jit INPUTS, so same-
    shaped rebuilds (policy churn) share one executable."""
    return model(data, lens, remotes)


def _call_model_attr(model, data, lens, remotes):
    """Model-as-argument trace twin of ``model.verdicts_attr``."""
    return model.verdicts_attr(data, lens, remotes)


class _SidecarConn:
    """Service-side state for one datapath connection."""

    __slots__ = ("conn", "client", "bufs", "engine", "fast_ok", "skip",
                 "module_id", "demoted_mod", "columnar_dead")

    def __init__(self, conn, client, engine, module_id: int = 0):
        self.conn = conn  # in-process oracle Connection
        self.client = client
        # Mirror of the datapath's unconsumed buffer, per direction
        # (False=orig/request, True=reply).
        self.bufs = {False: bytearray(), True: bytearray()}
        self.engine = engine  # batch engine for request direction, or None
        self.fast_ok = engine is not None
        # Bytes already covered by an earlier PASS/DROP verdict that
        # overshot the then-buffered input (a parser may decide on a
        # frame prefix, reference: libcilium.h OnData comment); they are
        # consumed on arrival without re-parsing.
        self.skip = {False: 0, True: 0}
        self.module_id = module_id
        # Set while this conn has been demoted off a quarantined device
        # engine onto the oracle path; remembers the module so the
        # engine can be rebound once the device heals and the oracle
        # residue drains.
        self.demoted_mod = None
        # Columnar lane-exit dead latch: the arena's overflow latch
        # when the conn left the lane with NO engine to adopt it (the
        # scalar twin of FlowState.overflowed).  The overflowed bytes
        # are gone, so every further request entry must answer a typed
        # protocol error — resuming the parse mid-stream would emit
        # wrong op byte counts on the wire.
        self.columnar_dead = False


class EpochParityError(AssertionError):
    """A staged epoch's device tables disagreed with the host oracle —
    the swap is rejected and the old epoch keeps serving."""


class _SwapJob:
    """One staged policy-table swap riding the builder queue."""

    __slots__ = ("module_id", "staged_map", "done", "status", "epoch",
                 "phase")

    def __init__(self, module_id: int, staged_map):
        self.module_id = module_id
        self.staged_map = staged_map
        self.done = threading.Event()
        self.status = int(FilterResult.UNKNOWN_ERROR)
        self.epoch = -1
        # Typestate: staged -> committed | rejected, mediated through
        # EPOCH_SWAP_PROTOCOL (a job never leaves the terminal states).
        self.phase = SWAP_STAGED


class _TabSnap:
    """One-round consistent view of the vectorized-path conn tables,
    taken under the registry lock at the start of each dispatch round so
    eligibility checks and chunk issue never race policy_update /
    new_connection table mutations (including engine slot reuse).

    Holds only the rows for the round's (sorted, unique) conn ids —
    O(round conns), not O(table size).  Out-of-range ids materialize as
    engine=-1 / dirty=1 so they fail vec eligibility naturally."""

    __slots__ = ("ids", "engine", "src", "dirty", "objs", "single",
                 "swap_s", "cache", "cache_epoch", "cache_rule", "epoch")

    def __init__(self, ids, engine, src, dirty, objs, single=False,
                 cache=None, cache_epoch=None, cache_rule=None,
                 epoch=0):
        self.ids = ids
        self.engine = engine
        self.src = src
        self.dirty = dirty
        self.objs = objs
        # True when the snapshot rows are exactly one item's conn_ids in
        # arrival order — lookups are then the identity (no search).
        self.single = single
        # Time this snapshot's lock acquisition spent blocked behind an
        # epoch-swap pointer flip (the round books it as table_swap).
        self.swap_s = 0.0
        # Verdict-cache columns for the round's conns (armed state /
        # claim epoch / claimed rule row) plus the policy epoch
        # captured under the SAME lock — a hit requires the claim epoch
        # to equal this captured epoch, so a round snapshotted before a
        # flip serves the flip-preceding epoch consistently (exactly
        # the in-flight-round contract engine rounds already follow).
        n = len(ids)
        self.cache = (
            cache if cache is not None else np.zeros(n, np.uint8)
        )
        self.cache_epoch = (
            cache_epoch if cache_epoch is not None
            else np.full(n, -1, np.int64)
        )
        self.cache_rule = (
            cache_rule if cache_rule is not None
            else np.full(n, -1, np.int32)
        )
        self.epoch = epoch

    def lookup(self, cids: np.ndarray) -> np.ndarray:
        """Positions of cids in the snapshot rows (every data-item conn
        id is in self.ids by construction)."""
        n = len(cids)
        if self.single and n == len(self.ids) and n <= len(_IDENTITY):
            return _IDENTITY[:n]
        return np.searchsorted(self.ids, cids.astype(np.int64))


# Shared identity-permutation prefix for single-item snapshot lookups.
_IDENTITY = np.arange(1 << 14)


class _ColumnarLog:
    """Batched access-log sink for the fast path: one record per device
    batch instead of one Python object per request.  The per-batch ring
    is bounded; the running counters are exact."""

    def __init__(self, maxlen: int = 4096):
        from collections import deque

        self.batches = deque(maxlen=maxlen)
        self.requests = 0
        self.denied = 0

    def log_batch(self, proto: str, n: int, denied: int) -> None:
        self.requests += n
        self.denied += denied
        self.batches.append({"proto": proto, "n": n, "denied": denied})


class VerdictService:
    """Unix-socket verdict service.

    One acceptor thread, one reader thread per shim connection, one
    dispatcher worker owning all device dispatch (so device models are
    only ever called from a single thread — jit caches stay warm and
    per-flow engine state needs no locking beyond the dispatcher's
    serialization).
    """

    def __init__(self, socket_path: str, config: DaemonConfig | None = None):
        self.socket_path = socket_path
        self.config = config or DaemonConfig()
        # Overload & fault containment: the guard owns the quarantine
        # state machine (device -> quarantine -> host fallback), the
        # dispatcher enforces the admission cap and the round watchdog
        # (-> shed).  All rungs of the ladder are typed and observable.
        self.guard = DeviceGuard(
            timeout_s=self.config.device_call_timeout_s,
            reprobe_interval_s=self.config.device_reprobe_interval_s,
            fail_threshold=self.config.device_fail_threshold,
            on_change=self._on_quarantine_change,
        )
        self._queue_age_s = self.config.shed_queue_age_ms / 1000.0
        self.dispatcher = BatchDispatcher(
            self._process,
            max_batch=self.config.batch_flows,
            timeout_ms=self.config.batch_timeout_ms,
            max_pending=self.config.shed_queue_entries,
            stall_timeout_s=self.config.device_call_timeout_s,
            on_batch_error=self._on_batch_error,
            on_stall=self._on_dispatch_stall,
        )
        # Latency decomposition: per-round stage stamps -> microsecond
        # histograms + sampled spans / slow exemplars (trace.py).  The
        # tracer is always constructed; trace_stage_metrics=False turns
        # the metric observes off (the bench's disabled baseline).
        self.tracer = VerdictTracer(
            sample_every=self.config.trace_sample_every,
            slow_ms=self.config.trace_slow_ms,
            ring=self.config.trace_ring,
            stage_metrics=self.config.trace_stage_metrics,
            batch_capacity=self.config.batch_flows,
        )
        # Flight recorder: always-on incident timeline fed from the
        # protocols.py transition observer (every mediated typestate
        # edge), overload markers, and a per-round occupancy sampler
        # riding the tracer's finish_round.  Fail-closed edges trigger
        # postmortem bundles on a detached thread (blackbox.py) — the
        # enrichment providers below take this service's locks, which
        # is exactly why they must never run on the transition thread.
        self.recorder = blackbox.FlightRecorder(
            ring=self.config.timeline_ring,
            bundle_dir=self.config.timeline_bundle_dir,
            slow_only=self.config.timeline_slow_only,
        )
        self.recorder.stage_provider = self.tracer.status
        self.recorder.status_provider = self._postmortem_status
        self.recorder.occupancy_probe = self._occupancy_probe
        self.recorder.install()
        self.tracer.recorder = self.recorder
        # Device-economics ledger: every executable-producing site
        # routes through ledger.record_compile (lint R23 proves it)
        # and every dispatch round's formation stamp rides the
        # tracer's finish_round — compile causes and batch-formation
        # provenance become recorded data (ledger.py).
        self.ledger = ledger_mod.DeviceLedger(
            ring=self.config.timeline_ring,
        )
        self.ledger.install()
        self.tracer.ledger = self.ledger
        # Containment telemetry (status/metrics).
        self.shed_entries = 0
        self.batch_crashes = 0
        self.fallback_entries = 0
        self.error_entries = 0
        self._lock = threading.Lock()  # conn/engine registry
        self._conns: dict[int, _SidecarConn] = {}
        self._engines: dict[tuple, object] = {}
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._clients: list["_ClientHandler"] = []
        self._stopped = False
        self.fast_log = _ColumnarLog()
        # Per-batch-size scratch for verdict frame assembly (op pattern
        # template + constant columns) — bounds per-frame numpy work to
        # one template copy and two strided stores.
        self._frame_tpl: dict[int, tuple] = {}
        # Per-stage CPU accounting of the group fast path (seam_probe
        # runs only): stage -> [calls, thread-CPU seconds].  This is the
        # published seam breakdown the latency bench reports.
        self.seam_stages: dict[str, list] = {}
        # Vectorized-path conn table: parallel arrays indexed by conn_id
        # (grown on demand) so batch eligibility and remote-identity
        # lookups are O(1) numpy gathers instead of per-entry dict walks.
        self._tab_size = 0
        self._tab_engine = np.empty(0, np.int32)  # engine idx, -1 = none
        self._tab_src = np.empty(0, np.int32)  # remote identity (src_id)
        self._tab_dirty = np.empty(0, np.uint8)  # 1 = residual state
        # In-flight columnar-round refcount per conn (guarded by _lock,
        # bulk np.add.at updates): the array twin of _async_pending for
        # the reassembler lane, consulted by the sync-round deferral,
        # the epoch flip and the stale-conn catch-up so a later round
        # can never overtake an issued-not-finished columnar round.
        self._tab_async = np.empty(0, np.uint32)
        # Established-flow verdict cache (policy/invariance.py): per-
        # conn byte-invariance claims as parallel arrays so the hit
        # check is one vectorized mask per round.  State: 0 unchecked,
        # 1 armed (invariant-allow), 2 checked-no-claim.  A hit
        # additionally requires the claim epoch to equal the round's
        # snapshot epoch — the structural invalidation: every pointer
        # flip retires all armed rows without touching them.
        self._flow_cache_on = self.config.flow_cache
        self._tab_cache = np.empty(0, np.uint8)
        self._tab_cache_epoch = np.empty(0, np.int64)
        self._tab_cache_rule = np.empty(0, np.int32)
        # Last-HIT recency stamp per armed row: at the
        # flow_cache_entries cap the least-recently-hit row is evicted
        # (LRU) instead of new flows silently never arming.
        self._tab_seen_tick = np.empty(0, np.int64)
        self._cache_tick = 0
        self._cache_armed = 0  # armed rows (flow_cache_entries cap)
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_invalidations = 0
        self.cache_evictions = 0
        self._engine_objs: list[object] = []
        self._engine_idx: dict[int, int] = {}  # id(engine) -> table idx
        self._engine_free: list[int] = []
        self._objs_cache: tuple | None = None  # invalidated on mutation
        # Flow-level verdict observability: the per-node record ring
        # MSG_OBSERVE / `cilium observe` reads.  flow_observe=False
        # removes record emission and the attributed device call (the
        # flow_observe_overhead bench's disabled baseline).
        self._flow_observe = self.config.flow_observe
        self.flowlog = (
            FlowLog(capacity=self.config.flowlog_ring,
                    opts=self.config.opts)
            if self._flow_observe else None
        )
        # id(model) -> (model, jitted fn); the model reference pins the
        # id so a gc'd model can never alias a cache entry.
        self._jit_cache: dict[int, tuple] = {}
        self._jit_gather: dict[int, tuple] = {}
        self._jit_attr: dict[int, tuple] = {}
        # Shape signatures prewarm has fully warmed (every bucket, both
        # row and gather paths): a churn rebuild whose tables land in
        # the same buckets skips its warm launches entirely.
        self._prewarmed_shapes: dict = {}
        # Dispatch mode: 'eager'/'jit' honored as configured; 'auto' is
        # resolved by measurement at the first engine prewarm (guarded
        # by _dispatch_lock: concurrent first binds must not measure
        # twice or observe a mid-measurement mode flip).
        self._use_jit = self.config.dispatch_mode == "jit"
        self._dispatch_resolved = self.config.dispatch_mode != "auto"
        self._dispatch_lock = threading.Lock()
        self.dispatch_mode_chosen = (
            self.config.dispatch_mode
            if self._dispatch_resolved else None
        )
        self._exec_device = None
        if self.config.verdict_device == "cpu":
            import jax

            self._exec_device = jax.devices("cpu")[0]
        # Multi-chip sharded serving (parallel/rulesharding.py): the
        # (flows, rules) mesh resolves lazily at the FIRST engine
        # build (a service that never dispatches must not initialize a
        # backend) and is guarded by _mesh_lock.  A faulting mesh rung
        # walks a WIDTH LADDER instead of collapsing binary: full mesh
        # -> reshaped mesh over the surviving devices -> single-chip
        # fallback -> quarantine/host-oracle, every transition typed
        # (the guard's quarantine/heal ladder keeps owning
        # single-device health on the rung below).
        self._mesh = None
        self._mesh_resolved = False
        self._mesh_lock = threading.Lock()
        self._mesh_demoted: str | None = None
        self.mesh_demotions: dict[str, int] = {}
        # Width-ladder rung state.  The rung is DERIVED, never stored:
        # full = (_mesh_demoted None, _mesh_serving None); reshaped =
        # (None, Mesh over survivors); fallback = (reason, *).
        # _mesh_serving is the degraded mesh the engines currently
        # dispatch on; _mesh_lost is the attributed dead device-id set
        # (mirrors the DeviceGuard per-device health table).
        self._mesh_serving = None
        self._mesh_lost: set[int] = set()
        self.mesh_reshapes = 0
        self.mesh_reshape_failures: dict[str, int] = {}
        # Fallback-width window of the LAST completed reshape (fault
        # stamp -> reshaped flip), the bench drift-guard metric.
        self.mesh_reshape_window_ms = 0.0
        self._mesh_fault_at = 0.0
        # Capacity fraction of the current rung (1.0 full, width ratio
        # reshaped, 1/width fallback) — scales the admission queue cap
        # and the DRR credit windows so a degraded mesh sheds typed at
        # its actual capacity.
        self._mesh_capacity = 1.0
        # Test seam: per-device probe callable (device -> bool).  None
        # uses the real put+readback probe.
        self._device_probe_fn = None
        # Mesh ladder state staged by restore_handoff, consumed at
        # _resolve_mesh (a successor resumes reshaped instead of
        # re-probing a known-dead chip).
        self._handoff_mesh: dict | None = None
        # ROADMAP 5b: an explicit flow extent wider than the smallest
        # dispatch bucket grows the minimum bucket to match (set at
        # _resolve_mesh, read by the _min_bucket property).
        self._mesh_min_bucket = 0
        # Guarded re-promotion (ROADMAP 1b): demotion is no longer
        # sticky-until-restart — a timed re-probe (mirroring the
        # DeviceGuard quarantine heal, but on the policy-builder
        # thread) rebuilds one sharded executable off-path,
        # parity-probes it against the single-chip fallback, and flips
        # the retained sharded wrappers back in one pointer pass.
        self._mesh_reprobe_last = 0.0
        self._mesh_reprobe_inflight = False
        self.mesh_repromotions = 0
        # ROADMAP 1c: demotion-era engines re-sharded by the heal's
        # queued rebinds (status surface; see _run_mesh_rebuild).
        self.mesh_rebind_rebuilds = 0
        self.vec_batches = 0
        self.vec_entries = 0
        # Completion pipeline: the dispatcher issues device calls without
        # blocking (jax arrays are futures); this FIFO queue + worker
        # materializes results and sends responses, so host batch
        # assembly overlaps device compute and the device round-trip
        # latency never stalls the dispatch loop.  FIFO order preserves
        # per-connection op order across vec and entrywise rounds.
        self._completions: "queue.Queue" = queue.Queue()
        self._completion_thread: threading.Thread | None = None
        self._sends: "queue.Queue" = queue.Queue()
        self._send_thread: threading.Thread | None = None
        # Greedy dispatch (batch_timeout_ms == 0) implies a co-located
        # device whose readback is cheap: complete rounds inline on the
        # dispatcher thread — one fewer thread handoff per verdict.
        # ALL sends must then go inline (vec and entrywise) so per-conn
        # FIFO order is owned by one thread.
        self._inline_complete = self.config.batch_timeout_ms <= 0
        # Conns with an issued-but-unfinished async entrywise round
        # (refcounts; guarded by _lock).  Sync rounds touching them are
        # deferred to the send thread — see _process_entrywise.
        self._async_pending: dict[int, int] = {}
        # Columnar reassembly engine (sidecar/reasm.py): the mixed-path
        # slow lane's carry buffers, frame splitting and op assembly as
        # array passes per ROUND.  Pipelined mode only (greedy rounds
        # are 1-2 small messages — the columnar fixed cost loses); the
        # scalar engine path survives as the oracle/fallback rung.
        self._reasm = (
            Reassembler(
                cap_per_conn=self.config.max_flow_buffer,
                arena_capacity=self.config.reasm_arena_bytes,
            )
            if self.config.reasm and not self._inline_complete
            else None
        )
        # Columnar rounds that bailed back to the scalar rung, by
        # reason (status surface: a silent fallback must be visible).
        self.reasm_fallbacks: dict[str, int] = {}
        # Cut-through telemetry (greedy mode): rounds processed directly
        # on the shim reader thread, skipping the dispatcher handoff.
        self.inline_batches = 0
        self._prev_switch_interval: float | None = None
        # Transport ladder telemetry: attach rejections (no peer object
        # to count them on) and ring-delivered entry totals.  Per-
        # session ring/fallback state lives on each _ClientHandler.
        self.transport_rejects: dict[str, int] = {}
        self.shm_entries = 0
        # Multi-tenant fan-in: one SessionState per accepted shim
        # connection (transport.py).  _sess_lock guards the registry
        # only — never held across blocking work.  Dead sessions are
        # retained (bounded) so an operator can attribute a shed or
        # quarantine to a pod AFTER it died.
        self._sess_lock = threading.Lock()
        self._sessions: dict[int, SessionState] = {}
        self._dead_sessions: "deque[dict]" = _deque(maxlen=32)
        self._session_seq = 0
        # Reconnect-storm tracking per announced identity (bounded LRU
        # — see _session_hello): monotonic connect stamps inside the
        # rolling window.  _metric_idents is the bounded Prometheus
        # label vocabulary for per-session metrics.
        self._ident_connects: dict[str, "deque[float]"] = {}
        self._metric_idents: set[str] = set()
        # DRR admission fairness: the per-session credit window
        # (outstanding entries), recomputed lazily at most every 50ms.
        self._share_val = self.config.shed_queue_entries
        self._share_ts = 0.0
        # Segment-reclaim timers for sessions that died without
        # MSG_SHM_DETACH (cancelled at stop()).
        self._reclaim_timers: list[threading.Timer] = []
        self.shm_reclaims = 0
        # Policy-table epochs (guarded by _lock where noted).  Every
        # committed rule-table generation gets a monotonic epoch:
        # engines are stamped with the epoch they were compiled under,
        # in-flight rounds finish on the epoch their snapshot captured,
        # and flow records carry the epoch so a rule id is never
        # resolved against a table it did not index.
        self.policy_epoch = 0
        # Staged compile-then-swap runs on ONE builder thread so the
        # dispatch path never pays an XLA compile: the handler stages
        # the host-compiled policy map, the builder rebuilds device
        # engines + asserts per-epoch parity OFF-PATH, and the commit
        # is a pointer flip under _lock (bounded; surfaced as the
        # round decomposition's table_swap stage).
        self._build_queue: "queue.Queue" = queue.Queue()
        self._builder_thread: threading.Thread | None = None
        # Conn ids with an in-flight builder rebind (quarantine-heal
        # path) so the dispatch loop never compiles and never
        # double-submits; guarded by _lock.
        self._rebind_inflight: set[int] = set()
        # Conns a swap could not rebind (in-flight deferred round /
        # undrained engine ops at flip time): they finish on their
        # captured engine, and the entrywise path catches them up to
        # the current epoch — migrating the retained buffer — once the
        # round drains.  Guarded by _lock; read lock-free (set
        # membership) on the dispatch path.
        self._stale_conns: set[int] = set()
        # Most recent swap's lock-hold window (monotonic start, end):
        # rounds whose snapshot acquisition overlapped it book the
        # overlap as their table_swap stage.
        self._swap_window = (0.0, 0.0)
        self.policy_swaps = 0
        self.policy_swap_failures: dict[str, int] = {}
        self.last_swap_ms = 0.0
        # Hitless restart (Envoy-hot-restart-style handoff + PR 1
        # fencing semantics).  restart_generation is the monotonic
        # fencing token: a successor that pulled our snapshot runs at
        # generation+1, and the surrendered (fenced) predecessor
        # rejects every late write TYPED — policy updates NACK
        # FilterResult.FENCED, data frames shed SHED_FENCED — so a
        # zombie old process can never serve a verdict the successor's
        # epoch would contradict.
        self.restart_generation = 1
        self._fenced = False
        self.fence_rejects = 0
        self._path_released = False  # surrendered the socket path
        self.handoff_at = 0.0  # monotonic: when WE surrendered
        self.handoff_loaded_at = 0.0  # monotonic: snapshot restored
        self.handoff_ts = 0.0  # predecessor's wall-clock stamp
        # Restored-but-not-yet-replayed state from a predecessor's
        # snapshot: consumed (popped) as clients replay their sessions,
        # conns and grants against us — a replayed row matching the
        # snapshot revalidates in place (counted); anything left over
        # is just forgotten (the client replay is authoritative).
        self._handoff_sessions: dict[str, dict] = {}
        self._handoff_conns: dict[int, dict] = {}
        self._handoff_grants: dict[int, tuple] = {}
        self._handoff_residue: dict[int, dict] = {}
        self._handoff_rules: list = []
        self.handoff_session_restores = 0
        self.handoff_conn_restores = 0
        self.handoff_grant_restores = 0
        self.handoff_residue_restores = 0
        self.handoff_warm_shapes = 0
        self.handoff_refused: dict[str, int] = {}
        self.shm_stale_swept = 0  # startup /dev/shm orphan sweep

    # -- lifecycle --------------------------------------------------------

    # GIL switch interval while a greedy (co-located) service is up.
    # The interpreter default is 5ms — on a small host one Python thread
    # mid-bytecode can stall every other seam thread for 5ms, which IS
    # the latency tail.  0.5ms was chosen by sweep: lower values (50µs)
    # make jax's internal mutexes spin under contention (measured
    # ~400µs of burned thread-CPU per device call), higher ones grow
    # the convoy tail.
    GIL_SWITCH_INTERVAL_S = float(
        os.environ.get("CILIUM_TPU_GIL_SWITCH_S", 5e-4)
    )

    def start(self) -> "VerdictService":
        if self._inline_complete:
            import sys

            self._prev_switch_interval = sys.getswitchinterval()
            sys.setswitchinterval(self.GIL_SWITCH_INTERVAL_S)
        # Startup stale-segment sweep: a kill -9'd predecessor's shm
        # orphans (owner pid dead, lease expired) are force-unlinked
        # before serving — in-service reclaim timers die with their
        # service, so without this sweep crash orphans leak until
        # reboot.
        self.shm_stale_swept = sweep_stale_segments(
            self.config.shm_lease_s
        )
        if self.shm_stale_swept:
            metrics.SidecarStaleSegmentsSwept.inc(
                amount=self.shm_stale_swept
            )
            log.info(
                "swept %d stale predecessor shm segments",
                self.shm_stale_swept,
            )
        # Graceful takeover: if a live predecessor still owns the
        # socket path, pull its handoff snapshot over the side channel
        # BEFORE unlinking the path out from under it.  Any failure
        # falls through to the cold-boot path below — cold state is
        # always correct (stale-segment reclaim + grant revalidation +
        # client replay), it just isn't warm.
        self._pull_handoff()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(16)
        self.dispatcher.start()
        self._completion_thread = threading.Thread(
            target=self._completion_loop, name="verdict-complete", daemon=True
        )
        self._completion_thread.start()
        self._send_thread = threading.Thread(
            target=self._send_loop, name="verdict-send", daemon=True
        )
        self._send_thread.start()
        self._builder_thread = threading.Thread(
            target=self._policy_builder_loop, name="policy-builder",
            daemon=True,
        )
        self._builder_thread.start()
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stopped = True
        # Deregister from the process-wide transition observer first: a
        # stopping service must not record (or bundle) its neighbors'
        # edges in multi-service processes (handoff).
        self.recorder.uninstall()
        self.ledger.uninstall()
        # shutdown BEFORE close: the acceptor thread parked in accept()
        # holds the fd, and a bare close() defers the kernel teardown —
        # the listener would keep accepting into its backlog and a
        # reconnecting shim would attach to this ZOMBIE service (whose
        # dispatcher is dead) instead of failing over to the restarted
        # one.  Unlink the path immediately for the same reason.
        if self._listener is not None:
            shutdown_close(self._listener)
        if not self._path_released:
            # A surrendered (fenced) service already released the path
            # to its successor — unlinking here would delete the
            # SUCCESSOR's fresh socket.
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        # Close shim connections so their reader/writer peers see EOF
        # immediately (a restarting shim must not block in recv on a
        # dead service).
        with self._lock:
            clients = list(self._clients)
        for client in clients:
            shutdown_close(client.sock)
        self.dispatcher.stop()
        if self._builder_thread is not None:
            self._build_queue.put(None)
            self._builder_thread.join(timeout=5)
        if self._completion_thread is not None:
            self._completion_put(("stop",))
            self._completion_thread.join(timeout=5)
        if self._send_thread is not None:
            self._send_thread.join(timeout=5)
        # Pending shm-segment reclaims die with the service (the lease
        # contract is per-service-life; a replacement service cannot
        # tell a leased orphan from a live session's rings anyway).
        with self._sess_lock:
            timers, self._reclaim_timers = self._reclaim_timers, []
        for t in timers:
            t.cancel()
        # (The socket path was unlinked up front — a second unlink here
        # could delete a RESTARTED service's fresh socket.)
        if self._prev_switch_interval is not None:
            import sys

            sys.setswitchinterval(self._prev_switch_interval)
            self._prev_switch_interval = None

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            if self._stopped:
                # Raced stop(): never hand a connection to a dead
                # service — the peer must see EOF and fail over.
                shutdown_close(sock)
                return
            client = _ClientHandler(self, sock)
            with self._lock:
                self._clients.append(client)
            t = threading.Thread(target=client.read_loop, daemon=True)
            t.start()
            self._threads.append(t)

    # -- hitless restart: handoff snapshot / restore / fencing ------------

    def snapshot_handoff(self) -> dict:
        """Serialize the state a successor needs to serve warm: policy
        epoch, session identities, conn registry rows, armed grant
        rows, per-conn flow-buffer residue, the live rule-source index
        and the quarantine latch — one versioned JSON-safe dict (the
        Envoy hot-restart parent->child state transfer, over our side
        channel).  Every field written here is consumed by
        ``restore_handoff`` or explicitly versioned-out (lint R17
        audits the pair)."""
        with self._sess_lock:
            sessions = [
                {
                    "identity": s.identity,
                    "submitted": int(s.submitted),
                    "answered": int(s.answered),
                }
                for s in self._sessions.values()
                if s.named
            ]
        conns: list = []
        grants: list = []
        residue: list = []
        rules: set = set()
        with self._lock:
            epoch = self.policy_epoch
            for key in self._engines:
                _mod, policy_name, ingress, port, proto = key
                rules.add((policy_name, bool(ingress), int(port or 0),
                           proto))
            for cid, sc in self._conns.items():
                c = sc.conn
                conns.append({
                    "conn_id": int(cid),
                    "policy": c.policy_name,
                    "ingress": bool(c.ingress),
                    "src_id": int(c.src_id),
                    "proto": c.parser_name,
                })
                # Residue lives wherever the conn's lane keeps it: the
                # engine flow buffer (fast path), the columnar arena
                # carry, or the oracle mirror in sc.bufs — composed in
                # _demote_to_oracle's order (engine bytes precede
                # arena carry precede the mirror) so the successor's
                # oracle parses the stream exactly as the predecessor
                # would have.  Reads are non-destructive: the conn
                # keeps serving unchanged if the handoff aborts.
                ro = b""
                flows = getattr(sc.engine, "flows", None)
                if flows is not None:
                    flow = flows.get(cid)
                    if flow is not None and getattr(
                        flow, "buffer", None
                    ):
                        ro = bytes(flow.buffer)
                if self._reasm is not None:
                    ro += self._reasm.arena.peek(cid)
                ro += bytes(sc.bufs[False])
                rr = bytes(sc.bufs[True])
                if ro or rr or sc.skip[False] or sc.skip[True]:
                    residue.append({
                        "conn_id": int(cid),
                        "orig": base64.b64encode(ro).decode("ascii"),
                        "reply": base64.b64encode(rr).decode("ascii"),
                        "skip_orig": int(sc.skip[False]),
                        "skip_reply": int(sc.skip[True]),
                    })
                if (
                    self._flow_cache_on
                    and cid < self._tab_size
                    and self._tab_cache[cid] == 1
                ):
                    grants.append({
                        "conn_id": int(cid),
                        "epoch": int(self._tab_cache_epoch[cid]),
                        "rule": int(self._tab_cache_rule[cid]),
                    })
        return {
            "version": wire.HANDOFF_VERSION,
            "generation": self.restart_generation,
            "ts": time.time(),
            "socket_path": self.socket_path,
            "policy_epoch": epoch,
            "sessions": sessions,
            "conns": conns,
            "grants": grants,
            "residue": residue,
            "rules": [
                {"policy": p, "ingress": i, "port": pt, "proto": pr}
                for p, i, pt, pr in sorted(rules)
            ],
            "guard": self.guard.snapshot_state(),
            # Mesh width-ladder rung: the successor resumes RESHAPED
            # around the known-dead chips instead of re-probing them
            # through a fault (consumed at _resolve_mesh).
            "mesh": {
                "lost": sorted(int(x) for x in self._mesh_lost),
                "reshapes": int(self.mesh_reshapes),
            },
        }

    def restore_handoff(self, snap: dict) -> bool:
        """Successor half: adopt a predecessor's snapshot.  Version-
        gated (a FUTURE snapshot version is refused typed — cold boot
        serves correctly); restores the committed policy epoch, the
        restart generation (+1 — the fencing token), the quarantine
        latch, and stages sessions/conns/grants/residue for the client
        replay to revalidate row by row."""
        try:
            version = int(snap.get("version", -1))
            generation = int(snap["generation"])
            epoch = int(snap["policy_epoch"])
        except (KeyError, TypeError, ValueError):
            self.handoff_refused["malformed"] = (
                self.handoff_refused.get("malformed", 0) + 1
            )
            return False
        if version < 1 or version > wire.HANDOFF_VERSION:
            # Versioned-out: a snapshot from a NEWER schema is refused
            # whole (never half-parsed) — cold boot is always correct.
            self.handoff_refused["version"] = (
                self.handoff_refused.get("version", 0) + 1
            )
            return False
        if snap.get("socket_path") != self.socket_path:
            self.handoff_refused["path-mismatch"] = (
                self.handoff_refused.get("path-mismatch", 0) + 1
            )
            return False
        self.restart_generation = generation + 1
        self.policy_epoch = epoch
        self.handoff_ts = float(snap.get("ts") or 0.0)
        self.handoff_loaded_at = time.monotonic()
        self._handoff_sessions = {
            r["identity"]: r
            for r in snap.get("sessions") or []
            if r.get("identity")
        }
        self._handoff_conns = {
            int(r["conn_id"]): r for r in snap.get("conns") or []
        }
        self._handoff_grants = {
            int(r["conn_id"]): (int(r["epoch"]), int(r["rule"]))
            for r in snap.get("grants") or []
        }
        self._handoff_residue = {
            int(r["conn_id"]): r for r in snap.get("residue") or []
        }
        self._handoff_rules = list(snap.get("rules") or [])
        self.guard.restore_state(snap.get("guard") or {})
        # Versioned-in mesh ladder state (.get: absent in pre-PR-17
        # snapshots — cold mesh resolution is always correct).  Staged
        # only; consumed when the mesh actually resolves.
        mesh_row = snap.get("mesh")
        if isinstance(mesh_row, dict):
            try:
                self._handoff_mesh = {
                    "lost": sorted(
                        {int(x) for x in mesh_row.get("lost") or ()}
                    ),
                    "reshapes": int(mesh_row.get("reshapes") or 0),
                }
            except (TypeError, ValueError):
                self._handoff_mesh = None
        # Executable-cache adoption (same-process successor only): the
        # restored rule sources rebuild into the SAME shape signatures,
        # so the deposited prewarm ledger makes churn rebuilds skip
        # their warm launches — no cold recompile of unchanged tables.
        warmed = _HANDOFF_SHAPE_CACHE.pop(self.socket_path, None)
        if warmed:
            self._prewarmed_shapes.update(warmed)
            self.handoff_warm_shapes = len(warmed)
        metrics.SidecarRestartGeneration.set(
            float(self.restart_generation)
        )
        log.info(
            "handoff snapshot restored: generation %d -> %d, epoch %d, "
            "%d sessions, %d conns, %d grants, %d residue rows, "
            "%d warm shapes",
            generation, self.restart_generation, epoch,
            len(self._handoff_sessions), len(self._handoff_conns),
            len(self._handoff_grants), len(self._handoff_residue),
            self.handoff_warm_shapes,
        )
        return True

    def handoff_surrender(
        self, successor_gen: int, deadline_s: float
    ) -> tuple[dict | None, str]:
        """Predecessor half (runs on the requesting handler's reader
        thread): quiesce, snapshot, fence, release the socket path.
        After this returns the service is a ZOMBIE — it answers
        nothing new (typed rejects only) and exists solely so late
        writers get their typed refusal instead of silence.  A stale
        claimant (generation <= ours, PR 1 fencing semantics) and a
        second claimant (already fenced) are both refused typed."""
        if 0 < successor_gen <= self.restart_generation:
            self.handoff_refused["stale-generation"] = (
                self.handoff_refused.get("stale-generation", 0) + 1
            )
            return None, (
                f"stale successor generation {successor_gen} <= "
                f"{self.restart_generation}"
            )
        with self._lock:
            if self._fenced:
                self.handoff_refused["already-fenced"] = (
                    self.handoff_refused.get("already-fenced", 0) + 1
                )
                return None, "already fenced by an earlier successor"
            self._fenced = True
        # Quiesce bounded by the successor's declared deadline: rounds
        # in flight at surrender are answered by THIS process (the
        # cross-restart exactly-once contract's "old process" arm).
        # The fence above already stops new data admission
        # (_fanin_admit sheds SHED_FENCED), so the queue only drains.
        self.dispatcher.flush(timeout=max(deadline_s, 0.0))
        self.dispatcher.fenced = True
        snap = self.snapshot_handoff()
        # Deposit the warm-shape ledger for a same-process successor
        # (see _HANDOFF_SHAPE_CACHE).
        if self._prewarmed_shapes:
            _HANDOFF_SHAPE_CACHE[self.socket_path] = dict(
                self._prewarmed_shapes
            )
        # Release the listener and the path so the successor can bind:
        # shutdown (not bare close) pops the acceptor thread out of
        # accept() immediately.
        listener = self._listener
        if listener is not None:
            shutdown_close(listener)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        self._path_released = True
        self.handoff_at = time.monotonic()
        metrics.SidecarHandoffSurrenders.inc()
        log.warning(
            "handoff surrendered (generation %d, epoch %d): fenced, "
            "socket path released", self.restart_generation,
            snap["policy_epoch"],
        )
        return snap, ""

    def _pull_handoff(self) -> None:
        """Successor half of the side channel: dial the predecessor's
        socket (we have not bound yet), request its snapshot
        (MSG_HANDOFF), restore it.  Every failure — no predecessor,
        dead socket (crash restart), timeout, refusal, malformed reply
        — degrades to the cold-boot path, which is always correct."""
        if not self.config.restart_handoff:
            return
        if not os.path.exists(self.socket_path):
            return
        deadline = self.config.handoff_deadline_s
        try:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(deadline)
            sock.connect(self.socket_path)
        except OSError:
            return  # crash restart: the path is a dead remnant
        try:
            wire.send_msg(
                sock, wire.MSG_HANDOFF, wire.pack_handoff(0, deadline)
            )
            reader = wire.BufferedReader(sock)
            while True:
                msg_type, payload = reader.recv_msg()
                if msg_type == wire.MSG_HANDOFF_REPLY:
                    break
            snap, err = wire.unpack_handoff_reply(payload)
            if snap is None:
                self.handoff_refused["predecessor"] = (
                    self.handoff_refused.get("predecessor", 0) + 1
                )
                log.warning("handoff refused by predecessor: %s", err)
                return
            self.restore_handoff(snap)
        except Exception:  # noqa: BLE001 — cold boot serves correctly
            log.warning(
                "handoff pull failed; starting cold", exc_info=True
            )
        finally:
            shutdown_close(sock)

    # -- control plane (called from client reader threads) ----------------

    def open_module(self, params, debug: bool) -> int:
        return pl.open_module(params, debug)

    def status(self) -> dict:
        """Service counters for operators/status/bugtool (the
        reference's nearest analog is the Envoy admin surface the agent
        scrapes for `cilium status`)."""
        with self._lock:
            n_conns = len(self._conns)
            n_engines = len(self._engines)
            clients = list(self._clients)
        return {
            "connections": n_conns,
            "engines": n_engines,
            # Transport ladder (shm fast path vs socket fallback): one
            # entry per live shim session — mode, ring occupancy/credit
            # cursors, doorbell batching, fallback counters.
            "transport": {
                "sessions": [c.transport_status() for c in clients],
                "rejects": dict(self.transport_rejects),
                "shm_entries": self.shm_entries,
                "shm_reclaims": self.shm_reclaims,
            },
            # Fan-in sessions: one row per live shim session (identity,
            # state, exactly-once counters, per-reason sheds/
            # quarantines) plus the bounded post-mortem ring — the
            # operator's per-pod attribution surface.
            "sessions": {
                "live": [
                    s.status() for s in sorted(
                        self._session_rows(), key=lambda s: s.id
                    )
                ],
                "dead": list(self._dead_sessions),
                "fair_share": self._share_val,
            },
            "dispatch_mode": self.dispatch_mode_chosen,
            # Multi-chip mesh rung: layout + demotion state; None when
            # multi-chip serving is off or no engine has resolved it.
            "mesh": self._mesh_status(),
            # Policy-table epoch churn: the committed epoch, swap
            # counters, and typed fail-closed rejections (the old
            # epoch kept serving through every one of them).
            "policy": {
                "epoch": self.policy_epoch,
                "swaps": self.policy_swaps,
                "swap_failures": dict(self.policy_swap_failures),
                "pending_builds": self._build_queue.qsize(),
                "last_swap_ms": self.last_swap_ms,
            },
            # Hitless-restart surface: the fencing generation, handoff
            # age/restore counters (successor side), the zombie's typed
            # rejects (predecessor side), and the startup orphan sweep.
            "restart": {
                "generation": self.restart_generation,
                "fenced": self._fenced,
                "fence_rejects": self.fence_rejects,
                "handoff_age_s": (
                    round(time.monotonic() - self.handoff_loaded_at, 3)
                    if self.handoff_loaded_at else None
                ),
                "handoff_refused": dict(self.handoff_refused),
                "session_restores": self.handoff_session_restores,
                "conn_restores": self.handoff_conn_restores,
                "grant_restores": self.handoff_grant_restores,
                "residue_restores": self.handoff_residue_restores,
                "warm_shapes": self.handoff_warm_shapes,
                "stale_segments_swept": self.shm_stale_swept,
            },
            "requests": self.fast_log.requests,
            "denied": self.fast_log.denied,
            "vec_batches": self.vec_batches,
            "vec_entries": self.vec_entries,
            "inline_batches": self.inline_batches,
            "dispatcher": {
                "batches": self.dispatcher.batches,
                "entries": self.dispatcher.entries,
                "fill": self.dispatcher.fill_dispatches,
                "deadline": self.dispatcher.deadline_dispatches,
                "queue_depth": self.dispatcher.pending_weight,
                "queue_oldest_ms": round(
                    self.dispatcher.oldest_age_s() * 1e3, 3
                ),
                "stall_deposals": self.dispatcher.stall_deposals,
                "shed_submits": self.dispatcher.shed_submits,
                "busy_seconds": round(self.dispatcher.busy_seconds, 3),
            },
            # Latency decomposition (sidecar/trace.py): per-stage means
            # by serving path + span/exemplar counters.
            "latency": self.tracer.status(),
            # Flight recorder (sidecar/blackbox.py): timeline ring
            # occupancy, fail-closed event/bundle counters, unified
            # serving-tier rungs.
            "timeline": self.recorder.status(),
            # Device-economics ledger (sidecar/ledger.py): compile
            # causes, the dispatch-path-compile invariant counter,
            # resident executables, and per-trigger batch-formation
            # provenance.
            "ledger": {
                **self.ledger.status(),
                "formation": self.ledger.formation(),
            },
            # Flow-record ring occupancy (flowlog/): None = disabled.
            "flowlog": (
                self.flowlog.stats() if self.flowlog is not None else None
            ),
            # Columnar reassembly engine (sidecar/reasm.py): round/
            # frame counters + arena occupancy; None = disabled (greedy
            # mode or reasm=False).  The tier-1 mixed smoke asserts
            # rounds > 0 so a silent fallback to the scalar rung can
            # never go green.
            "reasm": (
                {**self._reasm.status(),
                 "fallbacks": dict(self.reasm_fallbacks)}
                if self._reasm is not None else None
            ),
            # Established-flow verdict cache: armed rows + hit/miss/
            # invalidation counters; None = disabled (flow_cache off —
            # the true baseline).
            "flow_cache": (
                {
                    "armed": self._cache_armed,
                    "cap": self.config.flow_cache_entries,
                    "hits": self.cache_hits,
                    "misses": self.cache_misses,
                    "invalidations": self.cache_invalidations,
                    "evictions": self.cache_evictions,
                }
                if self._flow_cache_on else None
            ),
            # Degradation ladder: device -> quarantine -> host fallback
            # -> shed.  Every rung typed and counted.
            "containment": {
                "shed_entries": self.shed_entries,
                "error_entries": self.error_entries,
                "batch_crashes": self.batch_crashes,
                "fallback_entries": self.fallback_entries,
                **self.guard.status(),
            },
        }

    def _session_rows(self) -> list:
        with self._sess_lock:
            return list(self._sessions.values())

    def trace_dump(self, n: int = 100, kind: str | None = None,
                   session: int | None = None) -> dict:
        """Span-ring snapshot + tracer status for `cilium sidecar
        trace` (MSG_TRACE).  ``session`` filters spans to one fan-in
        session so a shed/slow exemplar can be pinned to a pod."""
        return {
            "spans": self.tracer.spans(n, kind, session=session),
            "latency": self.tracer.status(),
        }

    def timeline_dump(self, n: int = 100, since: int = 0,
                      table: str | None = None) -> dict:
        """Timeline snapshot for `cilium sidecar timeline`
        (MSG_TIMELINE): declared-edge events (filtered by minimum seq
        and/or table), occupancy buckets, postmortem summaries, and
        the recorder's own status."""
        return self.recorder.dump(n=n, since=since, table=table)

    def ledger_dump(self, n: int = 100, since: int = 0,
                    cause: str | None = None) -> dict:
        """Ledger snapshot for `cilium sidecar ledger` (MSG_LEDGER):
        compile events (filtered by minimum seq and/or cause), the
        per-trigger formation summary, and the ledger's own status."""
        return self.ledger.dump(n=n, since=since, cause=cause)

    def _postmortem_status(self) -> dict:
        """The status() sections a postmortem bundle carries — the
        fail-closed-relevant subset (mesh rung, guard ladder, policy
        epoch, dispatcher depth), NOT the full status: bundles must
        stay small enough to write under incident load.  Runs on the
        recorder's bundle thread only (takes this service's locks)."""
        full = self.status()
        return {
            k: full.get(k)
            for k in ("mesh", "containment", "policy", "dispatcher",
                      "sessions", "transport", "flow_cache")
        }

    def _occupancy_probe(self) -> tuple:
        """Queue depth + admission headroom for the occupancy sampler
        (plain attribute reads — called once per dispatch round)."""
        d = self.dispatcher
        cap = d.max_pending
        depth = d.pending_weight
        headroom = (max(cap - depth, 0) / cap) if cap else None
        return depth, headroom

    def close_module(self, module_id: int) -> None:
        pl.close_module(module_id)

    def policy_update(self, module_id: int,
                      policies_json: bytes) -> tuple[int, int]:
        """Non-stop policy churn entry: stage, build off-path, swap.

        Parse + host policy compile run here (fast, and a failure NACKs
        with the active policy untouched — the old contract).  The
        expensive half — device table rebuild + jit prewarm + per-epoch
        parity — runs on the builder thread so no dispatch round ever
        pays a compile; the commit is one pointer flip under _lock.
        Returns (status, committed epoch): OK means the new epoch IS
        serving; any failure is fail-closed — the previous epoch keeps
        serving bit-identically and the failure is typed
        (policy_swap_failures_total{reason})."""
        if self._fenced:
            # Zombie predecessor: the successor owns the epoch line now.
            # Typed NACK — the caller retries against the new socket.
            self.fence_rejects += 1
            metrics.SidecarFenceRejects.inc("policy_update")
            self._swap_failed("fenced")
            return int(FilterResult.FENCED), self.policy_epoch
        ins = pl.find_instance(module_id)
        if ins is None:
            return int(FilterResult.INVALID_INSTANCE), self.policy_epoch
        try:
            configs = [policy_from_dict(d) for d in json.loads(policies_json)]
        except Exception:  # noqa: BLE001 — NACK, active policy untouched
            log.exception("policy update rejected (parse)")
            self._swap_failed("parse")
            return int(FilterResult.POLICY_DROP), self.policy_epoch
        try:
            staged_map = ins.policy_prepare(configs)
        except Exception:  # noqa: BLE001 — NACK, active policy untouched
            log.exception("policy update rejected (host compile)")
            self._swap_failed("host-compile")
            return int(FilterResult.POLICY_DROP), self.policy_epoch
        job = _SwapJob(module_id, staged_map)
        self._build_queue.put(("swap", job))
        if not job.done.wait(self.config.policy_swap_timeout_s):
            # The build keeps running and will still swap when it
            # lands; only the CONFIRMATION timed out.  Typed so the
            # caller can re-poll status()["policy"]["epoch"].
            self._swap_failed("ack-timeout")
            return int(FilterResult.UNKNOWN_ERROR), self.policy_epoch
        return job.status, job.epoch

    # -- policy epoch builder (one thread; the only epoch incrementer) -----

    def _swap_failed(self, reason: str) -> None:
        self.policy_swap_failures[reason] = (
            self.policy_swap_failures.get(reason, 0) + 1
        )
        metrics.PolicySwapFailures.inc(reason)

    def _policy_builder_loop(self) -> None:
        while True:
            item = self._build_queue.get()
            if item is None:
                # Drain: pending jobs fail typed instead of stranding
                # their handlers until the ack timeout.
                while True:
                    try:
                        kind, job = self._build_queue.get_nowait()
                    except queue.Empty:
                        return
                    if kind == "swap":
                        self._swap_failed("shutdown")
                        with blackbox.annotate(reason="shutdown",
                                               epoch=self.policy_epoch):
                            job.phase = EPOCH_SWAP_PROTOCOL.advance(
                                job.phase, SWAP_REJECTED
                            )
                        job.status = int(FilterResult.UNKNOWN_ERROR)
                        job.epoch = self.policy_epoch
                        job.done.set()
            kind, job = item
            try:
                if kind == "swap":
                    self._run_swap(job)
                elif kind == "rebind":
                    self._run_rebind(*job)
                elif kind == "grants":
                    # Grant delivery queued off the dispatcher: the
                    # blocking client.send must never run inside the
                    # per-entry classification loop (revalidation in
                    # _send_cache_grants makes late delivery safe).
                    self._send_cache_grants(job)
                elif kind == "mesh_reprobe":
                    self._run_mesh_ladder(immediate=False)
                elif kind == "mesh_reshape":
                    # Queued by _demote_mesh right at the fault: walk
                    # DOWN the width ladder around the attributed dead
                    # devices (never up — promotion is owned by the
                    # paced re-probe above).
                    self._run_mesh_ladder(immediate=True)
                elif kind == "mesh_rebuild":
                    self._run_mesh_rebuild(*job)
            except Exception:  # noqa: BLE001 — builder must survive
                log.exception("policy builder job failed")
                if kind == "swap":
                    self._swap_failed("device-build")
                    if job.phase == SWAP_STAGED:
                        # A job that already reached a terminal phase
                        # inside _run_swap stays there.
                        with blackbox.annotate(reason="device-build",
                                               epoch=self.policy_epoch):
                            job.phase = EPOCH_SWAP_PROTOCOL.advance(
                                job.phase, SWAP_REJECTED
                            )
                    job.status = int(FilterResult.POLICY_DROP)
                    job.epoch = self.policy_epoch
                    job.done.set()

    def _engine_key_for(self, module_id: int, conn) -> tuple:
        return (module_id, conn.policy_name, conn.ingress, conn.port,
                conn.parser_name)

    def _run_swap(self, job: "_SwapJob") -> None:
        """Builder-thread half of one epoch: rebuild every live engine
        for the module against the STAGED policy map (prewarm included
        — shape-bucketed, so repeat churn hits the executable cache),
        re-assert device/host bit-identity, then commit with one
        pointer flip.  Any failure before the flip leaves the live
        tables untouched: the old epoch keeps serving."""
        module_id = job.module_id
        ins = pl.find_instance(module_id)
        if ins is None:
            self._swap_failed("no-instance")
            with blackbox.annotate(reason="no-instance",
                                   epoch=self.policy_epoch):
                job.phase = EPOCH_SWAP_PROTOCOL.advance(job.phase,
                                                        SWAP_REJECTED)
            job.status = int(FilterResult.INVALID_INSTANCE)
            job.epoch = self.policy_epoch
            job.done.set()
            return
        epoch = self.policy_epoch + 1  # sole incrementer: this thread
        # Modules are refcounted onto instances: every module id bound
        # to THIS instance serves the swapped map, so all their engines
        # rebuild with the epoch (a conn opened under a sibling module
        # id must not keep a superseded table).
        mods = {module_id}
        with self._lock:
            for sc in self._conns.values():
                if sc.conn.instance is ins:
                    mods.add(sc.module_id)
            keys = {k for k in self._engines if k[0] in mods}
            for sc in self._conns.values():
                if sc.conn.instance is ins and sc.conn.parser_name in (
                    ENGINE_PROTOS
                ):
                    keys.add(self._engine_key_for(sc.module_id, sc.conn))
            prior_engines = dict(self._engines)
        new_engines: dict[tuple, object] = {}
        try:
            # Any trace the rebuild provokes is churn by definition;
            # _rebuild_cause refines new-shape vs. vocab per engine, the
            # scope catches jit misses the classifier can't see.
            with ledger_mod.cause_scope(
                ledger_mod.CAUSE_CHURN_NEW_SHAPE, epoch=epoch
            ):
                for key in sorted(keys, key=repr):
                    _mod, policy_name, ingress, port, proto = key
                    policy = job.staged_map.get(policy_name)
                    with self._device_ctx():
                        eng = self._make_engine(
                            ins, policy, policy_name, ingress, port,
                            proto, prior=prior_engines.get(key),
                        )
                    if (
                        self.config.policy_epoch_parity
                        and not self.config.seam_probe
                    ):
                        if proto == "r2d2":
                            self._assert_epoch_parity(
                                eng, policy, ingress, port
                            )
                        elif proto == "dns":
                            self._assert_epoch_parity_dns(
                                eng, policy, ingress, port
                            )
                    eng.epoch = epoch
                    new_engines[key] = eng
        except EpochParityError:
            log.exception("policy swap rejected (epoch parity)")
            self._swap_failed("parity")
            with blackbox.annotate(reason="parity", epoch=epoch):
                job.phase = EPOCH_SWAP_PROTOCOL.advance(job.phase,
                                                        SWAP_REJECTED)
            job.status = int(FilterResult.POLICY_DROP)
            job.epoch = self.policy_epoch
            job.done.set()
            return
        except Exception:  # noqa: BLE001 — fail closed, old epoch serves
            log.exception("policy swap rejected (device build)")
            self._swap_failed("device-build")
            with blackbox.annotate(reason="device-build", epoch=epoch):
                job.phase = EPOCH_SWAP_PROTOCOL.advance(job.phase,
                                                        SWAP_REJECTED)
            job.status = int(FilterResult.POLICY_DROP)
            job.epoch = self.policy_epoch
            job.done.set()
            return
        # Revoke shim-side cache grants BEFORE the flip: a shim that
        # processed the revoke cannot short-circuit on the superseded
        # epoch once the new one serves (the service-side epoch key is
        # structural regardless; this closes the client half to the
        # revoke's delivery lag).
        self._send_cache_revokes(epoch)
        self._commit_epoch(ins, mods, job.staged_map, new_engines,
                           epoch)
        with blackbox.annotate(reason="committed", epoch=epoch):
            job.phase = EPOCH_SWAP_PROTOCOL.advance(job.phase,
                                                    SWAP_COMMITTED)
        job.status = int(FilterResult.OK)
        job.epoch = epoch
        job.done.set()

    def _commit_epoch(self, ins, mods: set, staged_map,
                      new_engines: dict, epoch: int) -> None:
        """The pointer flip: publish the staged host map and the staged
        engine table, rebind live conns, and migrate engine-retained
        flow bytes — all under _lock, bounded-time (no compile, no
        I/O).  Rounds blocked behind this hold book the overlap as
        their table_swap stage."""
        t0 = time.monotonic()
        with self._lock:
            ins.policy_commit(staged_map)
            # Re-resolve sibling modules AT COMMIT TIME: a module
            # bound to this instance during the (slow) staged build is
            # not in the pre-build ``mods`` snapshot, and leaving its
            # engines in place would keep a superseded table alive for
            # a later rebind to find.
            for k in self._engines:
                if k[0] not in mods and pl.find_instance(k[0]) is ins:
                    mods.add(k[0])
            dropped = [
                v for k, v in self._engines.items() if k[0] in mods
            ]
            self._engines = {
                k: v for k, v in self._engines.items()
                if k[0] not in mods
            }
            self._engines.update(new_engines)
            self._release_engines(dropped)
            for eng in dropped:
                # Id-keyed jit entries die with their model; the
                # shape-keyed entries are the churn executable cache
                # and deliberately survive the swap.
                mid = id(getattr(eng, "model", None))
                for cache in (self._jit_cache, self._jit_gather,
                              self._jit_attr):
                    if cache.pop(mid, None) is not None:
                        self.ledger.executable_evicted((id(cache), mid))
            async_pending = set(self._async_pending)
            # Verdict-cache invalidation is the epoch key itself (a
            # stale hit is structurally impossible once policy_epoch
            # moves below); this sweep just retires the rows so the
            # armed count and the invalidation counter stay truthful,
            # and re-arms rebound conns against the NEW tables.
            invalidated = 0
            grants: list = []
            if self._flow_cache_on and self._tab_size:
                armed = self._tab_cache == 1
                invalidated = int(armed.sum())
                with blackbox.annotate(reason="epoch-flip", epoch=epoch):
                    self._tab_cache[self._tab_cache != 0] = (
                        FLOW_CACHE_PROTOCOL.require_edges(
                            (CACHE_ARMED, CACHE_DECLINED), CACHE_UNARMED
                        )
                    )
                self._tab_cache_epoch[:] = -1
                self._tab_cache_rule[:] = -1
                self._cache_armed = 0
                self.cache_invalidations += invalidated
            rebinds = []
            for cid, sc in self._conns.items():
                if sc.conn.instance is not ins:
                    continue
                old_eng = sc.engine
                engine_proto = sc.conn.parser_name in ENGINE_PROTOS
                if old_eng is None and engine_proto and (
                    sc.bufs[False] or sc.skip[False]
                ):
                    # Demoted conn with undrained oracle-mirror
                    # request residue: binding an engine NOW would
                    # strand those bytes (engine entries never consume
                    # sc.bufs) — keep the oracle serving and let
                    # _maybe_rebind bind after the residue drains
                    # (pointer reads only; the engines now exist).
                    sc.demoted_mod = sc.module_id
                    self._tab_set_engine(cid, None)
                    continue
                if old_eng is not None and (
                    cid in async_pending
                    or (cid < self._tab_size and self._tab_async[cid])
                    or not self._flow_migratable(old_eng, cid)
                ):
                    # In-flight deferred round (or undrained engine
                    # ops): the conn finishes on the epoch it
                    # snapshotted — its state stays on the OLD engine
                    # and the stale-epoch catch-up on the dispatch
                    # path rebinds (and migrates the buffer) once the
                    # round drains.  The freed slot keeps it off the
                    # vec path meanwhile.
                    self._stale_conns.add(cid)
                    self._tab_set_engine(cid, None)
                    continue
                eng = new_engines.get(
                    self._engine_key_for(sc.module_id, sc.conn)
                )
                if eng is not None and old_eng is not None \
                        and eng is not old_eng:
                    self._migrate_flow(old_eng, eng, cid, sc)
                sc.engine = eng
                sc.fast_ok = (
                    eng is not None and sc.conn.parser_name in FAST_PROTOS
                )
                sc.demoted_mod = None
                self._tab_set_engine(cid, eng)
                g = self._arm_flow_cache(cid, sc)
                if g is not None:
                    grants.append(g)
                if (
                    eng is None
                    and engine_proto
                    and cid not in self._rebind_inflight
                ):
                    # Opened mid-build under a key the staged set did
                    # not cover: rebuild off-path (oracle serves until
                    # the bind lands) — never leave an engine-capable
                    # conn stranded on the slow path.
                    self._rebind_inflight.add(cid)
                    rebinds.append((sc.module_id, cid))
            self.policy_epoch = epoch
            t1 = time.monotonic()
            self._swap_window = (t0, t1)
        for job in rebinds:
            self._build_queue.put(("rebind", job))
        if invalidated:
            metrics.VerdictCacheInvalidations.inc(
                "epoch-flip", amount=invalidated
            )
        if grants:
            # Fresh grants under the NEW epoch (after the flip, so a
            # shim can never receive a grant it must immediately treat
            # as stale).
            self._send_cache_grants(grants)
        hold = t1 - t0
        self.policy_swaps += 1
        self.last_swap_ms = round(hold * 1e3, 3)
        metrics.PolicySwapsTotal.inc()
        metrics.PolicySwapSeconds.observe(hold)
        metrics.PolicyEpochGauge.set(float(epoch))
        log.info(
            "policy epoch %d committed for module(s) %s (%d engine(s), "
            "flip %.2fms)", epoch, sorted(mods), len(new_engines),
            hold * 1e3,
        )

    @staticmethod
    def _flow_migratable(old_eng, conn_id: int) -> bool:
        """True when the conn can adopt a new epoch's engine NOW.
        Two flow shapes:

        - r2d2 ``FlowState``: ops is a LIST, reply_inject a bytearray —
          migratable once both are drained (the byte buffer itself
          moves in _migrate_flow);
        - l7 ``_EngineFlow``: ops/bufs/skip are per-direction dicts,
          and the parser state behind a buffered partial frame is not
          portable across policy objects — the conn stays on its
          captured epoch until the frame drains at a boundary (a frame
          judged half-old-half-new would be worse than a briefly-stale
          conn; the stale-conn catch-up retries per entry)."""
        fl = old_eng.flows.get(conn_id) if hasattr(old_eng, "flows") \
            else None
        if fl is None:
            return True
        ops = getattr(fl, "ops", None)
        if isinstance(ops, dict):  # l7 _EngineFlow
            if any(ops.values()):
                return False
            return not (
                fl.bufs[False] or fl.bufs[True]
                or fl.skip[False] or fl.skip[True]
            )
        return not (ops or getattr(fl, "reply_inject", None))

    @staticmethod
    def _migrate_flow(old_eng, new_eng, conn_id: int, sc) -> None:
        """Carry a conn's engine-retained request bytes across the
        epoch swap so no byte is lost or replayed.  Callers gate on
        _flow_migratable / async-pending first — a conn whose state is
        still owed to an in-flight round (or holds an unportable l7
        partial frame) is deferred to the stale-conn catch-up
        instead."""
        fl = old_eng.flows.get(conn_id) if hasattr(old_eng, "flows") \
            else None
        if fl is None:
            return
        buf = getattr(fl, "buffer", None)
        if buf is None:
            # l7 _EngineFlow: gated EMPTY by _flow_migratable — nothing
            # to move; the inert flow dies with the released engine and
            # the new engine builds a fresh one on first feed.
            return
        if buf:
            conn = sc.conn
            nf = new_eng.flow(
                conn_id, remote_id=fl.remote_id,
                policy_name=conn.policy_name, ingress=conn.ingress,
                dst_id=conn.dst_id, src_addr=conn.src_addr,
                dst_addr=conn.dst_addr,
            )
            nf.buffer += bytes(buf)
            buf.clear()
        old_eng.flows.pop(conn_id, None)

    def _run_rebind(self, module_id: int, conn_id: int) -> None:
        """Builder-thread engine (re)bind for a conn whose key had no
        live engine (quarantine heal): the compile happens HERE, never
        on the dispatch path.  The conn keeps serving on the oracle
        until the bind lands.  A device that re-quarantined before this
        job ran is handled by _bind_engine itself — it re-demotes
        (sets demoted_mod) so the heal path retries, never a silent
        drop."""
        with self._lock:
            sc = self._conns.get(conn_id)
        grant = None
        try:
            if sc is not None and sc.engine is None:
                with ledger_mod.cause_scope(ledger_mod.CAUSE_HEAL_REBIND,
                                            epoch=self.policy_epoch):
                    self._bind_engine(module_id, sc)
                with self._lock:
                    if self._conns.get(conn_id) is sc:
                        self._tab_set_engine(
                            conn_id, sc.engine if sc.fast_ok else None
                        )
                        grant = self._arm_flow_cache(conn_id, sc)
        finally:
            with self._lock:
                self._rebind_inflight.discard(conn_id)
        if grant is not None:
            self._send_cache_grants([grant])

    # Deterministic per-epoch parity probe: every valid command crossed
    # with distinctive files; remotes are drawn from the candidate
    # model's own remote table plus never-allowed sentinels.
    _PARITY_PROBES = (
        ("READ", "/public/app"), ("READ", "/etc/shadow"), ("READ", ""),
        ("WRITE", "/public/app"), ("WRITE", "/data/x"),
        ("HALT", ""), ("RESET", ""),
    )

    def _assert_epoch_parity(self, engine, policy, ingress: bool,
                             port: int) -> None:
        """Re-assert device-model vs host-oracle bit-identity for a
        staged engine before its epoch can commit: one prewarmed-shape
        device batch over the probe grid, compared against the staged
        policy's host walk.  A mismatch raises EpochParityError and
        fails the swap typed — a miscompiled table can never serve."""
        model = engine.model
        if isinstance(model, ConstVerdict):
            return
        from ..proxylib.parsers.r2d2 import R2d2RequestData

        rem_tab = np.asarray(model.remote_ids).ravel()
        remotes = sorted(set(int(r) for r in rem_tab if r > 0))[:4]
        remotes += [1, 999983]  # a common id + a never-allocated one
        cases = [
            (cmd, f, rem)
            for cmd, f in self._PARITY_PROBES
            for rem in remotes
        ]
        b = self._min_bucket
        while b < len(cases):
            b *= 2
        width = self.config.batch_width
        data = np.zeros((b, width), np.uint8)
        lens = np.zeros(b, np.int32)
        rems = np.zeros(b, np.int32)
        for i, (cmd, f, rem) in enumerate(cases):
            frame = (f"{cmd} {f}\r\n" if f else f"{cmd}\r\n").encode()
            row = np.frombuffer(frame, np.uint8)
            data[i, : len(row)] = row
            lens[i] = len(row)
            rems[i] = rem
        out = self._model_call(model, data, lens, rems)
        allow = np.asarray(out[-1])[: len(cases)]
        for i, (cmd, f, rem) in enumerate(cases):
            host = policy is not None and policy.matches(
                ingress, port, rem, R2d2RequestData(cmd, f)
            )
            if bool(allow[i]) != bool(host):
                raise EpochParityError(
                    f"epoch parity violation: probe "
                    f"(cmd={cmd!r} file={f!r} remote={rem}) device="
                    f"{bool(allow[i])} host={host}"
                )

    # DNS probe names: an exact candidate, a subdomain (wildcard tier),
    # an unrelated name, the root, and a structurally invalid query —
    # enough to exercise the needle, automaton, byte-free and validity
    # tiers of a staged DNS table.
    _DNS_PARITY_NAMES = (
        "www.example.com", "api.internal.example.com", "evil.test",
        "example.com", "",
    )

    def _assert_epoch_parity_dns(self, engine, policy, ingress: bool,
                                 port: int) -> None:
        """DNS twin of _assert_epoch_parity: staged device table vs
        the staged policy's host walk over a probe grid of query
        frames (the invalid-QNAME probe included — the validity gate
        is part of the contract)."""
        model = engine.model
        if isinstance(model, ConstVerdict):
            return
        from ..proxylib.parsers.dns import (
            DNS_QNAME_OFF,
            DnsRequestData,
            encode_dns_query,
            parse_dns_query,
        )

        rem_tab = np.asarray(model.remote_ids).ravel()
        remotes = sorted(set(int(r) for r in rem_tab if r > 0))[:4]
        remotes += [1, 999983]  # a common id + a never-allocated one
        frames = [
            encode_dns_query(n) for n in self._DNS_PARITY_NAMES
        ]
        # Invalid probe: a compression pointer where a label length
        # belongs (denied by every name-constrained row on both rungs).
        bad = bytearray(encode_dns_query("bad.example.com"))
        bad[DNS_QNAME_OFF] = 0xC0
        frames.append(bytes(bad))
        cases = [(f, rem) for f in frames for rem in remotes]
        b = self._min_bucket
        while b < len(cases):
            b *= 2
        width = self.config.batch_width
        while width < max(len(f) for f in frames):
            width *= 2
        data = np.zeros((b, width), np.uint8)
        lens = np.zeros(b, np.int32)
        rems = np.zeros(b, np.int32)
        for i, (frame, rem) in enumerate(cases):
            row = np.frombuffer(frame, np.uint8)
            data[i, : len(row)] = row
            lens[i] = len(row)
            rems[i] = rem
        out = self._model_call(model, data, lens, rems)
        allow = np.asarray(out[-1])[: len(cases)]
        for i, (frame, rem) in enumerate(cases):
            name = parse_dns_query(frame)
            req = DnsRequestData(
                name=name if name is not None else "",
                valid=name is not None,
            )
            host = policy is not None and policy.matches(
                ingress, port, rem, req
            )
            if bool(allow[i]) != bool(host):
                raise EpochParityError(
                    f"epoch parity violation: dns probe "
                    f"(name={req.name!r} valid={req.valid} "
                    f"remote={rem}) device={bool(allow[i])} host={host}"
                )

    def new_connection(self, module_id, conn_id, ingress, src_id, dst_id,
                       proto, src_addr, dst_addr, policy_name, flags=0,
                       client=None):
        """Returns ``(result, grant_or_None, result_flags)``.  The
        registration grant is NOT sent here: the caller delivers it
        AFTER the MSG_CONN_RESULT reply, so the shim's post-RPC
        stale-grant drop (conn-id reuse) is socket-ordered before the
        fresh grant and can never erase it.  ``flags`` carries the
        shim's CONN_FLAG_RETAINED claim (session replay: its retained
        buffers survived the restart untouched); ``result_flags``
        answers with CONN_RESULT_FLAG_RESIDUE_ADOPTED when the
        predecessor's mid-frame residue was installed for this conn."""
        if self._fenced:
            self.fence_rejects += 1
            metrics.SidecarFenceRejects.inc("new_connection")
            return int(FilterResult.FENCED), None, 0
        res, conn = pl.on_new_connection(
            module_id, proto, conn_id, ingress, src_id, dst_id,
            src_addr, dst_addr, policy_name,
        )
        if res != FilterResult.OK:
            return int(res), None, 0
        sc = _SidecarConn(conn, client, None, module_id=module_id)
        self._bind_engine(module_id, sc)
        rebind = False
        adopted = False
        with self._lock:
            # Re-resolve against the CURRENT epoch's table: an epoch
            # swap may have committed between the bind above and this
            # registration, and the conn must never enter the registry
            # holding a superseded engine (it would serve the old
            # policy until the next swap touched it).
            if sc.engine is not None:
                cur = self._engines.get(
                    self._engine_key_for(module_id, conn)
                )
                if cur is not None and cur is not sc.engine:
                    sc.engine = cur
                elif cur is None:
                    # The key vanished under a racing swap (our freshly
                    # built engine was dropped with the old epoch):
                    # serve on the oracle and rebuild off-path.
                    sc.engine = None
                    sc.fast_ok = False
                    if conn_id not in self._rebind_inflight:
                        self._rebind_inflight.add(conn_id)
                        rebind = True
            self._conns[conn_id] = sc
            # Handoff restore: if the predecessor knew this conn under
            # the SAME identity tuple, adopt its mid-frame flow-buffer
            # residue so a frame split across the restart reassembles
            # instead of misparsing.  Adoption is DOUBLY gated: the
            # identity tuple must match (conn-id reuse across the
            # restart drops the residue — fresh state is correct,
            # stale bytes are not) AND the shim must claim RETAINED
            # (its retained-buffer mirror survived the blackout with
            # no typed-failed round).  Without the claim the shim has
            # dropped its copy fail-closed, and installing the
            # predecessor's bytes here would put the parser AHEAD of
            # the shim's buffer — every subsequent op would land
            # shifted, silently passing or dropping the wrong bytes.
            # Grants are NOT restored here: _arm_flow_cache re-derives
            # them under the restored epoch (revalidate-or-revoke), we
            # only count the matches.
            prev = self._handoff_conns.pop(conn_id, None)
            if prev is not None:
                if (
                    prev.get("policy") == policy_name
                    and prev.get("ingress") == bool(ingress)
                    and prev.get("src_id") == int(src_id)
                    and prev.get("proto") == proto
                ):
                    self.handoff_conn_restores += 1
                    res_row = self._handoff_residue.pop(conn_id, None)
                    if res_row is not None and (
                        flags & wire.CONN_FLAG_RETAINED
                    ):
                        try:
                            sc.bufs[False] = bytearray(
                                base64.b64decode(res_row["orig"])
                            )
                            sc.bufs[True] = bytearray(
                                base64.b64decode(res_row["reply"])
                            )
                            sc.skip[False] = int(res_row["skip_orig"])
                            sc.skip[True] = int(res_row["skip_reply"])
                            adopted = bool(
                                sc.bufs[False] or sc.bufs[True]
                                or sc.skip[False] or sc.skip[True]
                            )
                        except (KeyError, TypeError, ValueError,
                                binascii.Error):
                            sc.bufs = {False: bytearray(),
                                       True: bytearray()}
                            sc.skip = {False: 0, True: 0}
                    if adopted:
                        # Residue must be CONSUMED, and engine entries
                        # never drain sc.bufs: enter through the
                        # demoted-to-oracle state (exactly the
                        # quarantine-demotion shape) so the oracle
                        # serves the reassembled frame and
                        # _maybe_rebind restores the device path once
                        # the carry drains.  The racing-swap rebind
                        # queued above would bind an engine over the
                        # residue — cancel it; the heal path re-queues
                        # after the drain.
                        self.handoff_residue_restores += 1
                        sc.engine = None
                        sc.fast_ok = False
                        sc.demoted_mod = module_id
                        if rebind:
                            rebind = False
                            self._rebind_inflight.discard(conn_id)
                else:
                    self._handoff_residue.pop(conn_id, None)
                    self._handoff_grants.pop(conn_id, None)
            if self._tab_ensure(conn_id):
                self._tab_src[conn_id] = conn.src_id
                self._tab_dirty[conn_id] = 0
            self._tab_set_engine(conn_id, sc.engine if sc.fast_ok else None)
            # Verdict cache: the byte-invariance claim is per-epoch
            # static, so a flow arms AT REGISTRATION — pure-L3/L4 and
            # allow-all tables never pay a single device round.
            grant = self._arm_flow_cache(conn_id, sc)
            hg = self._handoff_grants.pop(conn_id, None)
            if hg is not None and grant is not None and hg[1] == grant[3]:
                # Predecessor's grant survived revalidation: the fresh
                # arm landed on the SAME rule row.  The epoch is NOT
                # compared — the replay re-commits policy before conns
                # register, so the re-derived grant is expected to
                # carry the successor's newer epoch.
                self.handoff_grant_restores += 1
        if rebind:
            self._build_queue.put(("rebind", (module_id, conn_id)))
        if self.flowlog is not None:
            # Connection metadata registered ONCE here (and dropped at
            # close) so per-round record emission stores bare arrays —
            # the query side joins against this registry.  The session
            # id rides along so `cilium observe --session` can
            # attribute records to one shim.
            sess = getattr(client, "session", None)
            self.flowlog.register_conn(
                conn_id, policy_name, ingress, src_id, dst_id,
                src_addr, dst_addr, proto, conn.port,
                session=sess.id if sess is not None else 0,
            )
        return int(res), grant, (
            wire.CONN_RESULT_FLAG_RESIDUE_ADOPTED if adopted else 0
        )

    _TAB_MAX = 1 << 22  # conns with larger ids use the entrywise path

    def _tab_ensure(self, conn_id: int) -> bool:
        """Grow the conn table to cover conn_id; False if out of range."""
        if conn_id >= self._TAB_MAX:
            return False
        if conn_id >= self._tab_size:
            new_size = max(4096, self._tab_size)
            while new_size <= conn_id:
                new_size *= 2
            for name, fill, dt in (
                ("_tab_engine", -1, np.int32),
                ("_tab_src", 0, np.int32),
                ("_tab_dirty", 0, np.uint8),
                ("_tab_async", 0, np.uint32),
                ("_tab_cache", 0, np.uint8),
                ("_tab_cache_epoch", -1, np.int64),
                ("_tab_cache_rule", -1, np.int32),
                ("_tab_seen_tick", 0, np.int64),
            ):
                arr = np.full(new_size, fill, dt)
                arr[: self._tab_size] = getattr(self, name)
                setattr(self, name, arr)
            self._tab_size = new_size
        return True

    def _tab_set_engine(self, conn_id: int, engine) -> None:
        if not self._tab_ensure(conn_id):
            return
        if engine is None:
            self._tab_engine[conn_id] = -1
            return
        idx = self._engine_idx.get(id(engine))
        if idx is None:
            if self._engine_free:
                idx = self._engine_free.pop()
                self._engine_objs[idx] = engine
            else:
                idx = len(self._engine_objs)
                self._engine_objs.append(engine)
            self._engine_idx[id(engine)] = idx
            self._objs_cache = None
        self._tab_engine[conn_id] = idx

    def _release_engines(self, engines: list) -> None:
        """Return dropped engines' table slots to the free list so
        superseded models (and their device buffers) can be collected."""
        for eng in engines:
            idx = self._engine_idx.pop(id(eng), None)
            if idx is not None:
                self._engine_objs[idx] = None
                self._engine_free.append(idx)
                self._objs_cache = None

    def _conn_residual_dirty(self, conn_id: int, sc: "_SidecarConn") -> bool:
        """The single definition of 'this conn holds residual state':
        engine flow buffer(s), oracle buffers, skip counts, or a
        columnar-arena carry (the reassembler's per-conn residue lives
        OUTSIDE the engine flow — see sidecar/reasm.py)."""
        if self._reasm is not None and self._reasm.arena.has_residue(
            conn_id
        ):
            return True
        flow = sc.engine.flows.get(conn_id) if sc.engine is not None else None
        buffered = False
        if flow is not None:
            if hasattr(flow, "buffer"):  # simple batch engines
                buffered = bool(flow.buffer)
            else:  # device-assisted engines: per-direction buffers
                buffered = bool(flow.bufs[False] or flow.bufs[True])
            # A flow that tripped the retained-bytes cap is dead: keep
            # it off the vec path so every further entry re-surfaces
            # the typed error through the engine feed.
            buffered = buffered or getattr(flow, "overflowed", False)
        return bool(
            buffered
            or sc.bufs[False]
            or sc.bufs[True]
            or sc.skip[False]
            or sc.skip[True]
        )

    def _tab_mark_many(self, pairs: list) -> None:
        """Batch dirty-flag refresh: one lock acquisition for a whole
        round's worth of conns instead of one per entry (the per-entry
        variant measured ~1.6k lock trips per mixed round)."""
        updates = [
            (conn_id, 1 if self._conn_residual_dirty(conn_id, sc) else 0)
            for conn_id, sc in pairs
        ]
        with self._lock:
            size = self._tab_size
            for conn_id, dirty in updates:
                if conn_id < size:
                    self._tab_dirty[conn_id] = dirty

    def _tab_mark(self, conn_id: int, sc: "_SidecarConn") -> None:
        """Refresh the dirty flag from actual residual state."""
        dirty = self._conn_residual_dirty(conn_id, sc)
        # Write under the lock: _tab_ensure (new_connection, another
        # thread) reallocates the table arrays, and a lock-free store
        # could land in the discarded old array, leaving a stale-clean
        # dirty bit that re-admits a stateful conn to the vec path.
        with self._lock:
            if conn_id < self._tab_size:
                self._tab_dirty[conn_id] = 1 if dirty else 0

    # -- established-flow verdict cache (policy/invariance.py) -------------

    def _arm_flow_cache(self, conn_id: int, sc: "_SidecarConn"):
        """Compute/refresh this conn's byte-invariance claim from its
        bound engine (caller holds ``_lock``; the conn table row is
        ensured).  Arms engines whose framing is registered in
        reasm.FRAMINGS — the cache tiers' frame-alignment gate is that
        framing's whole-frame check (CRLF tail for r2d2, the
        length-prefix walk for DNS) — and only on ALLOW claims (denied
        frames carry per-frame inject side effects the short-circuit
        would skip).  At the ``flow_cache_entries`` cap the least-
        recently-HIT armed row is evicted to make room
        (verdict_cache_evictions_total) — eviction is capacity
        management, not invalidation: the victim's claim stays true
        for its epoch, so an already-delivered shim grant needs no
        revoke.  Returns the ``(client, conn_id, epoch, rule,
        framing_kind)`` grant to send OUTSIDE the lock, or None.
        Shim-local grants carry the conn's framing kind (ROADMAP 3c):
        the shim keys its pre-push alignment check off the grant row —
        CRLF tail for r2d2, the length-prefix walk for DNS — so every
        framing registered in reasm.FRAMINGS gets the local tier."""
        if not self._flow_cache_on or conn_id >= self._tab_size:
            return None
        engine = sc.engine
        framing = _engine_framing(engine)
        claim = None
        epoch = self.policy_epoch
        if framing is not None and hasattr(engine, "verdict_invariant"):
            claim = engine.verdict_invariant(sc.conn.src_id)
            epoch = getattr(engine, "epoch", 0)
        was_armed = self._tab_cache[conn_id] == 1
        if claim is not None and claim[0]:
            if (
                not was_armed
                and self._cache_armed >= self.config.flow_cache_entries
            ):
                self._evict_flow_cache_lru()
            if was_armed or (
                self._cache_armed < self.config.flow_cache_entries
            ):
                rule = int(claim[1])
                if not was_armed:
                    self._cache_armed += 1
                with blackbox.annotate(reason="arm", conn=conn_id,
                                       epoch=epoch):
                    self._tab_cache[conn_id] = (
                        FLOW_CACHE_PROTOCOL.advance(
                            self._tab_cache[conn_id], CACHE_ARMED
                        )
                    )
                self._tab_cache_epoch[conn_id] = epoch
                self._tab_cache_rule[conn_id] = rule
                self._tab_seen_tick[conn_id] = self._next_cache_tick()
                client = sc.client
                if client is not None and getattr(
                    client, "cache_ok", False
                ):
                    return client, conn_id, epoch, rule, framing.kind
                return None
        if was_armed:
            self._cache_armed -= 1
            self.cache_invalidations += 1
            # Mirror the status counter: an armed row losing its claim
            # on re-arm is an invalidation in both surfaces.
            metrics.VerdictCacheInvalidations.inc("re-arm")
        with blackbox.annotate(reason="no-claim", conn=conn_id,
                               epoch=epoch):
            self._tab_cache[conn_id] = FLOW_CACHE_PROTOCOL.advance(
                self._tab_cache[conn_id], CACHE_DECLINED
            )
        self._tab_cache_epoch[conn_id] = epoch
        self._tab_cache_rule[conn_id] = -1
        return None

    def _next_cache_tick(self) -> int:
        """Monotonic recency stamp for the armed-row LRU (round-grain:
        one tick per touch event, bulk touches share a tick)."""
        self._cache_tick += 1
        return self._cache_tick

    def _touch_cache_rows(self, conn_ids) -> None:
        """Refresh the last-HIT stamp of armed rows after a cache-hit
        group (one vectorized store per round, never per entry)."""
        ids = np.asarray(conn_ids, np.int64)
        ids = ids[(ids >= 0) & (ids < self._tab_size)]
        if len(ids):
            # lint: disable=R19 -- deliberately lock-free on the dispatch hot path: _tab_seen_tick is an advisory LRU recency stamp; a race with table growth costs at worst one stale stamp (a marginally suboptimal eviction), never correctness, and taking _lock here would serialize every cache-hit round
            self._tab_seen_tick[ids] = self._next_cache_tick()

    def _evict_flow_cache_lru(self) -> None:
        """Drop the least-recently-hit armed row to make room at the
        ``flow_cache_entries`` cap (caller holds ``_lock``).  Counted
        separately from invalidations: the victim's claim is still
        TRUE for its epoch — this is capacity management, so the
        (advisory) shim grant, if any, keeps its local short-circuit
        and stays correct."""
        armed = np.flatnonzero(self._tab_cache[: self._tab_size] == 1)
        if not len(armed):
            return
        victim = int(armed[np.argmin(self._tab_seen_tick[armed])])
        # Back to unarmed: re-armable later.
        with blackbox.annotate(reason="lru-evict", conn=victim):
            self._tab_cache[victim] = FLOW_CACHE_PROTOCOL.advance(
                self._tab_cache[victim], CACHE_UNARMED
            )
        self._tab_cache_epoch[victim] = -1
        self._tab_cache_rule[victim] = -1
        self._cache_armed -= 1
        self.cache_evictions += 1
        metrics.VerdictCacheEvictions.inc()

    def _disarm_flow_cache(self, conn_id: int, reason: str | None) -> None:
        """Drop one conn's cache row (caller holds ``_lock``): lane
        transitions (quarantine demotion) and close.  The claim itself
        stays table-valid — the rebind path re-arms from the fallback
        engine once the conn's residue drains."""
        if conn_id >= self._tab_size:
            return
        if self._tab_cache[conn_id] == 1:
            self._cache_armed -= 1
            self.cache_invalidations += 1
            if reason is not None:
                metrics.VerdictCacheInvalidations.inc(reason)
        with blackbox.annotate(reason=reason or "close", conn=conn_id):
            self._tab_cache[conn_id] = FLOW_CACHE_PROTOCOL.advance(
                self._tab_cache[conn_id], CACHE_UNARMED
            )
        self._tab_cache_epoch[conn_id] = -1
        self._tab_cache_rule[conn_id] = -1

    def _send_cache_grants(self, grants: list) -> None:
        """Deliver collected (client, conn_id, epoch, rule) grants.
        Each is revalidated against the LIVE conn row under ``_lock``
        right before packing — a conn that closed or was re-registered
        since collection must never receive the stale grant (a reused
        conn id would inherit the old identity's allow at the shim) —
        then sent outside the lock (a grant is advisory: a lost frame
        only costs the shim its local short-circuit, never
        correctness).  Callers hold no ``_lock``."""
        live: list = []
        with self._lock:
            for client, conn_id, epoch, rule, fkind in grants:
                sc = self._conns.get(conn_id)
                if (
                    sc is not None
                    and sc.client is client
                    and conn_id < self._tab_size
                    and self._tab_cache[conn_id] == 1
                    and self._tab_cache_epoch[conn_id] == epoch
                    and self._tab_cache_rule[conn_id] == rule
                ):
                    live.append(
                        (client,
                         wire.pack_cache_grant(
                             conn_id, epoch, rule,
                             flags=wire.CACHE_FLAG_ALLOW,
                             framing=fkind,
                         ))
                    )
        for client, payload in live:
            try:
                client.send(wire.MSG_CACHE_GRANT, payload)
            except Exception:  # noqa: BLE001 — client may be gone
                log.exception("cache grant send failed")

    def _send_cache_revokes(self, epoch: int) -> None:
        """Pre-flip revocation: tell every opted-in shim the NEW epoch
        so grants under older epochs die at the client BEFORE the
        pointer flip commits.  Sent from the builder thread (bounded by
        the handlers' SO_SNDTIMEO); the service-side epoch key stays
        the structural guarantee regardless."""
        if not self._flow_cache_on:
            return
        with self._lock:
            clients = [
                c for c in self._clients if getattr(c, "cache_ok", False)
            ]
        payload = wire.pack_cache_revoke(epoch)
        for client in clients:
            try:
                client.send(wire.MSG_CACHE_REVOKE, payload)
            except Exception:  # noqa: BLE001 — client may be gone
                log.exception("cache revoke send failed")

    def _record_cached_entries(self, hits: list) -> None:
        """Cached-path flow records for scalar-tier hits: per-entry
        (rule, kind, epoch) resolved against the engine CAPTURED at hit
        time (slot-reuse-safe), one columnar add_round for the round."""
        if self.flowlog is None or not hits:
            return
        n = len(hits)
        conn_ids = np.fromiter(
            (h[2] for h in hits), np.int64, count=n
        )
        rules = np.fromiter((h[3] for h in hits), np.int32, count=n)
        kinds = [
            self._kind_for(getattr(h[4], "model", None), h[3])
            for h in hits
        ]
        epochs = np.fromiter(
            (getattr(h[4], "epoch", 0) for h in hits), np.int64,
            count=n,
        )
        self.flowlog.add_round(
            PATH_CACHED,
            conn_ids,
            np.full(n, CODE_FORWARDED, np.int8),
            rules,
            cols={"match_kind": kinds, "epoch": epochs},
        )

    def _record_cached_round(self, conn_ids, rules, kinds, epoch) -> None:
        """Flow records for one cached group: path ``cached``, the
        ORIGINAL attributed rule rows, the claim epoch — one columnar
        add_round, never per entry."""
        if self.flowlog is None or not len(conn_ids):
            return
        self.flowlog.add_round(
            PATH_CACHED,
            np.asarray(conn_ids, np.int64),
            np.full(len(conn_ids), CODE_FORWARDED, np.int8),
            np.asarray(rules, np.int32),
            kinds=kinds,
            epoch=epoch,
        )

    def _bind_engine(self, module_id: int, sc: _SidecarConn) -> None:
        """Attach the device batch engine for this connection's
        (policy, direction, port, proto), building the model on first
        use.  Epoch-safe: the build reads the policy map of ONE epoch;
        if a swap commits while the build runs, the stale engine is
        discarded and the bind retries against the new epoch (never
        inserted — a swap must not be undone by a racing first-bind)."""
        conn = sc.conn
        proto = conn.parser_name
        if proto not in ENGINE_PROTOS:
            return  # other protocols: oracle path
        if self.guard.quarantined:
            # Never build/prewarm against a quarantined device (the
            # compile would hang this reader thread).  The conn starts
            # on the oracle path and is bound once the device heals.
            sc.demoted_mod = module_id
            return
        key = (module_id, conn.policy_name, conn.ingress, conn.port, proto)
        for _attempt in range(4):
            with self._lock:
                eng = self._engines.get(key)
                epoch0 = self.policy_epoch
            if eng is not None:
                break
            # Build and prewarm OUTSIDE the registry lock: XLA compiles
            # are slow and must not stall unrelated control/data traffic.
            # Built under the configured verdict device so the model's
            # tables are colocated with its dispatch.  This is the
            # first-bind cold path (once per key); churn rebuilds ride
            # the async builder instead.
            ins = pl.find_instance(module_id)
            policy = ins.policy_map().get(conn.policy_name)
            with self._device_ctx():
                # lint: disable=R12 -- first-bind cold path off the dispatch loop (reader/builder thread, once per engine key); churn recompiles ride the policy builder
                # lint: disable=R23 -- the cold first-bind IS ledgered: no cause_scope here is the contract — record_compile inside _make_engine defaults the cause to "cold", and _run_rebind wraps this call in the heal-rebind scope (an inner scope here would mask it)
                built = self._make_engine(
                    ins, policy, conn.policy_name, conn.ingress,
                    conn.port, proto,
                )
            built.epoch = epoch0
            with self._lock:
                if self.policy_epoch != epoch0:
                    continue  # epoch moved under the build: retry
                # Double-checked insert: a racing binder may have won.
                eng = self._engines.setdefault(key, built)
            break
        if eng is None:
            return  # persistent epoch churn: serve on the oracle path
        sc.engine = eng
        # Whole-frame engines (r2d2, dns) are vectorized-path capable.
        sc.fast_ok = proto in FAST_PROTOS

    def _make_engine(self, ins, policy, policy_name: str, ingress: bool,
                     port: int, proto: str, prior=None):
        """Compile one engine from an EXPLICIT policy object — shared
        by the first-bind path (live map) and the epoch builder
        (staged map), so the two can never drift.

        ``prior`` is the engine this build replaces (epoch swaps pass
        the outgoing generation); the ledger uses its model's shape key
        to classify the rebuild as vocab churn vs. new-shape churn."""
        t0 = time.perf_counter()
        if proto == "r2d2":
            from ..models.r2d2 import build_r2d2_model

            if self.config.seam_probe:
                from ..models.base import SeamProbe

                model = SeamProbe()
            else:
                mesh = self._serving_mesh()
                if mesh is not None:
                    # Multi-chip build: rule rows split-balanced and
                    # padded across RULE_AXIS, single-chip fallback
                    # compiled alongside (the device-loss rung).
                    from ..parallel.rulesharding import mesh_r2d2_model

                    model = mesh_r2d2_model(policy, ingress, port, mesh)
                else:
                    model = build_r2d2_model(policy, ingress, port)
            eng = R2d2BatchEngine(
                model,
                capacity=self.config.batch_flows,
                width=self.config.batch_width,
                logger=ins.access_logger,
                max_buffer=self.config.max_flow_buffer,
                attr_enabled=self._flow_observe,
            )
            self._finish_engine_build(eng, proto, prior, t0)
            return eng
        if proto == "dns":
            # The DNS engine rung: same scalar contract as r2d2 (the
            # flagship FlowState machinery, subclassed with the
            # length-prefix framing hooks), mesh-aware build with the
            # single-chip fallback compiled alongside.
            from ..models.dns import build_dns_model
            from ..runtime.dnsengine import DnsBatchEngine

            mesh = self._serving_mesh()
            if mesh is not None:
                from ..parallel.rulesharding import mesh_dns_model

                model = mesh_dns_model(policy, ingress, port, mesh)
            else:
                model = build_dns_model(policy, ingress, port)
            eng = DnsBatchEngine(
                model,
                capacity=self.config.batch_flows,
                width=self.config.batch_width,
                logger=ins.access_logger,
                max_buffer=self.config.max_flow_buffer,
                attr_enabled=self._flow_observe,
            )
            self._finish_engine_build(eng, proto, prior, t0)
            return eng
        from ..runtime.l7engine import (
            CassandraBatchEngine,
            HttpSidecarEngine,
            MemcacheBatchEngine,
        )

        if proto == "cassandra":
            from ..models.cassandra import build_cassandra_model

            model = build_cassandra_model(policy, ingress, port)
            cls = CassandraBatchEngine
        elif proto == "http":
            from ..models.http import build_http_model_for_port

            mesh = self._serving_mesh()
            if mesh is not None:
                from ..parallel.rulesharding import mesh_http_model

                model = mesh_http_model(policy, ingress, port, mesh)
            else:
                model = build_http_model_for_port(policy, ingress, port)
            cls = HttpSidecarEngine
        else:
            from ..models.memcached import build_memcache_model

            model = build_memcache_model(policy, ingress, port)
            cls = MemcacheBatchEngine
        eng = cls(
            policy, ingress, port, model,
            logger=ins.access_logger,
            capacity=self.config.batch_flows,
            max_buffer=self.config.max_flow_buffer,
            attr_enabled=self._flow_observe,
        )
        # Verdict-cache judge tier (flow_cache): byte-invariant
        # identities are answered host-side from the claim instead of
        # riding the device batch (cassandra/memcached make no claim,
        # so the flag is inert there).
        eng.cache_enabled = self._flow_cache_on
        # Containment hooks: the judge step is skipped while the device
        # is quarantined (host policy.matches fallback, bit-identical),
        # and judge crashes count toward the poisoned-engine threshold.
        eng.device_gate = lambda: not self.guard.quarantined
        eng.device_fail_hook = lambda exc: self._record_contained_failure(
            f"judge-crash: {type(exc).__name__}"
        )
        # Judge dispatch through the service (shared jit caches + the
        # mesh demotion rung): device loss on a sharded l7 model
        # demotes to the single-chip fallback instead of host-judging
        # every subsequent round through the crash containment.
        eng.judge_dispatch = functools.partial(
            self._engine_judge_dispatch, eng
        )
        # l7 engines have no prewarm rung (the judge executable traces
        # lazily through the shared jit caches, where the ledger's shim
        # times it); the recorded unit here is the host-side automaton
        # build itself.
        try:
            self.ledger.record_compile(
                proto, time.perf_counter() - t0,
                cause=self._rebuild_cause(model, prior),
                shape=self._model_shape_key(model),
                rules=self._rule_bucket_of(model),
                kind="engine-build", epoch=self.policy_epoch,
            )
        except Exception:  # noqa: BLE001 — ledger must not cost the build
            pass
        return eng

    def _finish_engine_build(self, eng, proto: str, prior, t0: float) -> None:
        """Prewarm a freshly built engine and ledger the build — but
        ONLY when the prewarm actually launched a trace.  A same-bucket
        epoch swap lands on warm executables end to end and must record
        ZERO compile events; that silence is the asserted invariant the
        churn soak pins (warm churn performs no compiles)."""
        warmed = self.prewarm(eng)
        if not warmed:
            return
        model = getattr(eng, "model", None)
        try:
            self.ledger.record_compile(
                proto, time.perf_counter() - t0,
                # Explicit cause when we can classify the rebuild from
                # the shape delta; None falls through to the enclosing
                # cause_scope (mesh-reshape / repromotion / heal-rebind)
                # and finally to "cold" on the first bind.
                cause=self._rebuild_cause(model, prior),
                shape=self._model_shape_key(model),
                rules=self._rule_bucket_of(model),
                kind="engine-build", epoch=self.policy_epoch,
            )
        except Exception:  # noqa: BLE001 — ledger must not cost the build
            pass

    def _rebuild_cause(self, model, prior):
        """Classify an epoch rebuild from the shape delta against the
        engine it replaces: rule bucket held but automaton axes moved →
        vocab churn (new DFA/NFA state counts at the same bucket); any
        bucket/structure change → new-shape churn.  None (→ enclosing
        scope / cold) when there is no prior generation."""
        if prior is None:
            return None
        prior_model = getattr(prior, "model", None)
        if prior_model is None or model is None:
            return ledger_mod.CAUSE_CHURN_NEW_SHAPE
        old_b = self._rule_bucket_of(prior_model)
        new_b = self._rule_bucket_of(model)
        if old_b is not None and old_b == new_b:
            return ledger_mod.CAUSE_CHURN_VOCAB
        return ledger_mod.CAUSE_CHURN_NEW_SHAPE

    @staticmethod
    def _rule_bucket_of(model):
        """Best-effort padded rule-row bucket: the leading dim of the
        per-rule match table (cmd_len for r2d2, name_len for dns);
        None for models without one (SeamProbe, l7 judge models)."""
        for attr in ("cmd_len", "name_len"):
            v = getattr(model, attr, None)
            shp = getattr(v, "shape", None)
            if shp:
                return int(shp[0])
        return None

    def _engine_judge_dispatch(self, eng, data, lengths, remotes):
        """(complete, len, allow, rule-or-None) for an l7 engine's
        judge step — reads eng.model at CALL time so a mesh demotion's
        pointer flip (or an epoch swap) takes effect mid-stream."""
        return self._model_call_attr(eng.model, data, lengths, remotes)

    def close_connection(self, conn_id: int, expect=None) -> None:
        # Routed through the dispatcher by the caller so in-flight data
        # for this conn is processed first.  ``expect`` pins the
        # connection object captured at submit time: if the id was
        # reused for a NEW connection before the deferred close ran, the
        # fresh connection must survive.
        with self._lock:
            sc = self._conns.get(conn_id)
            if sc is None or (expect is not None and sc is not expect):
                return
            del self._conns[conn_id]
            self._stale_conns.discard(conn_id)
            self._rebind_inflight.discard(conn_id)
            if conn_id < self._tab_size:
                self._tab_engine[conn_id] = -1
                self._tab_dirty[conn_id] = 0
            self._disarm_flow_cache(conn_id, "close")
        if sc.engine is not None:
            sc.engine.close_flow(conn_id)
        if self._reasm is not None:
            self._reasm.arena.drop(conn_id)
        pl.close_connection(conn_id)
        if self.flowlog is not None:
            self.flowlog.forget_conn(conn_id)

    # -- fan-in sessions (N shims, one dispatcher) ------------------------

    def _new_session(self) -> SessionState:
        with self._sess_lock:
            self._session_seq += 1
            sess = SessionState(self._session_seq)
            self._sessions[sess.id] = sess
            metrics.SidecarSessionsActive.set(float(len(self._sessions)))
        return sess

    def _session_dead(self, sess: SessionState, reason: str) -> None:
        """Retire one session from the live registry; idempotent per
        session.  Only DATA-PLANE sessions (named, or having submitted
        work) enter the bounded post-mortem ring and the deaths
        metric: a monitoring loop's control connections would
        otherwise cycle the ring and bury the one dead row that
        mattered (the pod that crashed)."""
        relevant = sess.named or sess.submitted > 0
        # Both arms route through the declared-edge mediation (R18):
        # the control-plane arm records the death reason without
        # bumping the typed metric, instead of flipping the state
        # field bare (which would also skip the dead-stays-dead and
        # declared-edge checks mark_dead enforces).
        sess.mark_dead(sess.death_reason or reason, counted=relevant)
        with self._sess_lock:
            if self._sessions.pop(sess.id, None) is not None and relevant:
                self._dead_sessions.append(sess.status())
            metrics.SidecarSessionsActive.set(float(len(self._sessions)))

    # Bounded label/storm-table vocabularies: identities are
    # wire-supplied, so both the Prometheus label set and the
    # reconnect-history table must be capped — a shim cycling pod
    # names (or a crash-looping deployment renaming per restart) must
    # not grow either without bound for the node's lifetime.
    _METRIC_IDENT_CAP = 256
    _STORM_TABLE_CAP = 1024

    def _session_hello(self, sess: SessionState, identity: str) -> None:
        """Identity announcement: name the session and run crash-loop
        detection — an identity reconnecting faster than the storm
        threshold starts this session QUARANTINED (typed), so a
        crash-looping pod costs one latch check per flood frame instead
        of full classification, and its neighbors nothing at all.  The
        control plane (module/policy/conn replay) still serves, so a
        healed pod exits the latch by simply staying up.  Only the
        FIRST hello on a session is honored (set_identity), and the
        metric label falls back to 'other' past the bounded identity
        vocabulary — status rows always carry the full identity."""
        if sess.named:
            return  # one identity per session; later hellos ignored
        sess.set_identity(identity)
        if not identity:
            return
        identity = sess.identity  # length-capped form
        # Handoff restore: a known identity reconnecting right after a
        # graceful restart is EXEMPT from the storm history — the
        # restart drove the reconnect, the pod is not crash-looping.
        # (The exactly-once audit spans the boundary as a sum: old-
        # process answers + new-process answers + typed local sheds.)
        restored = self._handoff_sessions.pop(identity, None) is not None
        if restored:
            self.handoff_session_restores += 1
        storm_n = self.config.session_reconnect_storm
        now = time.monotonic()
        window = self.config.session_reconnect_window_s
        with self._sess_lock:
            if (
                identity in self._metric_idents
                or len(self._metric_idents) < self._METRIC_IDENT_CAP
            ):
                self._metric_idents.add(identity)
                sess.metric_identity = identity
            else:
                sess.metric_identity = "other"
            if not storm_n or restored:
                return
            hist = self._ident_connects.get(identity)
            if hist is None:
                while len(self._ident_connects) >= self._STORM_TABLE_CAP:
                    # Bounded LRU: evict the least-recently-connecting
                    # identity (dict preserves insertion order; re-
                    # inserting on every hello keeps it recency-ordered).
                    self._ident_connects.pop(
                        next(iter(self._ident_connects))
                    )
                # Sized from the configured threshold: a fixed cap
                # below storm_n would silently disable detection
                # (len(hist) could never exceed the threshold).
                hist = _deque(maxlen=storm_n + 1)
            else:
                del self._ident_connects[identity]
            self._ident_connects[identity] = hist
            hist.append(now)
            while hist and now - hist[0] > window:
                hist.popleft()
            storm = len(hist) > storm_n
        if storm and not sess.quarantined_now():
            log.warning(
                "session %d (%s): reconnect storm (%d connects in "
                "%.1fs); quarantining for %.1fs",
                sess.id, identity, storm_n, window,
                self.config.session_quarantine_s,
            )
            sess.quarantine(
                QUARANTINE_RECONNECT_STORM,
                self.config.session_quarantine_s,
            )

    def _drr_share(self) -> int:
        """Per-session queue share: the admission queue split across
        CONNECTED sessions plus one headroom slot, floored at
        session_share_min.  Connected — not recently-active: an
        activity-windowed count is unstable under the very starvation
        it exists to prevent (a flooder's giant rounds slow its
        neighbors until they look idle, which GROWS the flooder's
        share — a feedback loop measured at 2s neighbor p99).  The +1
        headroom slot is load-bearing too: splitting by sessions alone
        hands a lone flooder the entire queue, and a neighbor's first
        submission then meets the GLOBAL cap — a typed queue_full
        shed, but still a denial of service.  Recomputed lazily
        (≤ every 50ms) — the per-batch fast path pays one float
        compare."""
        now = time.monotonic()
        if now - self._share_ts > 0.05:
            with self._sess_lock:
                # Data-plane sessions only: a control-plane connection
                # (each `cilium sidecar status`/`trace` invocation is a
                # short-lived unnamed session that never submits data)
                # must not shrink every real pod's share.
                n_sessions = sum(
                    1 for x in self._sessions.values()
                    if x.named or x.submitted
                )
            # The numerator is the mesh rung's ACTUAL capacity (PR 15
            # queue split x the ladder's capacity fraction): a
            # half-width mesh halves every session's credit window so
            # degraded overload sheds typed at admission instead of
            # queueing into deadline-shed p99 explosions.
            entries = int(
                self.config.shed_queue_entries * self._mesh_capacity
            )
            self._share_val = max(
                entries // max(n_sessions + 1, 2),
                self.config.session_share_min,
            )
            self._share_ts = now
        return self._share_val

    def _fanin_admit(self, sess, batch) -> str:
        """Fan-in admission gate, run on the submitting session's own
        reader thread before any queue/cut-through hand-off.  Returns
        '' to admit, else the typed shed reason the caller owes the
        batch (quarantine latch, then the DRR credit window).

        The quota is a per-session OUTSTANDING window: credits are
        entries, spent at admission and returned only when the entry's
        typed answer is written (submitted − answered — the same
        counters the exactly-once surface audits, so the window is
        correct across the dispatcher queue AND the completion
        pipeline; a queued-weight quota alone lets a flooder shift its
        backlog into the issued-not-answered FIFO where neighbors
        still queue behind it).  A session under its share is never
        refused — work conserving — and a flood's buffering lands on
        the flooder, typed, not on its neighbors' latency."""
        if self._fenced:
            # Fenced zombie predecessor: every data-plane frame after
            # surrender is refused typed (never silently) so a slow
            # shim that has not reconnected yet sees a clean shed.
            self.fence_rejects += 1
            metrics.SidecarFenceRejects.inc("data")
            return SHED_FENCED
        if sess is None:
            return ""
        if sess.quarantined_now():
            return SHED_SESSION_QUARANTINED
        # Classic-DRR one-batch overshoot: the PRE-batch outstanding is
        # compared against the share (``submitted`` already counts this
        # batch — the caller bumps it before the gate — so subtract it
        # back).  A session at or under its share is never refused, no
        # matter the batch size: comparing post-batch outstanding would
        # permanently shed (and eventually 'flood'-quarantine) an IDLE
        # session whose single wire batch exceeds the share.  The
        # window overshoot is bounded by one wire batch.
        if (
            sess.submitted - batch.count - sess.answered
            > self._drr_share()
        ):
            # Over-quota strike: sustained flooding escalates to the
            # session quarantine latch (cheaper than re-classifying
            # every flood frame, and typed for the operator).  The
            # clock is read HERE only — the under-share happy path
            # stays at one subtraction and one compare.
            strikes = self.config.session_flood_strikes
            if strikes:
                now = time.monotonic()
                if now - sess.strike_window_start > (
                    self.config.session_strike_window_s
                ):
                    sess.strike_window_start = now
                    sess.strikes = 0
                sess.strikes += 1
                if sess.strikes >= strikes:
                    sess.strikes = 0
                    sess.quarantine(
                        QUARANTINE_FLOOD,
                        self.config.session_quarantine_s,
                    )
            return SHED_SESSION_QUOTA
        return ""

    def _schedule_shm_reclaim(self, peer: ShmPeer) -> None:
        """A session died holding attached rings and never sent
        MSG_SHM_DETACH: the creator (the dead shim) will never unlink
        its segments, so the survivor must — after the attach lease
        expires (a shim alive behind a half-open socket reconnects
        with FRESH segments, so a post-lease unlink can never pull a
        live ring out from under anyone)."""
        t = threading.Timer(
            max(self.config.shm_lease_s, 0.0),
            self._reclaim_shm_segments, args=(peer,),
        )
        t.daemon = True
        t.name = "shm-reclaim"
        with self._sess_lock:
            self._reclaim_timers = [
                x for x in self._reclaim_timers if x.is_alive()
            ]
            self._reclaim_timers.append(t)
        t.start()

    def _reclaim_shm_segments(self, peer: ShmPeer) -> None:
        if not peer.reclaim():
            # Nothing to unlink: the creator beat us to it (e.g. a
            # half-open-socket shim that reconnected and later closed
            # orderly).  Counting this would make the leak-detection
            # metric report phantom recoveries.
            return
        self.shm_reclaims += 1
        metrics.SidecarShmReclaims.inc()
        log.info(
            "reclaimed orphaned shm segments (generation %d) after "
            "lease expiry", peer.generation,
        )

    # -- data plane (dispatcher worker thread only) -----------------------

    @staticmethod
    def _batch_nbytes(batch) -> int:
        """Payload bytes a queued batch will put on the device at
        issue (blob length for DataBatch, summed row lengths for
        MatrixBatch) — the byte-weighted half of queue occupancy."""
        blob = getattr(batch, "blob", None)
        if blob is not None:
            return len(blob)
        lens = getattr(batch, "lengths", None)
        return int(lens.sum()) if lens is not None else 0

    def submit_data(self, client, batch: wire.DataBatch,
                    backlogged: bool = False) -> None:
        if not batch.arrival:  # wire unpack stamps ingress; keep it
            batch.arrival = time.monotonic()
        sess = getattr(client, "session", None)
        if sess is not None:
            sess.submitted += batch.count
        item = ("data", client, batch)
        reason = self._fanin_admit(sess, batch)
        if reason:
            self._shed_item(item, reason)
            return
        if not backlogged and self._try_cut_through(item):
            return
        if not self.dispatcher.submit(item, weight=batch.count,
                                      session=sess,
                                      nbytes=self._batch_nbytes(batch)):
            self._shed_item(item, "queue_full")

    def submit_matrix(self, client, mb: wire.MatrixBatch,
                      backlogged: bool = False) -> None:
        if not mb.arrival:  # wire unpack stamps ingress; keep it
            mb.arrival = time.monotonic()
        sess = getattr(client, "session", None)
        if sess is not None:
            sess.submitted += mb.count
        item = ("mat", client, mb)
        reason = self._fanin_admit(sess, mb)
        if reason:
            self._shed_item(item, reason)
            return
        if not backlogged and self._try_cut_through(item):
            return
        if not self.dispatcher.submit(item, weight=mb.count,
                                      session=sess,
                                      nbytes=self._batch_nbytes(mb)):
            self._shed_item(item, "queue_full")

    def _try_cut_through(self, item) -> bool:
        """Greedy-mode cut-through: process the round directly on the
        shim reader thread when the service is idle — removes the
        reader→dispatcher thread handoff (a GIL-scheduling wait, not a
        fixed cost).  Under load the reader routes to the dispatcher
        instead, whose busy-worker queueing is what aggregates the
        backlog into large rounds.  (Full reader-side drain-and-process
        was tried and reverted: it keeps rounds at 1-2 messages, so
        per-round fixed costs multiply and the tail worsens ~2×.)

        Per-connection FIFO is preserved: a connection's data arrives on
        exactly one reader thread, so an earlier item from this client is
        either already processed or sitting in the dispatcher queue — in
        which case the queue is non-empty and we line up behind it.
        """
        if not self._inline_complete:
            return False
        disp = self.dispatcher
        # Lock-free peek: queued or popped-but-unprocessed work anywhere
        # means this item must line up behind it (the _busy set-before-
        # clear ordering in dispatch._pop_locked makes this peek safe).
        if disp._pending or disp._busy:
            return False
        # Non-blocking: if a round is mid-process, queue to the
        # dispatcher so the worker coalesces everything that arrived
        # during the in-flight round into ONE device call.  Capture the
        # lock OBJECT (mirroring BatchDispatcher._run): the stall
        # watchdog swaps _in_process_lock for a fresh one at deposal —
        # reachable while cut-through holds it (a popped batch blocking
        # on this lock trips the watchdog) — and a re-read release would
        # raise RuntimeError on the unheld replacement out of
        # submit_data while leaking this lock held forever.
        lock = disp._in_process_lock
        if not lock.acquire(blocking=False):
            return False
        released = False
        try:
            if lock is not disp._in_process_lock:
                # Deposed between read and acquire: a replacement
                # generation owns the queue (and a new lock) — line up
                # behind it rather than racing its rounds.
                return False
            # Arm the stall watchdog for this inline round (rechecks
            # pending/busy under the dispatcher condition): a device
            # call hung HERE on an idle service would otherwise never
            # be detected — no deposal, no quarantine, no typed reply,
            # one wedged shim reader.
            rid = disp.begin_inline_round(
                [item], nbytes=self._batch_nbytes(item[2])
            )
            if rid is None:
                return False
            self.inline_batches += 1
            try:
                self._process([item])
            except Exception as exc:  # noqa: BLE001 — reader must survive
                log.exception("cut-through process failed")
                # Same crash containment as the dispatcher path: every
                # entry gets a typed error verdict, never a silent drop
                # (suppressed if the watchdog already shed this round).
                try:
                    self._on_batch_error([item], exc)
                except Exception:  # noqa: BLE001
                    log.exception("cut-through error containment failed")
            finally:
                # Release BEFORE closing the round, mirroring _run's
                # release-then-clear-_busy ordering: the watchdog treats
                # a free in-process lock as "process() returned, its
                # verdicts are sent" and skips deposal.  Closing the
                # round first would leave a window (busy=True, lock
                # held, verdicts already sent) where a round completing
                # just past the deadline gets deposed and its served
                # seq double-replied with a SHED batch.
                released = True
                lock.release()
                disp.end_inline_round(rid)
                threading.current_thread()._disp_round = None
        finally:
            if not released:
                lock.release()
        return True

    @staticmethod
    def _batch_desc(batch, client=None) -> tuple:
        """(seq, n, arrival, first conn, session) — the tracer's
        per-wire-batch descriptor for e2e observation and span naming.
        The session id (0 = unknown) lets `cilium sidecar trace
        --session` attribute an exemplar to one shim."""
        sess = getattr(client, "session", None)
        return (
            batch.seq, batch.count, batch.arrival,
            int(batch.conn_ids[0]) if batch.count else 0,
            sess.id if sess is not None else 0,
        )

    @staticmethod
    def _oldest_arrival(items: list) -> float:
        """Oldest ingress stamp across a round's data items (the
        tracer's admit boundary — worst queue wait in the round)."""
        arr = [it[2].arrival for it in items if it[2].arrival]
        return min(arr) if arr else 0.0

    @staticmethod
    def _ring_wait(items: list) -> float:
        """Worst shm slot-commit → doorbell-drain wait across a round's
        data items — the tracer's STAGE_RING input (0 for socket-
        delivered rounds, whose arrival IS the frame decode)."""
        waits = [it[2].ring_wait for it in items if it[2].ring_wait]
        return max(waits) if waits else 0.0

    def _run_mat_group(self, items: list, t_pop: float) -> bool:
        """Whole-round fast path: every item is a complete-flag matrix
        batch, judged with ONE eligibility gather, ONE (chunked) device
        dispatch, ONE batched readback, and ONE verdict frame per
        client.  This collapses the per-item costs that dominate
        aggregated rounds (measured: eligibility 17µs + frame 14µs +
        client unpack 8µs per item).  Returns False — with no side
        effects — when the group needs the general path."""
        stages = self.seam_stages if self.config.seam_probe else None
        t0 = time.thread_time() if stages is not None else 0.0

        def mark(stage: str) -> None:
            nonlocal t0
            if stages is None:
                return
            t1 = time.thread_time()
            rec = stages.setdefault(stage, [0, 0.0])
            rec[0] += 1
            rec[1] += t1 - t0
            t0 = t1

        if len(items) == 1:
            mb0 = items[0][2]
            ids = mb0.conn_ids
            lengths = mb0.lengths
            rows = mb0.rows
        else:
            ids = np.concatenate([it[2].conn_ids for it in items])
            lengths = np.concatenate([it[2].lengths for it in items])
            rows = np.vstack([it[2].rows for it in items])
        n = len(ids)
        if n == 0:
            return False
        idx = ids.astype(np.int64)
        mark("concat")
        t_before = time.monotonic()
        with self._lock:
            swap_s = self._swap_overlap(t_before)
            if self._tab_size == 0 or int(idx.max()) >= self._tab_size:
                return False
            eng_idx = self._tab_engine[idx]
            e0 = int(eng_idx[0])
            if e0 < 0 or (eng_idx != e0).any():
                return False
            if self._tab_dirty[idx].any():
                return False
            remotes = self._tab_src[idx]
            engine = self._engine_objs[e0]
        if engine is None or isinstance(engine.model, ConstVerdict):
            return False
        if int(lengths.min()) < 2 or int(lengths.max()) > self.config.batch_width:
            return False
        mark("eligibility")
        rt = self.tracer.begin_round(
            PATH_VEC, n, self._oldest_arrival(items), t_pop,
            ring_s=self._ring_wait(items), swap_s=swap_s,
        )
        rt.formed()
        # Issue device chunks with the precomputed remotes, then one
        # batched readback for the whole round.
        lens32 = lengths.astype(np.int32)
        issued = []
        max_chunk = self.config.batch_flows
        for a in range(0, n, max_chunk):
            b = min(a + max_chunk, n)
            cn = b - a
            f_pad = self._min_bucket
            while f_pad < cn:
                f_pad *= 2
            if cn == f_pad:
                data, lens, rem = rows[a:b], lens32[a:b], remotes[a:b]
            else:
                data = np.zeros((f_pad, self.config.batch_width), np.uint8)
                data[:cn] = rows[a:b]
                lens = np.zeros(f_pad, np.int32)
                lens[:cn] = lens32[a:b]
                rem = np.zeros(f_pad, np.int32)
                rem[:cn] = remotes[a:b]
            _, _, chunk_allow, chunk_rule = self._model_call_attr(
                engine.model, data, lens, rem
            )
            issued.append((chunk_allow, chunk_rule, a, b, cn))
        mark("device_issue")
        rt.submitted()
        allow, rules = self._readback_chunks(issued, n)
        mark("readback")
        # Device-complete is this FENCED boundary (np.asarray readback)
        # — block_until_ready can return pre-execution on the tunneled
        # transport and would book device time into the send stage.
        rt.completed()
        self.fast_log.log_batch(
            getattr(engine, "proto", "r2d2"), n, int(n - allow.sum())
        )
        self.vec_batches += 1
        self.vec_entries += n
        metrics.ProxyBatches.inc()
        # Responses: one frame per client — a plain VERDICT_BATCH for a
        # single seq, a VERDICT_MULTI covering all its seqs otherwise.
        per_client: dict[int, list] = {}
        start = 0
        for _, client, mb in items:
            per_client.setdefault(id(client), [client, [], [], [], []])
            rec = per_client[id(client)]
            rec[1].append(mb.seq)
            rec[2].append(mb.count)
            rec[3].append((start, start + mb.count))
            rec[4].append(mb)
            start += mb.count
        rt.drained()
        for client, seqs, counts, spans, mbs in per_client.values():
            # ``batches=mbs``: send() marks every covered wire batch
            # answered under the write lock before writing, so a stall
            # deposal tripped by a LATER client's wedged send in this
            # same round can never SHED-double-reply a seq served here.
            try:
                if len(seqs) == 1:
                    a, b = spans[0]
                    client.send(
                        wire.MSG_VERDICT_BATCH,
                        self._verdict_frame(
                            seqs[0], ids[a:b], lengths[a:b], allow[a:b],
                            getattr(engine, "DENY_INJECT", None),
                        ),
                        batches=mbs,
                    )
                    continue
                if spans[-1][1] - spans[0][0] == sum(counts):
                    # Contiguous spans (the single-client round and any
                    # unbroken run): zero-copy views.
                    a, b = spans[0][0], spans[-1][1]
                    c_ids, c_lens, c_allow = ids[a:b], lengths[a:b], allow[a:b]
                else:
                    sel = np.concatenate(
                        [np.arange(a, b) for a, b in spans]
                    )
                    c_ids, c_lens, c_allow = ids[sel], lengths[sel], allow[sel]
                body = self._verdict_body(
                    c_ids, c_lens, c_allow,
                    getattr(engine, "DENY_INJECT", None),
                )
                client.send(
                    wire.MSG_VERDICT_MULTI,
                    wire.pack_verdict_multi(seqs, counts, len(c_ids), body),
                    batches=mbs,
                )
            except Exception:  # noqa: BLE001 — client may be gone
                log.exception("verdict send failed")
        mark("respond")
        if not self._round_thread_suppressed():
            self.tracer.finish_round(
                rt, [self._batch_desc(it[2], it[1]) for it in items]
            )
            self._record_vec_round(engine, ids, allow, rules)
        return True

    def _readback_chunks(self, issued: list, n: int):
        """Materialize a round's (allow, rule) chunk futures into host
        arrays.  np.asarray per array beats one batched device_get for
        the typical 1-2 co-located chunks (measured 3µs vs 20µs).
        Device errors deny (and unattribute) the chunk."""
        allow = np.empty(n, bool)
        rules = np.full(n, -1, np.int32)
        for fut, rfut, a, b, cn in issued:
            try:
                allow[a:b] = np.asarray(fut)[:cn]
            except Exception:  # noqa: BLE001 — deny on device error
                log.exception("device readback failed")
                allow[a:b] = False
                continue
            if rfut is not None:
                # Separate containment: the rule array exists for
                # OBSERVABILITY only — a failed rule readback
                # unattributes the chunk, it must never flip verdicts
                # that already materialized successfully.
                try:
                    rules[a:b] = np.asarray(rfut)[:cn]
                except Exception:  # noqa: BLE001 — unattribute only
                    log.exception("rule-attribution readback failed")
        return allow, rules

    def _record_vec_round(self, engine, conn_ids, allow, rules) -> None:
        """One flow-record batch for a vec/matrix round: columnar
        arrays straight from the readback, ONE ring append (R7: no
        per-entry work on the hot path).  Epoch and kinds legend both
        come from the CAPTURED engine — the tables the rule ids
        actually index — never from a re-read that churn could have
        rebound."""
        if self.flowlog is None:
            return
        self.flowlog.add_round(
            PATH_VEC,
            conn_ids,
            np.where(allow, CODE_FORWARDED, CODE_DENIED).astype(np.int8),
            rules,
            kinds=getattr(engine.model, "match_kinds", ()),
            epoch=getattr(engine, "epoch", 0),
        )

    @staticmethod
    def _entry_code(result: int, ops) -> int | None:
        """Flow-record verdict code for one entrywise response: first
        DROP/ERROR op decides, else PASS forwards; a MORE-only entry
        made no decision (no record)."""
        if result == int(FilterResult.SHED):
            return CODE_SHED
        if result != int(FilterResult.OK):
            return CODE_ERROR
        has_pass = False
        for op, _n in ops:
            if op == int(DROP):
                return CODE_DENIED
            if op == int(ERROR):
                return CODE_ERROR
            if op == int(PASS):
                has_pass = True
        return CODE_FORWARDED if has_pass else None

    @staticmethod
    def _kind_for(model, rule: int) -> str:
        kinds = getattr(model, "match_kinds", ()) if model is not None else ()
        return kinds[rule] if 0 <= rule < len(kinds) else ""

    def _engine_rule_kind(self, engine, conn_id: int,
                          sc=None) -> tuple[int, str, int]:
        """(rule, kind, epoch) for an entry decided by a CAPTURED
        engine — the slot-reuse-safe attribution: churn may free and
        reuse the engine's table slot (or rebind sc.engine) before the
        record is emitted, so the rule id must resolve against the
        engine that judged it, stamped with that engine's epoch."""
        fl = engine.flows.get(conn_id)
        if fl is not None:
            conn = getattr(fl, "conn", None)
            rule = (
                conn.last_rule_id if conn is not None
                else getattr(fl, "last_rule_id", -1)
            )
            return (
                int(rule),
                self._kind_for(engine.model, int(rule)),
                getattr(engine, "epoch", 0),
            )
        if sc is not None:
            return int(sc.conn.last_rule_id), "", self.policy_epoch
        return -1, "", -1

    def _entry_rule_kind(self, sc, conn_id: int) -> tuple[int, str]:
        """Rule attribution for an entrywise entry decided inside an
        engine pump or the oracle parser: the device-assisted engines
        and the oracle stamp Connection.last_rule_id (via matches_at /
        the precomputed-verdict queue), the r2d2 pump stamps
        FlowState.last_rule_id.  EMISSION-time fallback only — decision
        layers capture via _engine_rule_kind instead wherever the
        engine is snapshotted (rules_out), so churn cannot rebind
        sc.engine between decision and record."""
        if sc is None:
            return -1, ""
        eng = sc.engine
        if eng is not None:
            fl = eng.flows.get(conn_id)
            if fl is not None:
                conn = getattr(fl, "conn", None)
                rule = (
                    conn.last_rule_id if conn is not None
                    else getattr(fl, "last_rule_id", -1)
                )
                return int(rule), self._kind_for(eng.model, int(rule))
        # Oracle path (no engine): the in-process Connection's walk.
        return int(sc.conn.last_rule_id), ""

    def _record_entrywise(self, path: str, items: list, responses: dict,
                          rules_out: dict | None,
                          cached: set | None = None) -> None:
        """One flow-record batch for an entrywise round: the hot loop
        builds plain lists; the ring lock is taken ONCE in add_round
        (R7: per-round, never per-entry-under-the-lock).  ``cached``
        holds (item_id, entry_idx) keys already recorded on the
        `cached` path at decision time — skipped here so a hit is
        never double-recorded under the wrong path label."""
        if self.flowlog is None:
            return
        # Plain reference: per-key dict reads are GIL-atomic, and a conn
        # closed mid-iteration just materializes without metadata.
        conns = self._conns
        conn_ids: list[int] = []
        codes: list[int] = []
        rules: list[int] = []
        kinds: list[str] = []
        epochs: list[int] = []
        for item in items:
            resp = responses.get(id(item))
            if resp is None:
                continue
            batch = item[2]
            for i in range(batch.count):
                r = resp[i]
                if r is None:
                    continue
                if cached is not None and (id(item), i) in cached:
                    continue  # recorded on the `cached` path already
                conn_id, result, ops = r[0], r[1], r[2]
                code = self._entry_code(result, ops)
                if code is None:
                    continue
                sc = conns.get(conn_id)
                judged = (
                    rules_out.get((id(item), i)) if rules_out else None
                )
                if judged is not None:
                    rule, kind, ep = judged  # captured at judge time
                    if code != CODE_FORWARDED:
                        # A non-forwarded entry must not borrow a
                        # stale allowing rule (see the else arm).
                        rule, kind = -1, ""
                elif code == CODE_FORWARDED:
                    rule, kind = self._entry_rule_kind(sc, conn_id)
                    ep = self.policy_epoch
                else:
                    # last_rule_id is the LAST decision's rule; a
                    # non-forwarded entry (its first DROP decided) must
                    # not borrow a later allowing frame's rule —
                    # denied/shed/error records are unattributed, like
                    # the vec path's deny rows.
                    rule, kind, ep = -1, "", -1
                conn_ids.append(conn_id)
                codes.append(code)
                rules.append(rule)
                kinds.append(kind)
                epochs.append(ep)
        if conn_ids:
            self.flowlog.add_round(
                path,
                np.asarray(conn_ids, np.int64),
                np.asarray(codes, np.int8),
                np.asarray(rules, np.int32),
                cols={
                    "match_kind": kinds,
                    "epoch": np.asarray(epochs, np.int64),
                },
            )

    def observe_dump(self, req: dict) -> dict:
        """Flow-record query for MSG_OBSERVE (`cilium observe`)."""
        if self.flowlog is None:
            return {"records": [], "stats": {"disabled": True}}
        records = self.flowlog.query(
            n=int(req.get("n", 100)),
            verdict=req.get("verdict"),
            path=req.get("path"),
            rule=req.get("rule"),
            conn=req.get("conn"),
            since=req.get("since"),
            epoch=req.get("epoch"),
            session=req.get("session"),
        )
        return {"records": records, "stats": self.flowlog.stats()}

    def submit_ring(self, client, records: list,
                    reader_backlog: bool = False) -> None:
        """Admission for one drained doorbell batch.  A single-record
        drain keeps the cut-through path (an idle stream's latency win
        survives the transport swap); a multi-record drain enqueues in
        ONE dispatcher lock trip (submit_many) so a deep doorbell does
        not pay a lock round trip per frame — the worker aggregates it
        into one device round exactly like a socket backlog.  Fan-in
        fairness runs per frame here too: the ring IS the credit loop,
        so an over-quota frame shed typed at this gate frees its slot
        immediately (head already advanced at drain) — DRR credit
        issuance, with the refusal accounted to the one session."""
        if len(records) == 1:
            kind, batch = records[0]
            self.shm_entries += batch.count
            if kind == "data":
                self.submit_data(client, batch, backlogged=reader_backlog)
            else:
                self.submit_matrix(client, batch,
                                   backlogged=reader_backlog)
            return
        sess = getattr(client, "session", None)
        items = []
        for kind, batch in records:
            self.shm_entries += batch.count
            if sess is not None:
                sess.submitted += batch.count
            item = (kind, client, batch)
            reason = self._fanin_admit(sess, batch)
            if reason:
                self._shed_item(item, reason)
            else:
                items.append((item, batch.count,
                              self._batch_nbytes(batch)))
        for item in self.dispatcher.submit_many(items, session=sess):
            self._shed_item(item, "queue_full")

    def submit_close(self, conn_id: int) -> None:
        with self._lock:
            sc = self._conns.get(conn_id)
        # force: a close must never be shed, or the conn leaks.
        self.dispatcher.submit(("close", conn_id, sc), weight=0, force=True)

    # -- fault containment -------------------------------------------------

    def _on_quarantine_change(self, quarantined: bool) -> None:
        metrics.DeviceQuarantined.set(1.0 if quarantined else 0.0)
        if quarantined:
            metrics.DeviceQuarantineEvents.inc()

    def _typed_entries(self, batch, result: int) -> list:
        """One typed (conn_id, result, no-ops) response per entry — the
        fail-closed shape for shed/crash verdicts (any non-OK result is
        a connection error to the datapath consumer)."""
        return [
            (int(cid), int(result), [], b"", b"")
            for cid in batch.conn_ids
        ]

    def _shed_item(self, item, reason: str) -> None:
        """Fail-closed DROP with a typed SHED response — the admission
        queue never hangs or silently drops an entry.  An item whose
        real verdicts already went out (a multi-group round can serve
        its vec group, then hang in a later group before deposal) is
        skipped: round-id suppression only stops sends issued AFTER the
        shed, it cannot retract one already on the wire, and a second
        reply for a consumed seq desyncs the shim.  The early
        ``answered`` read only saves building the reply; the
        AUTHORITATIVE check-and-mark happens under the client write
        lock inside send_verdicts, which also covers a real-verdict
        sendall still in flight (the wedged send that tripped the
        watchdog marks its batches before writing)."""
        _, client, batch = item
        if batch.answered:
            return
        n = batch.count
        try:
            sent = client.send_verdicts(
                batch.seq,
                self._typed_entries(batch, FilterResult.SHED),
                batch=batch,
            )
        except Exception:  # noqa: BLE001 — client may be gone
            log.exception("shed response send failed")
            return
        if sent:
            # Counted only when THIS reply answered the seq: a real-
            # verdict send that won the race under the write lock means
            # the entry was served, and booking it as shed too would
            # double-count it (status and the overload bench's shed
            # rate would over-report).
            self.shed_entries += n
            metrics.SidecarShedTotal.inc(reason, amount=n)
            # Overload marker for the incident timeline: one coalesced
            # ring event per shed reason per window, never per entry.
            self.recorder.record_overload(reason, n)
            sess = getattr(client, "session", None)
            if sess is not None:
                # Session-scoped attribution (fan-in): the operator can
                # pin a shed to the one pod that caused it.
                sess.count_shed(reason, n)
            self.tracer.record_shed(
                batch.seq, n, batch.arrival,
                int(batch.conn_ids[0]) if n else 0, reason,
                session=sess.id if sess is not None else 0,
            )
            if self.flowlog is not None:
                # One columnar batch per shed wire batch (cold path).
                self.flowlog.add_round(
                    PATH_SHED,
                    batch.conn_ids,
                    np.full(n, CODE_SHED, np.int8),
                    reason=reason,
                )

    def _on_batch_error(self, items: list, exc: BaseException) -> None:
        """Crash containment: a failed process(batch) produces typed
        per-entry error verdicts for EVERY entry in the batch instead of
        being swallowed — no client blocks on a crashed round."""
        self.batch_crashes += 1
        metrics.SidecarBatchCrashes.inc()
        self._record_contained_failure(
            f"batch-crash: {type(exc).__name__}"
        )
        for it in items:
            if it[0] == "close":
                try:
                    self.close_connection(*it[1:])
                except Exception:  # noqa: BLE001
                    log.exception("close during crash containment failed")
                continue
            _, client, batch = it
            if batch.answered:
                # This item's real verdicts (or its SHED reply) already
                # went out — e.g. a greedy multi-group round that served
                # its vec group inline before a later group crashed.  A
                # second reply would desync the shim; an in-flight send
                # is caught by the same check under the client write
                # lock inside send_verdicts.
                continue
            try:
                sent = client.send_verdicts(
                    batch.seq,
                    self._typed_entries(batch, FilterResult.UNKNOWN_ERROR),
                    batch=batch,
                )
            except Exception:  # noqa: BLE001
                log.exception("error response send failed")
                continue
            if sent:  # see _shed_item: never double-book served entries
                self.error_entries += batch.count
                sess = getattr(client, "session", None)
                if sess is not None:
                    sess.count_shed("error", batch.count)
                if self.flowlog is not None:
                    self.flowlog.add_round(
                        PATH_SHED,
                        batch.conn_ids,
                        np.full(batch.count, CODE_ERROR, np.int8),
                        reason="batch-crash",
                    )

    def _on_dispatch_stall(self, items: list) -> None:
        """Watchdog deposed a stuck round (device hang): quarantine the
        device and shed the stuck batch with typed verdicts — the stuck
        round's own late sends (from its thread or from pipeline
        records it queued) are round-suppressed."""
        self.guard.record_stall("dispatch-stall")
        metrics.DeviceStalls.inc()
        self.recorder.record_overload("stall_deposal", len(items))
        # A wedged round on a mesh is indistinguishable here from a
        # lost mesh device: drop to the single-chip rung BEFORE the
        # quarantine ladder re-probes, so the heal path resumes on an
        # executable that cannot be waiting on a dead device's
        # collective.
        if self._mesh is not None and self._mesh_demoted is None:
            self._demote_mesh("device-stall")
        for it in items:
            if it[0] == "close":
                # Re-queue for the replacement worker; never lost.
                self.dispatcher.submit(it, weight=0, force=True)
                continue
            self._shed_item(it, "stall")

    def _device_probe(self) -> None:
        """One real device round (used by quarantine re-probes): prefer
        an r2d2 engine's own model; fall back to a bare device op when
        no row-shaped model exists.  Raises/hangs exactly when the
        device path is still unhealthy."""
        with self._lock:
            eng = next(
                (
                    e for e in self._engines.values()
                    if isinstance(e, R2d2BatchEngine)
                    and not isinstance(e.model, ConstVerdict)
                ),
                None,
            )
        if eng is not None:
            b = self._min_bucket
            w = self.config.batch_width
            with self._device_ctx():
                out = eng.model(
                    np.zeros((b, w), np.uint8),
                    np.zeros(b, np.int32),
                    np.zeros(b, np.int32),
                )
            np.asarray(out[-1])
            return
        import jax
        import jax.numpy as jnp

        with self._device_ctx():
            jax.device_get(jnp.ones(8))

    def _admit(self, items: list) -> list:
        """Admission pass at dispatch time: shed entries whose wire
        deadline or queue age passed while queued, pace quarantine
        re-probes, and sample queue-depth telemetry."""
        self.guard.maybe_reprobe(self._device_probe)
        self._maybe_mesh_reprobe()
        metrics.SidecarQueueDepth.set(float(self.dispatcher.pending_weight))
        now = time.monotonic()
        kept = []
        for it in items:
            if it[0] == "close":
                kept.append(it)
                continue
            b = it[2]
            expired = (
                b.deadline is not None and now > b.deadline
            ) or (
                self._queue_age_s
                and b.arrival
                and now - b.arrival > self._queue_age_s
            )
            if expired:
                self._shed_item(it, "deadline")
            else:
                kept.append(it)
        return kept

    def _demote_to_oracle(self, conn_id: int, sc: "_SidecarConn") -> None:
        """Move a conn off a quarantined pure-device engine onto the
        in-process oracle path, migrating the engine's retained request
        bytes into the oracle buffer mirror so no byte is lost or
        replayed.  The oracle IS the definition of bit-exactness, so
        verdicts keep flowing unchanged while the device is out."""
        engine = sc.engine
        if engine is None:
            return
        if self._reasm is not None:
            # Columnar-arena carry precedes the engine flow buffer (an
            # arena conn holds its residue THERE, never in the flow);
            # the dead/overflowed latch is dropped exactly like the
            # popped flow's below — the oracle serves fresh.
            residue, _dead = self._reasm.arena.release(conn_id)
            if residue:
                sc.bufs[False] = bytearray(residue) + sc.bufs[False]
        flow = engine.flows.pop(conn_id, None)
        if flow is not None and getattr(flow, "buffer", None):
            # Engine-retained request bytes precede anything the oracle
            # mirror may hold for this direction.
            sc.bufs[False] = bytearray(flow.buffer) + sc.bufs[False]
        sc.engine = None
        sc.fast_ok = False
        sc.demoted_mod = sc.module_id
        with self._lock:
            if conn_id < self._tab_size:
                self._tab_engine[conn_id] = -1
                self._tab_dirty[conn_id] = 1
            # The claim stays table-valid, but this conn now carries
            # migrated residue the cache's clean-flow gate must see;
            # the heal rebind re-arms from the (fallback) engine.
            self._disarm_flow_cache(conn_id, "demote")

    def _maybe_rebind(self, conn_id: int, sc: "_SidecarConn") -> None:
        """Un-demote after the device heals: once the oracle residue
        has drained, bind the engine back so the conn resumes the
        device path.  Runs on the DISPATCH path, so it never compiles:
        an existing engine for the key binds inline (pointer reads
        only); a missing one is built by the policy builder thread
        while the conn keeps serving on the oracle."""
        if (
            sc.demoted_mod is None
            or sc.bufs[False]
            or sc.bufs[True]
            or sc.skip[False]
            or sc.skip[True]
        ):
            return
        mod = sc.demoted_mod
        key = self._engine_key_for(mod, sc.conn)
        grant = None
        with self._lock:
            eng = self._engines.get(key)
            if eng is not None:
                sc.demoted_mod = None
                sc.engine = eng
                sc.fast_ok = sc.conn.parser_name in FAST_PROTOS
                self._tab_set_engine(
                    conn_id, eng if sc.fast_ok else None
                )
                # Quarantine healed: re-arm the invariance claim from
                # the rebound engine (the demotion disarmed it).
                grant = self._arm_flow_cache(conn_id, sc)
            elif conn_id not in self._rebind_inflight:
                self._rebind_inflight.add(conn_id)
                sc.demoted_mod = None
                eng = False  # sentinel: queue the off-path rebuild
        if eng is False:
            self._build_queue.put(("rebind", (mod, conn_id)))
        elif grant is not None:
            # Dispatch path: hand the (blocking) grant send to the
            # builder thread — advisory delivery, revalidated there.
            self._build_queue.put(("grants", [grant]))

    def _process(self, items: list) -> None:
        """Dispatcher entry: triage aggregated items.

        Whole DATA batches that are homogeneous (request direction,
        single complete frame per entry, stateless conns on one engine)
        take the fully vectorized path — O(1) numpy ops + one device
        call, no per-entry Python.  Everything else falls to the
        entrywise path below.  A vec-eligible batch is demoted if it
        shares a connection with an entrywise batch in the same round,
        preserving per-connection op order.
        """
        self.guard.round_start()
        # Queue-pop boundary for the latency decomposition: everything
        # before this stamp is admission-queue time.
        t_pop = time.monotonic()
        items = self._admit(items)
        closes = [it[1:] for it in items if it[0] == "close"]
        data_items = [it for it in items if it[0] in ("data", "mat")]
        # Quarantined device: the whole round bypasses the vectorized
        # paths and renders through the host fallback (entrywise) —
        # bounded-latency degradation, never a hang.
        quarantined = self.guard.quarantined
        # Established-flow verdict cache, whole-item tier: items whose
        # EVERY entry hits (armed conn, matching epoch, clean, frame-
        # aligned) are answered straight from the claim — no device
        # round, no engine state, bytes already at the service but the
        # (flows, rules) round never happens.  Offered BEFORE the
        # mat-group fast path so the greedy whole-round shape (the
        # hottest serving lane) also short-circuits; mixed items fall
        # through to the columnar Phase-A per-entry mask.
        # The _cache_armed read is racy-by-design: 0 skips the tier's
        # snapshot + per-item masks entirely (cache-on but nothing
        # armed must not tax the greedy fast path below, which runs
        # snapshot-free), and a conn arming concurrently just waits
        # one round for its first short-circuit.
        snap = None
        if (
            self._flow_cache_on
            and not quarantined
            and data_items
            and self._cache_armed > 0
        ):
            snap = self._tab_snapshot(data_items)
            if snap is not None:
                data_items = self._serve_cached_items(
                    data_items, snap, t_pop
                )
                if not data_items:
                    for close_args in closes:
                        self.close_connection(*close_args)
                    self._round_record_ok()
                    return
        # Whole-round fast path (greedy mode): every data item a
        # complete-flag matrix batch of the configured width — one
        # grouped eligibility/dispatch/readback/response pass.
        if (
            not quarantined
            and self._inline_complete
            and data_items
            and all(
                it[0] == "mat"
                and (it[2].flags & wire.MAT_FLAG_COMPLETE)
                and it[2].width == self.config.batch_width
                for it in data_items
            )
            and self._run_mat_group(data_items, t_pop)
        ):
            # Misses by definition: offered to the cache tier above
            # and not served (or the tier skipped with zero armed
            # rows — same thing).  No-op counter when the cache is
            # off.
            self._count_cache_misses(
                sum(it[2].count for it in data_items)
            )
            for close_args in closes:
                self.close_connection(*close_args)
            self._round_record_ok()
            return
        # Snapshot the conn tables under the lock once per round: the
        # eligibility checks and chunk issue below run lock-free on the
        # dispatcher thread while policy_update/new_connection mutate
        # the tables (including _engine_objs slot reuse), so every read
        # in this round must come from one consistent view.
        if snap is None:
            snap = self._tab_snapshot(data_items)
        vec: list[tuple] = []  # (item, engine) — item kind "data" or "mat"
        general: list = []  # (arrival_idx, item)
        for k, it in enumerate(data_items):
            if quarantined:
                eng = None
                if it[0] == "mat":
                    it = ("data", it[1], _matrix_to_batch(it[2]))
            elif it[0] == "mat":
                eng = self._matrix_eligible(it[2], snap)
                if eng is None:
                    it = ("data", it[1], _matrix_to_batch(it[2]))
            else:
                eng = self._vec_eligible(it[2], snap)
            if eng is not None:
                vec.append((k, it, eng))
            else:
                general.append((k, it))
        if vec and general:
            gen_conns = np.unique(
                np.concatenate([it[2].conn_ids for _, it in general])
            )
            kept = []
            for k, it, eng in vec:
                if np.isin(it[2].conn_ids, gen_conns).any():
                    if it[0] == "mat":
                        it = ("data", it[1], _matrix_to_batch(it[2]))
                    general.append((k, it))
                else:
                    kept.append((k, it, eng))
            if len(kept) != len(vec):
                # Re-establish arrival order among entrywise items.
                general.sort(key=lambda rec: rec[0])
            vec = kept
        if vec:
            self._run_vec([(it, eng) for _, it, eng in vec], snap, t_pop)
        if general:
            self._process_entrywise(
                [it for _, it in general], t_pop,
                swap_s=snap.swap_s if snap is not None else 0.0,
            )
        for close_args in closes:
            self.close_connection(*close_args)
        # The round completed without raising — reset the poisoned-
        # engine crash streak.
        self._round_record_ok()

    def _swap_overlap(self, t_before: float) -> float:
        """Portion of a just-finished _lock acquisition that was spent
        blocked behind the epoch-swap pointer flip: the overlap of
        [t_before, now] with the last swap's lock-hold window.  Zero
        for every round that did not actually contend with a swap."""
        w0, w1 = self._swap_window
        if not w1:
            return 0.0
        return max(0.0, min(w1, time.monotonic()) - max(w0, t_before))

    def _round_thread_suppressed(self) -> bool:
        """True on a thread whose guard bookkeeping must be dropped —
        the same deposed-worker/shed-round predicate that suppresses
        sends.  A zombie round unsticking minutes after deposal must
        touch NEITHER direction of the streak: its record_ok would
        reset a genuine streak the replacement worker is accumulating
        (or consume a live round's taint), and its record_failure
        would taint the live rounds for a crash the deposal already
        booked via record_stall."""
        disp = self.dispatcher
        return disp.thread_is_deposed() or disp.thread_round_is_shed()

    def _round_record_ok(self) -> None:
        """guard.record_ok for a completed round — see
        _round_thread_suppressed."""
        if not self._round_thread_suppressed():
            self.guard.record_ok()

    def _record_contained_failure(self, reason: str) -> None:
        """guard.record_failure for a contained in-round failure —
        gated like record_ok; covers every crash-streak input reachable
        from an abandoned thread (batch crash, engine pump crash, the
        device-assisted engines' judge-crash hook)."""
        if not self._round_thread_suppressed():
            self.guard.record_failure(reason)

    def _tab_snapshot(self, data_items: list) -> "_TabSnap | None":
        if not data_items:
            return None
        single = False
        if len(data_items) == 1:
            one = data_items[0][2].conn_ids.astype(np.int64)
            # Single-item rounds with already strictly-increasing ids
            # (the common matrix-batch shape) skip the unique() sort and
            # mark the snapshot identity-ordered for O(1) lookups.
            if len(one) and np.all(one[1:] > one[:-1]):
                ids = one
                single = True
            else:
                ids = np.unique(one)
        else:
            ids = np.unique(
                np.concatenate(
                    [it[2].conn_ids for it in data_items]
                ).astype(np.int64)
            )
        t_before = time.monotonic()
        want_cache = self._flow_cache_on
        with self._lock:
            swap_s = self._swap_overlap(t_before)
            epoch = self.policy_epoch
            if self._tab_size == 0:
                snap = _TabSnap(
                    ids,
                    np.full(len(ids), -1, np.int32),
                    np.zeros(len(ids), np.int32),
                    np.ones(len(ids), np.uint8),
                    (),
                    single,
                    epoch=epoch,
                )
                snap.swap_s = swap_s
                return snap
            objs = self._objs_cache
            if objs is None:
                objs = self._objs_cache = tuple(self._engine_objs)
            if len(ids) and int(ids[-1]) < self._tab_size:
                # All in range (ids sorted): plain gathers — the fancy
                # index copies, which IS the snapshot.
                snap = _TabSnap(
                    ids,
                    self._tab_engine[ids],
                    self._tab_src[ids],
                    self._tab_dirty[ids],
                    objs,
                    single,
                    cache=(
                        self._tab_cache[ids] if want_cache else None
                    ),
                    cache_epoch=(
                        self._tab_cache_epoch[ids] if want_cache
                        else None
                    ),
                    cache_rule=(
                        self._tab_cache_rule[ids] if want_cache
                        else None
                    ),
                    epoch=epoch,
                )
                snap.swap_s = swap_s
                return snap
            in_range = ids < self._tab_size
            clipped = np.where(in_range, ids, 0)
            engine = np.where(
                in_range, self._tab_engine[clipped], -1
            ).astype(np.int32)
            src = np.where(in_range, self._tab_src[clipped], 0).astype(np.int32)
            dirty = np.where(
                in_range, self._tab_dirty[clipped], 1
            ).astype(np.uint8)
            cache = cache_epoch = cache_rule = None
            if want_cache:
                cache = np.where(
                    in_range, self._tab_cache[clipped], 0
                ).astype(np.uint8)
                cache_epoch = np.where(
                    in_range, self._tab_cache_epoch[clipped], -1
                ).astype(np.int64)
                cache_rule = np.where(
                    in_range, self._tab_cache_rule[clipped], -1
                ).astype(np.int32)
        snap = _TabSnap(ids, engine, src, dirty, objs, single,
                        cache=cache, cache_epoch=cache_epoch,
                        cache_rule=cache_rule, epoch=epoch)
        snap.swap_s = swap_s
        return snap

    def _matrix_eligible(self, mb: wire.MatrixBatch, snap: "_TabSnap"):
        """Engine for a fixed-width matrix batch, or None to fall back."""
        n = mb.count
        if n == 0 or mb.width != self.config.batch_width:
            return None
        pos = snap.lookup(mb.conn_ids)
        eng_idx = snap.engine[pos]
        e0 = int(eng_idx[0])
        if e0 < 0 or (eng_idx != e0).any():
            return None
        if snap.dirty[pos].any():
            return None
        lengths = mb.lengths
        if int(lengths.min()) < 2 or int(lengths.max()) > mb.width:
            return None
        engine = snap.objs[e0]
        if engine is None or isinstance(engine.model, ConstVerdict):
            return None
        framing = _engine_framing(engine)
        if framing is None:
            return None
        if mb.flags & wire.MAT_FLAG_COMPLETE:
            # The edge declared whole-frame rows (it owns framing);
            # skip the per-row content scan.
            return engine
        if not framing.rows_single_frame(mb.rows, lengths).all():
            return None
        return engine

    def _vec_eligible(self, batch: wire.DataBatch, snap: "_TabSnap"):
        """The engine serving every entry of this batch vectorized, or
        None if any entry needs the entrywise path."""
        n = batch.count
        if n == 0:
            return None
        if batch.flags.any():  # reply or end_stream entries
            return None
        pos = snap.lookup(batch.conn_ids)
        eng_idx = snap.engine[pos]
        e0 = int(eng_idx[0])
        if e0 < 0 or (eng_idx != e0).any():
            return None
        if snap.dirty[pos].any():
            return None
        lengths = batch.lengths
        if int(lengths.min()) < 2 or int(lengths.max()) > self.config.batch_width:
            return None
        engine = snap.objs[e0]
        if engine is None or isinstance(engine.model, ConstVerdict):
            return None
        framing = _engine_framing(engine)
        if framing is None:
            return None
        blob = np.frombuffer(batch.blob, np.uint8)
        if len(blob) != int(lengths.sum()):
            return None
        # Exactly one whole frame per entry, ending at the entry
        # boundary — the engine's declared framing owns the check
        # (CRLF tail + single CR for r2d2, the length-prefix walk for
        # DNS).
        if not framing.segments_single_frame(
            blob, batch.offsets[:-1].astype(np.int64),
            lengths.astype(np.int64),
        ).all():
            return None
        return engine

    # Fixed device batch buckets: padded shapes are drawn from this small
    # set so XLA compiles each (bucket, width) once and never again — the
    # anti-churn guard for mixed batch sizes.  Greedy (co-located) mode
    # uses a smaller floor: its common round is one ~10-30-entry message
    # processed inline, and local compiles are cheap; the remote path
    # keeps the 256 floor so prewarm pays 3 fewer multi-second compiles
    # through the tunneled link.
    MIN_BUCKET = 256
    MIN_BUCKET_GREEDY = 32

    @property
    def _min_bucket(self) -> int:
        # ROADMAP 5b: a mesh flow extent wider than the base floor
        # grows the minimum bucket to match (set at _resolve_mesh), so
        # every padded batch still divides across a >32-wide mesh.
        base = (
            self.MIN_BUCKET_GREEDY if self._inline_complete
            else self.MIN_BUCKET
        )
        return max(base, self._mesh_min_bucket)

    def _buckets(self) -> list[int]:
        out = [self._min_bucket]
        while out[-1] < self.config.batch_flows:
            out.append(out[-1] * 2)
        return out

    def _device_ctx(self):
        """Context routing model build/dispatch to the configured
        verdict device ('cpu' removes the device-link term)."""
        if self._exec_device is None:
            import contextlib

            return contextlib.nullcontext()
        import jax

        return jax.default_device(self._exec_device)

    def _jit_for(self, cache: dict, model, trace_fn, arg_fn=None):
        """Jit-dispatch cache, two keying modes.

        **Shape-keyed** (models exposing ``dispatch_bare()``, the r2d2
        path): the executable takes the model as a pytree ARGUMENT, so
        the cache key is the model's tree structure + leaf
        shapes/dtypes — NOT its identity.  Policy churn that rebuilds
        same-bucketed tables (models/r2d2.py pads rule rows to power-
        of-two buckets) then reuses the compiled executable and only
        uploads fresh arrays; these entries deliberately survive epoch
        swaps.  ``arg_fn(model, *args)`` is the trace function.

        **Id-keyed** (everything else): the stored model reference pins
        the id so a gc'd model can never alias an entry.  (Binding the
        device via in_shardings instead of the default-device ctx was
        tried and reverted: 15µs/call isolated but ~400µs of spinning
        thread-CPU under multi-thread contention on a small host.)"""
        key = self._model_shape_key(model) if arg_fn is not None else None
        if key is not None:
            fn = cache.get(key)  # lint: disable=R13 -- shape-keyed executable cache: keys are TABLE SHAPES, not table contents, so entries are epoch-independent by construction and deliberately survive swaps (the churn executable cache)
            if fn is None:
                self._evict_shape_entries(cache)
                # lint: disable=R12 -- cache-miss only: every serving shape is prewarmed off-path at engine build/swap; a miss here is the documented lazy greedy-mode gather compile (local, cheap)
                fn = self._ledgered_jit(cache, key, arg_fn, model)
                cache[key] = fn  # lint: disable=R13 -- shape-keyed by design (see the read above): same-bucketed churn MUST hit this entry across epochs
            return functools.partial(fn, model.dispatch_bare())
        ent = cache.get(id(model))  # lint: disable=R13 -- id-keyed entries die WITH their model: _commit_epoch pops them at the pointer flip, so no entry can outlive its epoch
        if ent is None:
            # lint: disable=R12 -- cache-miss only: prewarm traces every bucket shape at engine build (builder/reader thread); dispatch rounds only ever hit this dict
            fn = self._ledgered_jit(cache, id(model), trace_fn, model,
                                    id_keyed=True)
            ent = (model, fn)
            cache[id(model)] = ent  # lint: disable=R13 -- id-keyed: popped by _commit_epoch at the flip (see the read above)
        return ent[1]

    def _ledgered_jit(self, cache: dict, key, trace_fn, model,
                      id_keyed: bool = False):
        """THE jit half of the ledger choke point (ledger.py): wrap a
        fresh executable so its FIRST invocation — where jax actually
        traces and compiles — is timed and recorded, then swap the
        bare executable into the cache (zero steady-state overhead:
        later lookups bypass the shim entirely).  The cause comes from
        the recording thread's ledger scope (the first call runs
        immediately after the miss, on the missing thread, so the
        miss-site scope is still live); an unscoped miss whose shape
        key was previously EVICTED records churn-new-shape — the
        evict-then-reuse retrace is churn cost, not a cold start —
        and any other unscoped miss records cold."""
        import jax

        # lint: disable=R12 -- this IS the ledger choke point the hot-path pragmas above refer to; the wrap is lazy (trace happens at first call) and misses only ever happen for un-prewarmed shapes
        jfn = jax.jit(trace_fn)
        led = self.ledger
        rkey = (id(cache), key)
        cause = None
        if ledger_mod.current_scope() is None and led.was_evicted(rkey):
            cause = ledger_mod.CAUSE_CHURN_NEW_SHAPE
        led.executable_resident(rkey)
        family = type(model).__name__
        shape_sig = None if id_keyed else key
        # Which executable FAMILY this cache serves: the same model
        # shape legitimately traces once per role (gather vs direct vs
        # attribution are distinct executables), and the census must
        # keep them apart or a first-use attr trace masks a gather
        # re-trace.
        role = (
            "gather" if cache is self._jit_gather
            else "attr" if cache is self._jit_attr
            else "direct"
        )
        done = []

        def shim(*args):
            t0 = time.perf_counter()
            out = jfn(*args)
            if not done:
                done.append(True)
                try:
                    led.record_compile(
                        family, time.perf_counter() - t0, cause=cause,
                        shape=shape_sig, kind="jit", role=role,
                        epoch=self.policy_epoch,
                    )
                    # Retire the shim: the cache entry becomes the
                    # bare executable.
                    if id_keyed:
                        ent = cache.get(key)
                        if ent is not None and ent[1] is shim:
                            cache[key] = (ent[0], jfn)  # lint: disable=R13 -- same id-keyed entry being replaced in place (epoch lifecycle unchanged)
                    elif cache.get(key) is shim:
                        cache[key] = jfn  # lint: disable=R13 -- same shape-keyed entry being replaced in place (see _jit_for)
                except Exception:  # noqa: BLE001 -- accounting must not cost the round
                    pass
            return out

        return shim

    # Distinct table-shape signatures a shape-keyed cache may hold
    # before the oldest are evicted: bounds executable memory on a
    # long-running service under regex-vocabulary churn (each new
    # automaton state count is a new shape).  Well above any
    # steady-state working set — eviction is the runaway backstop, not
    # a tuning knob.
    SHAPE_CACHE_MAX = 64

    def _evict_shape_entries(self, cache: dict) -> None:
        """Evict the oldest shape-keyed entries once the cache holds
        SHAPE_CACHE_MAX distinct shapes (dict order = insertion order;
        id-keyed entries are untouched — their lifecycle is the engine
        drop at swap)."""
        shape_keys = [k for k in cache if isinstance(k, tuple)]
        while len(shape_keys) >= self.SHAPE_CACHE_MAX:
            victim = shape_keys.pop(0)
            cache.pop(victim, None)
            self._prewarmed_shapes.pop(victim, None)
            # THE resident-executable decrement (one definition,
            # ledger-owned): the gauge moves here and at the id-keyed
            # epoch retirement, nowhere else — and the ledger's
            # evicted-key memory makes a later reuse of this shape
            # record churn-new-shape, not cold.
            self.ledger.executable_evicted((id(cache), victim))

    # -- multi-chip mesh rung ---------------------------------------------

    def _resolve_mesh(self):
        """The service's (flows, rules) device mesh, or None when
        multi-chip serving is off.  'auto' requires more than one REAL
        accelerator device (virtual CPU devices share the host's cores
        — a collective there only adds overhead); 'on' forces a mesh
        at any device count (the CPU-mesh tests and smoke benches).
        The flow extent is floored to a power of two so every
        power-of-two dispatch bucket divides it, and capped at the
        smallest bucket."""
        if self._mesh_resolved:
            return self._mesh
        with self._mesh_lock:
            if self._mesh_resolved:
                return self._mesh
            mesh = None
            if self.config.mesh != "off":
                from ..parallel.mesh import FLOW_AXIS, RULE_AXIS, serving_mesh

                with self._device_ctx():
                    mesh = serving_mesh(
                        self.config.mesh,
                        self.config.mesh_rule_shards,
                        self.config.mesh_flow_shards,
                        max_flow=self.MIN_BUCKET_GREEDY,
                    )
                if mesh is not None:
                    log.info(
                        "mesh serving: %d device(s) as (flows=%d, "
                        "rules=%d)", mesh.size,
                        mesh.shape[FLOW_AXIS], mesh.shape[RULE_AXIS],
                    )
                    # ROADMAP 5b: an EXPLICIT flow extent beyond the
                    # smallest dispatch bucket grows the minimum
                    # bucket to the extent, so >32-device pods shard
                    # the flow axis fully and every padded batch
                    # still divides across the mesh.
                    base = (
                        self.MIN_BUCKET_GREEDY if self._inline_complete
                        else self.MIN_BUCKET
                    )
                    fl = mesh.shape[FLOW_AXIS]
                    if fl > base:
                        self._mesh_min_bucket = fl
                        log.info(
                            "mesh flow extent %d grows the minimum "
                            "dispatch bucket (%d -> %d)", fl, base, fl,
                        )
                elif self.config.mesh == "on":
                    log.warning(
                        "mesh=on but no (flows=%s, rules=%s) mesh "
                        "fits the available devices; serving "
                        "single-chip",
                        self.config.mesh_flow_shards or "auto",
                        max(self.config.mesh_rule_shards, 1),
                    )
            self._mesh = mesh
            self._mesh_resolved = True
            if mesh is not None and self._handoff_mesh:
                self._adopt_handoff_mesh(mesh)
            self._handoff_mesh = None
            metrics.MeshActive.set(
                1.0 if mesh is not None and self._mesh_demoted is None
                else 0.0
            )
            self._publish_mesh_capacity()
        return mesh

    def _adopt_handoff_mesh(self, mesh) -> None:
        """Resume the predecessor's ladder rung (under _mesh_lock, at
        resolution): its attributed dead devices that still exist in
        OUR mesh are marked lost up front, and serving starts directly
        on the reshaped rung — a successor never re-probes a
        known-dead chip through a fault.  Device ids that no longer
        resolve are dropped (the backend was re-enumerated; the paced
        re-probe re-adjudicates)."""
        from ..parallel.mesh import FLOW_AXIS, RULE_AXIS, reshape_mesh

        ho = self._handoff_mesh or {}
        mesh_ids = {d.id for d in mesh.devices.flat}
        lost = {int(x) for x in ho.get("lost") or ()} & mesh_ids
        self.mesh_reshapes = int(ho.get("reshapes") or 0)
        if not lost:
            return
        self._mesh_lost = set(lost)
        already = set(self.guard.lost_devices())
        for dev_id in sorted(lost):
            if str(dev_id) not in already:
                self.guard.record_device_fault(dev_id, "handoff")
        metrics.MeshLostDevices.set(float(len(lost)))
        survivors = [d for d in mesh.devices.flat if d.id not in lost]
        target = None
        if self.config.mesh_reshape:
            with self._device_ctx():
                target = reshape_mesh(
                    survivors, mesh.shape[RULE_AXIS],
                    max_flow=mesh.shape[FLOW_AXIS],
                )
        if target is not None:
            with blackbox.annotate(reason="handoff-resume"):
                MESH_LADDER_PROTOCOL.advance(self._mesh_rung(),
                                             MESH_RESHAPED)
            self._mesh_serving = target
            log.warning(
                "mesh resumes RESHAPED from handoff: %d device(s) "
                "lost %s, serving (flows=%d, rules=%d)", len(lost),
                sorted(lost), target.shape[FLOW_AXIS],
                target.shape[RULE_AXIS],
            )
        else:
            with blackbox.annotate(reason="handoff-degraded"):
                MESH_LADDER_PROTOCOL.advance(self._mesh_rung(),
                                             MESH_FALLBACK)
            self._mesh_demoted = "handoff-degraded"
            self.mesh_demotions["handoff-degraded"] = (
                self.mesh_demotions.get("handoff-degraded", 0) + 1
            )
            metrics.MeshDemotions.inc("handoff-degraded")
            log.warning(
                "mesh handoff carried %d lost device(s) and no "
                "reshaped width fits: serving single-chip", len(lost),
            )

    def _serving_mesh(self):
        """Mesh for NEW engine builds: the current rung's mesh — the
        reshaped survivor mesh while degraded, None once demoted to
        the fallback rung (every model compiled there is
        single-chip)."""
        mesh = self._resolve_mesh()
        if self._mesh_demoted is not None:
            return None
        return self._mesh_serving or mesh

    def _live_model(self, model):
        """Mesh-rung resolution for one dispatch: a demoted service
        serves every sharded model's single-chip fallback executable
        (bit-identical by the sharding parity contract)."""
        fb = getattr(model, "fallback", None)
        if fb is not None and self._mesh_demoted is not None:
            return fb
        return model

    # Device-id attribution over a fault's text: backend runtimes name
    # the failing chip ("TPU_3", "device 2", "cpu:1") in transfer and
    # collective errors; the match is intersected with the mesh's own
    # id set so a stray number never marks a device.
    _DEV_ID_RE = re.compile(
        r"(?:cpu|tpu|gpu|device)[ _:]{0,2}(\d+)", re.IGNORECASE
    )

    def _attribute_fault_devices(self, exc) -> set:
        """Which mesh devices did this fault name?  Three sources, all
        intersected with the full mesh's device ids: an explicit
        ``failed_devices`` attribute on the exception, device ids
        parsed from the message text, and devices that VANISHED from
        the backend's device set (unplugged chip).  Empty when the
        fault is not attributable to a chip — the demotion then holds
        for the paced re-probe to adjudicate."""
        mesh = self._mesh
        if mesh is None:
            return set()
        ids: set = set()
        if exc is not None:
            for d in getattr(exc, "failed_devices", ()) or ():
                try:
                    ids.add(int(getattr(d, "id", d)))
                except (TypeError, ValueError):
                    continue
            for m in self._DEV_ID_RE.finditer(str(exc)):
                ids.add(int(m.group(1)))
        mesh_ids = {d.id for d in mesh.devices.flat}
        try:
            import jax

            live = {d.id for d in jax.devices()}
            ids |= mesh_ids - live
        except Exception:  # noqa: BLE001 — a dead backend attributes nothing
            pass
        return ids & mesh_ids

    def _probe_mesh_device(self, dev) -> bool:
        """One tiny put+readback against a single device: True when it
        answers.  Runs off-path (builder thread / probe pool) only."""
        import jax

        arr = jax.device_put(np.arange(8, dtype=np.int32), dev)
        return int(np.asarray(arr).sum()) == 28

    def _probe_mesh_devices(self, devices) -> set:
        """Probe every full-mesh device in a disposable bounded pool
        (a HUNG device must cost one timeout, not wedge the builder
        thread serially per chip) and return the dead id set; each
        failure is recorded in the guard's per-device health table.
        ``_device_probe_fn`` is the test seam."""
        dead: set = set()
        probe = self._device_probe_fn or self._probe_mesh_device
        timeout = self.guard.timeout_s or 5.0
        ex = ThreadPoolExecutor(
            max_workers=min(max(len(devices), 1), 8),
            thread_name_prefix="mesh-probe",
        )
        try:
            futs = [(ex.submit(probe, d), d) for d in devices]
            for fut, dev in futs:
                try:
                    ok = bool(fut.result(timeout))
                except Exception:  # noqa: BLE001 — raise/timeout == dead
                    ok = False
                if not ok:
                    dead.add(dev.id)
                    self.guard.record_device_fault(
                        dev.id, "probe-failed"
                    )
        finally:
            ex.shutdown(wait=False)
        return dead

    def _publish_mesh_capacity(self) -> None:
        """Publish the current rung's capacity fraction and scale
        admission by it: the dispatcher's global queue cap and the DRR
        credit numerator (_drr_share) both shrink to the degraded
        width, so a half-width mesh sheds typed at its ACTUAL capacity
        instead of queueing into deadline-shed p99 explosions."""
        full = self._mesh
        if full is None or full.size <= 0:
            frac = 1.0
        elif self._mesh_demoted is not None:
            frac = 1.0 / float(full.size)
        elif self._mesh_serving is not None:
            frac = float(self._mesh_serving.size) / float(full.size)
        else:
            frac = 1.0
        self._mesh_capacity = frac
        metrics.MeshCapacity.set(frac)
        entries = self.config.shed_queue_entries
        if entries:
            # Floor deep degradation at session_share_min so the cap
            # never starves admission entirely — but the floor must
            # never RAISE a small configured cap above its full-width
            # value (the operator's bound wins at frac=1.0).
            self.dispatcher.scale_admission(
                min(entries,
                    max(int(entries * frac),
                        self.config.session_share_min))
            )
        # Invalidate the lazy DRR share so the very next admission
        # sees the new fraction (not up to 50ms later).
        self._share_ts = 0.0

    def _mesh_rung(self) -> str:
        """The CURRENT width-ladder rung, derived from the two mesh
        pointers (the ladder is a ``derived``-kind typestate: no single
        stored field, so flip sites validate their edge through
        MESH_LADDER_PROTOCOL.advance against this derivation)."""
        if self._mesh_demoted is not None:
            return MESH_FALLBACK
        if self._mesh_serving is not None:
            return MESH_RESHAPED
        return MESH_FULL

    def _demote_mesh(self, reason: str, exc=None) -> None:
        """PR 2 ladder, mesh rung: a lost/erroring mesh device demotes
        the whole service to the single-chip executables — one pointer
        pass under _lock, typed (mesh_demotions_total{reason}) and
        counted, never a wedged round.  The dispatch path never
        resumes collectives on its own: the fault is attributed to its
        device(s) (health table + _mesh_lost) and an IMMEDIATE
        off-path reshape job walks the width ladder down around them
        (_run_mesh_ladder) — the fallback rung covers only the rebuild
        window; un-attributable faults hold demoted until the timed
        re-probe re-adjudicates.  With mesh_reprobe_interval_s = 0 the
        pre-PR-12 sticky-until-restart behavior holds."""
        attributed = self._attribute_fault_devices(exc)
        swapped = 0
        first = False
        with self._lock:
            # Fold the attribution in even when already demoted (a
            # second chip dying on the fallback rung still belongs in
            # the health table and the next reshape's dead set).
            self._mesh_lost |= attributed
            if self._mesh_demoted is None:
                first = True
                with blackbox.annotate(reason=reason):
                    MESH_LADDER_PROTOCOL.advance(self._mesh_rung(),
                                                 MESH_FALLBACK)
                self._mesh_demoted = reason
                self._mesh_serving = None
                self._mesh_fault_at = time.monotonic()
                # Pace the first re-probe one full interval after the
                # demotion (a device that just failed rarely heals
                # instantly).
                self._mesh_reprobe_last = self._mesh_fault_at
                for eng in self._engines.values():
                    m = getattr(eng, "model", None)
                    fb = getattr(m, "fallback", None)
                    if fb is not None:
                        # Retain the sharded wrapper for
                        # re-promotion: its tables are
                        # host-rebuildable state, and a flip back
                        # after a successful probe is one pointer
                        # pass.  A demotion FROM the reshaped rung
                        # keeps the earlier FULL-width retained
                        # wrapper (the reshaped model is rebuilt,
                        # never retained).  If the devices are still
                        # bad, the next sharded dispatch demotes
                        # again, typed — never a crashed round.
                        if getattr(eng, "_mesh_model", None) is None:
                            eng._mesh_model = m
                        eng.model = fb
                        # Sharded models are shape-keyed
                        # (dispatch_bare), so no per-id cache entry
                        # exists to drop; the compiled mesh
                        # executables stay in the shape cache as
                        # inert entries (demoted dispatch resolves
                        # through _live_model before any lookup).
                        swapped += 1
        for dev_id in sorted(attributed):
            self.guard.record_device_fault(dev_id, reason)
        if not first:
            return
        self.mesh_demotions[reason] = (
            self.mesh_demotions.get(reason, 0) + 1
        )
        metrics.MeshDemotions.inc(reason)
        metrics.MeshActive.set(0.0)
        metrics.MeshLostDevices.set(float(len(self._mesh_lost)))
        self._publish_mesh_capacity()
        log.error(
            "mesh serving demoted to single-chip executables (%s): "
            "%d engine(s) flipped, %d device(s) attributed", reason,
            swapped, len(attributed),
        )
        # Walk the ladder DOWN off-path right away (no paced wait):
        # with attributed/probed-dead devices the builder rebuilds a
        # reshaped mesh over the survivors and the fallback rung lasts
        # only the rebuild window.
        if self.config.mesh_reshape and self.config.mesh_reprobe_interval_s:
            self._build_queue.put(("mesh_reshape", None))

    def _maybe_mesh_reprobe(self) -> None:
        """Traffic-driven re-promotion pacing (called once per dispatch
        round, like guard.maybe_reprobe): while BELOW the full rung
        (demoted or reshaped), queue at most one off-path ladder walk
        per mesh_reprobe_interval_s onto the policy-builder thread —
        the walk promotes back up (reshaped -> full, fallback ->
        reshaped/full) as devices heal.  0 disables (sticky)."""
        interval = self.config.mesh_reprobe_interval_s
        if not interval or (
            self._mesh_demoted is None and self._mesh_serving is None
        ):
            return
        if self.guard.quarantined:
            # Never queue a compile+dispatch against a quarantined
            # device: a HUNG device (the case quarantine exists for)
            # would wedge the builder thread — and with it every
            # future swap/rebind — behind the probe.  The pacing
            # clock retries after the guard's own re-probe heals.
            return
        now = time.monotonic()
        with self._lock:
            if self._mesh_reprobe_inflight:
                return
            if now - self._mesh_reprobe_last < interval:
                return
            self._mesh_reprobe_inflight = True
            self._mesh_reprobe_last = now
        self._build_queue.put(("mesh_reprobe", None))

    # Probe rows for the re-promotion parity check: a remote-gated
    # literal row, a regex row, and an always-match row — enough to
    # exercise the stacked tables, the cross-shard attribution reduce,
    # and the padding rows of an unbalanced split.
    _MESH_PROBE_ROWS = (
        (frozenset({7}), "READ", "/public/.*"),
        (frozenset(), "HALT", ""),
        (frozenset({9}), "", ""),
    )

    def _mesh_probe_batch(self):
        """Probe batch shared by every ladder parity/materialization
        check: five frames covering remote-gated literal, regex,
        always-match and padding rows."""
        b = max(self.MIN_BUCKET_GREEDY, self._mesh_min_bucket)
        width = self.config.batch_width
        data = np.zeros((b, width), np.uint8)
        lens = np.zeros(b, np.int32)
        rems = np.zeros(b, np.int32)
        cases = [
            (b"READ /public/app\r\n", 7),
            (b"READ /public/app\r\n", 8),
            (b"HALT\r\n", 3),
            (b"WRITE /x\r\n", 9),
            (b"RESET\r\n", 9),
        ]
        for i, (frame, rem) in enumerate(cases):
            row = np.frombuffer(frame, np.uint8)
            data[i, : len(row)] = row
            lens[i] = len(row)
            rems[i] = rem
        return data, lens, rems

    def _mesh_parity_probe(self, mesh) -> bool:
        """Rebuild ONE sharded probe wrapper from scratch against
        ``mesh``, run it beside its single-chip twin over the probe
        batch, and require bit-identical (allow, rule) output — the
        gate EVERY ladder flip (reshape or re-promotion) must pass
        before any engine pointer moves."""
        from ..parallel.mesh import RULE_AXIS
        from ..parallel.rulesharding import (
            ShardedVerdictModel,
            build_sharded_r2d2_from_rows,
            shard_offsets,
        )
        from ..models.r2d2 import build_r2d2_model_from_rows

        rows = list(self._MESH_PROBE_ROWS)
        n_shards = mesh.shape[RULE_AXIS]
        with self._device_ctx():
            probe = ShardedVerdictModel(
                build_sharded_r2d2_from_rows(
                    rows, n_shards, bucket=True
                ),
                shard_offsets(len(rows), n_shards),
                mesh, "r2d2",
                # lint: disable=R23 -- parity-probe twin: built, compared, and discarded in this function — never a resident serving executable, so ledgering it would inflate the compile census with probe noise
                fallback=build_r2d2_model_from_rows(
                    rows, bucket=True
                ),
            )
        data, lens, rems = self._mesh_probe_batch()
        fb = probe.fallback
        with self._device_ctx():
            _, _, a_s, r_s = probe.verdicts_attr(data, lens, rems)
            _, _, a_f, r_f = fb.verdicts_attr(data, lens, rems)
        return bool(
            np.array_equal(np.asarray(a_s), np.asarray(a_f))
            and np.array_equal(np.asarray(r_s), np.asarray(r_f))
        )

    def _reshape_failed(self, reason: str) -> None:
        self.mesh_reshape_failures[reason] = (
            self.mesh_reshape_failures.get(reason, 0) + 1
        )

    def _run_mesh_ladder(self, immediate: bool) -> None:
        """Builder-thread walk of the mesh width ladder: adjudicate
        the dead device set (per-device probes + the attributed
        _mesh_lost), pick the target rung (full when nothing is dead,
        else the widest bucketable mesh over the survivors), parity-
        gate it against the single-chip twin, and flip every engine
        onto it in one pointer pass.  ``immediate`` is the post-fault
        job _demote_mesh queues: it only walks DOWN (a fault with no
        attributable dead device holds the fallback rung for the
        paced walk to adjudicate — transient XLA errors must not
        promote themselves).  A second fault racing the walk aborts
        the flip typed and falls through to the rung ITS demotion
        chose.  Failure anywhere leaves the current rung in place and
        the pacing clock owns the retry."""
        try:
            full = self._mesh
            if full is None:
                return
            with self._lock:
                demoted = self._mesh_demoted
                serving = self._mesh_serving
                prev_lost = set(self._mesh_lost)
            if demoted is None and serving is None:
                return  # full rung — stale job
            # Re-checked on the builder thread: quarantine may have
            # latched between queueing and execution (same hung-device
            # hazard _maybe_mesh_reprobe gates against).
            if self.guard.quarantined:
                return
            from ..parallel.mesh import FLOW_AXIS, RULE_AXIS, reshape_mesh

            # -- adjudicate the dead set -------------------------------
            dead = self._probe_mesh_devices(list(full.devices.flat))
            if immediate:
                dead |= prev_lost
                if not dead:
                    return
            else:
                for dev_id in sorted(prev_lost - dead):
                    self.guard.mark_device_ok(dev_id)
            with self._lock:
                self._mesh_lost = set(dead)
            metrics.MeshLostDevices.set(float(len(dead)))
            if demoted is None and serving is not None and dead == prev_lost:
                return  # reshaped rung already matches the dead set
            # -- pick the target rung ----------------------------------
            d0 = sum(self.mesh_demotions.values())
            target = None
            if not dead:
                target = full
            elif self.config.mesh_reshape:
                survivors = [
                    d for d in full.devices.flat if d.id not in dead
                ]
                with self._device_ctx():
                    target = reshape_mesh(
                        survivors, full.shape[RULE_AXIS],
                        max_flow=full.shape[FLOW_AXIS],
                    )
            if target is None:
                reason = (
                    "below-min-width" if self.config.mesh_reshape
                    else "reshape-disabled"
                )
                self._reshape_failed(reason)
                if demoted is None:
                    # Serving reshaped but the dead set grew past any
                    # bucketable width: drop to the fallback rung via
                    # the typed pointer pass, never a raw state write.
                    self._demote_mesh(reason)
                return
            # -- parity-gate the target --------------------------------
            if not self._mesh_parity_probe(target):
                self._reshape_failed("parity")
                log.warning(
                    "mesh ladder parity mismatch at (flows=%d, "
                    "rules=%d); rung holds",
                    target.shape[FLOW_AXIS], target.shape[RULE_AXIS],
                )
                return
            if target is full and serving is None:
                self._promote_mesh_classic(d0)
                return
            # -- rebuild + flip (reshape down, or reshaped -> full) ----
            with ledger_mod.cause_scope(
                ledger_mod.CAUSE_REPROMOTION if target is full
                else ledger_mod.CAUSE_MESH_RESHAPE,
                epoch=self.policy_epoch,
            ):
                builds = self._rebuild_engines_on(target)
            flipped = 0
            with self._lock:
                if sum(self.mesh_demotions.values()) != d0:
                    # A second fault raced this walk: abort the flip
                    # typed — the new demotion queued its own
                    # immediate job, which re-walks the ladder with
                    # the grown dead set (the next rung down).
                    self._reshape_failed("raced-fault")
                    return
                for key, (eng, built, epoch0, old) in builds.items():
                    cur = self._engines.get(key)
                    if (
                        cur is not eng
                        or getattr(eng, "epoch", 0) != epoch0
                        or eng.model is not old
                    ):
                        continue  # swapped mid-build: the swap built
                        # against _serving_mesh already
                    eng.model = built
                    if target is full:
                        eng._mesh_model = None
                    flipped += 1
                with blackbox.annotate(
                    reason="repromote" if target is full else "reshape"
                ):
                    MESH_LADDER_PROTOCOL.advance(
                        self._mesh_rung(),
                        MESH_FULL if target is full else MESH_RESHAPED,
                    )
                self._mesh_serving = None if target is full else target
                self._mesh_demoted = None
            if target is full:
                self.mesh_repromotions += 1
                metrics.MeshRepromotions.inc()
                log.info(
                    "mesh serving re-promoted to full width after "
                    "off-path parity probe (%d engine(s) rebuilt)",
                    flipped,
                )
            else:
                self.mesh_reshapes += 1
                metrics.MeshReshapes.inc()
                if serving is None and self._mesh_fault_at:
                    self.mesh_reshape_window_ms = (
                        time.monotonic() - self._mesh_fault_at
                    ) * 1e3
                log.warning(
                    "mesh RESHAPED around %d dead device(s) %s: "
                    "serving (flows=%d, rules=%d), %d engine(s) "
                    "flipped", len(dead), sorted(dead),
                    target.shape[FLOW_AXIS], target.shape[RULE_AXIS],
                    flipped,
                )
            metrics.MeshActive.set(1.0)
            self._publish_mesh_capacity()
        except Exception:  # noqa: BLE001 — rung holds, retry paced
            log.exception("mesh ladder walk failed; rung holds")
        finally:
            with self._lock:
                self._mesh_reprobe_inflight = False

    def _promote_mesh_classic(self, d0: int) -> None:
        """Fallback -> full promotion when every device answers: the
        retained sharded wrappers flip back in one pointer pass under
        _lock (typed, counted); engines built DURING the demotion get
        their sharded rebuilds queued (ROADMAP 1c) instead of waiting
        for the next epoch swap."""
        data, lens, rems = self._mesh_probe_batch()
        # Probe one RETAINED wrapper too: its device buffers must
        # still answer (a restarted device may have dropped them —
        # then the flip-back would only re-demote, typed, so this
        # probe keeps that churn off the dispatch path).
        with self._lock:
            retained = [
                getattr(e, "_mesh_model", None)
                for e in self._engines.values()
            ]
        retained = [m for m in retained if m is not None]
        if retained:
            with self._device_ctx():
                out = retained[0](data, lens, rems)
                np.asarray(out[-1])
        promoted = 0
        rebuilds: list = []
        with self._lock:
            if self._mesh_demoted is None:
                return  # raced a concurrent heal
            if sum(self.mesh_demotions.values()) != d0:
                return  # raced a concurrent fault
            for eng in self._engines.values():
                mm = getattr(eng, "_mesh_model", None)
                if mm is not None:
                    eng.model = mm
                    eng._mesh_model = None
                    promoted += 1
            with blackbox.annotate(reason="probe-heal"):
                MESH_LADDER_PROTOCOL.advance(self._mesh_rung(),
                                             MESH_FULL)
            self._mesh_demoted = None
            self._mesh_serving = None
            # ROADMAP 1c: engines BUILT while demoted hold plain
            # single-chip models (no retained wrapper, no
            # fallback attr) — queue their sharded rebuilds so
            # they heal too instead of waiting for the next epoch
            # swap.  (Re-promoted engines above now expose
            # .fallback and drop out of this scan.)
            if not self.config.seam_probe:
                for key, eng in self._engines.items():
                    m = getattr(eng, "model", None)
                    if (
                        key[4] in ("r2d2", "http", "dns")
                        and getattr(eng, "_mesh_model", None) is None
                        and m is not None
                        and not isinstance(m, ConstVerdict)
                        and getattr(m, "fallback", None) is None
                    ):
                        rebuilds.append(
                            (key, getattr(eng, "epoch", 0))
                        )
        for job in rebuilds:
            self._build_queue.put(("mesh_rebuild", job))
        self.mesh_repromotions += 1
        metrics.MeshRepromotions.inc()
        metrics.MeshActive.set(1.0)
        self._publish_mesh_capacity()
        log.info(
            "mesh serving re-promoted after off-path parity probe "
            "(%d engine(s) flipped back)", promoted,
        )

    def _rebuild_engines_on(self, mesh) -> dict:
        """Off-path rebuild of every meshable engine's model against
        ``mesh`` (the reshape fan-out): returns {key: (engine, built,
        epoch0, old_model)} for the flip pass to apply under _lock
        with staleness checks (engine replaced, epoch moved, model
        pointer moved — any of which means an epoch swap already
        rebuilt it against _serving_mesh)."""
        with self._lock:
            snap = [
                (key, eng, getattr(eng, "epoch", 0),
                 getattr(eng, "model", None))
                for key, eng in self._engines.items()
            ]
        builds: dict = {}
        for key, eng, epoch0, old in snap:
            if key[4] not in ("r2d2", "http", "dns"):
                continue
            if old is None or isinstance(old, ConstVerdict):
                continue
            built = self._build_mesh_model_for(key, mesh)
            if built is not None:
                builds[key] = (eng, built, epoch0, old)
        return builds

    def _run_mesh_rebuild(self, key: tuple, epoch0: int) -> None:
        """Builder-thread half of the ROADMAP 1c heal: rebuild ONE
        demotion-era engine's model against the live mesh and flip the
        pointer in — only if the engine is still registered under the
        same key, its epoch has not moved (a swap would have rebuilt
        it sharded already), and the mesh is still promoted.  Verdicts
        are bit-identical across the flip by the sharding parity
        contract (same policy rows, same flattened order), so a
        mid-round flip is as safe as the demotion flip itself."""
        with self._lock:
            eng = self._engines.get(key)
        if (
            eng is None
            or self._mesh_demoted is not None
            or self.guard.quarantined
            or getattr(eng, "epoch", 0) != epoch0
        ):
            return
        model = getattr(eng, "model", None)
        if (
            model is None
            or isinstance(model, ConstVerdict)
            or getattr(model, "fallback", None) is not None
        ):
            return  # already sharded (or nothing to shard)
        # The CURRENT rung's mesh: a rebind while the service runs
        # reshaped must shard onto the survivor mesh, never the full
        # layout a dead chip would fault.
        mesh = self._serving_mesh()
        if mesh is None:
            return
        # A demotion-era engine healing onto the promoted mesh is the
        # tail of the repromotion, so its build books under that cause.
        with ledger_mod.cause_scope(ledger_mod.CAUSE_REPROMOTION,
                                    epoch=self.policy_epoch):
            built = self._build_mesh_model_for(key, mesh)
        if built is None:
            return
        with self._lock:
            if (
                self._engines.get(key) is eng
                and self._mesh_demoted is None
                and getattr(eng, "epoch", 0) == epoch0
                and eng.model is model
            ):
                eng.model = built
                self.mesh_rebind_rebuilds += 1
                metrics.MeshRebindRebuilds.inc()
                log.info(
                    "mesh rebind: demotion-era engine %r re-serving "
                    "sharded", key,
                )

    def _build_mesh_model_for(self, key: tuple, mesh):
        """Off-path build of ONE engine's sharded model against
        ``mesh`` (the single assembly seam shared by the rebind heal
        and the reshape fan-out): resolve the engine's policy through
        the module registry, build the family's sharded wrapper with
        its single-chip twin, and materialize one probe call so a
        broken mesh fails HERE (typed, demotion path) and never on
        dispatch.  None when the policy folded to a constant, the
        module is gone, or the build/probe fails — the engine then
        keeps its current model."""
        module_id, policy_name, ingress, port, proto = key
        if proto not in ("r2d2", "http", "dns"):
            return None
        ins = pl.find_instance(module_id)
        if ins is None:
            return None
        policy = ins.policy_map().get(policy_name)
        t0 = time.perf_counter()
        try:
            with self._device_ctx():
                # lint: disable=R12 -- off-path builder-thread rebuild (the mesh-heal/reshape rung), never the dispatch loop
                if proto == "r2d2":
                    from ..parallel.rulesharding import mesh_r2d2_model

                    built = mesh_r2d2_model(policy, ingress, port, mesh)
                elif proto == "dns":
                    from ..parallel.rulesharding import mesh_dns_model

                    built = mesh_dns_model(policy, ingress, port, mesh)
                else:
                    from ..parallel.rulesharding import mesh_http_model

                    built = mesh_http_model(policy, ingress, port, mesh)
                if getattr(built, "fallback", None) is None:
                    return None  # folded to a constant: nothing to flip
                b = max(self.MIN_BUCKET_GREEDY, self._mesh_min_bucket)
                w = self.config.batch_width
                out = built(
                    np.zeros((b, w), np.uint8),
                    np.zeros(b, np.int32),
                    np.zeros(b, np.int32),
                )
                np.asarray(out[-1])
        except Exception:  # noqa: BLE001 — engine keeps its model
            log.exception("mesh model build failed for %r", key)
            return None
        # Cause rides the caller's scope: mesh-reshape from the ladder
        # walk, repromotion from the full-width flip / 1c heal.
        try:
            self.ledger.record_compile(
                proto, time.perf_counter() - t0,
                shape=self._model_shape_key(built),
                rules=self._rule_bucket_of(built),
                mesh=tuple(sorted(
                    (getattr(mesh, "shape", None) or {}).items()
                )),
                kind="engine-build", epoch=self.policy_epoch,
            )
        except Exception:  # noqa: BLE001 — ledger must not cost the build
            pass
        return built

    def _mesh_guarded(self, model, call):
        """Issue one device dispatch; when a SHARDED dispatch raises
        (lost mesh device, failed collective, transfer error), demote
        the mesh rung typed and reissue on the single-chip fallback so
        the round is answered instead of crashed."""
        try:
            return call(model)
        except Exception as exc:
            fb = getattr(model, "fallback", None)
            if fb is None:
                raise
            log.exception(
                "sharded dispatch failed; demoting to single-chip"
            )
            # The exception text carries the fault attribution (which
            # shard/device raised) — the reshape ladder walks down
            # around exactly those devices.
            self._demote_mesh("device-call", exc=exc)
            return call(fb)

    def _mesh_status(self) -> dict | None:
        """Mesh-rung status surface: None while unresolved (no engine
        built yet) or when multi-chip serving is off."""
        if not self._mesh_resolved or self._mesh is None:
            return None
        from ..parallel.mesh import FLOW_AXIS, RULE_AXIS

        serving = self._mesh_serving
        if self._mesh_demoted is not None:
            rung = "fallback"
        elif serving is not None:
            rung = "reshaped"
        else:
            rung = "full"
        return {
            "devices": int(self._mesh.size),
            "flow_shards": int(self._mesh.shape[FLOW_AXIS]),
            "rule_shards": int(self._mesh.shape[RULE_AXIS]),
            "active": self._mesh_demoted is None,
            "demoted": self._mesh_demoted,
            "demotions": dict(self.mesh_demotions),
            "repromotions": self.mesh_repromotions,
            "rebind_rebuilds": self.mesh_rebind_rebuilds,
            # Width-ladder state: the current rung, the width actually
            # serving, the attributed dead set, and the admission
            # coupling — the operator's one look at "how degraded".
            "rung": rung,
            "serving_devices": (
                1 if rung == "fallback"
                else int((serving or self._mesh).size)
            ),
            "lost_devices": sorted(self._mesh_lost),
            "reshapes": self.mesh_reshapes,
            "reshape_failures": dict(self.mesh_reshape_failures),
            "capacity_frac": self._mesh_capacity,
            "reshape_window_ms": self.mesh_reshape_window_ms,
        }

    def _model_call(self, model, data, lens, remotes, use_jit=None):
        """One device dispatch per batch.  The mode is a MEASURED
        config (config.dispatch_mode): 'eager' pipelines per-op async
        dispatch, 'jit' fuses the model into one launch; 'auto' times
        both at first prewarm (the service's real pattern: async issue
        + one batched readback) and keeps the faster.  ``use_jit``
        overrides the resolved mode (used by the measurement itself so
        it never mutates shared state mid-flight)."""
        uj = self._use_jit if use_jit is None else use_jit

        def call(m):
            with self._device_ctx():
                if uj and not isinstance(m, ConstVerdict):
                    fn = self._jit_for(
                        self._jit_cache, m, m.__call__,
                        arg_fn=_call_model,
                    )
                    return fn(data, lens, remotes)
                return m(data, lens, remotes)

        return self._mesh_guarded(self._live_model(model), call)

    def _model_call_attr(self, model, data, lens, remotes):
        """_model_call plus device-side rule attribution: returns
        (complete, msg_len, allow, rule-or-None).  The rule index rides
        the SAME fused computation (an argmax over the hit matrix the
        verdict reduction already builds — no extra device pass; on a
        mesh, the shard-local argmax plus the cross-shard min-index
        reduction, still one device round); when flow observability is
        off or the model has no attributed variant, this degrades to
        the plain call with rule None."""
        model = self._live_model(model)
        fn = (
            getattr(model, "verdicts_attr", None)
            if self._flow_observe else None
        )
        if fn is None:
            c, m, a = self._model_call(model, data, lens, remotes)
            return c, m, a, None
        uj = self._use_jit

        def call(m):
            with self._device_ctx():
                if uj and not isinstance(m, ConstVerdict):
                    jfn = self._jit_for(
                        self._jit_attr, m,
                        lambda d, ln, r: m.verdicts_attr(d, ln, r),
                        arg_fn=_call_model_attr,
                    )
                    return jfn(data, lens, remotes)
                return m.verdicts_attr(data, lens, remotes)

        return self._mesh_guarded(model, call)

    def _measure_dispatch_mode(self, engine) -> None:
        """Resolve dispatch_mode='auto': time the service's ACTUAL
        per-round pattern — issue N batches without blocking, then ONE
        batched ``jax.device_get`` — in each mode and keep the faster.
        (Timing ``block_until_ready`` instead would measure N serial
        readbacks and mask the dispatch-side difference: on a
        high-latency link each jit launch blocks ~1 RTT while eager op
        dispatch streams asynchronously.)"""
        import time as _time

        import jax

        b = self._min_bucket
        width = self.config.batch_width
        data = np.zeros((b, width), np.uint8)
        lens = np.zeros(b, np.int32)
        rem = np.zeros(b, np.int32)

        def burst(uj: bool) -> float:
            outs = [
                self._model_call(engine.model, data, lens, rem, use_jit=uj)[-1]
                for _ in range(8)
            ]
            jax.device_get(outs)  # warm (compile / first launch)
            t0 = _time.perf_counter()
            outs = [
                self._model_call(engine.model, data, lens, rem, use_jit=uj)[-1]
                for _ in range(8)
            ]
            jax.device_get(outs)
            return _time.perf_counter() - t0

        t_eager = burst(False)
        t_jit = burst(True)
        self._use_jit = t_jit < t_eager
        self.dispatch_mode_chosen = "jit" if self._use_jit else "eager"
        log.info(
            "dispatch mode auto: eager=%.1fms jit=%.1fms -> %s",
            t_eager * 1e3, t_jit * 1e3, self.dispatch_mode_chosen,
        )

    def _model_shape_key(self, model):
        """Hashable shape signature for a shape-cacheable model, or
        None — THE one key derivation shared by the shape-keyed jit
        caches and the prewarm-skip check (a second copy could drift
        and silently unpair them).  Memoized on the model: tables are
        immutable after build, and the flatten would otherwise run per
        dispatch."""
        key = getattr(model, "_shape_key_memo", None)
        if key is not None:
            return key
        bare_fn = getattr(model, "dispatch_bare", None)
        if bare_fn is None:
            return None
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(bare_fn())
        key = (
            treedef,
            tuple((tuple(lf.shape), str(lf.dtype)) for lf in leaves),
        )
        try:
            model._shape_key_memo = key
        except Exception:  # noqa: BLE001 — slots/frozen models: skip memo
            pass
        return key

    def _shape_key_cached(self, cache: dict, model) -> bool:
        """True when the model's shape-keyed executables were already
        warmed — a churn rebuild of a same-bucketed table then skips
        the warm launches entirely (the whole point of the bucketed
        shapes: repeat churn costs an array upload, not a trace, and
        not even a warm launch)."""
        key = self._model_shape_key(model)
        return key is not None and key in cache

    def _mark_shape_prewarmed(self, model) -> None:
        key = self._model_shape_key(model)
        if key is None:
            return
        # No private eviction loop here (the PR 20 dedupe): a warmed
        # shape lives in at least one shape-keyed jit cache, and
        # _evict_shape_entries — the ONE eviction path, which also
        # moves the ledger's resident gauge — pops this dict alongside
        # the cache entry, so this book stays bounded by the caches'
        # SHAPE_CACHE_MAX without a second definition of "resident".
        self._prewarmed_shapes[key] = True

    def prewarm(self, engine) -> bool:
        """Compile the engine model for every bucket shape up front so
        the first real batch never pays a compile.  Shape-cached models
        (r2d2) whose executable already exists — churn rebuilding a
        same-bucketed table — skip the warm launches entirely.
        Returns True when any warm launch actually ran (the signal the
        engine-build ledger record is gated on: a fully-warm rebuild
        produced no executable and records nothing).  Warm launches
        record cause ``prewarm`` — off-path warming is its own cause
        regardless of what provoked the build; the provoking cause
        (cold/churn/mesh) rides the engine-build record instead."""
        if isinstance(engine.model, ConstVerdict):
            return False
        with ledger_mod.cause_scope(ledger_mod.CAUSE_PREWARM,
                                    epoch=self.policy_epoch):
            if not self._dispatch_resolved:
                with self._dispatch_lock:
                    if not self._dispatch_resolved:
                        # lint: disable=R12 -- one-time dispatch-mode probe at the FIRST prewarm ever (double-checked): the lock exists precisely to run this measurement once; prewarm runs on reader/builder threads, never dispatch
                        self._measure_dispatch_mode(engine)
                        self._dispatch_resolved = True
            warmed = self._prewarm_model(engine.model)
            fb = getattr(engine.model, "fallback", None)
            if fb is not None:
                # The demotion rung warms at build too: a device-loss
                # flip must not pay its first single-chip compile on
                # the dispatch path.
                warmed = self._prewarm_model(fb) or warmed
        return warmed

    def _prewarm_model(self, model) -> bool:
        if self._shape_key_cached(self._prewarmed_shapes, model):
            return False
        width = self.config.batch_width
        for b in self._buckets():
            # The attributed variant is the serving-path call when flow
            # observability is on; it degrades to the plain call (rule
            # None) otherwise — either way this warms the executable
            # real rounds will launch.
            out = self._model_call_attr(
                model,
                np.zeros((b, width), np.uint8),
                np.zeros(b, np.int32),
                np.zeros(b, np.int32),
            )
            np.asarray(out[2])
            if not self._inline_complete:
                # The gather (blob-window) path has its own executable
                # per flow bucket — warm it so first real traffic never
                # pays a compile on the high-latency link.  Greedy
                # (co-located) services skip this: their compiles are
                # local and cheap, so first-use compiles lazily instead
                # of doubling every engine build.
                allow, _rule = self._gathered_call(
                    model,
                    np.zeros(self.BLOB_CHUNK, np.uint8),
                    np.zeros(b, np.int32),
                    np.zeros(b, np.int32),
                    np.zeros(b, np.int32),
                )
                np.asarray(allow)
        self._mark_shape_prewarmed(model)
        return True

    @staticmethod
    def _framing_alignment_mask(snap, eng_idx, cand, aligner):
        """THE per-engine frame-alignment mask of the verdict-cache
        tiers (whole-item and columnar Phase-A share it so the two can
        never drift): for every engine among the candidate rows,
        resolve its framing (CRLF fallback for conns without a
        table-resident engine — the http judge tier and other
        non-vectorized engines keep the historic PR 12 CRLF tail
        gate) and apply ``aligner(framing, row_mask)``."""
        aligned = np.zeros(len(cand), bool)
        for e in np.unique(eng_idx[cand]):
            framing = (
                _engine_framing(snap.objs[int(e)]) if e >= 0 else None
            ) or FRAMINGS[FRAMING_CRLF]
            selm = cand & (eng_idx == e)
            aligned[selm] = aligner(framing, selm)
        return aligned

    def _cache_item_hits(self, it, snap: "_TabSnap"):
        """Per-entry verdict-cache hit mask for one data/mat item, or
        None when nothing hits.  A hit requires: armed row, claim epoch
        == the snapshot's policy epoch (the structural invalidation),
        no residual state (clean dirty bit), request direction, and a
        frame-aligned payload (ends with CRLF) so an invalidation at
        any later point leaves the flow parseable from a boundary."""
        kind, _client, b = it
        n = b.count
        if n == 0:
            return None
        pos = snap.lookup(b.conn_ids)
        hit = (
            (snap.cache[pos] == 1)
            & (snap.cache_epoch[pos] == snap.epoch)
            & (snap.dirty[pos] == 0)
        )
        if not hit.any():
            return None
        if kind != "mat":
            hit &= b.flags == 0
            blob = np.frombuffer(b.blob, np.uint8)
            lengths = b.lengths.astype(np.int64)
            starts = b.offsets[:-1].astype(np.int64)
            if len(blob) != int(lengths.sum()):
                return None
        # Frame alignment per the hitting conns' ENGINE framing: a
        # short-circuit must only ever cover whole frames of that
        # framing (_framing_alignment_mask is the one definition).
        if kind == "mat":
            def aligner(framing, selm):
                return framing.rows_aligned(b.rows[selm], b.lengths[selm])
        else:
            def aligner(framing, selm):
                return framing.segments_aligned(
                    blob, starts[selm], lengths[selm]
                )
        hit &= self._framing_alignment_mask(
            snap, snap.engine[pos], hit, aligner
        )
        return hit if hit.any() else None

    def _count_cache_hits(self, n: int) -> None:
        self.cache_hits += n
        metrics.VerdictCacheHits.inc("service", amount=n)

    def _count_cache_misses(self, n: int) -> None:
        if self._flow_cache_on and n:
            self.cache_misses += n
            metrics.VerdictCacheMisses.inc(amount=n)

    def _flowlog_cached(self, snap: "_TabSnap", conn_ids: np.ndarray,
                        pos: np.ndarray) -> None:
        """Cached-path flow records for one hit group, one add_round
        per engine (the kinds legend the claimed rule rows index)."""
        if self.flowlog is None or not len(conn_ids):
            return
        eng_idx = snap.engine[pos]
        rules = snap.cache_rule[pos]
        for e in np.unique(eng_idx):
            selm = eng_idx == e
            engine = snap.objs[int(e)] if e >= 0 else None
            self._record_cached_round(
                conn_ids[selm].astype(np.int64),
                rules[selm],
                getattr(getattr(engine, "model", None),
                        "match_kinds", ()),
                snap.epoch,
            )

    def _serve_cached_items(self, items: list, snap: "_TabSnap",
                            t_pop: float) -> list:
        """Whole-item tier of the verdict cache: answer every item
        whose entries ALL hit with one `_verdict_body`-shaped all-allow
        frame (bit-identical to a recomputed all-allow vec round) and
        return the rest for the normal paths.  Per-conn FIFO holds: an
        item sharing a conn with a non-cached item in this round keeps
        the normal path, and pipelined-mode sends ride the completion
        FIFO so they can never overtake an in-flight earlier round."""
        t_c0 = time.monotonic()
        masks = [self._cache_item_hits(it, snap) for it in items]
        full = [m is not None and bool(m.all()) for m in masks]
        if not any(full):
            return items
        rest_items = [it for it, f in zip(items, full) if not f]
        rest_conns = None
        if rest_items:
            rest_conns = np.unique(np.concatenate(
                [it[2].conn_ids for it in rest_items]
            ))
        kept: list = []
        served: list = []
        for it, f in zip(items, full):
            if f and (
                rest_conns is None
                or not np.isin(it[2].conn_ids, rest_conns).any()
            ):
                served.append(it)
            else:
                kept.append(it)
        if not served:
            return items
        cache_s = time.monotonic() - t_c0
        swap_s = snap.swap_s
        snap.swap_s = 0.0
        for it in served:
            _kind, client, b = it
            n = b.count
            rt = self.tracer.begin_round(
                PATH_CACHED, n, self._oldest_arrival([it]), t_pop,
                ring_s=self._ring_wait([it]), swap_s=swap_s,
            )
            swap_s = 0.0
            rt.cache_s = cache_s
            cache_s = 0.0  # the mask cost books on the first round only
            rt.formed()
            rt.submitted()
            rt.completed()
            try:
                frame = self._verdict_frame(
                    b.seq, b.conn_ids, b.lengths,
                    np.ones(n, bool),
                )
            except Exception:  # noqa: BLE001 — fail closed, typed
                log.exception("cached verdict frame build failed")
                try:
                    if client.send_verdicts(
                        b.seq,
                        self._typed_entries(
                            b, FilterResult.UNKNOWN_ERROR
                        ),
                        batch=b,
                    ):
                        self.error_entries += n
                except Exception:  # noqa: BLE001
                    log.exception("typed error send failed")
                continue
            rt.drained()
            rtd = (rt, [self._batch_desc(b, client)])
            if self._inline_complete:
                try:
                    client.send(wire.MSG_VERDICT_BATCH, frame,
                                batches=[b])
                except Exception:  # noqa: BLE001 — client may be gone
                    log.exception("cached verdict send failed")
                if not self._round_thread_suppressed():
                    self.tracer.finish_round(rt, [self._batch_desc(b, client)])
            else:
                self._completion_put(("frame", client, frame, b, rtd))
            if not self._round_thread_suppressed():
                self._count_cache_hits(n)
                # LRU recency: one bulk stamp per served item (lock-
                # free like the hit mask itself; a racing table grow
                # only costs a stale stamp, never correctness).
                self._touch_cache_rows(b.conn_ids.astype(np.int64))
                self._flowlog_cached(
                    snap, b.conn_ids.astype(np.int64),
                    snap.lookup(b.conn_ids),
                )
        return kept

    def _run_vec(self, vec_items: list, snap: "_TabSnap",
                 t_pop: float) -> None:
        """One device call per engine chunk over the concatenated
        batches, ops emitted columnar straight from the verdict arrays."""
        self._count_cache_misses(
            sum(it[2].count for it, _ in vec_items)
        )
        groups: dict[int, list] = {}
        for it, eng in vec_items:
            groups.setdefault(id(eng), []).append((it, eng))
        # The snapshot's swap wait is booked on the round's FIRST trace
        # only (one blocked acquisition, however many path groups).
        swap_s = snap.swap_s
        snap.swap_s = 0.0
        for group in groups.values():
            engine = group[0][1]
            mats = [it for it, _ in group if it[0] == "mat"]
            datas = [it for it, _ in group if it[0] == "data"]
            # Matrix items arrive pre-padded: device chunks are plain
            # row-slices, no gather.  Aggregate across items so one
            # device pass covers the whole round.
            if mats:
                rt = self.tracer.begin_round(
                    PATH_VEC, sum(it[2].count for it in mats),
                    self._oldest_arrival(mats), t_pop,
                    ring_s=self._ring_wait(mats), swap_s=swap_s,
                )
                swap_s = 0.0
                if len(mats) == 1:
                    m_rows = mats[0][2].rows
                    m_lens = mats[0][2].lengths.astype(np.int32)
                    m_ids = mats[0][2].conn_ids
                else:
                    m_rows = np.concatenate([it[2].rows for it in mats])
                    m_lens = np.concatenate(
                        [it[2].lengths for it in mats]
                    ).astype(np.int32)
                    m_ids = np.concatenate([it[2].conn_ids for it in mats])
                rt.formed()
                issued = self._issue_chunks(engine, m_rows, m_lens, m_ids, snap)
                rt.submitted()
                sends, start = [], 0
                for _, client, mb in mats:
                    sends.append(
                        (client, mb.seq, mb.conn_ids, mb.lengths,
                         start, start + mb.count, mb)
                    )
                    start += mb.count
                if self._inline_complete:
                    self._finish_vec(issued, start, sends, rt, engine)
                else:
                    self._completion_put(
                        ("vec", issued, start, sends, rt, engine)
                    )
            if not datas:
                continue
            rt = self.tracer.begin_round(
                PATH_VEC, sum(it[2].count for it in datas),
                self._oldest_arrival(datas), t_pop,
                ring_s=self._ring_wait(datas), swap_s=swap_s,
            )
            swap_s = 0.0
            batches = [it[2] for it in datas]
            conn_ids = np.concatenate([b.conn_ids for b in batches])
            lengths = np.concatenate(
                [b.lengths for b in batches]
            ).astype(np.int32)
            blob = np.frombuffer(
                b"".join(b.blob for b in batches), np.uint8
            )
            n = len(conn_ids)
            offs = np.concatenate(
                ([0], np.cumsum(lengths, dtype=np.int64))
            )[:-1].astype(np.int32)
            rt.formed()
            issued = self._issue_chunks_blob(
                engine, blob, offs, lengths, conn_ids, snap
            )
            rt.submitted()
            sends, start = [], 0
            for _, client, batch in datas:
                sends.append(
                    (client, batch.seq, conn_ids[start : start + batch.count],
                     lengths[start : start + batch.count],
                     start, start + batch.count, batch)
                )
                start += batch.count
            if self._inline_complete:
                self._finish_vec(issued, n, sends, rt, engine)
            else:
                self._completion_put(("vec", issued, n, sends, rt, engine))

    def _issue_chunks(self, engine, rows, lengths, conn_ids,
                      snap: "_TabSnap") -> list:
        """Issue device calls over [n, width] rows in fixed bucket-shaped
        chunks WITHOUT blocking; returns [(allow_future, rule_future,
        a, b, cn)] (rule None without attribution) for the completion
        worker to materialize."""
        n = len(conn_ids)
        width = rows.shape[1]
        issued = []
        max_chunk = self.config.batch_flows
        for a in range(0, n, max_chunk):
            b = min(a + max_chunk, n)
            cn = b - a
            f_pad = self._min_bucket
            while f_pad < cn:
                f_pad *= 2
            if cn == f_pad:
                # Exact bucket fit: no pad-copy of the row matrix
                # (saves a ~0.5MB memcpy per full chunk on the hot path).
                data = rows[a:b]
                lens = lengths[a:b]
            else:
                data = np.zeros((f_pad, width), np.uint8)
                data[:cn] = rows[a:b]
                lens = np.zeros(f_pad, np.int32)
                lens[:cn] = lengths[a:b]
            remotes = np.zeros(f_pad, np.int32)
            remotes[:cn] = snap.src[snap.lookup(conn_ids[a:b])]
            _, _, chunk_allow, chunk_rule = self._model_call_attr(
                engine.model, data, lens, remotes
            )
            if self._inline_complete and hasattr(chunk_allow, "copy_to_host_async"):
                # Co-located/greedy mode materializes chunks
                # sequentially right after issue; starting the
                # device->host copies now lets them overlap.  On a
                # high-latency link this is NOT done: per-array copies
                # would defeat the completion worker's batched readback
                # (one round trip for all pending arrays).
                chunk_allow.copy_to_host_async()
                if chunk_rule is not None:
                    chunk_rule.copy_to_host_async()
            issued.append((chunk_allow, chunk_rule, a, b, cn))
        return issued

    # Fixed device blob window for the gather path: every chunk uploads
    # exactly this many payload bytes, so jit sees ONE blob shape per
    # flow bucket (prewarmable) while the uplink still carries
    # ~payload-sized traffic instead of width-padded rows.
    BLOB_CHUNK = 65536

    def _issue_chunks_blob(self, engine, blob, offs, lengths, conn_ids,
                           snap: "_TabSnap") -> list:
        """Like _issue_chunks, but uploads the EXACT payload bytes and
        builds the [n, width] row view with an on-device gather —
        decisive when the chip is behind a bandwidth-limited link, and
        a cheap HBM gather when co-located.  Chunks are cut by BOTH the
        flow cap and the BLOB_CHUNK byte window."""
        n = len(conn_ids)
        ends = offs.astype(np.int64) + lengths
        issued = []
        max_chunk = self.config.batch_flows
        a = 0
        while a < n:
            b = min(a + max_chunk, n)
            base = int(offs[a])
            if int(ends[b - 1]) - base > self.BLOB_CHUNK:
                b = int(
                    np.searchsorted(ends, base + self.BLOB_CHUNK, side="right")
                )
                b = max(b, a + 1)  # an entry never exceeds the window
            cn = b - a
            f_pad = self._min_bucket
            while f_pad < cn:
                f_pad *= 2
            nb = int(ends[b - 1]) - base
            bp = np.zeros(self.BLOB_CHUNK, np.uint8)
            bp[:nb] = blob[base : base + nb]
            o = np.zeros(f_pad, np.int32)
            o[:cn] = offs[a:b] - base
            lens = np.zeros(f_pad, np.int32)
            lens[:cn] = lengths[a:b]
            remotes = np.zeros(f_pad, np.int32)
            remotes[:cn] = snap.src[snap.lookup(conn_ids[a:b])]
            chunk_allow, chunk_rule = self._gathered_call(
                engine.model, bp, o, lens, remotes
            )
            if self._inline_complete and hasattr(chunk_allow, "copy_to_host_async"):
                chunk_allow.copy_to_host_async()
                if chunk_rule is not None:
                    chunk_rule.copy_to_host_async()
            issued.append((chunk_allow, chunk_rule, a, b, cn))
            a = b
        return issued

    def _gathered_call(self, model, blob_dev, offs, lens, remotes):
        """Dispatch gather+model as ONE jit executable — always jit,
        regardless of the measured row-path mode: the fused
        gather+model launch is a single dispatch on any transport,
        while an eager gather chain pays per-op dispatch (measured
        catastrophic — seconds per round — through the tunneled
        link).  Returns (allow, rule-or-None); with flow observability
        on and an attributed model, the rule argmax is fused into the
        same executable."""
        width = self.config.batch_width
        model = self._live_model(model)
        attr = self._flow_observe and hasattr(model, "verdicts_attr")

        def call(m):
            with self._device_ctx():
                fn = self._jit_for(
                    self._jit_gather,
                    m,
                    lambda bl, o, ln, r: _gather_model(
                        m, bl, o, ln, r, width, attr
                    ),
                    arg_fn=lambda mm, bl, o, ln, r: _gather_model(
                        mm, bl, o, ln, r, width, attr
                    ),
                )
                return fn(blob_dev, offs, lens, remotes)

        # ConstVerdict engines never reach here: vec eligibility
        # excludes them (their verdict needs no payload at all).
        out = self._mesh_guarded(model, call)
        if attr:
            return out[2], out[3]
        return out[-1], None

    def _completion_put(self, rec) -> None:
        """Queue a record into the completion pipeline tagged with the
        issuing thread's dispatcher ROUND id.  The stall watchdog sheds
        a stuck round's whole batch with typed SHED verdicts —
        including groups that round already handed to this pipeline —
        so the send loop must drop those groups' real verdicts or a
        client receives two replies for one seq (and misapplies ops on
        a shim that already consumed it).  The tag is per-round, not
        per-generation: a deposed worker's EARLIER rounds completed
        normally and were never shed, and suppressing their queued
        records would silently lose verdicts."""
        rid = getattr(threading.current_thread(), "_disp_round", None)
        self._completions.put((rid, rec))

    def _finish_vec(self, issued, n, sends, rt=None, engine=None) -> None:
        """Inline completion (greedy mode): materialize this round's
        futures and send — runs on the dispatcher thread, so per-conn
        FIFO order is trivially preserved.  The queue/worker variant in
        _completion_loop batches readbacks instead (high-latency link).
        Failures are isolated per chunk/per client like the queue path:
        one dead client or device error must not abort the round."""
        allow, rules = self._readback_chunks(issued, n)
        if rt is not None:
            rt.completed()  # fenced: np.asarray above IS the readback
        self.fast_log.log_batch(
            getattr(engine, "proto", "r2d2"), n, int(n - allow.sum())
        )
        self.vec_batches += 1
        self.vec_entries += n
        metrics.ProxyBatches.inc()
        self._send_vec_frames(
            sends, allow, getattr(engine, "DENY_INJECT", None)
        )
        if not self._round_thread_suppressed():
            if rt is not None:
                self.tracer.finish_round(
                    rt, [self._batch_desc(s[6], s[0]) for s in sends]
                )
            if engine is not None and sends:
                self._record_vec_round(
                    engine,
                    np.concatenate([s[2] for s in sends]),
                    allow, rules,
                )

    def _send_vec_frames(self, sends, allow,
                         deny_inject: bytes | None = None) -> None:
        """Emit a vec round's verdicts: one VERDICT_BATCH frame per
        original message, coalesced into one sendall (+ one writer-lock
        trip) per client — the dominant per-item cost in aggregated
        rounds.  Each message's wire batch rides along so send_frames
        marks it answered under the write lock before writing.  Frame
        build and client failures are isolated: one bad entry or dead
        client must not abort the rest of the round."""
        per_client: dict[int, tuple] = {}
        for client, seq, ids, lens, a, b, batch in sends:
            try:
                frame = self._verdict_frame(
                    seq, ids, lens, allow[a:b], deny_inject
                )
            except Exception:  # noqa: BLE001
                log.exception("verdict frame build failed")
                # Fail closed, never silent: the shim is owed exactly
                # one reply for this seq, and nothing downstream will
                # answer it (the round completes normally).
                try:
                    sent = client.send_verdicts(
                        seq,
                        self._typed_entries(
                            batch, FilterResult.UNKNOWN_ERROR
                        ),
                        batch=batch,
                    )
                except Exception:  # noqa: BLE001
                    log.exception("error response send failed")
                    continue
                if sent:  # see _shed_item: no double-booking
                    self.error_entries += batch.count
                continue
            _, frames, bs = per_client.setdefault(
                id(client), (client, [], [])
            )
            frames.append(frame)
            bs.append(batch)
        for client, frames, bs in per_client.values():
            try:
                client.send_frames(
                    wire.MSG_VERDICT_BATCH, frames, batches=bs
                )
            except Exception:  # noqa: BLE001 — client may be gone
                log.exception("verdict send failed")

    # Max concurrent device->host readbacks.  Measured on the tunneled
    # chip: one batched jax.device_get costs ~1 link RTT regardless of
    # array count, and 24 CONCURRENT gets still complete in ~1.3 RTT —
    # so G slots cut the "arrived mid-readback" wait from a full RTT
    # (r2's measured p99 was 2.0x RTT for exactly this reason) to
    # ~RTT/G, while the drain-coalescing below keeps the number of
    # outstanding gets bounded when rounds outpace the slots.  Sizing:
    # a get takes ~1.2 RTT end-to-end, so slots must cover
    # 1.2*RTT / round_interval concurrent groups — ~20 for 7ms rounds
    # on a 120ms link; 32 leaves headroom (24+ concurrent gets measured
    # to still complete in ~1.3 RTT).
    READBACK_SLOTS = 32

    def _completion_loop(self) -> None:
        """Stage 1 of the completion pipeline: drains pending records,
        coalesces them into one batched device→host readback per free
        slot (≤READBACK_SLOTS concurrent), and forwards each group with
        its readback future to the send loop in FIFO order."""
        import jax
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(
            max_workers=self.READBACK_SLOTS,
            thread_name_prefix="verdict-readback",
        )
        slots = threading.Semaphore(self.READBACK_SLOTS)

        def readback(futs):
            try:
                return jax.device_get(futs)
            finally:
                slots.release()

        def drain(recs):
            while True:
                try:
                    recs.append(self._completions.get_nowait())
                except queue.Empty:
                    return recs

        while True:
            recs = drain([self._completions.get()])
            # Wait for a readback slot; whatever lands meanwhile is
            # coalesced into this group's single batched get.
            slots.acquire()
            recs = drain(recs)
            stop = any(r[0] == "stop" for _rid, r in recs)
            futs = []
            for _rid, r in recs:
                if r[0] == "vec":
                    # Per chunk: the allow future, then (attribution
                    # on) the rule future — the send loop consumes
                    # them in the same order.
                    for fut, rfut, _, _, _ in r[1]:
                        futs.append(fut)
                        if rfut is not None:
                            futs.append(rfut)
                elif r[0] == "entry2":
                    futs.extend(r[1])
            if futs:
                vals_f = pool.submit(readback, futs)
            else:
                vals_f = None
                slots.release()
            self._sends.put((recs, vals_f, len(futs)))
            if stop:
                self._sends.put(None)
                pool.shutdown(wait=False)
                return

    def _send_loop(self) -> None:
        """Stage 2: waits on each group's readback IN ORDER and emits
        verdict batches — per-connection FIFO is preserved because
        sends happen on this one thread in submission order."""
        while True:
            item = self._sends.get()
            if item is None:
                return
            recs, vals_f, n_futs = item
            try:
                # Bounded wait: a readback stalled past the device
                # deadline quarantines the device and fails THIS group
                # closed (typed deny) instead of wedging the strictly-
                # FIFO send pipeline behind it forever.
                timeout = (
                    self.guard.timeout_s if self.guard.enabled else None
                )
                vals = vals_f.result(timeout) if vals_f is not None else []
            except _FuturesTimeout:
                # (concurrent.futures.TimeoutError is a distinct class
                # from the builtin TimeoutError before py3.11)
                log.error("device readback stalled; quarantining")
                self.guard.record_stall("readback-stall")
                metrics.DeviceStalls.inc()
                if self._mesh is not None and self._mesh_demoted is None:
                    # Same reasoning as the dispatch-stall demotion: a
                    # readback that never lands on a mesh means a
                    # device dropped out of the collective.
                    self._demote_mesh("device-stall")
                vals = [None] * n_futs
            except Exception:  # noqa: BLE001
                log.exception("device readback failed")
                vals = [None] * n_futs
            # One batched get covered every vec group in this drain:
            # stamp their fenced device-complete boundary NOW, before
            # earlier records' sends run, or later groups would book
            # sibling send time as device time.
            for _rid, r in recs:
                if r[0] == "vec":
                    r[4].completed()
            vi = 0
            cur = threading.current_thread()
            for rid, r in recs:
                # Adopt the record's round id for the duration of its
                # sends: a record issued by a round the stall watchdog
                # shed already had its whole batch answered with typed
                # SHED verdicts, so the thread_round_is_shed()
                # suppression in _ClientHandler.send* must cover THIS
                # thread's sends of that record too — or a client
                # receives both a real VERDICT_BATCH and a SHED batch
                # for one seq.  Rounds that completed before their
                # worker was deposed keep their own (un-shed) ids and
                # are emitted normally — never silently lost.
                cur._disp_round = rid
                try:
                    deposed = self.dispatcher.thread_round_is_shed()
                    if r[0] == "vec":
                        _, issued, n, sends, rt, engine = r
                        n_futs_round = sum(
                            2 if rfut is not None else 1
                            for _, rfut, _, _, _ in issued
                        )
                        if deposed:
                            vi += n_futs_round  # keep later slices aligned
                            continue
                        allow = np.empty(n, bool)
                        rules = np.full(n, -1, np.int32)
                        for _, rfut, a, b, cn in issued:
                            v = vals[vi]
                            vi += 1
                            rv = None
                            if rfut is not None:
                                rv = vals[vi]
                                vi += 1
                            if v is None:
                                allow[a:b] = False
                            else:
                                allow[a:b] = np.asarray(v)[:cn]
                                if rv is not None:
                                    rules[a:b] = np.asarray(rv)[:cn]
                        rt.drained()
                        self.fast_log.log_batch(
                            getattr(engine, "proto", "r2d2"), n,
                            int(n - allow.sum()),
                        )
                        self.vec_batches += 1
                        self.vec_entries += n
                        metrics.ProxyBatches.inc()
                        self._send_vec_frames(
                            sends, allow,
                            getattr(engine, "DENY_INJECT", None),
                        )
                        self.tracer.finish_round(
                            rt, [self._batch_desc(s[6], s[0]) for s in sends]
                        )
                        if engine is not None and sends:
                            self._record_vec_round(
                                engine,
                                np.concatenate([s[2] for s in sends]),
                                allow, rules,
                            )
                    elif r[0] == "entry2":
                        # Runs even when deposed: finish() drains engine
                        # ops/inject and the async-pending refcounts
                        # (skipping it would wedge deferred rounds and
                        # misattribute ops); its sends are suppressed by
                        # the generation adopted above.
                        _, rfuts, finish = r
                        nf = len(rfuts)
                        chunk = vals[vi : vi + nf]
                        vi += nf  # before finish: a throw must not
                        # misalign later records' slices.  deferred_scope:
                        # pump/judge crashes inside a deferred completion
                        # happen on THIS thread, outside any dispatcher
                        # round — recorded sticky so the next round_start
                        # can't erase them before they hold the streak.
                        self.guard.deferred_scope(finish, chunk)
                    elif r[0] == "ready":
                        _, client, batch, entries, rtd = r
                        client.send_verdicts(
                            batch.seq, entries, batch=batch
                        )
                        if rtd is not None and not deposed:
                            rt, descs = rtd
                            self.tracer.finish_round(rt, descs)
                    elif r[0] == "frame":
                        # Verdict-cache whole-item round: the frame was
                        # prebuilt at decision time; it rides this FIFO
                        # so a cached answer can never overtake an
                        # earlier in-flight round's verdicts for the
                        # same conn.
                        _, client, frame, batch, rtd = r
                        client.send(
                            wire.MSG_VERDICT_BATCH, frame,
                            batches=[batch],
                        )
                        if rtd is not None and not deposed:
                            rt, descs = rtd
                            self.tracer.finish_round(rt, descs)
                except Exception:  # noqa: BLE001 — worker must survive
                    log.exception("completion failed")
                finally:
                    cur._disp_round = None

    _ERR_ROW = np.frombuffer(b"ERROR\r\n", np.uint8)

    def _verdict_body(self, conn_ids, lengths, allow,
                      deny_inject: bytes | None = None) -> bytes:
        """Columnar op assembly: every entry is (PASS|DROP frame, MORE 1)
        — identical to the streaming oracle's op sequence for one
        complete frame (reference: r2d2parser.go:158-213).
        ``deny_inject`` is the serving engine's per-denied-frame reply
        bytes (None = the historic r2d2 ``ERROR\r\n``; DNS injects
        nothing)."""
        n = len(conn_ids)
        tpl = self._frame_tpl.get(n)
        if tpl is None:
            ops0 = np.zeros(2 * n, wire.FILTER_OP)
            ops0["op"][1::2] = int(MORE)
            ops0["n_bytes"][1::2] = 1
            tpl = (ops0, np.zeros(n, np.uint32), np.full(n, 2, np.uint32))
            if len(self._frame_tpl) < 4096:
                self._frame_tpl[n] = tpl
        ops0, zeros_u32, twos_u32 = tpl
        ops = ops0.copy()
        ops["op"][0::2] = np.where(allow, int(PASS), int(DROP))
        ops["n_bytes"][0::2] = lengths
        err_row = (
            self._ERR_ROW if deny_inject is None
            else np.frombuffer(deny_inject, np.uint8)
        )
        nd = n - int(allow.sum())
        if nd and len(err_row):
            inj_blob = np.broadcast_to(
                err_row, (nd, len(err_row))
            ).tobytes()
            inj_reply = np.where(allow, 0, len(err_row)).astype(np.uint32)
        else:
            inj_blob = b""
            inj_reply = zeros_u32
        return wire.pack_verdict_body(
            conn_ids, zeros_u32, twos_u32, zeros_u32, inj_reply, ops, inj_blob
        )

    def _verdict_frame(self, seq, conn_ids, lengths, allow,
                       deny_inject: bytes | None = None) -> bytes:
        return struct.pack("<QI", seq, len(conn_ids)) + self._verdict_body(
            conn_ids, lengths, allow, deny_inject
        )

    def _catch_up_epoch(self, conn_id: int, sc: "_SidecarConn") -> None:
        """Stale-conn epoch catch-up: a swap left this conn on its
        captured engine because an in-flight round still owed state
        against it.  Once that round drained (no async-pending
        refcount, ops empty), adopt the current epoch's engine and
        migrate the retained buffer — pointer reads only, no
        compile."""
        with self._lock:
            if conn_id in self._async_pending or (
                conn_id < self._tab_size and self._tab_async[conn_id]
            ):
                return  # round still in flight: retry on a later entry
            old_eng = sc.engine
            if old_eng is not None and not self._flow_migratable(
                old_eng, conn_id
            ):
                return
            eng = self._engines.get(
                self._engine_key_for(sc.module_id, sc.conn)
            )
            if eng is not None and old_eng is not None \
                    and eng is not old_eng:
                self._migrate_flow(old_eng, eng, conn_id, sc)
            if eng is not None:
                sc.engine = eng
                sc.fast_ok = sc.conn.parser_name in FAST_PROTOS
            else:
                sc.engine = None
                sc.fast_ok = False
            self._tab_set_engine(
                conn_id, eng if sc.fast_ok else None
            )
            self._stale_conns.discard(conn_id)
            # Caught up to the current epoch: refresh the invariance
            # claim against the adopted engine's table.
            grant = self._arm_flow_cache(conn_id, sc)
        if grant is not None:
            # Dispatch path (per-entry classifier): never send inline
            # — after a swap EVERY stale conn funnels through here in
            # one round, and a blocked shim socket would serialize
            # thousands of sends on the dispatcher.  The builder
            # thread delivers; revalidation makes late delivery safe.
            self._build_queue.put(("grants", [grant]))

    def _classify_entry(self, item, i: int, conns_snapshot: dict,
                        quarantined: bool, responses: dict,
                        fast: list, slow: list,
                        slow_conns: set, cache_hits: list | None = None,
                        ) -> None:
        """Route ONE entry onto the fast/slow/oracle lanes — THE shared
        per-entry classifier of the scalar entrywise path, also used by
        the columnar round for its residual (non-columnar) minority so
        the two rounds can never drift."""
        _, client, batch = item
        key = id(item)
        conn_id, reply, end_stream, data = batch.entry(i)
        sc = conns_snapshot.get(conn_id)
        if sc is None:
            responses[key][i] = (
                conn_id,
                int(FilterResult.UNKNOWN_CONNECTION),
                [],
                b"",
                b"",
            )
            return
        if sc.columnar_dead and not reply:
            # Lane-exit dead latch (columnar overflow with no engine
            # adopter): the overflowed bytes are gone, so the scalar
            # twin of FlowState.overflowed applies — every further
            # request entry answers a typed protocol error, never a
            # mid-stream resume over the dropped bytes.
            responses[key][i] = (
                conn_id, int(FilterResult.OK),
                [(int(ERROR), int(OpError.ERROR_INVALID_FRAME_LENGTH))],
                b"", b"",
            )
            return
        if quarantined:
            # Pure-device engines (no oracle inside) fall back
            # to the in-process oracle; device-assisted engines
            # keep their engine (the device_gate makes their
            # judge step a host policy.matches, bit-identical).
            if sc.engine is not None and not getattr(
                sc.engine, "handles_reply", False
            ):
                self._demote_to_oracle(conn_id, sc)
            self.fallback_entries += 1
            metrics.SidecarFallbackVerdicts.inc()
        elif sc.demoted_mod is not None:
            self._maybe_rebind(conn_id, sc)
        elif conn_id in self._stale_conns:
            # A swap deferred this conn's rebind behind an
            # in-flight round; catch it up to the current
            # epoch before this entry routes.
            self._catch_up_epoch(conn_id, sc)
        if sc.skip[reply]:
            take = min(sc.skip[reply], len(data))
            sc.skip[reply] -= take
            data = data[take:]
            if not data:
                self._tab_mark(conn_id, sc)
                responses[key][i] = (
                    conn_id, int(FilterResult.OK), [], b"", b"",
                )
                return
        eng_flow = (
            sc.engine.flows.get(conn_id) if sc.engine is not None else None
        )
        framing = _engine_framing(sc.engine)
        # Verdict-cache hit, scalar tier (the greedy-mode and minority-
        # entry twin of the columnar Phase-A mask): armed conn, claim
        # epoch current, no residue anywhere, frame-aligned payload.
        # The cache arrays are read lock-free like conns_snapshot —
        # bounded round-grain staleness; a stale read only costs a
        # miss (arming is monotone within an epoch, and disarms flip
        # the state before any residue can exist).
        if (
            cache_hits is not None
            and not reply
            and not end_stream
            and conn_id not in slow_conns
            and framing is not None
            and framing.payload_aligned(data)
            and not sc.bufs[False]
            and conn_id < self._tab_size
            and self._tab_cache[conn_id] == 1
            and self._tab_cache_epoch[conn_id] == self.policy_epoch
            and (
                eng_flow is None
                or not (
                    getattr(eng_flow, "buffer", None)
                    or getattr(eng_flow, "overflowed", False)
                )
            )
        ):
            rule = int(self._tab_cache_rule[conn_id])
            responses[key][i] = (
                conn_id, int(FilterResult.OK),
                [(int(PASS), len(data)), (int(MORE), 1)], b"", b"",
            )
            self._touch_cache_rows(np.array([conn_id], np.int64))
            cache_hits.append((key, i, conn_id, rule, sc.engine))
            return
        if (
            sc.fast_ok
            and not reply
            and conn_id not in slow_conns
            and not sc.bufs[False]
            and (
                eng_flow is None
                or not (eng_flow.buffer or eng_flow.overflowed)
            )
            and not isinstance(sc.engine.model, ConstVerdict)
            and framing is not None
            and framing.payload_single_frame(data)
            and len(data) <= self.config.batch_width
        ):
            fast.append((key, i, sc, conn_id, data))
        else:
            slow_conns.add(conn_id)
            slow.append((key, i, sc, conn_id, reply, end_stream, data))

    def _process_entrywise(self, items: list, t_pop: float = 0.0,
                           swap_s: float = 0.0) -> None:
        # Columnar reassembly lane first (sidecar/reasm.py): the CRLF
        # slow lane as array passes per ROUND.  Quarantined rounds are
        # the host rung; greedy mode keeps the scalar path (1-2 entry
        # rounds lose on the columnar fixed cost).
        if (
            self._reasm is not None
            and not self.guard.quarantined
            and self._process_columnar(items, t_pop, swap_s)
        ):
            return
        # Per-entry path, preserving per-connection order: an entry is
        # fast only if nothing earlier in this round put its connection
        # on the slow path.
        responses: dict[int, list] = {}  # id(item) -> per-entry results
        fast: list[tuple] = []  # (item_key, entry_idx, sc, data)
        slow: list[tuple] = []
        slow_conns: set[int] = set()

        quarantined = self.guard.quarantined
        # Path label for the decomposition: a quarantined round IS the
        # host-fallback rung (oracle demotion / host policy.matches);
        # otherwise the entrywise round is the engine/parser slow path.
        rt = self.tracer.begin_round(
            PATH_HOST if quarantined else PATH_ORACLE,
            sum(it[2].count for it in items),
            self._oldest_arrival(items),
            t_pop or None,
            ring_s=self._ring_wait(items),
            swap_s=swap_s,
        )
        cache_hits: list | None = [] if self._flow_cache_on else None
        for item in items:
            _, client, batch = item
            responses[id(item)] = [None] * batch.count
            with self._lock:
                conns_snapshot = self._conns
            for i in range(batch.count):
                self._classify_entry(item, i, conns_snapshot,
                                     quarantined, responses, fast,
                                     slow, slow_conns,
                                     cache_hits=cache_hits)
        cached_keys: set | None = None
        if cache_hits:
            cached_keys = {(k, i) for k, i, *_ in cache_hits}
            if not self._round_thread_suppressed():
                self._count_cache_hits(len(cache_hits))
                self._record_cached_entries(cache_hits)
        if self._flow_cache_on:
            # Misses are REQUEST-direction entries only (the metric's
            # definition, and the columnar tier's n_elig): replies and
            # end-stream entries are never cache candidates, so they
            # must not deflate a hit rate derived from the counters.
            self._count_cache_misses(
                len(fast)
                + sum(1 for s in slow if not s[4] and not s[5])
            )

        # Async round (completion-pipeline mode): when every slow entry
        # is either CRLF-extractable (engine exposes feed_extract) or
        # host-only work, the whole round issues its device calls
        # without reading back — the completion loop batches the
        # readbacks, overlapping the ~1-RTT device_get with the next
        # round's dispatch exactly like the vec path.  The wave path's
        # one-readback-per-pump (≈1 link RTT each) made mixed rounds
        # RTT-serial: 10k verdicts/s through the tunnel vs the vec
        # path's millions (see BENCH_NOTES round 5).
        if not self._inline_complete and self._slow_async_eligible(slow):
            rt.formed()
            # Attribution captures for the whole round, keyed
            # (item_key, entry_idx) — filled at DECISION time (issue /
            # finish halves) against the engines captured there.
            rules_out: dict = {}
            fast_issued = self._issue_fast(fast) if fast else []
            buckets, plan = self._issue_slow_async(
                slow, responses, rules_out
            )
            rt.submitted()
            # Per group/bucket: the allow future, then (attribution on)
            # the rule future — _finish_fast/_finish_slow_async consume
            # vals in the same order.
            futs = []
            for g in fast_issued:
                futs.append(g[0])
                if g[1] is not None:
                    futs.append(g[1])
            for bk in buckets:
                futs.append(bk[0])
                if bk[1] is not None:
                    futs.append(bk[1])
            n_fast_futs = sum(
                2 if g[1] is not None else 1 for g in fast_issued
            )
            pend = {conn_id for _k, _i, _sc, conn_id, *_ in plan}
            if pend:
                with self._lock:
                    for cid in pend:
                        self._async_pending[cid] = (
                            self._async_pending.get(cid, 0) + 1
                        )

            def finish(vals: list | None) -> None:
                try:
                    # The completion loop's batched device_get (or the
                    # inline np.asarray fallback) fenced this round.
                    rt.completed()
                    self._finish_fast(
                        fast_issued, responses,
                        vals=(
                            vals[:n_fast_futs] if vals is not None
                            else [None] * n_fast_futs
                        ),
                        rules_out=rules_out,
                    )
                    self._finish_slow_async(
                        buckets, plan, responses,
                        vals=(
                            vals[n_fast_futs:] if vals is not None
                            else [None] * (len(futs) - n_fast_futs)
                        ),
                        rules_out=rules_out,
                    )
                    rt.drained()
                    for item in items:
                        _, client, batch = item
                        try:
                            client.send_verdicts(
                                batch.seq, responses[id(item)],
                                batch=batch,
                            )
                        except Exception:  # noqa: BLE001 — client gone
                            log.exception("verdict send failed")
                    if not self._round_thread_suppressed():
                        self.tracer.finish_round(
                            rt,
                            [self._batch_desc(it[2], it[1]) for it in items],
                        )
                        self._record_entrywise(
                            rt.path, items, responses, rules_out,
                            cached=cached_keys,
                        )
                finally:
                    if pend:
                        with self._lock:
                            for cid in pend:
                                n = self._async_pending.get(cid, 1) - 1
                                if n <= 0:
                                    self._async_pending.pop(cid, None)
                                else:
                                    self._async_pending[cid] = n

            self._completion_put(("entry2", futs, finish))
            return

        # Sync fallback.  If any conn in this round has an UNFINISHED
        # async round, its engine state (ops/inject) is still owed to
        # the send thread's finish — running pump/take here would race
        # it and interleave op attribution.  Defer the whole round to
        # the completion queue (futs=[]): it executes on the send
        # thread strictly AFTER the pending finish, preserving both
        # state exclusivity and per-conn response order.
        deferred = False
        if not self._inline_complete and (
            self._async_pending or self._reasm is not None
        ):
            with self._lock:
                pending_now = set(self._async_pending)
            round_conns = {rec[3] for rec in slow}
            round_conns.update(rec[3] for rec in fast)
            if pending_now:
                deferred = bool(round_conns & pending_now)
            if not deferred and self._reasm is not None and round_conns:
                # The reassembler lane tracks its in-flight conns in
                # the _tab_async array (bulk updates): a sync round
                # touching one must queue behind its finish too.
                # (Filtered in Python first: a u64 wire id >= 2^63
                # would overflow np.fromiter's int64.)
                small = [c for c in round_conns
                         if 0 <= c < self._TAB_MAX]
                rc = np.fromiter(small, np.int64, count=len(small))
                with self._lock:
                    rc = rc[rc < self._tab_size]
                    deferred = bool(len(rc)) and bool(
                        self._tab_async[rc].any()
                    )

        def run_sync_and_respond(_vals: list | None = None) -> None:
            rt.formed()
            rules_out: dict = {}
            if fast:
                self._run_fast(fast, responses, rules_out)
            self._run_slow_batched(slow, responses, rules_out)
            # Sync paths read back inside the engine pump/fast finish:
            # submit/complete collapse onto this boundary and the work
            # shows up in the drain stage (still fenced — the pump's
            # np.asarray readbacks have executed by here).
            rt.drained()
            for i_item, item in enumerate(items):
                _, client, batch = item
                if self._inline_complete or deferred:
                    try:
                        client.send_verdicts(
                            batch.seq, responses[id(item)], batch=batch
                        )
                    except Exception:  # noqa: BLE001 — client may be gone
                        log.exception("verdict send failed")
                else:
                    # The LAST item's ready record carries the round
                    # trace (+ every covered batch's descriptor): the
                    # send loop emits records in FIFO order, so the
                    # round closes once every frame is on the wire.
                    last = i_item == len(items) - 1
                    self._completion_put(
                        ("ready", client, batch, responses[id(item)],
                         (rt, [self._batch_desc(it2[2], it2[1]) for it2 in items])
                         if last else None)
                    )
            if self._inline_complete or deferred:
                if not self._round_thread_suppressed():
                    self.tracer.finish_round(
                        rt, [self._batch_desc(it[2], it[1]) for it in items]
                    )
            # Record emission at decision time (the pipelined sends are
            # already queued in FIFO order behind this round).
            if not self._round_thread_suppressed():
                self._record_entrywise(rt.path, items, responses,
                                       rules_out, cached=cached_keys)

        if deferred:
            self._completion_put(("entry2", [], run_sync_and_respond))
        else:
            run_sync_and_respond()

    # -- columnar reassembly lane (sidecar/reasm.py) ----------------------

    def _reasm_fallback(self, reason: str) -> None:
        self.reasm_fallbacks[reason] = (
            self.reasm_fallbacks.get(reason, 0) + 1
        )

    def _reasm_bail(self, conn_ids: np.ndarray,
                    reason: str | None) -> bool:
        """Whole-round fallback to the scalar rung.  Any round conn
        still holding columnar carry state must exit the lane FIRST:
        the scalar classifier reads engine/oracle buffers, not the
        arena, and serving it with the carry invisible would judge
        frames without their carried prefix — wrong op byte counts on
        the wire and bytes stranded in the arena.  Returns False for
        the caller's tail call.  ``reason`` None skips the fallback
        counter (a round with nothing lane-eligible is ordinary scalar
        traffic, not a reassembler fallback)."""
        if reason is not None:
            self._reasm_fallback(reason)
        rc = np.unique(conn_ids)
        for cid in rc[self._reasm.arena.has_slot(rc)]:
            self._reasm_release_to_scalar(int(cid))
        return False

    def _reasm_release_to_scalar(self, conn_id: int) -> None:
        """Pull one conn's carry out of the columnar arena and hand it
        to the scalar side (engine flow buffer via adopt_residue, or
        the oracle mirror when no engine is bound) — the lane-exit
        transition.  Runs on the dispatcher thread BEFORE the conn's
        entries are classified scalar, so the shared residual-dirty
        predicate sees the bytes in their scalar home.

        Every byte (and the dead/overflow latch) released here must
        land in an accountable home — the R14 lane-exit contract: a
        closed conn's slot is dropped EXPLICITLY (never pulled out
        first and leaked), and a dead latch with no engine adopter
        transfers to the conn's own ``columnar_dead`` so further
        entries answer a typed protocol error instead of resuming the
        parse over the dropped bytes (the PR 10 silent-loss class)."""
        sc = self._conns.get(conn_id)
        if sc is None:
            # Conn already closed: no peer awaits these bytes; the
            # explicit drop is close_connection's own arena contract.
            self._reasm.arena.drop(conn_id)
            return
        data, dead = self._reasm.arena.release(conn_id)
        engine = sc.engine
        if engine is not None and hasattr(engine, "adopt_residue"):
            conn = sc.conn
            engine.adopt_residue(
                conn_id, data, dead,
                remote_id=conn.src_id, policy_name=conn.policy_name,
                ingress=conn.ingress, dst_id=conn.dst_id,
                src_addr=conn.src_addr, dst_addr=conn.dst_addr,
            )
        else:
            if dead:
                sc.columnar_dead = True
            if data:
                sc.bufs[False] = bytearray(data) + sc.bufs[False]
        self._tab_mark(conn_id, sc)

    def _process_columnar(self, items: list, t_pop: float,
                          swap_s: float) -> bool:
        """Serve one entrywise round through the columnar reassembler:
        carry append, frame splitting and op/inject/record assembly as
        array passes per ROUND instead of feed/settle Python per ENTRY.

        Phase A is side-effect-free eligibility: anything the lane
        cannot prove safe (reply/end_stream flags, non-CRLF or
        ConstVerdict engines, demoted/stale/transitional conns,
        duplicate conns in one round, too few eligible entries, a
        leftover entry that would force a synchronous engine pump)
        either taints its conn to the scalar minority or bails the
        whole round back to the scalar path — which remains the
        oracle rung, byte-identical by the parity tests.  Phase B
        ingests into the arena, issues ONE model call per
        (engine, width) bucket without reading back, and queues a
        finish that renders verdict frames columnar."""
        reasm = self._reasm
        batches = [it[2] for it in items]
        counts = [b.count for b in batches]
        n_round = int(sum(counts))
        if n_round == 0:
            return False
        # --- Phase A: columnar view + eligibility (no side effects) ---
        if len(batches) == 1:
            b0 = batches[0]
            conn_ids_u = b0.conn_ids
            flags = b0.flags
            lengths = b0.lengths.astype(np.int64)
            blob_b = b0.blob
            ends = b0.offsets[1:].astype(np.int64)
        else:
            conn_ids_u = np.concatenate([b.conn_ids for b in batches])
            flags = np.concatenate([b.flags for b in batches])
            lengths = np.concatenate(
                [b.lengths for b in batches]
            ).astype(np.int64)
            blob_b = b"".join(b.blob for b in batches)
            ends = np.cumsum(lengths)
        starts = ends - lengths
        # Range-check the RAW u64 ids before any int64 view: a wire id
        # >= 2^63 would wrap negative and fancy-index the wrong rows
        # in the conn tables / arena map.
        conn_ids = conn_ids_u.astype(np.int64)
        if len(conn_ids_u) and int(conn_ids_u.max()) >= ByteArena.MAP_MAX:
            return self._reasm_bail(conn_ids, "conn_id_range")
        blob = np.frombuffer(blob_b, np.uint8)
        if len(blob) != int(lengths.sum()):
            return self._reasm_bail(conn_ids, "blob_shape")
        snap = self._tab_snapshot(items)
        pos = snap.lookup(conn_ids)
        eng_idx = snap.engine[pos]
        elig = (flags == 0) & (eng_idx >= 0)
        dirty = snap.dirty[pos].astype(bool)
        has_slot = reasm.arena.has_slot(conn_ids)
        # A dirty conn is lane-eligible only when its residue IS the
        # arena carry (the lane's own state); scalar residue anywhere
        # keeps the conn on the scalar rung until it drains.
        elig &= (~dirty) | has_slot
        if elig.any():
            for e in np.unique(eng_idx[elig]):
                engine = snap.objs[int(e)]
                # Per-framing dispatch (reasm.FRAMINGS): an engine
                # rides the lane iff its declared framing has a
                # registered scanner — CRLF (r2d2 class) and the DNS
                # length prefix today; an engine declaring anything
                # else (cassandra/kafka until their parser state goes
                # arena-portable) must never be scanned with the wrong
                # framing into garbage frames.
                if (
                    engine is None
                    or _engine_framing(engine) is None
                    or isinstance(engine.model, ConstVerdict)
                ):
                    elig &= eng_idx != e
        with self._lock:
            stale = (
                np.fromiter(self._stale_conns, np.int64,
                            count=len(self._stale_conns))
                if self._stale_conns else None
            )
        if stale is not None and len(stale):
            elig &= ~np.isin(conn_ids, stale)
        # Duplicate conns in one round have a sequential carry
        # dependency (entry k+1's stream starts from entry k's
        # residue): route them scalar, whole-conn, preserving order.
        order = np.argsort(conn_ids, kind="stable")
        so = conn_ids[order]
        dup_mask = None
        if len(so) > 1:
            dup = so[1:] == so[:-1]
            if dup.any():
                dup_mask = np.isin(conn_ids, np.unique(so[1:][dup]))
                elig &= ~dup_mask
        # Verdict-cache hit lane (Phase A, still side-effect-free):
        # armed conns whose claim epoch matches the snapshot epoch,
        # with no residue and a frame-aligned payload, are filtered
        # out of the device round in this one vectorized mask — they
        # are answered from the claim in Phase B, before ingest or
        # bucket issue ever sees them.  Duplicate conns stay out: an
        # earlier entry this round may leave residue the hit's clean
        # check cannot see yet.
        hit = None
        t_c0 = time.monotonic()
        if self._flow_cache_on:
            hit = (
                (flags == 0)
                & (snap.cache[pos] == 1)
                & (snap.cache_epoch[pos] == snap.epoch)
                & (~dirty)
            )
            if hit.any():
                hit &= self._framing_alignment_mask(
                    snap, eng_idx, hit,
                    lambda framing, selm: framing.segments_aligned(
                        blob, starts[selm], lengths[selm]
                    ),
                )
            if dup_mask is not None:
                hit &= ~dup_mask
            if hit.any():
                elig &= ~hit
            else:
                hit = None
        cache_s = (time.monotonic() - t_c0) if hit is not None else 0.0
        n_hit = int(hit.sum()) if hit is not None else 0
        n_elig = int(elig.sum())
        if n_elig < max(int(self.config.reasm_min_entries), 1) and not (
            n_hit and n_elig == 0
        ):
            # Too small for the columnar fixed cost (cache hits pay
            # almost none, so an all-hit round proceeds regardless);
            # the scalar rung serves everything, hits included
            # (_classify_entry has the same hit check).
            return self._reasm_bail(
                conn_ids, "round_too_small" if n_elig else None
            )
        # Leftover-minority soundness: the round issues async; any
        # entry that would need a synchronous engine pump (or a
        # transitional rebind/catch-up that could create one) forfeits
        # the lane — the scalar round owns those shapes.
        rest = np.flatnonzero(~elig)
        conns = self._conns
        for k in rest:
            if hit is not None and hit[k]:
                continue  # answered from the claim in Phase B
            cid = int(conn_ids[k])
            fl = int(flags[k])
            sc = conns.get(cid)
            if sc is None:
                continue  # UNKNOWN_CONNECTION: typed inline, async-safe
            if sc.demoted_mod is not None or cid in self._stale_conns:
                return self._reasm_bail(conn_ids, "transitional_conn")
            engine = sc.engine
            if engine is None or isinstance(engine.model, ConstVerdict):
                continue  # host-only work
            if fl & wire.FLAG_END_STREAM:
                return self._reasm_bail(conn_ids, "end_stream")
            if fl & wire.FLAG_REPLY:
                if getattr(engine, "handles_reply", False):
                    return self._reasm_bail(conn_ids, "engine_reply")
                continue  # oracle host-only reply
            if not hasattr(engine, "feed_extract"):
                return self._reasm_bail(conn_ids, "engine_pump")
        # --- Phase B: committed ---------------------------------------
        # Lane-exit for tainted conns still holding arena state: their
        # residue moves to the scalar side before classification (the
        # one release definition — _reasm_bail with no fallback count).
        # Cache hits are not tainted — they hold no carry by the hit
        # mask's clean check.
        lane_exit = rest if hit is None else rest[~hit[rest]]
        if len(lane_exit):
            self._reasm_bail(conn_ids[lane_exit], None)
        rt = self.tracer.begin_round(
            PATH_ORACLE, n_round, self._oldest_arrival(items), t_pop,
            ring_s=self._ring_wait(items), swap_s=swap_s,
        )
        responses: dict[int, list] = {
            id(item): [None] * item[2].count for item in items
        }
        base = np.concatenate(([0], np.cumsum(counts))).astype(np.int64)
        # Cache-hit entries answered from the claim: `_verdict_body`'s
        # (PASS frame, MORE 1) shape, original rule attributed on the
        # `cached` path, device round never issued.
        cached_keys: set | None = None
        if n_hit:
            hit_idx = np.flatnonzero(hit)
            cached_keys = set()
            for k in hit_idx:
                bi = int(np.searchsorted(base, k, side="right")) - 1
                item = items[bi]
                ei = int(k - base[bi])
                responses[id(item)][ei] = (
                    int(conn_ids[k]), int(FilterResult.OK),
                    [(int(PASS), int(lengths[k])), (int(MORE), 1)],
                    b"", b"",
                )
                cached_keys.add((id(item), ei))
            rt.cache_s = cache_s
            if not self._round_thread_suppressed():
                self._count_cache_hits(n_hit)
                self._touch_cache_rows(conn_ids[hit_idx])
                self._flowlog_cached(
                    snap, conn_ids[hit_idx], pos[hit_idx]
                )
        self._count_cache_misses(n_elig)
        fast: list = []
        slow: list = []
        slow_conns: set = set()
        if len(rest):
            with self._lock:
                conns_snapshot = self._conns
            for k in rest:
                if cached_keys is not None and hit[k]:
                    continue  # already answered from the claim
                bi = int(np.searchsorted(base, k, side="right")) - 1
                self._classify_entry(
                    items[bi], int(k - base[bi]), conns_snapshot,
                    False, responses, fast, slow, slow_conns,
                )
        # Ingest + pack (the `reasm` stage of the decomposition).
        t_r0 = time.monotonic()
        e_live = np.flatnonzero(elig)
        groups: list = []
        crash_sel: list = []
        for e in np.unique(eng_idx[e_live]):
            sel = e_live[eng_idx[e_live] == e]
            engine = snap.objs[int(e)]
            try:
                rnd = reasm.ingest(
                    conn_ids[sel], starts[sel], lengths[sel], blob,
                    framing=_engine_framing(engine),
                )
            except Exception:  # noqa: BLE001 — framing hooks are pluggable
                # A raise-capable per-framing hook (reasm.FRAMINGS
                # scan/reader callbacks) crashed for THIS engine's
                # group.  Ingest commits transactionally (the scan
                # runs before any carry mutation), so the arena still
                # holds every group conn's carry intact: the group
                # exits the lane typed and serves through the scalar
                # oracle rung THIS round — real verdicts, zero byte
                # loss — while the other groups keep their columnar
                # service (lint R15's per-entry containment shape;
                # round-level _on_batch_error would instead answer
                # the whole round UNKNOWN_ERROR).
                log.exception("columnar ingest failed; engine group "
                              "falls back to the scalar rung")
                self._reasm_fallback("framing_crash")
                self._record_contained_failure("framing-crash")
                self._reasm_bail(conn_ids[sel], None)
                crash_sel.append(sel)
                continue
            if rnd.over.any():
                # Same accounting as the scalar engine rung's
                # _overflow (the oracle path owns the global metric).
                engine.buffer_overflows += int(rnd.over.sum())
            buckets = reasm.pack_buckets(
                rnd, self.config.batch_width, self._min_bucket,
                snap.src[pos[sel]],
            )
            groups.append([sel, engine, rnd, buckets, None])
        if crash_sel:
            # Crashed groups ride the round's scalar minority: carries
            # were released to the engines above, so the shared
            # classifier routes every entry slow and the finish merge
            # (rest) emits their tuples in entry order.
            crashed = np.concatenate(crash_sel)
            with self._lock:
                conns_crash = self._conns
            for k in crashed:
                bi = int(np.searchsorted(base, k, side="right")) - 1
                self._classify_entry(
                    items[bi], int(k - base[bi]), conns_crash,
                    False, responses, fast, slow, slow_conns,
                )
            rest = (
                np.concatenate((rest, crashed)) if len(rest) else crashed
            )
            e_live = e_live[~np.isin(e_live, crashed)]
        # Dirty flags written NOW, before the next round classifies
        # (same contract as the scalar lane's _tab_mark_many): residue
        # or a dead latch keeps the conn off the vec path.
        with self._lock:
            for sel, engine, rnd, _bk, _is in groups:
                cids = rnd.conn_ids
                ok = cids < self._tab_size
                dirty_new = (
                    (rnd.res_len > 0) | rnd.dead | rnd.over
                ).astype(np.uint8)
                self._tab_dirty[cids[ok]] = dirty_new[ok]
        rt.reasm_s = time.monotonic() - t_r0
        rt.formed()
        # Issue: legacy minority first (host-only work inline, device
        # futures kept), then one model call per columnar bucket.
        rules_out: dict = {}
        fast_issued = self._issue_fast(fast) if fast else []
        sbuckets, plan = self._issue_slow_async(slow, responses,
                                               rules_out)
        for grp in groups:
            _sel, engine, _rnd, buckets, _ = grp
            issued = []
            for fi, data_m, lens_b, rem in buckets:
                # lint: disable=R15 -- device faults ARE typed here: _mesh_guarded demotes and retries single-chip, and a still-raising round reaches the dispatcher's _on_batch_error, which answers every entry UNKNOWN_ERROR (the round-level containment backstop)
                _c, _m, allow, rule = self._model_call_attr(
                    engine.model, data_m, lens_b, rem
                )
                issued.append((fi, allow, rule))
            grp[4] = issued
        rt.submitted()
        futs: list = []
        for g in fast_issued:
            futs.append(g[0])
            if g[1] is not None:
                futs.append(g[1])
        n_fast_futs = len(futs)
        for bk in sbuckets:
            futs.append(bk[0])
            if bk[1] is not None:
                futs.append(bk[1])
        n_legacy_futs = len(futs)
        for _sel, _eng, _rnd, _bk, issued in groups:
            for _fi, allow, rule in issued:
                futs.append(allow)
                if rule is not None:
                    futs.append(rule)
        # In-flight registration: dict refcounts for the legacy plan
        # conns, one bulk array add for the columnar conns.
        pend = {cid for _k, _i, _sc, cid, *_ in plan}
        reasm_cids = conn_ids[e_live]
        with self._lock:
            for cid in pend:
                self._async_pending[cid] = (
                    self._async_pending.get(cid, 0) + 1
                )
            in_rng = reasm_cids[reasm_cids < self._tab_size]
            np.add.at(self._tab_async, in_rng, 1)

        def finish(vals: list | None) -> None:
            try:
                rt.completed()
                self._finish_fast(
                    fast_issued, responses,
                    vals=(
                        vals[:n_fast_futs] if vals is not None
                        else [None] * n_fast_futs
                    ),
                    rules_out=rules_out,
                )
                self._finish_slow_async(
                    sbuckets, plan, responses,
                    vals=(
                        vals[n_fast_futs:n_legacy_futs]
                        if vals is not None
                        else [None] * (n_legacy_futs - n_fast_futs)
                    ),
                    rules_out=rules_out,
                )
                try:
                    self._finish_columnar(
                        items, base, responses, groups, rest,
                        vals[n_legacy_futs:] if vals is not None
                        else [None] * (len(futs) - n_legacy_futs),
                        rt, rules_out, cached=cached_keys,
                    )
                except Exception:  # noqa: BLE001 — fail closed, typed
                    # The shim is owed exactly one reply per seq and
                    # nothing downstream will answer it: a columnar
                    # finish crash answers every covered batch typed
                    # (send() stands down per batch if a racing reply
                    # already landed).
                    log.exception(
                        "columnar finish failed; answering typed"
                    )
                    for item in items:
                        _, cl_, batch = item
                        try:
                            if cl_.send_verdicts(
                                batch.seq,
                                self._typed_entries(
                                    batch, FilterResult.UNKNOWN_ERROR
                                ),
                                batch=batch,
                            ):
                                self.error_entries += batch.count
                        except Exception:  # noqa: BLE001
                            log.exception("typed error send failed")
            finally:
                with self._lock:
                    for cid in pend:
                        n = self._async_pending.get(cid, 1) - 1
                        if n <= 0:
                            self._async_pending.pop(cid, None)
                        else:
                            self._async_pending[cid] = n
                    in_r = reasm_cids[reasm_cids < self._tab_size]
                    dec = self._tab_async[in_r]
                    self._tab_async[in_r] = np.where(dec > 0, dec - 1, 0)

        self._completion_put(("entry2", futs, finish))
        return True

    def _finish_columnar(self, items: list, base: np.ndarray,
                         responses: dict, groups: list, rest,
                         vals: list, rt, rules_out: dict,
                         cached: set | None = None) -> None:
        """Finish half of the columnar round: materialize the bucket
        readbacks, render per-entry ops/injects as array scatters,
        merge the scalar minority's tuples in entry order, and emit one
        verdict frame per wire batch — plus the round's columnar flow
        records with engine-captured epoch/kind attribution."""
        reasm = self._reasm
        n_round = int(base[-1])
        vi = 0
        finished = []  # (sel, engine, rnd, allow_f, rule_f, assembled)
        for sel, engine, rnd, _buckets, issued in groups:
            nf = rnd.frame_count()
            allow_f = np.zeros(nf, bool)
            rule_f = np.full(nf, -1, np.int32)
            for fi, allow_dev, rule_dev in issued:
                v = vals[vi] if vi < len(vals) else None
                vi += 1
                rv = None
                if rule_dev is not None:
                    rv = vals[vi] if vi < len(vals) else None
                    vi += 1
                if v is None:
                    try:
                        a = np.asarray(allow_dev)
                    except Exception:  # noqa: BLE001 — deny on error
                        log.exception("device readback failed")
                        a = None
                else:
                    a = np.asarray(v)
                if a is None:
                    continue  # frames stay denied + unattributed
                allow_f[fi] = a[: len(fi)]
                if rv is not None:
                    rule_f[fi] = np.asarray(rv)[: len(fi)]
                elif rule_dev is not None:
                    try:
                        rule_f[fi] = np.asarray(rule_dev)[: len(fi)]
                    except Exception:  # noqa: BLE001 — unattribute only
                        log.exception("rule readback failed")
            assembled = reasm.assemble(rnd, allow_f)
            finished.append((sel, engine, rnd, allow_f, rule_f,
                             assembled))
            self.fast_log.log_batch(
                getattr(engine, "proto", "r2d2"), nf,
                int(nf - int(allow_f.sum())),
            )
        # Round-wide merge: per-entry counts first, then one scatter
        # pass for ops and injects (scalar minority written per entry).
        oc_full = np.zeros(n_round, np.int64)
        res_full = np.full(n_round, int(FilterResult.OK), np.uint32)
        injo_full = np.zeros(n_round, np.int64)
        injr_full = np.zeros(n_round, np.int64)
        for sel, _eng, _rnd, _af, _rf, (op_counts, _ops, inj_len,
                                        _blob, _nd) in finished:
            oc_full[sel] = op_counts
            injr_full[sel] = inj_len
        rest_resp = []  # (round_idx, response tuple)
        for k in rest:
            bi = int(np.searchsorted(base, k, side="right")) - 1
            item = items[bi]
            r = responses[id(item)][int(k - base[bi])]
            if r is None:  # defensive: a lane bug must fail typed
                r = (int(item[2].conn_ids[int(k - base[bi])]),
                     int(FilterResult.UNKNOWN_ERROR), [], b"", b"")
            rest_resp.append((int(k), r))
            oc_full[k] = len(r[2])
            res_full[k] = r[1]
            injo_full[k] = len(r[3])
            injr_full[k] = len(r[4])
        op_dst = np.concatenate(
            ([0], np.cumsum(oc_full))
        ).astype(np.int64)
        inj_tot = injo_full + injr_full
        inj_dst = np.concatenate(
            ([0], np.cumsum(inj_tot))
        ).astype(np.int64)
        ops_round = np.zeros(int(op_dst[-1]), wire.FILTER_OP)
        inj_round = np.zeros(int(inj_dst[-1]), np.uint8)
        for sel, _eng, _rnd, _af, _rf, (op_counts, ops_g, inj_len,
                                        inj_blob, _nd) in finished:
            g_off = np.concatenate(
                ([0], np.cumsum(op_counts))
            )[:-1].astype(np.int64)
            gather_segments(ops_g, g_off, op_counts, out=ops_round,
                            dst_starts=op_dst[sel])
            gi_off = np.concatenate(
                ([0], np.cumsum(inj_len))
            )[:-1].astype(np.int64)
            gather_segments(inj_blob, gi_off, inj_len, out=inj_round,
                            dst_starts=inj_dst[sel])
        for k, r in rest_resp:
            off = int(op_dst[k])
            for j, (op, nb) in enumerate(r[2]):
                ops_round[off + j] = (int(op), int(nb))
            d = int(inj_dst[k])
            if r[3]:
                io = np.frombuffer(r[3], np.uint8)
                inj_round[d : d + len(io)] = io
                d += len(io)
            if r[4]:
                ir = np.frombuffer(r[4], np.uint8)
                inj_round[d : d + len(ir)] = ir
        rt.drained()
        # One verdict frame per wire batch, sliced from the round
        # arrays; entries whose op list exceeds the ABI capacity route
        # the whole item through the splitting tuple path.
        for bi, item in enumerate(items):
            _, client, batch = item
            a, b = int(base[bi]), int(base[bi + 1])
            try:
                if bool((oc_full[a:b] > wire.MAX_OPS_PER_ENTRY).any()):
                    entries = self._columnar_item_tuples(
                        batch, a, b, oc_full, op_dst, ops_round,
                        injo_full, injr_full, inj_dst, inj_round,
                        res_full, rest_resp,
                    )
                    client.send_verdicts(batch.seq, entries,
                                         batch=batch)
                    continue
                payload = wire.pack_verdict_batch(
                    batch.seq,
                    batch.conn_ids,
                    res_full[a:b],
                    oc_full[a:b].astype(np.uint32),
                    injo_full[a:b].astype(np.uint32),
                    injr_full[a:b].astype(np.uint32),
                    ops_round[op_dst[a] : op_dst[b]],
                    inj_round[inj_dst[a] : inj_dst[b]].tobytes(),
                )
                client.send(wire.MSG_VERDICT_BATCH, payload,
                            batches=[batch])
            except Exception:  # noqa: BLE001 — client may be gone
                log.exception("columnar verdict send failed")
        if self._round_thread_suppressed():
            return
        self.tracer.finish_round(
            rt, [self._batch_desc(it[2], it[1]) for it in items]
        )
        # Scalar-minority records ride the shared entrywise emitter
        # (columnar entries hold None responses and are skipped, and
        # cache-hit entries were already recorded on the `cached` path
        # at decision time); columnar records are one add_round per
        # engine group with the CAPTURED engine's kinds legend + epoch
        # — slot-reuse-safe exactly like the vec rounds.
        self._record_entrywise(rt.path, items, responses, rules_out,
                               cached=cached)
        if self.flowlog is None:
            return
        for _sel, engine, rnd, allow_f, rule_f, (own_oc, _ops, _il,
                                                 _ib, n_den) in finished:
            has_frames = rnd.n_frames > 0
            forwarded = rnd.live & has_frames & (n_den == 0)
            denied = (rnd.live & has_frames & (n_den > 0)) | rnd.over
            errorc = rnd.dead
            rec = forwarded | denied | errorc
            if not rec.any():
                continue
            codes = np.where(
                forwarded, CODE_FORWARDED,
                np.where(errorc, CODE_ERROR, CODE_DENIED),
            ).astype(np.int8)
            rules = np.where(
                forwarded, reasm.last_rules(rnd, rule_f), -1
            ).astype(np.int32)
            self.flowlog.add_round(
                rt.path,
                rnd.conn_ids[rec],
                codes[rec],
                rules[rec],
                kinds=getattr(engine.model, "match_kinds", ()),
                epoch=getattr(engine, "epoch", 0),
            )

    def _columnar_item_tuples(self, batch, a: int, b: int, oc_full,
                              op_dst, ops_round, injo_full, injr_full,
                              inj_dst, inj_round, res_full,
                              rest_resp) -> list:
        """Materialize one item's entries as scalar response tuples —
        the op-capacity-splitting fallback (send_verdicts owns the
        continuation-entry split; >16-op entries are rare)."""
        scalar = {k: r for k, r in rest_resp}
        entries = []
        for k in range(a, b):
            r = scalar.get(k)
            if r is not None:
                entries.append(r)
                continue
            off = int(op_dst[k])
            cnt = int(oc_full[k])
            d = int(inj_dst[k])
            io = int(injo_full[k])
            ir = int(injr_full[k])
            entries.append((
                int(batch.conn_ids[k - a]),
                int(res_full[k]),
                [(int(o["op"]), int(o["n_bytes"]))
                 for o in ops_round[off : off + cnt]],
                inj_round[d : d + io].tobytes(),
                inj_round[d + io : d + io + ir].tobytes(),
            ))
        return entries

    @staticmethod
    def _slow_async_eligible(slow: list) -> bool:
        """True when no slow entry would need a synchronous device
        readback: every entry either goes through feed_extract (CRLF
        engines, request direction), a ConstVerdict engine (host-only
        pump), or the host-only oracle parser."""
        for _key, _i, sc, _conn_id, reply, end_stream, _data in slow:
            engine = sc.engine
            if engine is None:
                continue  # oracle, host-only
            if isinstance(engine.model, ConstVerdict):
                continue  # pump() special-cases ConstVerdict host-side
            if hasattr(engine, "feed_extract") and not reply and not end_stream:
                continue  # extractable
            if reply and not getattr(engine, "handles_reply", False):
                continue  # oracle, host-only
            return False  # engine pump path would read back synchronously
        return True

    def _issue_slow_async(self, slow: list, responses: dict,
                          rules_out: dict | None = None):
        """Issue half of the async slow path: feed every extractable
        entry, collect its completed frames, batch ALL frames into one
        model call per (engine, width) bucket — futures only.  Oracle
        entries (host parsers) are computed right here.  Returns
        (buckets, plan): buckets = [(allow_dev, rule_dev, metas,
        engine)] where metas = [(plan_idx, msg, msg_len)], plan =
        per-entry records for the finish half."""
        plan = []  # (kind, key, i, sc, conn_id, frames | None)
        by_group: dict[tuple, list] = {}  # (id(engine), width) -> metas
        engines: dict[int, object] = {}
        oracle_marks = []
        for key, i, sc, conn_id, reply, end_stream, data in slow:
            engine = sc.engine
            extractable = (
                engine is not None
                and hasattr(engine, "feed_extract")
                and not isinstance(engine.model, ConstVerdict)
                and not reply
                and not end_stream
            )
            if not extractable:
                # ConstVerdict engines, oracle conns, reply, end_stream:
                # all host-only here (see _slow_async_eligible).
                responses[key][i] = self._run_slow_safe(
                    sc, conn_id, reply, end_stream, data
                )
                if rules_out is not None:
                    if engine is not None and (
                        getattr(engine, "handles_reply", False)
                        or not reply
                    ):
                        # Same routing as _run_slow: the engine decided.
                        rules_out[(key, i)] = self._engine_rule_kind(
                            engine, conn_id, sc
                        )
                    else:
                        rules_out[(key, i)] = (
                            int(sc.conn.last_rule_id), "",
                            self.policy_epoch,
                        )
                oracle_marks.append((conn_id, sc))
                continue
            conn = sc.conn
            # lint: disable=R7 -- the scalar oracle/fallback rung beside the columnar lane (reasm-ineligible minorities, greedy mode, parity oracle); the columnar path serves the volume
            frames = engine.feed_extract(
                conn_id, data, remote_id=conn.src_id,
                policy_name=conn.policy_name, ingress=conn.ingress,
                dst_id=conn.dst_id, src_addr=conn.src_addr,
                dst_addr=conn.dst_addr,
            )
            flowdebug.log(
                _flow_log, "flow %d extract: %d frame(s)",
                conn_id, len(frames),
            )
            # The MORE decision belongs to THIS entry's residue — decide
            # it now, not at finish time, when a later round may already
            # have drained or refilled the buffer.
            flow = engine.flows.get(conn_id)
            more = bool(frames) or bool(flow is not None and flow.buffer)
            rec = (key, i, sc, conn_id, engine, more, [])
            plan.append(rec)
            engines[id(engine)] = engine
            for msg, msg_len in frames:
                w = self.config.batch_width
                while msg_len > w:
                    w *= 2
                by_group.setdefault((id(engine), w), []).append(
                    (rec, msg, msg_len)
                )
        buckets = []
        for (eng_id, width), metas in sorted(by_group.items(),
                                             key=lambda kv: kv[0][1]):
            engine = engines[eng_id]
            n = len(metas)
            f_pad = self._min_bucket
            while f_pad < n:
                f_pad *= 2
            data_m = np.zeros((f_pad, width), np.uint8)
            lengths = np.zeros((f_pad,), np.int32)
            remotes = np.zeros((f_pad,), np.int32)
            for j, (rec, msg, msg_len) in enumerate(metas):
                row = np.frombuffer(engine.frame_row(msg), np.uint8)
                data_m[j, : len(row)] = row
                lengths[j] = msg_len
                remotes[j] = rec[2].conn.src_id
            _c, _m, allow, rule = self._model_call_attr(
                engine.model, data_m, lengths, remotes
            )
            # Record each frame's (bucket, slot) so the finish half can
            # emit ops in per-entry stream order.
            bi = len(buckets)
            for j, (rec, msg, msg_len) in enumerate(metas):
                rec[6].append((bi, j, msg, msg_len))
            buckets.append((allow, rule, metas, engine))
        if oracle_marks:
            self._tab_mark_many(oracle_marks)
        # Dirty flags for extract conns are written NOW, on the
        # dispatcher thread, before the next round can be classified:
        # a deferred mark would leave a stale-clean window in which a
        # vec/matrix batch re-admits a conn holding half a frame.
        # (Buffer state is final for this round — finish only drains
        # ops/inject, never buffers.)
        if plan:
            self._tab_mark_many([(rec[3], rec[2]) for rec in plan])
        return buckets, plan

    def _finish_slow_async(self, buckets: list, plan: list,
                           responses: dict, vals: list,
                           rules_out: dict | None = None) -> None:
        """Finish half: one readback per bucket (batched by the
        completion loop via ``vals`` — allow then, with attribution on,
        rule per bucket), then per-entry op emission in arrival order —
        MORE parity and inject draining identical to the wave path's
        pump()/take_ops."""
        allows = []
        ruless = []
        vi = 0
        for allow_dev, rule_dev, metas, _engine in buckets:
            v = vals[vi] if vi < len(vals) else None
            vi += 1
            rv = None
            if rule_dev is not None:
                rv = vals[vi] if vi < len(vals) else None
                vi += 1
            if v is None:
                try:
                    allows.append(np.asarray(allow_dev))
                except Exception:  # noqa: BLE001 — deny on device error
                    log.exception("device readback failed")
                    allows.append(np.zeros(len(metas), bool))
                    ruless.append(np.full(len(metas), -1, np.int32))
                    continue
            else:
                allows.append(np.asarray(v))
            if rv is not None:
                ruless.append(np.asarray(rv))
            elif rule_dev is not None:
                try:
                    ruless.append(np.asarray(rule_dev))
                except Exception:  # noqa: BLE001
                    ruless.append(np.full(len(metas), -1, np.int32))
            else:
                ruless.append(np.full(len(metas), -1, np.int32))
        for key, i, sc, conn_id, engine, more, slots in plan:
            try:
                # lint: disable=R7 -- scalar rung finish half (see _issue_slow_async): per-entry settle survives as the oracle beside the columnar lane
                ops, inject = engine.settle_entry(
                    conn_id,
                    [
                        (msg, msg_len, bool(allows[bi][j]),
                         int(ruless[bi][j]))
                        for bi, j, msg, msg_len in slots
                    ],
                    more,
                )
            except Exception:  # noqa: BLE001 — per-entry containment
                # The flow can be GONE by finish time: a quarantine
                # demotion (_demote_to_oracle pops engine.flows) or a
                # close raced this deferred completion — typically on a
                # deposed round whose seq the SHED reply already
                # answered.  One gone conn must not abort the rest of
                # the round's drain (their ops would leak into the next
                # round's take_ops); this entry fails closed typed.
                log.exception(
                    "async settle failed (conn %d)", conn_id
                )
                self.error_entries += 1
                responses[key][i] = (
                    conn_id, int(FilterResult.UNKNOWN_ERROR), [], b"", b"",
                )
                continue
            responses[key][i] = self._entry_response(
                conn_id, ops, b"", inject
            )
            if rules_out is not None:
                # Captured against the PLAN's engine (snapshotted at
                # issue time), never a re-read sc.engine: this finish
                # may run after a swap already rebound the conn.
                rules_out[(key, i)] = self._engine_rule_kind(
                    engine, conn_id, sc
                )

    def _issue_fast(self, fast: list) -> list:
        """Vectorized single-frame path, issue half: entries grouped
        per engine, one device call per group, futures kept — no
        readback here.  Returns [(allow_dev, rule_dev, recs)] (rule
        None without attribution)."""
        # Capture each record's engine ONCE at grouping: policy_update
        # rebinds sc.engine concurrently, and a re-read after grouping
        # could judge the group with a different engine's model.
        groups: dict[int, tuple] = {}
        for rec in fast:
            eng = rec[2].engine
            groups.setdefault(id(eng), (eng, []))[1].append(rec)
        issued = []
        for engine, recs in groups.values():
            n = len(recs)
            width = self.config.batch_width
            f_pad = self._min_bucket  # bucketed shapes, no jit churn
            while f_pad < n:
                f_pad *= 2
            data = np.zeros((f_pad, width), np.uint8)
            lengths = np.zeros((f_pad,), np.int32)
            remotes = np.zeros((f_pad,), np.int32)
            for i, (_, _, sc, _, payload) in enumerate(recs):
                arr = np.frombuffer(payload, np.uint8)
                data[i, : len(arr)] = arr
                lengths[i] = len(arr)
                remotes[i] = sc.conn.src_id
            complete, msg_len, allow, rule = self._model_call_attr(
                engine.model, data, lengths, remotes
            )
            issued.append((allow, rule, recs, engine))
        return issued

    def _finish_fast(self, issued: list, responses: dict,
                     vals: list | None = None,
                     rules_out: dict | None = None) -> None:
        """Readback + per-entry response build for _issue_fast groups.
        ``vals`` carries pre-fetched values (completion-loop batched
        device_get — allow then, with attribution on, rule per group);
        None entries mean the readback failed → deny.  ``rules_out``
        collects each entry's (deciding rule, match kind) keyed
        (item_key, entry_idx) for flow-record emission — the kind is
        resolved against the engine CAPTURED at judge time, not a
        re-read sc.engine (policy_update rebinds it concurrently and
        the rule row indexes the judging model's tables)."""
        vi = 0
        for allow_dev, rule_dev, recs, engine in issued:
            n = len(recs)
            rules = None
            if vals is not None:
                v = vals[vi]
                vi += 1
                rv = None
                if rule_dev is not None:
                    rv = vals[vi]
                    vi += 1
                allow = (
                    np.zeros(n, bool) if v is None else np.asarray(v)[:n]
                )
                # Unattribute when the ALLOW readback failed: the
                # entries were forced to deny, and stamping them with
                # the device's (allowing) rule would label a deny with
                # the rule that allowed it — mirror _readback_chunks.
                if rv is not None and v is not None:
                    rules = np.asarray(rv)[:n]
            else:
                try:
                    allow = np.asarray(allow_dev)[:n]
                    if rule_dev is not None:
                        rules = np.asarray(rule_dev)[:n]
                except Exception:  # noqa: BLE001 — deny on device error
                    log.exception("device readback failed")
                    allow = np.zeros(n, bool)
                    rules = None
            denied = int(n - allow.sum())
            self.fast_log.log_batch(
                getattr(engine, "proto", "r2d2"), n, denied
            )
            for i, (key, idx, sc, conn_id, payload) in enumerate(recs):
                if allow[i]:
                    ops = [(int(PASS), len(payload)), (int(MORE), 1)]
                    inj = b""
                else:
                    ops = [(int(DROP), len(payload)), (int(MORE), 1)]
                    inj = getattr(engine, "DENY_INJECT", b"ERROR\r\n")
                if rules_out is not None:
                    r_i = int(rules[i]) if rules is not None else -1
                    rules_out[(key, idx)] = (
                        r_i, self._kind_for(engine.model, r_i),
                        getattr(engine, "epoch", 0),
                    )
                responses[key][idx] = (
                    conn_id,
                    int(FilterResult.OK),
                    ops,
                    b"",
                    inj,
                )

    def _run_fast(self, fast: list, responses: dict,
                  rules_out: dict | None = None) -> None:
        """Synchronous fast path (inline mode): issue + finish."""
        self._finish_fast(self._issue_fast(fast), responses,
                          rules_out=rules_out)

    def _run_slow_batched(self, slow: list, responses: dict,
                          rules_out: dict | None = None) -> None:
        """Engine-backed slow entries are processed in WAVES: the nth
        entry of every connection is fed together and each engine is
        pumped ONCE per wave — a round's worth of frames (http/
        cassandra/memcached heads across every flow) is judged in one
        device batch per wave instead of one device call per entry,
        while per-connection order and per-entry op attribution are
        preserved (each conn contributes at most one entry per wave, so
        take_ops drains exactly that entry's ops).

        Oracle-path conns and end_stream entries keep the strict
        per-entry pipeline; once a connection has taken that path in
        this round, its later entries follow it (order)."""
        waves: list[list] = []
        wave_of: dict[int, int] = {}
        tainted: set[int] = set()
        leftovers: list = []
        for rec in slow:
            key, i, sc, conn_id, reply, end_stream, data = rec
            engine = sc.engine
            batchable = (
                engine is not None
                and not end_stream
                and conn_id not in tainted
                and (getattr(engine, "handles_reply", False) or not reply)
            )
            if not batchable:
                tainted.add(conn_id)
                leftovers.append(rec)
                continue
            w = wave_of.get(conn_id, 0)
            wave_of[conn_id] = w + 1
            while len(waves) <= w:
                waves.append([])
            # Engine snapshotted ONCE per record: policy_update rebinds
            # sc.engine concurrently, and feed/take must hit the same one.
            waves[w].append((rec, engine))

        for wave in waves:
            engines: dict[int, object] = {}
            failed: set[int] = set()
            for (key, i, sc, conn_id, reply, end_stream, data), engine in wave:
                self._feed_engine(engine, sc, conn_id, reply, data)
                engines[id(engine)] = engine
            for eid, engine in engines.items():
                try:
                    engine.pump()
                except Exception as exc:  # noqa: BLE001 — contain per engine
                    log.exception("engine pump failed")
                    self._record_contained_failure(
                        f"pump-crash: {type(exc).__name__}"
                    )
                    failed.add(eid)
            for (key, i, sc, conn_id, reply, end_stream, data), engine in wave:
                if id(engine) in failed:
                    self.error_entries += 1
                    responses[key][i] = (
                        conn_id, int(FilterResult.UNKNOWN_ERROR), [], b"", b"",
                    )
                else:
                    responses[key][i] = self._take_engine(
                        engine, conn_id, reply
                    )
                    if rules_out is not None:
                        # Attribution captured NOW, against the engine
                        # that judged the wave: churn may rebind
                        # sc.engine (and reuse its table slot) before
                        # record emission runs.
                        rules_out[(key, i)] = self._engine_rule_kind(
                            engine, conn_id, sc
                        )
                self._tab_mark(conn_id, sc)
        for rec in leftovers:
            key, i, sc, conn_id, reply, end_stream, data = rec
            responses[key][i] = self._run_slow_safe(
                sc, conn_id, reply, end_stream, data
            )
            if rules_out is not None:
                rules_out[(key, i)] = (
                    int(sc.conn.last_rule_id), "", self.policy_epoch,
                )
            self._tab_mark(conn_id, sc)

    @staticmethod
    def _feed_engine(engine, sc: "_SidecarConn", conn_id: int, reply: bool,
                     data: bytes) -> None:
        """One entry into an engine — the single definition of the feed
        kwargs contract, shared by the wave-batched and per-entry paths
        (they must never drift: both serve entries of the same conns)."""
        conn = sc.conn
        if getattr(engine, "handles_reply", False):
            engine.feed(
                conn_id, data, reply=reply, remote_id=conn.src_id,
                policy_name=conn.policy_name, dst_id=conn.dst_id,
                src_addr=conn.src_addr, dst_addr=conn.dst_addr,
            )
        else:
            engine.feed(
                conn_id, data, remote_id=conn.src_id,
                policy_name=conn.policy_name, ingress=conn.ingress,
                dst_id=conn.dst_id, src_addr=conn.src_addr,
                dst_addr=conn.dst_addr,
            )

    @staticmethod
    def _take_engine(engine, conn_id: int, reply: bool):
        """Drain one entry's ops into the response-tuple shape (shared
        by the wave-batched and per-entry paths)."""
        if getattr(engine, "handles_reply", False):
            ops, inj_o, inj_r = engine.take_ops(conn_id, reply)
        else:
            ops, inject = engine.take_ops(conn_id)
            inj_o, inj_r = b"", inject
        return VerdictService._entry_response(conn_id, ops, inj_o, inj_r)

    @staticmethod
    def _entry_response(conn_id: int, ops, inj_o: bytes, inj_r: bytes):
        """THE per-entry response tuple — the one definition shared by
        the wave path (_take_engine) and the async path
        (_finish_slow_async); they must never drift."""
        return (
            conn_id,
            int(FilterResult.OK),
            [(int(op), int(nn)) for op, nn in ops],
            inj_o,
            inj_r,
        )

    def _run_slow_safe(self, sc: _SidecarConn, conn_id: int, reply: bool,
                       end_stream: bool, data: bytes):
        """Per-entry crash containment: one entry's failure yields a
        typed error verdict for THAT entry instead of crashing the whole
        round (the dispatcher's on_batch_error remains the backstop)."""
        try:
            return self._run_slow(sc, conn_id, reply, end_stream, data)
        except Exception:  # noqa: BLE001
            log.exception("entry processing failed (conn %d)", conn_id)
            self.error_entries += 1
            return (conn_id, int(FilterResult.UNKNOWN_ERROR), [], b"", b"")

    def _run_slow(self, sc: _SidecarConn, conn_id: int, reply: bool,
                  end_stream: bool, data: bytes):
        """Stateful path: request direction through the batch engine when
        available, otherwise the in-process oracle parser."""
        # One engine snapshot for the whole entry: policy_update rebinds
        # sc.engine from a reader thread, and a mid-entry swap would
        # feed one engine but take_ops from another (empty) one.
        engine = sc.engine
        if engine is not None and (
            getattr(engine, "handles_reply", False) or not reply
        ):
            self._feed_engine(engine, sc, conn_id, reply, data)
            engine.pump()
            return self._take_engine(engine, conn_id, reply)

        # Oracle path: mirror the datapath buffer, loop while the parser
        # fills the op array (reference: cilium_proxylib.cc:301 do-while).
        buf = sc.bufs[reply]
        cap = self.config.max_flow_buffer
        if cap and len(buf) + len(data) > cap:
            # Bounded retained-data contract: a flow buffering past the
            # cap without a frame boundary gets a typed protocol-error
            # DROP of everything retained + incoming, and dies.  Result
            # stays OK so the shim APPLIES the DROP (consuming its
            # retained bytes) before the ERROR op surfaces PARSER_ERROR.
            dropped = len(buf) + len(data)
            buf.clear()
            metrics.FlowBufferOverflows.inc(sc.conn.parser_name)
            return (
                conn_id,
                int(FilterResult.OK),
                [
                    (int(DROP), dropped),
                    (int(ERROR), int(OpError.ERROR_INVALID_FRAME_LENGTH)),
                ],
                b"",
                b"",
            )
        buf += data
        all_ops: list[tuple[int, int]] = []
        result = FilterResult.OK
        # Loop while the parser fills the op array AND makes progress:
        # a full op array means more complete frames may still be
        # buffered, and a quiescent peer would never trigger another
        # pass, so draining must not be capped at a fixed iteration
        # count (tail frames would stall indefinitely).
        #
        # Each pass hands the parser a bounded WINDOW of the backlog
        # instead of the whole buffer: parsers re-join their input per
        # invocation, so feeding the full backlog every pass is
        # quadratic on large bursts.  A MORE emitted while bytes were
        # withheld by the window is an artifact — the window grows (or
        # the next pass continues after consumption) instead of
        # surfacing it.
        window = 1 << 16
        while True:
            avail = len(buf)
            windowed = avail > window
            chunk = bytes(memoryview(buf)[:window]) if windowed else bytes(buf)
            ops: list = []
            # end_stream only reaches the parser once the window covers
            # the whole backlog — withheld bytes mean the stream has not
            # actually ended from the parser's point of view.
            result = sc.conn.on_data(
                reply, end_stream and not windowed, [chunk], ops
            )
            consumed = 0
            progress = False
            deferred_more = False
            for op, nbytes in ops:
                if op == MORE and windowed:
                    deferred_more = True
                    continue
                all_ops.append((int(op), int(nbytes)))
                if op in (PASS, DROP):
                    take = min(nbytes, avail - consumed)
                    consumed += take
                    sc.skip[reply] += nbytes - take
                    if take:
                        progress = True
            if consumed:
                del buf[:consumed]
            if result != FilterResult.OK:
                break
            if deferred_more:
                if not progress:
                    window *= 2  # frame larger than the window
                continue
            if len(ops) < wire.MAX_OPS_PER_ENTRY:
                break
            if not progress:
                break
        inj_orig = sc.conn.orig_buf.take()
        inj_reply = sc.conn.reply_buf.take()
        return (conn_id, int(result), all_ops, inj_orig, inj_reply)


def _matrix_to_batch(mb: wire.MatrixBatch) -> wire.DataBatch:
    """Fallback conversion for matrix batches that miss the vectorized
    path: unpad rows into a variable-length DataBatch."""
    parts = [
        mb.rows[i, : int(mb.lengths[i])].tobytes() for i in range(mb.count)
    ]
    batch = wire.DataBatch(
        mb.seq,
        mb.conn_ids,
        np.zeros(mb.count, np.uint8),
        mb.lengths,
        b"".join(parts),
    )
    # Alias the answered cell: real-verdict sends mark the conversion,
    # but the dispatcher's _current_batch (what a deposal/crash sweep
    # iterates) still holds the ORIGINAL mat item — a separate flag
    # would let the sweep double-reply a seq the round already served.
    batch._acell = mb._acell
    batch.deadline = mb.deadline
    batch.arrival = mb.arrival
    batch.ring_wait = mb.ring_wait
    return batch


def _death_reason_for(e: OSError) -> str:
    """Typed session-death reason for a failed reply write: a sendall
    bounded by SO_SNDTIMEO surfaces EAGAIN (BlockingIOError) when the
    peer stopped reading, or socket.timeout on some platforms — both
    are the stalled-reader signature; anything else is a broken
    stream.  One definition so every _kill site types identically."""
    return (
        DEATH_SEND_TIMEOUT
        if isinstance(e, (socket.timeout, BlockingIOError))
        else DEATH_WRITE_FAILED
    )


class _ClientHandler:
    """Reader thread + serialized writer for one shim socket."""

    def __init__(self, service: VerdictService, sock: socket.socket):
        self.service = service
        self.sock = sock
        self._wlock = threading.Lock()
        self.module_id = 0
        # Fan-in session state (transport.SessionState): the unit of
        # fault isolation — admission quotas, quarantine latch, and
        # the per-session exactly-once counters all live here.
        self.session = service._new_session()
        # Shared-memory fast path for this session (transport.ShmPeer),
        # attached via MSG_SHM_ATTACH.  Data drains run on this
        # handler's reader thread (SPSC consumer); verdict pushes are
        # serialized under _wlock (SPSC producer).  A detached peer is
        # retained for status: its fallback counters and quarantine
        # reason outlive the rings (operators read them AFTER a fault).
        self.shm: ShmPeer | None = None
        self.shm_detached: ShmPeer | None = None
        # Verdict-cache opt-in (MSG_CACHE_ENABLE): the service never
        # sends MSG_CACHE_GRANT/REVOKE frames to a shim that did not
        # announce support — the native shim's dispatch table stays
        # untouched.
        self.cache_ok = False
        # Kernel send timeout (send only — settimeout would also bound
        # the reader's recv): a shim that stopped READING wedges
        # sendall while this handler's _wlock is held, and every later
        # replier for this client — including the stall watchdog's
        # deposal shed sweep — blocks behind it unboundedly, disabling
        # stall containment service-wide.  With the bound, the wedged
        # write errors out, releases the lock, and the handler is torn
        # down (_kill) — one dead peer costs its own connection, never
        # the watchdog.
        timeout_s = service.guard.timeout_s or 10.0
        try:
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("ll", int(timeout_s),
                            int((timeout_s % 1.0) * 1e6)),
            )
        except OSError:  # pragma: no cover — platform without SNDTIMEO
            pass

    def _kill(self, reason: str = DEATH_WRITE_FAILED) -> None:
        """Tear the socket down after a failed/timed-out write: the
        frame may be half-written, so the stream is unusable — a peer
        still reading it would desync.  shutdown() wakes the reader
        thread (which owns the close) and makes every later write fail
        fast; the shim sees EOF and fails over/reconnects.  The kill
        is typed on the session (send_timeout = the shim stopped
        reading and SO_SNDTIMEO fired — ONE session's cost, never the
        watchdog's): the reader's teardown path keeps the first
        recorded reason."""
        if self.session.death_reason is None:
            self.session.death_reason = reason
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    # -- shm transport (service half) -------------------------------------

    def transport_status(self) -> dict:
        shm = self.shm or self.shm_detached
        base = {
            "session": self.session.id,
            "identity": self.session.identity,
        }
        if shm is None:
            return {**base, "mode": TRANSPORT_SOCKET}
        return {**base, **shm.status()}

    def _transport_reject(self, reason: str) -> None:
        svc = self.service
        svc.transport_rejects[reason] = (
            svc.transport_rejects.get(reason, 0) + 1
        )
        metrics.SidecarTransportFallback.inc(reason)

    def _shm_attach(self, payload: bytes) -> dict:
        """Negotiate the shared-memory fast path: validate generation/
        magic/geometry and map the client's segments.  Every failure is
        a TYPED rejection — the client stays on the socket rung and the
        session keeps serving (fallback serves)."""
        rep = {
            "status": int(FilterResult.OK),
            "generation": 0,
            "error": "",
            # Segment lease: how long the service waits after this
            # session dies WITHOUT an MSG_SHM_DETACH before unlinking
            # the segments itself (the abrupt-death leak guard).
            "lease_s": self.service.config.shm_lease_s,
        }
        if not self.service.config.shm_transport:
            rep["status"] = int(FilterResult.UNKNOWN_ERROR)
            rep["error"] = "shm transport disabled by service config"
            self._transport_reject(REASON_DISABLED)
            return rep
        try:
            req = json.loads(payload.decode())
            peer = ShmPeer.attach({
                "generation": req["generation"],
                "data": req["data"],
                "verdict": req["verdict"],
            })
        except GenerationMismatch as e:
            # Stale/corrupt segment: its embedded generation (or magic/
            # geometry) contradicts the negotiated one.
            rep["status"] = int(FilterResult.UNKNOWN_ERROR)
            rep["error"] = str(e)
            self._transport_reject(REASON_GENERATION)
            return rep
        except RingError as e:
            rep["status"] = int(FilterResult.UNKNOWN_ERROR)
            rep["error"] = str(e)
            self._transport_reject(REASON_ATTACH_REJECTED)
            return rep
        except Exception as e:  # noqa: BLE001 — malformed request
            log.exception("shm attach failed")
            rep["status"] = int(FilterResult.UNKNOWN_ERROR)
            rep["error"] = f"{type(e).__name__}: {e}"
            self._transport_reject(REASON_ATTACH_REJECTED)
            return rep
        old, self.shm = self.shm, peer
        if old is not None:
            old.close()
        rep["generation"] = peer.generation
        # Transport promoted: timeline mark + re-arm the postmortem
        # latch (a successful attach is the heal for shm demotion).
        self.service.recorder.record_mark(
            "shm_attach", session=self.session.id
        )
        log.info(
            "shm transport attached (generation %d, %d data slots)",
            peer.generation, peer.data.slots,
        )
        return rep

    def _shm_detach(self, generation: int) -> None:
        shm = self.shm
        if shm is None or shm.generation != generation:
            return
        self.shm = None
        self.shm_detached = shm
        shm.close()

    def _shm_doorbell(self, payload: bytes, reader) -> None:
        """Drain the data ring through the doorbelled tail (reader
        thread = SPSC consumer), stamp ring-stage timing, and credit
        the freed slots back.  A torn slot quarantines the ring and
        demotes the session — typed, never a hang, never silent."""
        shm = self.shm
        if shm is None:
            # lint: disable=R14 -- a doorbell is a wakeup, not an entry: with no attached session nothing was admitted here, and detach/quarantine sweeps already answered any ring frames typed on the shim side
            return
        generation, data_tail, verdict_head = wire.unpack_shm_doorbell(
            payload
        )
        if generation != shm.generation:
            # lint: disable=R14 -- stale doorbell from a superseded session: its ring is destroyed and the shim's demotion sweep answered every undelivered seq typed before re-attaching; nothing is admitted on this path
            return
        if verdict_head > shm.v_credit_head:
            shm.v_credit_head = verdict_head
        target = data_tail
        while shm.active:
            records = []
            fault = False
            try:
                while shm.head < target:
                    msg_type, frame, t_commit = shm.data.read(shm.head)
                    if msg_type not in (
                        wire.MSG_DATA_BATCH,
                        wire.MSG_DATA_BATCH_DL,
                        wire.MSG_DATA_MATRIX,
                    ):
                        # lint: disable=R15 -- this raise IS the drain's typed exit: the RingError handler latches fault, frames drained before it are still submitted, and _shm_quarantine answers with a quarantined credit (the shim sheds never-admitted frames typed itself)
                        raise RingError(
                            f"unexpected data-ring frame type {msg_type}"
                        )
                    shm.head += 1
                    shm.data.set_head(shm.head)
                    records.append((self._parse_data(msg_type, frame),
                                    t_commit))
            except RingError:
                log.exception("data ring fault; quarantining shm session")
                fault = True
            # Frames drained BEFORE a torn slot are admitted work and
            # must be submitted: the quarantined credit's data_head is
            # this boundary, and the shim skips shedding everything
            # below it on the promise that real verdicts (socket frames
            # after the quarantine) are coming.  Discarding them here
            # would strand their callers against that promise — silent
            # loss by timeout.
            if records:
                self._shm_submit_records(shm, records, reader)
            if fault:
                self._shm_quarantine()
                return
            if not records:
                return
            # Tail-mirror recheck: frames published while this drain
            # (or its inline round) ran are picked up NOW instead of
            # waiting out a credit → re-doorbell round trip (the
            # notification bubble measured ~1ms of p99 at 100k/s).
            # The mirror is stored AFTER each slot's commit word, so
            # everything below it passes the same torn-slot check; a
            # doorbell is then purely a wakeup, never load-bearing.
            fresh = shm.data.tail
            if fresh <= shm.head:
                return
            target = fresh

    def _shm_submit_records(self, shm: ShmPeer, records: list,
                            reader) -> None:
        """Stamp and submit one drained run: ring-stage timing anchored
        at slot commit, one dispatcher admission, and the drain credit
        (suppressed when the round already emitted one — greedy-mode
        cut-through processes inline and its verdict-ring write sends a
        credit carrying the advanced head; the redundant frame measured
        ~60µs of p50 on the per-RPC seam)."""
        shm.counters.doorbell(len(records))
        now = time.monotonic()
        for (_kind, batch), t_commit in records:
            shm.counters.data_frames += 1
            wait = max(now - t_commit, 0.0) if t_commit else 0.0
            batch.ring_wait = wait
            if t_commit:
                # Anchor arrival (and any deadline budget) at slot
                # commit, not at drain: queue-age shedding and the
                # latency decomposition must see the ring wait.
                batch.arrival = t_commit
                if batch.deadline is not None:
                    batch.deadline -= wait
        credits_before = shm.counters.credits
        self.service.submit_ring(
            self, [rec for rec, _t in records],
            reader_backlog=reader.pending,
        )
        if shm.counters.credits == credits_before:
            self._send_credit()

    def _shm_quarantine(self, reason: str = REASON_TORN_SLOT) -> None:
        """Ring fault containment: latch THIS session off the shm rung
        and tell the shim with a quarantined credit.  The shim demotes
        to the socket transport and answers never-admitted ring frames
        typed itself (zero silent loss); this handler and all its
        flows keep serving over the socket — no other session is
        touched.

        Latch AND credit happen under _wlock: a verdict emitter is
        either fully done (its ring write is covered by this credit's
        vtail, so the shim drains it before demoting) or has not
        checked ``active`` yet (and will route to the socket).  A
        latch outside the lock could let a ring write land AFTER the
        quarantined credit — stranded in a ring the shim already
        destroyed, a silently lost verdict."""
        shm = self.shm
        if shm is None:
            return
        with self._wlock:
            if not shm.quarantine(reason):
                return
            self.service.recorder.record_mark(
                "shm_demotion", reason=reason, session=self.session.id
            )
            try:
                # lint: disable=R2 -- the quarantined credit must serialize with verdict-ring writes under this handler's write lock (see docstring); SO_SNDTIMEO bounds a wedge
                self._send_credit_locked(CREDIT_FLAG_QUARANTINED)
            except OSError as e:
                self._kill(_death_reason_for(e))

    def _send_credit(self, flags: int = 0) -> None:
        with self._wlock:
            if self.shm is None:
                return
            try:
                # lint: disable=R2 -- credit frames must serialize with verdict-ring writes under this handler's write lock (same contract as send()); SO_SNDTIMEO bounds a wedged peer
                self._send_credit_locked(flags)
            except OSError as e:
                self._kill(_death_reason_for(e))

    def _send_credit_locked(self, flags: int = 0) -> None:
        shm = self.shm
        shm.counters.credits += 1
        wire.send_msg(
            self.sock,
            wire.MSG_SHM_CREDIT,
            wire.pack_shm_credit(
                shm.generation, flags, shm.head, shm.verdict.tail
            ),
        )

    def _emit_frames_locked(self, msg_type: int,
                            payloads: list[bytes]) -> None:
        """Write frames to the client (write lock held; caller owns
        OSError containment).  Verdict frames ride the shm verdict
        ring — ONE credit frame wakes the shim for the whole round —
        when a session is attached and has room; anything else, and
        every ring-refused frame, goes out as a socket frame."""
        shm = self.shm
        rest = payloads
        if (
            shm is not None
            and shm.active
            and msg_type in (wire.MSG_VERDICT_BATCH,
                             wire.MSG_VERDICT_MULTI)
        ):
            rest = []
            pushed = 0
            for p in payloads:
                if not shm.verdict.fits(len(p)):
                    shm.counters.fallback(REASON_OVERSIZE)
                    shm.oversize_run += 1
                    rest.append(p)
                elif shm.verdict.try_push(msg_type, p,
                                          shm.v_credit_head):
                    pushed += 1
                    shm.oversize_run = 0
                else:
                    shm.counters.fallback(REASON_VERDICT_RING_FULL)
                    rest.append(p)
            if pushed:
                shm.counters.verdict_frames += pushed
                self._send_credit_locked()
            spree = self.service.config.shm_oversize_spree
            if spree and shm.oversize_run >= spree and shm.active:
                # Every frame this session produces misses the ring:
                # the per-frame fit check is pure overhead.  Demote
                # THIS session's shm rung typed (we already hold
                # _wlock — same latch-and-credit ordering contract as
                # _shm_quarantine).
                if shm.quarantine(REASON_OVERSIZE_SPREE):
                    self.service.recorder.record_mark(
                        "shm_demotion",
                        reason=REASON_OVERSIZE_SPREE,
                        session=self.session.id,
                    )
                    try:
                        # lint: disable=R2 -- quarantined credit under the held handler write lock, same contract as _shm_quarantine
                        self._send_credit_locked(CREDIT_FLAG_QUARANTINED)
                    except OSError as e:
                        self._kill(_death_reason_for(e))
        if rest:
            self.sock.sendall(
                b"".join(
                    wire.HEADER.pack(wire.MAGIC, msg_type, len(p)) + p
                    for p in rest
                )
            )

    def _suppressed(self) -> bool:
        """True on a thread whose round the stall watchdog shed (the
        stuck worker/cut-through reader itself, or the send loop
        emitting a record that round queued) and on a deposed worker —
        the batch already received typed shed verdicts, so a late send
        (after the stall clears) would duplicate/interleave replies."""
        disp = self.service.dispatcher
        return disp.thread_is_deposed() or disp.thread_round_is_shed()

    def send(self, msg_type: int, payload: bytes, batches=None) -> bool:
        """Returns True only when THIS call answered the covered
        seq(s) — it marked the batches and attempted the write (an
        OSError to a gone client still counts: there is no one left to
        shed to).  False means the call stood down without writing:
        round/generation-suppressed, or a racing reply already
        answered.  Fail-closed repliers key their shed/error COUNTERS
        on this — counting a stood-down reply would double-book an
        entry as both served and shed.  ``batches``: the wire batches
        this payload answers.  They are marked ``answered`` ATOMICALLY
        under the write lock BEFORE the write, so a fail-closed
        replier (shed/crash containment) racing a real-verdict send —
        including one currently wedged inside this very sendall, which
        is exactly what trips the stall watchdog — can never add a
        second reply for a seq the shim will consume.  ANY batch
        already answered stands the whole payload down: a packed
        multi-seq payload cannot be split, and a deposal sweep that
        got to one of its batches first will (or did) answer the
        siblings typed too — writing anyway would double-reply the
        answered seq."""
        if self._suppressed():
            return False
        with self._wlock:
            if batches:
                if any(b.answered for b in batches):
                    return False  # a racing reply already answered
                for b in batches:
                    b.answered = True
                # THE per-session answered count: the marking site is
                # the single point every typed reply (verdict, SHED,
                # error; ring or socket) passes exactly once, so the
                # fan-in exactly-once surface (submitted == answered
                # after quiesce) is counted where it is enforced.
                self.session.answered += sum(
                    getattr(b, "count", 0) for b in batches
                )
            try:
                # lint: disable=R2 -- _wlock IS the sendall serializer (the answered-flag dance requires it); a wedged write trips the stall watchdog and _kill breaks the socket
                self._emit_frames_locked(msg_type, [payload])
            except OSError as e:
                self._kill(_death_reason_for(e))
        return True

    def send_frames(self, msg_type: int, payloads: list[bytes],
                    batches=None) -> bool:
        """One sendall for a round's worth of frames to this client;
        ``batches`` parallels ``payloads``.  Same contract as send(),
        per frame: a frame whose batch was already answered is dropped
        under the write lock, the rest are marked answered before the
        write; True only when this call answered at least one frame."""
        if self._suppressed():
            return False
        with self._wlock:
            if batches is not None:
                keep = [
                    i for i, b in enumerate(batches) if not b.answered
                ]
                if not keep:
                    return False  # every frame lost its race: stand down
                for i in keep:
                    batches[i].answered = True
                # Same per-session answered count as send(): only the
                # frames THIS call actually answered.
                self.session.answered += sum(
                    getattr(batches[i], "count", 0) for i in keep
                )
                if len(keep) != len(payloads):
                    payloads = [payloads[i] for i in keep]
            try:
                # lint: disable=R2 -- same contract as send(): _wlock serializes the one-sendall round write; watchdog+_kill bound a wedge
                self._emit_frames_locked(msg_type, payloads)
            except OSError as e:
                self._kill(_death_reason_for(e))
        return True

    def send_verdicts(self, seq: int, entries: list, batch=None) -> bool:
        """entries: (conn_id, result, ops, inject_orig, inject_reply) —
        op lists longer than the ABI capacity split into continuation
        entries (reference: 16-op OnIO array, cilium_proxylib.cc:199).
        Same contract as send(); ``batch`` is the wire batch this
        reply answers."""
        conn_ids, results, op_counts = [], [], []
        inj_o, inj_r = [], []
        flat_ops: list[tuple[int, int]] = []
        blob = bytearray()
        for conn_id, result, ops, io, ir in entries:
            chunks = [
                ops[k : k + wire.MAX_OPS_PER_ENTRY]
                for k in range(0, len(ops), wire.MAX_OPS_PER_ENTRY)
            ] or [[]]
            for ci, chunk in enumerate(chunks):
                last = ci == len(chunks) - 1
                conn_ids.append(conn_id)
                results.append(result)
                op_counts.append(len(chunk))
                flat_ops.extend(chunk)
                if last:
                    inj_o.append(len(io))
                    inj_r.append(len(ir))
                    blob += io
                    blob += ir
                else:
                    inj_o.append(0)
                    inj_r.append(0)
        ops_arr = np.zeros((len(flat_ops),), wire.FILTER_OP)
        if flat_ops:
            ops_arr["op"] = [o for o, _ in flat_ops]
            ops_arr["n_bytes"] = [n for _, n in flat_ops]
        return self.send(
            wire.MSG_VERDICT_BATCH,
            wire.pack_verdict_batch(
                seq, conn_ids, results, op_counts, inj_o, inj_r,
                ops_arr, bytes(blob),
            ),
            batches=None if batch is None else [batch],
        )

    @staticmethod
    def _parse_data(msg_type: int, payload: bytes):
        if msg_type == wire.MSG_DATA_BATCH:
            return ("data", wire.unpack_data_batch(payload))
        if msg_type == wire.MSG_DATA_BATCH_DL:
            budget_s, batch = wire.unpack_data_batch_dl(payload)
            # Anchor the relative budget to this host's monotonic clock
            # at receive: entries still queued past it are shed typed.
            batch.deadline = time.monotonic() + budget_s
            return ("data", batch)
        return ("mat", wire.unpack_data_matrix(payload))

    def read_loop(self) -> None:
        reader = wire.BufferedReader(self.sock)
        svc = self.service
        try:
            while True:
                msg_type, payload = reader.recv_msg()
                if msg_type in (
                    wire.MSG_DATA_BATCH,
                    wire.MSG_DATA_BATCH_DL,
                    wire.MSG_DATA_MATRIX,
                ):
                    kind, batch = self._parse_data(msg_type, payload)
                    # Backlog probe: bytes already buffered behind this
                    # frame mean the reader is behind — route to the
                    # dispatcher so the worker aggregates the backlog
                    # into one device round.  An idle stream cuts
                    # through (processed right here, no handoff).
                    backlogged = reader.pending
                    if kind == "data":
                        svc.submit_data(self, batch, backlogged=backlogged)
                    else:
                        svc.submit_matrix(self, batch, backlogged=backlogged)
                elif msg_type == wire.MSG_SHM_DOORBELL:
                    self._shm_doorbell(payload, reader)
                elif msg_type == wire.MSG_SHM_ATTACH:
                    self.send(
                        wire.MSG_SHM_ATTACH_REPLY,
                        json.dumps(self._shm_attach(payload)).encode(),
                    )
                elif msg_type == wire.MSG_SHM_DETACH:
                    gen, dflags = wire.unpack_shm_detach(payload)
                    self._shm_detach(gen)
                    if not dflags & wire.DETACH_FLAG_NO_ACK:
                        self.send(
                            wire.MSG_ACK,
                            wire.pack_ack(int(FilterResult.OK)),
                        )
                elif msg_type == wire.MSG_SESSION_HELLO:
                    # Fire-and-forget identity announcement: names the
                    # session for quotas/metrics and runs crash-loop
                    # (reconnect-storm) detection.
                    svc._session_hello(
                        self.session, wire.unpack_session_hello(payload)
                    )
                elif msg_type == wire.MSG_CACHE_ENABLE:
                    # Fire-and-forget opt-in; grants start flowing for
                    # conns registered from here on.
                    self.cache_ok = True
                elif msg_type == wire.MSG_CLOSE:
                    self.service.submit_close(wire.unpack_close(payload))
                elif msg_type == wire.MSG_NEW_CONNECTION:
                    args = wire.unpack_new_connection(payload)
                    res, grant, cflags = self.service.new_connection(
                        *args, client=self
                    )
                    # Trailing result-flags word (RESIDUE_ADOPTED):
                    # old shims stop reading after the u4 result.
                    self.send(
                        wire.MSG_CONN_RESULT,
                        np.array([args[1]], "<u8").tobytes()
                        + np.array([res], "<u4").tobytes()
                        + np.array([cflags], "<u4").tobytes(),
                    )
                    if grant is not None:
                        # After the reply: the shim's post-RPC stale-
                        # grant drop is ordered BEFORE this frame.
                        self.service._send_cache_grants([grant])
                elif msg_type == wire.MSG_OPEN_MODULE:
                    params, debug = wire.unpack_open_module(payload)
                    self.module_id = self.service.open_module(params, debug)
                    self.send(
                        wire.MSG_MODULE_ID,
                        np.array([self.module_id], "<u8").tobytes(),
                    )
                elif msg_type == wire.MSG_POLICY_UPDATE:
                    module_id, pj = wire.unpack_policy_update(payload)
                    status, epoch = self.service.policy_update(
                        module_id, pj
                    )
                    self.send(
                        wire.MSG_ACK, wire.pack_ack_epoch(status, epoch)
                    )
                elif msg_type == wire.MSG_HANDOFF:
                    # Successor side channel: the claimant dialed our
                    # socket path.  Surrender runs on THIS reader
                    # thread (quiesce, snapshot, fence, release the
                    # path); a refusal is typed in the reply so the
                    # claimant cold-boots instead of hanging.
                    gen, deadline_s = wire.unpack_handoff(payload)
                    if gen < 0:
                        snap, err = None, "malformed handoff request"
                    else:
                        snap, err = svc.handoff_surrender(
                            gen, deadline_s
                        )
                    self.send(
                        wire.MSG_HANDOFF_REPLY,
                        wire.pack_handoff_reply(snap, err),
                    )
                elif msg_type == wire.MSG_STATUS:
                    self.send(
                        wire.MSG_STATUS_REPLY,
                        json.dumps(self.service.status()).encode(),
                    )
                elif msg_type == wire.MSG_TRACE:
                    # A malformed diagnostic request must never kill
                    # this read loop (it would tear down every flow on
                    # the shim connection): any parse/shape problem
                    # degrades to the defaults.
                    try:
                        req = json.loads(payload.decode()) if payload else {}
                        n = int(req.get("n", 100))
                        kind = req.get("kind")
                        if kind is not None:
                            kind = str(kind)
                        session = req.get("session")
                        if session is not None:
                            session = int(session)
                    except (ValueError, TypeError, AttributeError,
                            UnicodeDecodeError):
                        n, kind, session = 100, None, None
                    self.send(
                        wire.MSG_TRACE_REPLY,
                        json.dumps(
                            self.service.trace_dump(
                                n, kind, session=session
                            )
                        ).encode(),
                    )
                elif msg_type == wire.MSG_TIMELINE:
                    # Same containment as MSG_TRACE: a malformed
                    # diagnostic request degrades to defaults, never
                    # kills the shim connection's read loop.
                    try:
                        req = json.loads(payload.decode()) if payload else {}
                        n = int(req.get("n", 100))
                        since = int(req.get("since", 0))
                        table = req.get("table")
                        if table is not None:
                            table = str(table)
                    except (ValueError, TypeError, AttributeError,
                            UnicodeDecodeError):
                        n, since, table = 100, 0, None
                    self.send(
                        wire.MSG_TIMELINE_REPLY,
                        json.dumps(
                            self.service.timeline_dump(
                                n=n, since=since, table=table
                            )
                        ).encode(),
                    )
                elif msg_type == wire.MSG_LEDGER:
                    # Same containment as MSG_TRACE: a malformed
                    # diagnostic request degrades to defaults, never
                    # kills the shim connection's read loop.
                    try:
                        req = json.loads(payload.decode()) if payload else {}
                        n = int(req.get("n", 100))
                        since = int(req.get("since", 0))
                        cause = req.get("cause")
                        if cause is not None:
                            cause = str(cause)
                    except (ValueError, TypeError, AttributeError,
                            UnicodeDecodeError):
                        n, since, cause = 100, 0, None
                    self.send(
                        wire.MSG_LEDGER_REPLY,
                        json.dumps(
                            self.service.ledger_dump(
                                n=n, since=since, cause=cause
                            )
                        ).encode(),
                    )
                elif msg_type == wire.MSG_OBSERVE:
                    # Same containment as MSG_TRACE: a malformed
                    # diagnostic request degrades to defaults, never
                    # kills the shim connection's read loop.
                    try:
                        req = json.loads(payload.decode()) if payload else {}
                        if not isinstance(req, dict):
                            req = {}
                    except (ValueError, UnicodeDecodeError):
                        req = {}
                    try:
                        out = self.service.observe_dump(req)
                    except (TypeError, ValueError):
                        out = self.service.observe_dump({})
                    self.send(
                        wire.MSG_OBSERVE_REPLY, json.dumps(out).encode()
                    )
                else:
                    log.warning("unknown message type %d", msg_type)
        except wire.ConnectionClosed:
            pass
        except OSError:
            pass
        finally:
            # The reader owns the close (see _kill); shutdown first so
            # a send-loop thread mid-sendall on this socket fails fast
            # instead of deferring the fd teardown.
            shutdown_close(self.sock)
            # Peer death releases the ring mappings (the creator owns
            # the segments; our views just unmap).  A session that died
            # holding an ACTIVE shm rung is counted — the operator-
            # visible difference between orderly detach and a vanished
            # shim — and its segments are leased for reclaim: the dead
            # creator will never unlink them, so the survivor must
            # (after lease expiry) or /dev/shm leaks one ring pair per
            # crash.  In-flight rounds for this session need no sweep:
            # their sends hit the dead socket and are counted answered
            # (there is no one left to shed to), and the answered-cell
            # marking still runs under _wlock so a late replier races
            # exactly once.
            abrupt = False
            shm = self.shm
            if shm is not None:
                self.shm = None
                if shm.active:
                    abrupt = True
                    shm.counters.fallback(REASON_PEER_DEATH)
                shm.close()
                # No MSG_SHM_DETACH ever arrived for these rings —
                # orderly clients detach (or demote, which detaches)
                # before dying.  Schedule the survivor-side unlink.
                self.service._schedule_shm_reclaim(shm)
            # Retire the session typed: a kill path (_kill) recorded
            # its reason first; otherwise EOF with a live shm rung is
            # the abrupt-death signature and a plain EOF is orderly.
            self.service._session_dead(
                self.session,
                DEATH_ABRUPT if abrupt else DEATH_CLOSED,
            )
            # Prune this handler so reconnecting shims don't accumulate
            # dead entries for the service's lifetime.
            with self.service._lock:
                try:
                    self.service._clients.remove(self)
                except ValueError:
                    pass
