"""Added-latency benchmark for the sidecar seam.

Measures what the north star actually demands (BASELINE.json: <1ms added
p99): the latency a request experiences crossing the full seam —
client-side batch fill wait → wire hop → service dispatcher
(fill-vs-deadline) → device verdict → wire hop back — under open-loop
Poisson arrivals at configurable offered rates, versus the per-request
in-process oracle (the ported proxylib parser, the reference's
in-process cost).

Open loop: arrival timestamps are drawn ahead of time from an
exponential inter-arrival distribution and requests are released on
schedule regardless of completions, so queueing delay under overload
shows up honestly in the percentiles.  If the generator itself cannot
keep up with the offered rate, the run is flagged ``gen_saturated`` and
the achieved rate is reported.

Everything runs in one process (the TPU runtime is single-process per
chip); the service's device dispatch happens on the dispatcher thread,
the generator and reader on their own threads.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from dataclasses import dataclass

import numpy as np

from ..proxylib import instance as pl
from ..proxylib.types import FilterResult
from ..utils.option import DaemonConfig
from ..utils.sockutil import shutdown_close
from . import wire
from .client import SidecarClient
from .service import VerdictService

CONN_POOL = 4096


class NullVerdictServer:
    """The null-seam control: same unix socket, same wire framing, same
    reader-thread structure as VerdictService — but the verdict is an
    immediate constant written from the reader thread.  No dispatcher,
    no batching windows, no device.  Under the identical open-loop
    generator, this server's latency percentiles ARE the environmental
    floor (socket + framing + host scheduler); the seam's
    architecture-attributable added latency is seam_p99 − null_p99."""

    dispatch_mode_chosen = "null"

    class _Zero:
        batches = entries = fill_dispatches = deadline_dispatches = 0

    def __init__(self, socket_path: str) -> None:
        self.socket_path = socket_path
        self.dispatcher = self._Zero()
        self.inline_batches = 0
        self.vec_batches = 0
        self.vec_entries = 0
        self.seam_stages: dict = {}
        self._stopped = False
        try:
            os.unlink(socket_path)
        except OSError:
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(socket_path)
        self._listener.listen(8)
        self._threads: list[threading.Thread] = []

    def start(self) -> "NullVerdictServer":
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve, args=(sock,), daemon=True
            )
            t.start()
            self._threads.append(t)

    @staticmethod
    def _const_verdict(seq: int, conn_ids: np.ndarray) -> bytes:
        n = len(conn_ids)
        zeros = np.zeros(n, "<u4").tobytes()
        return (
            struct.pack("<QI", seq, n)
            + np.ascontiguousarray(conn_ids, "<u8").tobytes()
            + zeros  # results: all OK
            + zeros  # op_counts: none
            + zeros + zeros  # inject lens
        )

    def _serve(self, sock: socket.socket) -> None:
        reader = wire.BufferedReader(sock)
        try:
            while True:
                msg_type, payload = reader.recv_msg()
                if msg_type == wire.MSG_DATA_MATRIX:
                    seq, n = struct.unpack_from("<QI", payload, 0)
                    conn_ids = np.frombuffer(payload, "<u8", n, 17)
                    wire.send_msg(
                        sock, wire.MSG_VERDICT_BATCH,
                        self._const_verdict(seq, conn_ids),
                    )
                elif msg_type == wire.MSG_DATA_BATCH:
                    seq, n = struct.unpack_from("<QI", payload, 0)
                    conn_ids = np.frombuffer(payload, "<u8", n, 12)
                    wire.send_msg(
                        sock, wire.MSG_VERDICT_BATCH,
                        self._const_verdict(seq, conn_ids),
                    )
                elif msg_type == wire.MSG_NEW_CONNECTION:
                    args = wire.unpack_new_connection(payload)
                    wire.send_msg(
                        sock, wire.MSG_CONN_RESULT,
                        np.array([args[1]], "<u8").tobytes()
                        + np.array([int(FilterResult.OK)], "<u4").tobytes(),
                    )
                elif msg_type == wire.MSG_OPEN_MODULE:
                    wire.send_msg(
                        sock, wire.MSG_MODULE_ID,
                        np.array([1], "<u8").tobytes(),
                    )
                elif msg_type == wire.MSG_POLICY_UPDATE:
                    wire.send_msg(
                        sock, wire.MSG_ACK,
                        wire.pack_ack(int(FilterResult.OK)),
                    )
                elif msg_type == wire.MSG_STATUS:
                    wire.send_msg(sock, wire.MSG_STATUS_REPLY, b"{}")
                elif msg_type == wire.MSG_SHM_ATTACH:
                    # The null control is socket-only by design: reject
                    # typed so a shm-preferring client falls back fast
                    # instead of timing out its attach RPC.
                    wire.send_msg(
                        sock, wire.MSG_SHM_ATTACH_REPLY,
                        b'{"status": 7, "generation": 0,'
                        b' "error": "null server: socket only"}',
                    )
                # MSG_CLOSE and anything else: ignored
        except (wire.ConnectionClosed, OSError):
            pass
        finally:
            shutdown_close(sock)

    def stop(self) -> None:
        self._stopped = True
        # shutdown wakes the acceptor so the listener dies NOW — a
        # bare close deferred the teardown behind the blocked accept
        # and the port kept accepting into a stopped server (R3).
        shutdown_close(self._listener)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass


def _corpus(pool: int, seed: int = 7):
    """Mixed allow/deny r2d2 messages, one per pooled connection."""
    rng = np.random.default_rng(seed)
    msgs = []
    for i in range(pool):
        roll = rng.random()
        if roll < 0.35:
            msgs.append(f"READ /public/file{i % 997}.txt\r\n".encode())
        elif roll < 0.5:
            msgs.append(b"HALT\r\n")
        elif roll < 0.75:
            msgs.append(f"READ /private/file{i % 997}\r\n".encode())
        else:
            msgs.append(f"WRITE /public/f{i % 997}\r\n".encode())
    lengths = np.array([len(m) for m in msgs], np.uint32)
    blob = b"".join(msgs)
    offsets = np.concatenate(([0], np.cumsum(lengths.astype(np.int64))))
    return msgs, lengths, blob, offsets


@dataclass
class RateResult:
    offered_rate: float
    achieved_rate: float
    requests: int
    p50_ms: float
    p90_ms: float
    p99_ms: float
    max_ms: float
    gen_saturated: bool
    added_p50_ms: float
    added_p99_ms: float
    # Release lateness: how far behind schedule the open-loop generator
    # was when it actually shipped each request (diagnoses how much of
    # the measured latency is generator-side scheduling vs the seam).
    release_late_p50_ms: float = 0.0
    release_late_p99_ms: float = 0.0


class LatencyBench:
    def __init__(
        self,
        socket_path: str,
        batch_flows: int = 2048,
        batch_timeout_ms: float = 0.25,
        client_batch: int = 1024,
        client_timeout_ms: float = 0.2,
        policy=None,
        verdict_device: str = "default",
        dispatch_mode: str = "auto",
        seam_probe: bool = False,
        wire_mode: str = "matrix",  # matrix (pre-padded) | blob (compact)
        null_seam: bool = False,
        transport: str = "socket",  # socket | shm (client-side rings)
    ):
        from cilium_tpu.proxylib import (
            NetworkPolicy,
            PortNetworkPolicy,
            PortNetworkPolicyRule,
        )

        self.policy = policy or NetworkPolicy(
            name="latbench",
            policy=2,
            ingress_per_port_policies=[
                PortNetworkPolicy(
                    port=80,
                    rules=[
                        PortNetworkPolicyRule(
                            l7_proto="r2d2",
                            l7_rules=[
                                {"cmd": "READ", "file": "/public/.*"},
                                {"cmd": "HALT"},
                            ],
                        )
                    ],
                )
            ],
        )
        self.client_batch = client_batch
        self.client_timeout_s = client_timeout_ms / 1000.0
        self.wire_mode = wire_mode
        if null_seam:
            self.service = NullVerdictServer(socket_path).start()
        else:
            cfg = DaemonConfig(
                batch_flows=batch_flows,
                batch_timeout_ms=batch_timeout_ms,
                batch_width=64,
                verdict_device=verdict_device,
                dispatch_mode=dispatch_mode,
                seam_probe=seam_probe,
            )
            self.service = VerdictService(socket_path, cfg).start()
        # First new_connection triggers engine build + per-bucket XLA
        # compiles (slow through the TPU tunnel) — generous timeout.
        # transport="shm" negotiates the shared-memory rings; slots are
        # sized so a full client_batch matrix (2048 x 64B rows + the
        # columnar headers) fits one slot with headroom.
        self.client = SidecarClient(
            socket_path, timeout=600.0, transport=transport,
            shm_data_slots=64, shm_slot_bytes=1 << 20,
            shm_verdict_slots=64, shm_verdict_slot_bytes=1 << 19,
        )
        self.module = self.client.open_module([])
        assert self.module != 0
        assert self.client.policy_update(self.module, [self.policy]) == int(
            FilterResult.OK
        )
        self.msgs, self.pool_lengths, self.pool_blob, self.pool_offsets = _corpus(
            CONN_POOL
        )
        self.pool_conn_ids = np.arange(1, CONN_POOL + 1, dtype=np.uint64)
        # Pre-padded device-layout rows (the MSG_DATA_MATRIX pool): the
        # datapath edge pays the padding cost once, off the hot path.
        self.width = 64
        self.pool_rows = np.zeros((CONN_POOL, self.width), np.uint8)
        for i, m in enumerate(self.msgs):
            self.pool_rows[i, : len(m)] = np.frombuffer(m, np.uint8)
        self._next_seq = 1
        self._register_conns()

    def _register_conns(self) -> None:
        for cid in self.pool_conn_ids:
            res, _ = self.client.new_connection(
                self.module, "r2d2", int(cid), True, 1, 2,
                "1.1.1.1:1", "2.2.2.2:80", "latbench",
            )
            assert res == int(FilterResult.OK), res
        # One warm-up full batch so jit compilation happens before timing.
        n = self.client_batch
        self._send_range(10**9, 0, min(n, CONN_POOL))
        time.sleep(0.5)

    def _send_range(self, seq: int, a: int, b: int) -> None:
        """Ship pool entries [a, b) (indices mod CONN_POOL, a/b absolute
        with b-a <= CONN_POOL) as one batch: pre-padded matrix rows, or
        the compact payload blob (wire_mode='blob' — the uplink-lean
        path for bandwidth-limited device links)."""
        ai, bi = a % CONN_POOL, (b - 1) % CONN_POOL + 1
        off = self.pool_offsets
        if ai < bi:
            ids = self.pool_conn_ids[ai:bi]
            lens = self.pool_lengths[ai:bi]
            if self.wire_mode == "blob":
                self.client.send_blob(
                    seq, ids, lens, self.pool_blob[off[ai]:off[bi]]
                )
                return
            rows = self.pool_rows[ai:bi].tobytes()
        else:  # wraps the pool
            ids = np.concatenate(
                (self.pool_conn_ids[ai:], self.pool_conn_ids[:bi])
            )
            lens = np.concatenate(
                (self.pool_lengths[ai:], self.pool_lengths[:bi])
            )
            if self.wire_mode == "blob":
                self.client.send_blob(
                    seq, ids, lens,
                    self.pool_blob[off[ai]:] + self.pool_blob[:off[bi]],
                )
                return
            rows = (
                self.pool_rows[ai:].tobytes() + self.pool_rows[:bi].tobytes()
            )
        # complete=True: the pool rows are built as single whole frames,
        # so the edge declares framing and the service skips its scan.
        self.client.send_matrix(seq, self.width, ids, lens, rows, complete=True)

    def run_rate(self, rate: float, n_requests: int, seed: int = 3) -> RateResult:
        import gc

        # A cyclic-GC pass mid-run is a multi-ms stop-the-world pause —
        # pure measurement noise in the tail percentiles.  Refcounting
        # still reclaims everything the hot path allocates.
        gc.collect()
        gc.disable()
        try:
            return self._run_rate(rate, n_requests, seed)
        finally:
            gc.enable()

    @staticmethod
    def _tighten_timer_slack() -> None:
        """Best-effort per-thread timer slack reduction (default 50µs —
        measured to stretch a 100µs pacing sleep to ~175µs; 1µs slack
        brings it to ~120µs, which lands directly in release lateness)."""
        try:
            import ctypes

            libc = ctypes.CDLL("libc.so.6", use_errno=True)
            libc.prctl(29, 1000, 0, 0, 0)  # PR_SET_TIMERSLACK = 29, 1µs
        except Exception:  # noqa: BLE001 — diagnostics only
            pass

    def _run_rate(self, rate: float, n_requests: int, seed: int) -> RateResult:
        self._tighten_timer_slack()
        rng = np.random.default_rng(seed)
        inter = rng.exponential(1.0 / rate, n_requests)
        sched = np.cumsum(inter)  # scheduled arrival times (s from start)

        recv: list[tuple[int, float]] = []  # (seq, t_recv)
        sent: dict[int, tuple[int, int, float]] = {}  # seq -> (a, b, t_sent)
        done = threading.Event()
        expected_final = n_requests

        got_counter = {"n": 0}

        def on_verdict(vb):
            t = time.perf_counter()
            recv.append((vb.seq, t))
            a, b, _ = sent.get(vb.seq, (0, 0, 0.0))
            got_counter["n"] += b - a
            if got_counter["n"] >= expected_final:
                done.set()

        self.client.verdict_callback = on_verdict

        t0 = time.perf_counter()
        i = 0
        gen_behind = False
        release_late = np.empty(n_requests)
        while i < n_requests:
            now = time.perf_counter() - t0
            j = int(np.searchsorted(sched, now))
            j = min(j, n_requests)
            if j > i and now - sched[i] > max(0.005, 3 * self.client_timeout_s):
                gen_behind = True
            if (
                j - i >= self.client_batch
                or (j > i and now - sched[i] >= self.client_timeout_s)
                or (j >= n_requests and j > i)  # tail flush
            ):
                while i < j:
                    b = min(j, i + self.client_batch, i + CONN_POOL)
                    # Globally monotonic seqs: stragglers from an
                    # overloaded previous run can never collide with
                    # this run's sent map.
                    seq = self._next_seq
                    self._next_seq += 1
                    sent[seq] = (i, b, time.perf_counter())
                    release_late[i:b] = (
                        time.perf_counter() - t0
                    ) - sched[i:b]
                    self._send_range(seq, i, b)
                    i = b
            else:
                # Pace without starving the service threads of the GIL.
                time.sleep(0.0001)
        gen_elapsed = time.perf_counter() - t0
        done.wait(10.0)
        self.client.verdict_callback = None

        lat = []
        for sq, t_recv in recv:
            rec = sent.get(sq)
            if rec is None:
                continue
            a, b, _ = rec
            lat.append((t_recv - t0) - sched[a:b])
        lat = np.concatenate(lat) if lat else np.array([0.0])
        lat_ms = lat * 1000.0
        achieved = len(lat) / gen_elapsed
        return RateResult(
            offered_rate=rate,
            achieved_rate=achieved,
            requests=len(lat),
            p50_ms=float(np.percentile(lat_ms, 50)),
            p90_ms=float(np.percentile(lat_ms, 90)),
            p99_ms=float(np.percentile(lat_ms, 99)),
            max_ms=float(lat_ms.max()),
            # Saturated = the generator fell behind schedule OR it
            # delivered materially less than offered — a run that only
            # achieves <98% of its offered rate must not present its
            # (fill-vs-deadline flattered) percentiles as that rate's.
            gen_saturated=gen_behind or achieved / rate < 0.98,
            added_p50_ms=0.0,  # filled by caller after oracle measure
            added_p99_ms=0.0,
            release_late_p50_ms=float(
                np.percentile(release_late * 1000.0, 50)
            ),
            release_late_p99_ms=float(
                np.percentile(release_late * 1000.0, 99)
            ),
        )

    def oracle_latency_ms(self, n: int = 20000) -> tuple[float, float]:
        """Per-request latency of the ported in-process proxylib parser
        (the reference's in-process cost this seam is compared against)."""
        mod = pl.open_module([], True)
        ins = pl.find_instance(mod)
        ins.policy_update([self.policy])
        res, conn = pl.on_new_connection(
            mod, "r2d2", 999999999, True, 1, 2, "1.1.1.1:1", "2.2.2.2:80",
            "latbench",
        )
        assert res == FilterResult.OK
        times = np.empty(n)
        for k in range(n):
            m = self.msgs[k % len(self.msgs)]
            t0 = time.perf_counter()
            ops: list = []
            conn.on_data(False, False, [m], ops)
            times[k] = time.perf_counter() - t0
            conn.reply_buf.take()
        pl.close_module(mod)
        ms = times * 1000.0
        return float(np.percentile(ms, 50)), float(np.percentile(ms, 99))

    def close(self) -> None:
        self.client.close()
        self.service.stop()


def run_paired_colocated(
    socket_path: str, n_requests: int = 100_000, reps: int = 9,
    transport: str = "socket", **kw
) -> dict:
    """The colocated latency experiment with its control, PAIRED: each
    seam run executes adjacent in time to a null-seam run, and the
    architecture-attributable added p99 is the median of the per-pair
    (seam − null) deltas.  Running the blocks minutes apart let the
    shared host's drifting stall rate land asymmetrically on one side
    (observed: the same code measured delta 0.77ms and 1.02ms an hour
    apart); pairing cancels the drift the way the null server cancels
    the constant floor."""
    seam_kw = dict(kw)
    # ``transport`` applies to the SEAM client only; the null control
    # stays on the socket (same framing floor for every config), so
    # (seam − null) deltas are comparable between the socket and shm
    # configs and the difference between the two IS the copy
    # elimination.
    seam_kw["transport"] = transport
    seam_kw.setdefault("verdict_device", "cpu")
    seam_kw.setdefault("seam_probe", True)
    seam_kw.setdefault("batch_timeout_ms", 0.0)
    seam_kw.setdefault("client_timeout_ms", 0.3)
    seam_kw.setdefault("batch_flows", 8192)
    seam_kw.setdefault("client_batch", 2048)
    null_kw = {
        "null_seam": True,
        "client_timeout_ms": seam_kw["client_timeout_ms"],
        "client_batch": seam_kw["client_batch"],
    }
    seam = LatencyBench(socket_path, **seam_kw)
    null = LatencyBench(socket_path + "_null", **null_kw)
    try:
        os_noise = measure_os_noise()
        oracle_p50, oracle_p99 = seam.oracle_latency_ms()
        # Short runs keep each pair tight in time (the whole point);
        # many pairs let the median reject stall-struck ones.
        n = min(n_requests, 30_000)
        pairs = []
        for k in range(reps):
            rn = null.run_rate(100_000, n, seed=3 + k)
            rs = seam.run_rate(100_000, n, seed=3 + k)
            pairs.append((rn, rs))
        # Half a second of offered load at 1M/s (the run() formula's
        # rate*0.5 with the rate inlined).
        n1 = min(n_requests, 500_000)
        r1m_null = null.run_rate(1_000_000, n1, seed=11)
        r1m_seam = seam.run_rate(1_000_000, n1, seed=11)
        # Captured BEFORE close (close releases the ring session).
        transport_stats = seam.client.transport_status()
    finally:
        seam.close()
        null.close()
    deltas = sorted(rs.p99_ms - rn.p99_ms for rn, rs in pairs)
    seam_sorted = sorted(pairs, key=lambda p: p[1].p99_ms)
    seam_med = seam_sorted[len(pairs) // 2][1]
    null_med = sorted(
        (p[0] for p in pairs), key=lambda r: r.p99_ms
    )[len(pairs) // 2]
    seam_med.added_p50_ms = max(seam_med.p50_ms - oracle_p50, 0.0)
    seam_med.added_p99_ms = max(seam_med.p99_ms - oracle_p50, 0.0)
    r1m_seam.added_p99_ms = max(r1m_seam.p99_ms - oracle_p50, 0.0)
    return {
        "oracle_p50_ms": oracle_p50,
        "oracle_p99_ms": oracle_p99,
        "os_noise": os_noise,
        "dispatch_mode": seam.service.dispatch_mode_chosen,
        # What the seam client actually rode (mode + ring/doorbell/
        # fallback counters) — a result claiming "shm" with a session
        # that silently demoted to the socket must be readable as such.
        "seam_transport": transport_stats,
        "seam_100k": seam_med,
        "null_100k": null_med,
        "pair_deltas_ms": [round(d, 3) for d in deltas],
        "delta_p99_ms": deltas[len(deltas) // 2],
        "seam_p99_runs": [round(p[1].p99_ms, 3) for p in pairs],
        "null_p99_runs": [round(p[0].p99_ms, 3) for p in pairs],
        "seam_1m": r1m_seam,
        "null_1m": r1m_null,
        "seam_stages_us": {
            k: round(v[1] / max(v[0], 1) * 1e6, 1)
            for k, v in seam.service.seam_stages.items()
        },
    }


def measure_uplink_mbps(n: int = 6, size: int = 512 * 1024) -> float:
    """Serialized host→device transfer rate — the binding constraint for
    wire-fed verdict throughput on a remote-tunneled chip (measured as
    low as ~12MB/s; co-located links are orders of magnitude faster).
    Reported alongside latency so results can be read against the
    transport they were taken on."""
    import jax
    import numpy as np_

    x = np_.zeros((size,), np_.uint8)
    jax.block_until_ready(jax.device_put(x))  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        jax.block_until_ready(jax.device_put(x))
    dt = time.perf_counter() - t0
    return n * size / dt / 1e6


def measure_os_noise(window_s: float = 2.0) -> dict:
    """Scheduler-noise floor of the host: gaps observed by a tight
    single-thread loop with nothing else runnable in-process.  On the
    shared 1-core bench VMs, hypervisor/cotenant stalls of 1-17ms are
    routinely observed (~1-2% of wall time above 1ms) — an external
    additive term every latency percentile here inherits.  Reported
    alongside the percentiles so they can be read against the host."""
    gaps = []
    t_prev = time.perf_counter()
    t_end = t_prev + window_s
    while True:
        t = time.perf_counter()
        if t - t_prev > 0.0003:
            gaps.append(t - t_prev)
        t_prev = t
        if t > t_end:
            break
    g = np.array(gaps) if gaps else np.zeros(1)
    return {
        "window_s": window_s,
        "gaps_over_0p3ms": len(gaps),
        "gap_max_ms": round(float(g.max()) * 1e3, 2),
        "gap_sum_ms": round(float(g.sum()) * 1e3, 1),
        "stall_fraction": round(float(g.sum()) / window_s, 4),
    }


def measure_device_rtt_ms(n: int = 12) -> float:
    """Median host→device→host blocking round trip for a tiny jitted
    call.  On a co-located chip this is O(100µs); through a remote
    tunnel (axon) it can be ~100ms and dominates every latency figure —
    it is measured and reported so results can be projected to
    co-located hardware."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def tick(x):
        return x + 1

    x = jnp.zeros((8,), jnp.int32)
    np.asarray(tick(x))  # compile
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        np.asarray(tick(x))
        times.append(time.perf_counter() - t0)
    return float(np.median(times) * 1000.0)


def run(
    socket_path: str,
    rates=(100_000, 1_000_000, 5_000_000),
    n_requests: int = 100_000,
    colocated: bool = False,
    null_seam: bool = False,
    **kw,
) -> dict:
    if null_seam:
        # The control experiment: generator + wire + constant-verdict
        # echo.  Client-side batching windows match the colocated seam
        # config so the generator behaves identically; everything
        # server-side is removed.
        # Caller options (wire_mode, client windows, ...) pass through
        # so a customized seam run can be paired with an identically
        # configured control; server-side options are ignored by the
        # null server.  Same client hold window default as the
        # colocated seam run: the generator must release identically
        # for (seam − null) to isolate the seam.
        kw = dict(kw)
        kw["null_seam"] = True
        kw.setdefault("client_timeout_ms", 0.3)
        colocated = True  # median-of-5 + no device RTT measurement
        rtt_ms = 0.0
        uplink_mbps = 0.0
    elif colocated:
        # Device term removed: the seam-probe model (trivial all-allow
        # device op on the host CPU backend) keeps the full
        # client fill -> wire -> dispatcher -> device call -> readback
        # -> wire back path alive while removing BOTH the device-link
        # RTT and the verdict-compute term, so the measured latency is
        # the seam architecture itself.  (Running the real model on the
        # CPU backend instead would swap the removed device term for a
        # ~15ms/2048-batch XLA-CPU compute term — a bigger one than the
        # TPU's ~0.09ms — and measure queueing, not the seam; verdict
        # parity of the cpu-backed service is covered by tests, and the
        # on-TPU compute term is measured by the throughput benches.)
        # Windows stay at their sub-ms defaults.
        kw.setdefault("verdict_device", "cpu")
        kw.setdefault("seam_probe", True)
        # Greedy dispatch: with the device local there is no transport
        # cost worth amortizing, so the worker takes whatever is
        # pending the moment it frees up (arrivals self-coalesce while
        # a round is in flight).
        kw.setdefault("batch_timeout_ms", 0.0)
        # A small client hold window measurably beats ship-on-wakeup
        # here: ~0.17ms wakeup-quantum batches (~17 entries at 100k/s)
        # make the 1-core host run at ~100% duty on per-round fixed
        # cost, and the resulting GIL queueing costs more than the
        # hold.  Measured head-to-head at 100k/s: 0ms window p99 runs
        # [2.1, 2.6, 3.6]ms vs 0.3ms window [1.1, 1.2, 1.8]ms.
        kw.setdefault("client_timeout_ms", 0.3)
        rtt_ms = 0.0
        uplink_mbps = 0.0
    else:
        # Deadlines well under the link RTT: with the slotted completion
        # pipeline overlapping readbacks, extra batching wait no longer
        # buys anything — it only delays the first dispatch.
        rtt_ms = measure_device_rtt_ms()
        uplink_mbps = measure_uplink_mbps()
        kw.setdefault("batch_timeout_ms", max(0.25, rtt_ms / 16))
        kw.setdefault("client_timeout_ms", max(0.2, rtt_ms / 32))
        # Compact payload batches: the remote link's UPLINK bandwidth is
        # usually the binding constraint (measured as low as ~12MB/s on
        # the tunneled bench chip), so ship exact payload bytes and let
        # the device build the padded row view.
        kw.setdefault("wire_mode", "blob")
    # Deep rounds: the cap only binds under backlog, where amortizing
    # the ~200µs per-round fixed cost over more entries is what keeps
    # the 1M/s point stable (a 1024 cap measured p99 14ms there).
    kw.setdefault("batch_flows", 8192)
    kw.setdefault("client_batch", 2048)
    bench = LatencyBench(socket_path, **kw)
    try:
        os_noise = measure_os_noise()
        oracle_p50, oracle_p99 = bench.oracle_latency_ms()
        results = []
        p99_runs: dict[float, list] = {}
        for rate in rates:
            n = min(n_requests, max(20_000, int(rate * 0.5)))
            # The shared bench VMs suffer external multi-ms scheduler
            # stalls (see measure_os_noise) at ~1-2% of wall time —
            # enough to set p99 single-handedly in an unlucky window.
            # The colocated seam metric takes the median-of-5 run so
            # the architecture, not one hypervisor stall, is measured;
            # every run's p99 is reported alongside.
            reps = 5 if (colocated and rate <= 100_000) else 1
            runs = [bench.run_rate(rate, n, seed=3 + k) for k in range(reps)]
            runs.sort(key=lambda rr: rr.p99_ms)
            p99_runs[rate] = [round(rr.p99_ms, 3) for rr in runs]
            r = runs[len(runs) // 2]
            # Raw added latency vs the in-process oracle, and the
            # co-located-hardware projection (one link RTT plus the
            # RTT-scaled batching windows removed; on local TPU those
            # terms shrink to the configured sub-ms deadlines).
            r.added_p50_ms = max(r.p50_ms - oracle_p50, 0.0)
            r.added_p99_ms = max(r.p99_ms - oracle_p50, 0.0)
            results.append(r)
        return {
            "oracle_p50_ms": oracle_p50,
            "oracle_p99_ms": oracle_p99,
            "device_rtt_ms": rtt_ms,
            "uplink_mbps": uplink_mbps,
            "colocated": colocated,
            "dispatch_mode": bench.service.dispatch_mode_chosen,
            "os_noise": os_noise,
            "p99_runs": p99_runs,
            "rates": results,
            "dispatcher": {
                "batches": bench.service.dispatcher.batches,
                "fill": bench.service.dispatcher.fill_dispatches,
                "deadline": bench.service.dispatcher.deadline_dispatches,
                "inline": bench.service.inline_batches,
                "vec_batches": bench.service.vec_batches,
                "vec_entries": bench.service.vec_entries,
            },
            # Published seam breakdown (seam_probe runs): per-stage
            # thread-CPU of the group fast path, µs per round.
            "seam_stages_us": {
                k: round(v[1] / max(v[0], 1) * 1e6, 1)
                for k, v in bench.service.seam_stages.items()
            },
        }
    finally:
        bench.close()
