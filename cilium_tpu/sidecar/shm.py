"""Lock-free SPSC shared-memory rings for the shim ⇄ sidecar seam.

The unix-socket transport copies every flow byte four times (client
pack → kernel send → kernel recv → BufferedReader) before the service
can even look at it; BENCH_NOTES r5 put the socket seam at ~0.8-1.1ms
of attributable p99 while the kafka model sits compute-bound at ~745M
verdicts/s on device.  This module moves the BULK bytes off the socket
(Libra's selective-data-copying shape, PAPERS.md): per client session a
pair of single-producer/single-consumer rings in
``multiprocessing.shared_memory``:

- a **data ring** the shim pushes wire data-batch frames into (slot
  header: commit word, wire op, payload length, commit timestamp;
  payload is the UNCHANGED columnar wire frame — seq, conn ids,
  lengths, packed blob — so the service's existing unpack lifts it
  into device arrays without per-entry work), and
- a **verdict ring** the service writes verdict frames back into, in
  place of the socket hop.

The socket stays attached as the CONTROL channel and the fail-closed
fallback rung: ring attach/detach is negotiated over it, batched
``MSG_SHM_DOORBELL``/``MSG_SHM_CREDIT`` notifications ride it (no
thread ever spin-waits on a slot — lint R2's spin-wait rule guards
exactly that), and any ring fault demotes the session to the socket
path typed, never silently.

Memory model: one producer thread and one consumer thread per ring
(the client serializes pushes under its write lock; the service's
verdict pushes are serialized under the client-handler write lock).
Slot publication is a two-phase commit word — invalidated before the
payload write, set to ``position + 1`` after — so a producer dying
mid-write leaves a slot whose commit word CANNOT match the position
the doorbell claims was written: the consumer surfaces :class:`TornSlot`
instead of parsing garbage.  8-byte aligned stores from CPython are
single ``memcpy`` calls under the GIL; both ends of this seam are
same-host processes (AF_UNIX peers), so no cross-architecture ordering
is assumed beyond that.

Payloads are copied OUT of the slot (one bulk memcpy) before the head
advances: credits free slots immediately, and no numpy view into ring
memory can outlive the slot's reuse.  What the shm path removes is the
two kernel copies, the sendall/recv syscalls per frame, and the
framing-buffer churn — the per-entry Python was already gone (the wire
format is columnar).
"""

from __future__ import annotations

import os
import secrets
import struct
import time
from multiprocessing import shared_memory

RING_MAGIC = 0x53484D52  # "SHMR"

# Ring header (bytes 0..64): magic u32, generation u32, slots u32,
# slot_bytes u32, tail u64 (producer cursor), head u64 (consumer
# cursor).  The cursors are mirrored here for occupancy/status; the
# AUTHORITATIVE cursors travel in the doorbell/credit messages so the
# consumer never polls shared memory waiting for them to move.
_HEADER = struct.Struct("<IIII")
_HEADER_BYTES = 64
_TAIL_OFF = 16
_HEAD_OFF = 24
_CURSOR = struct.Struct("<Q")

# Slot header: commit u64 (position+1 when published, 0 while being
# written), msg_type u32, length u32, t_commit f64 (monotonic stamp at
# publish — same host, same clock as the service's arrival stamps).
_SLOT = struct.Struct("<QIId")
SLOT_HEADER_BYTES = 32  # _SLOT.size padded to an 8-byte-aligned 32


class RingError(Exception):
    """Shared-memory transport fault (typed; never a hang)."""


class TornSlot(RingError):
    """A slot the peer claimed committed fails its commit check — the
    producer died mid-write or the segment is corrupt.  The ring must
    be quarantined and the session demoted to the socket path."""


class GenerationMismatch(RingError):
    """Attach-time validation failure: the segment's embedded
    generation (or magic) does not match the negotiated one — a stale
    segment from a previous session must never serve."""


def _segment_name(kind: str) -> str:
    return f"ctpu-{kind}-{os.getpid()}-{secrets.token_hex(4)}"


def _segment_owner_pid(name: str) -> int:
    """Creator pid embedded in a ctpu segment name (-1 if unparseable).
    The name IS the ownership record: no registry survives a kill -9,
    but the pid in the filename does."""
    parts = name.split("-")
    if len(parts) < 4 or parts[0] != "ctpu":
        return -1
    try:
        return int(parts[-2])
    except ValueError:
        return -1


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    return True


def sweep_stale_segments(lease_s: float, shm_dir: str = "/dev/shm") -> int:
    """Startup reclaim of predecessor orphans: unlink every ctpu-*
    segment whose creator process is DEAD and whose file age exceeds
    the lease.  A live service reclaims its own peers' segments via
    lease timers; this sweep covers the window those timers cannot —
    the service itself was kill -9'd, so a crashed shim's (or the dead
    service's clients') segments have no survivor to reclaim them until
    the NEXT service boots.  Returns the number of segments removed.

    Safety: a segment whose creator is alive is never touched (its
    lease timer, if any, belongs to a live service), and the age gate
    keeps a segment created a moment before its owner's pid was
    recycled from being misjudged.  Mapped pages of any straggler stay
    valid after unlink (POSIX); only the name is reclaimed.
    """
    removed = 0
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return 0  # no tmpfs view (non-Linux) — nothing to sweep
    now = time.time()
    for name in names:
        if not name.startswith("ctpu-"):
            continue
        pid = _segment_owner_pid(name)
        if pid == os.getpid() or _pid_alive(pid):
            continue
        path = os.path.join(shm_dir, name)
        try:
            age = now - os.stat(path).st_mtime
        except OSError:
            continue  # raced another sweeper
        if pid != -1 and age <= lease_s:
            continue
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass  # raced another sweeper / permissions — not ours then
    return removed


class ShmRing:
    """One SPSC ring over one shared-memory segment.

    The creator (client) owns the segment lifetime (``unlink``); an
    attacher (service) only maps and validates it.  Neither end blocks:
    a full ring refuses the push (socket fallback), an empty ring is
    simply not drained until the next doorbell/credit.
    """

    def __init__(self, seg: shared_memory.SharedMemory, *, slots: int,
                 slot_bytes: int, generation: int, owner: bool):
        self.seg = seg
        self.slots = slots
        self.slot_bytes = slot_bytes
        self.generation = generation
        self.owner = owner
        self.closed = False

    # -- lifecycle --------------------------------------------------------

    @classmethod
    def create(cls, kind: str, generation: int, slots: int,
               slot_bytes: int) -> "ShmRing":
        size = _HEADER_BYTES + slots * slot_bytes
        seg = shared_memory.SharedMemory(
            name=_segment_name(kind), create=True, size=size
        )
        _HEADER.pack_into(seg.buf, 0, RING_MAGIC, generation, slots,
                          slot_bytes)
        _CURSOR.pack_into(seg.buf, _TAIL_OFF, 0)
        _CURSOR.pack_into(seg.buf, _HEAD_OFF, 0)
        # Commit words start at 0 == "never published" for every slot
        # (SharedMemory zero-fills new segments).
        return cls(seg, slots=slots, slot_bytes=slot_bytes,
                   generation=generation, owner=True)

    @classmethod
    def attach(cls, name: str, generation: int) -> "ShmRing":
        seg = shared_memory.SharedMemory(name=name, create=False)
        try:
            magic, gen, slots, slot_bytes = _HEADER.unpack_from(seg.buf, 0)
            if magic != RING_MAGIC:
                raise GenerationMismatch(
                    f"segment {name}: bad magic {magic:#x}"
                )
            if gen != generation:
                raise GenerationMismatch(
                    f"segment {name}: generation {gen} != negotiated "
                    f"{generation} (stale segment)"
                )
            if slots <= 0 or slot_bytes <= SLOT_HEADER_BYTES or (
                _HEADER_BYTES + slots * slot_bytes > seg.size
            ):
                raise GenerationMismatch(
                    f"segment {name}: implausible geometry "
                    f"{slots}x{slot_bytes} for {seg.size} bytes"
                )
        except RingError:
            seg.close()
            raise
        return cls(seg, slots=slots, slot_bytes=slot_bytes,
                   generation=generation, owner=False)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self.seg.close()

    def unlink(self) -> None:
        """Creator-side: release the backing segment.  Attached peers'
        mappings stay valid until they close (POSIX semantics)."""
        if self.owner:
            try:
                self.seg.unlink()
            except FileNotFoundError:
                pass

    def force_unlink(self) -> bool:
        """Survivor-side reclaim: unlink the backing segment REGARDLESS
        of ownership.  Only for the abrupt-peer-death path — the
        creator died without detaching and will never run its own
        unlink, so the /dev/shm file would outlive every mapping.  Safe
        against a creator that is actually alive (half-open socket):
        its mappings stay valid and its own later unlink of this name
        is an absorbed FileNotFoundError.  Returns True only when THIS
        call removed the segment (the caller's reclaim accounting must
        not count no-ops)."""
        try:
            self.seg.unlink()
            return True
        except FileNotFoundError:
            return False  # the creator got there first (orderly teardown)
        except OSError:
            return False  # already reclaimed / platform refuses

    # -- cursors (informational mirrors) ----------------------------------

    @property
    def tail(self) -> int:
        try:
            return _CURSOR.unpack_from(self.seg.buf, _TAIL_OFF)[0]
        except (ValueError, TypeError):  # segment released/closed
            return 0

    @property
    def head(self) -> int:
        try:
            return _CURSOR.unpack_from(self.seg.buf, _HEAD_OFF)[0]
        except (ValueError, TypeError):  # segment released/closed
            return 0

    def occupancy(self) -> int:
        return max(self.tail - self.head, 0)

    # -- producer ---------------------------------------------------------

    def fits(self, nbytes: int) -> bool:
        return nbytes <= self.slot_bytes - SLOT_HEADER_BYTES

    def try_push(self, msg_type: int, payload,
                 credited_head: int) -> bool:
        """Publish one frame; False when the ring is full relative to
        the peer's last credited head (caller falls back to the
        socket — NEVER blocks or spins).

        ``payload`` is one buffer OR a list of buffers written
        scatter-gather straight into the slot — the zero-copy path for
        columnar frames whose bulk part (pre-padded rows, packed blob)
        already exists as one contiguous buffer: no intermediate
        ``b"".join`` materialization."""
        if self.closed:
            return False
        parts = (
            payload if isinstance(payload, (list, tuple)) else (payload,)
        )
        total = sum(len(p) for p in parts)
        try:
            pos = self.tail
            if pos - credited_head >= self.slots:
                return False
            if not self.fits(total):
                return False
            off = _HEADER_BYTES + (pos % self.slots) * self.slot_bytes
            buf = self.seg.buf
            # Two-phase publish: invalidate, write, then commit pos+1.
            _CURSOR.pack_into(buf, off, 0)
            cur = off + SLOT_HEADER_BYTES
            for p in parts:
                buf[cur : cur + len(p)] = p
                cur += len(p)
            _SLOT.pack_into(buf, off, pos + 1, msg_type, total,
                            time.monotonic())
            _CURSOR.pack_into(buf, _TAIL_OFF, pos + 1)
        except (ValueError, TypeError):
            # The segment was released by a concurrent disconnect
            # teardown: refuse the push — the caller's socket fallback
            # (or its typed SidecarUnavailable) owns the outcome.
            return False
        return True

    # -- consumer ---------------------------------------------------------

    def read(self, pos: int) -> tuple[int, bytes, float]:
        """Copy slot ``pos`` out: (msg_type, payload, t_commit).
        Raises :class:`TornSlot` when the commit word or geometry does
        not match — only ever called for positions the peer's doorbell
        claimed were fully published."""
        off = _HEADER_BYTES + (pos % self.slots) * self.slot_bytes
        commit, msg_type, length, t_commit = _SLOT.unpack_from(
            self.seg.buf, off
        )
        if commit != pos + 1:
            raise TornSlot(
                f"slot {pos % self.slots}: commit {commit} != "
                f"expected {pos + 1} (producer died mid-write or "
                f"stale segment)"
            )
        if length > self.slot_bytes - SLOT_HEADER_BYTES:
            raise TornSlot(
                f"slot {pos % self.slots}: length {length} exceeds "
                f"slot capacity"
            )
        body = off + SLOT_HEADER_BYTES
        # One bulk copy out of the ring: the head may then advance (and
        # the slot be reused) without any live view into ring memory.
        return msg_type, bytes(self.seg.buf[body : body + length]), t_commit

    def set_head(self, pos: int) -> None:
        _CURSOR.pack_into(self.seg.buf, _HEAD_OFF, pos)

    def status(self) -> dict:
        return {
            "name": self.seg.name,
            "slots": self.slots,
            "slot_bytes": self.slot_bytes,
            "head": self.head,
            "tail": self.tail,
            "occupancy": self.occupancy(),
        }
