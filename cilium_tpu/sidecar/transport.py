"""Transport seam abstraction for the shim ⇄ verdict-service boundary.

Two rungs, selected per session and re-negotiated on every reconnect:

- ``socket`` — the original unix-socket byte path.  Always attached;
  carries ALL control traffic in both modes and is the fail-closed
  fallback rung for the data plane.
- ``shm``    — the zero-copy fast path (:mod:`sidecar.shm`): data
  batches ride a shared-memory ring shim→service, verdict frames ride
  a second ring back, and the socket carries only batched
  ``MSG_SHM_DOORBELL``/``MSG_SHM_CREDIT`` nudges.

This module owns the shared session-state shapes so both ends count
and report the SAME degradation ladder dimension
(``transport=shm|socket``): a ring fault is typed, counted under one
of the ``REASON_*`` constants below, demotes the session to the socket
rung, and shows up identically in ``cilium sidecar status`` and the
``sidecar_transport_fallback_total{reason}`` metric — never a hang,
never silent loss.
"""

from __future__ import annotations

import threading

from ..utils import metrics
from .shm import ShmRing

TRANSPORT_SOCKET = "socket"
TRANSPORT_SHM = "shm"

# Degradation/fallback reasons (the label set of
# sidecar_transport_fallback_total).  Per-batch reasons route ONE batch
# to the socket; session reasons demote the whole session.
REASON_RING_FULL = "ring_full"            # per-batch: data ring full
REASON_OVERSIZE = "oversize"              # per-batch: frame > slot
REASON_VERDICT_RING_FULL = "verdict_ring_full"  # per-frame, service side
REASON_TORN_SLOT = "torn_slot"            # session: quarantined ring
REASON_GENERATION = "generation_mismatch"  # session: stale segment
REASON_ATTACH_REJECTED = "attach_rejected"  # session: negotiation failed
REASON_DISABLED = "disabled"              # session: service knob off
REASON_PEER_DEATH = "peer_death"          # session: peer vanished

# MSG_SHM_CREDIT flag bits.
CREDIT_FLAG_QUARANTINED = 1


class _Counters:
    """Fallback/doorbell accounting shared by both ends (one lock-free
    integer bump per event; reads are status-path only)."""

    def __init__(self) -> None:
        self.fallbacks: dict[str, int] = {}
        self.doorbells = 0
        self.doorbell_items = 0
        self.credits = 0
        self.data_frames = 0
        self.verdict_frames = 0
        # Credit-piggybacked verdict polling (client side): drains of
        # the verdict ring driven by the post-commit tail MIRROR at a
        # natural boundary (a data push) instead of by a credit frame —
        # the elided doorbell RTTs.  Never a spin: polls happen only on
        # events the client was already performing.
        self.mirror_drains = 0
        self.mirror_frames = 0

    def fallback(self, reason: str, n: int = 1) -> None:
        self.fallbacks[reason] = self.fallbacks.get(reason, 0) + n
        metrics.SidecarTransportFallback.inc(reason, amount=n)

    def doorbell(self, items: int) -> None:
        self.doorbells += 1
        self.doorbell_items += items

    def status(self) -> dict:
        return {
            "fallbacks": dict(self.fallbacks),
            "doorbells": self.doorbells,
            "doorbell_batch_mean": round(
                self.doorbell_items / self.doorbells, 2
            ) if self.doorbells else 0.0,
            "credits": self.credits,
            "data_frames": self.data_frames,
            "verdict_frames": self.verdict_frames,
            "mirror_drains": self.mirror_drains,
            "mirror_frames": self.mirror_frames,
        }


class ShmSession:
    """Client-side shm session: data-ring producer + verdict-ring
    consumer, plus the doorbell/credit state machine.

    Push/doorbell calls are serialized by the client's write lock (the
    SPSC producer guarantee); the credit/drain side runs on the
    client's reader thread (the SPSC consumer guarantee)."""

    def __init__(self, data: ShmRing, verdict: ShmRing, generation: int):
        self.data = data
        self.verdict = verdict
        self.generation = generation
        self.active = True
        self.counters = _Counters()
        # Producer-side doorbell state (under the client write lock):
        # last doorbelled data tail, and the service's last credited
        # consume head (slots below it are free).
        self.db_tail = 0
        self.credit_head = 0
        # Verdict-ring consumer cursor and the head value last
        # piggybacked to the service.  Historically reader-thread-only
        # (SPSC); the mirror-poll path (client.poll_shm_verdicts) makes
        # the logical consumer a LOCK-SERIALIZED pair of threads —
        # every drain runs under drain_lock, so slot reads, v_head
        # advances and set_head stores never interleave.  RLock: a
        # verdict callback may push (and therefore poll) reentrantly.
        self.v_head = 0
        self.v_head_sent = 0
        self.drain_lock = threading.RLock()
        # Ring in-flight bookkeeping for zero-silent-loss demotion:
        # seq -> (ring position, conn_ids) for every data frame pushed
        # to the ring whose verdict has not come back.  GIL-atomic
        # per-key dict ops; writer = producer, eraser = reader thread.
        self.inflight: dict[int, tuple[int, object]] = {}

    @classmethod
    def create(cls, generation: int, data_slots: int, data_slot_bytes: int,
               verdict_slots: int, verdict_slot_bytes: int) -> "ShmSession":
        data = ShmRing.create("data", generation, data_slots,
                              data_slot_bytes)
        try:
            verdict = ShmRing.create("verdict", generation, verdict_slots,
                                     verdict_slot_bytes)
        except Exception:
            data.close()
            data.unlink()
            raise
        return cls(data, verdict, generation)

    def attach_request(self) -> dict:
        """The MSG_SHM_ATTACH JSON payload."""
        return {
            "generation": self.generation,
            "data": self.data.seg.name,
            "verdict": self.verdict.seg.name,
        }

    def destroy(self) -> None:
        self.active = False
        for ring in (self.data, self.verdict):
            ring.close()
            ring.unlink()

    def status(self) -> dict:
        return {
            "mode": TRANSPORT_SHM if self.active else TRANSPORT_SOCKET,
            "generation": self.generation,
            "data": self.data.status(),
            "verdict": self.verdict.status(),
            "inflight": len(self.inflight),
            **self.counters.status(),
        }


class ShmPeer:
    """Service-side shm session: data-ring consumer + verdict-ring
    producer for one client handler.

    Drains run on the handler's reader thread (SPSC consumer); verdict
    pushes are serialized under the handler's write lock (SPSC
    producer).  ``_state_lock`` only guards the active/demotion latch —
    never held across blocking work."""

    def __init__(self, data: ShmRing, verdict: ShmRing, generation: int):
        self.data = data
        self.verdict = verdict
        self.generation = generation
        self.active = True
        self.counters = _Counters()
        self.head = 0            # data-ring consume cursor (reader)
        self.v_credit_head = 0   # client's last piggybacked verdict head
        self._state_lock = threading.Lock()
        self.quarantine_reason: str | None = None

    @classmethod
    def attach(cls, req: dict) -> "ShmPeer":
        generation = int(req["generation"])
        data = ShmRing.attach(str(req["data"]), generation)
        try:
            verdict = ShmRing.attach(str(req["verdict"]), generation)
        except Exception:
            data.close()
            raise
        return cls(data, verdict, generation)

    def quarantine(self, reason: str) -> bool:
        """Latch the session off the shm rung (idempotent); True only
        for the transition so exactly one quarantined credit is sent."""
        with self._state_lock:
            if not self.active:
                return False
            self.active = False
            self.quarantine_reason = reason
        self.counters.fallback(reason)
        return True

    def close(self) -> None:
        self.active = False
        self.data.close()
        self.verdict.close()

    def status(self) -> dict:
        return {
            "mode": TRANSPORT_SHM if self.active else TRANSPORT_SOCKET,
            "generation": self.generation,
            "quarantine_reason": self.quarantine_reason,
            "data": self.data.status(),
            "verdict": self.verdict.status(),
            **self.counters.status(),
        }
