"""Transport seam abstraction for the shim ⇄ verdict-service boundary.

Two rungs, selected per session and re-negotiated on every reconnect:

- ``socket`` — the original unix-socket byte path.  Always attached;
  carries ALL control traffic in both modes and is the fail-closed
  fallback rung for the data plane.
- ``shm``    — the zero-copy fast path (:mod:`sidecar.shm`): data
  batches ride a shared-memory ring shim→service, verdict frames ride
  a second ring back, and the socket carries only batched
  ``MSG_SHM_DOORBELL``/``MSG_SHM_CREDIT`` nudges.

This module owns the shared session-state shapes so both ends count
and report the SAME degradation ladder dimension
(``transport=shm|socket``): a ring fault is typed, counted under one
of the ``REASON_*`` constants below, demotes the session to the socket
rung, and shows up identically in ``cilium sidecar status`` and the
``sidecar_transport_fallback_total{reason}`` metric — never a hang,
never silent loss.
"""

from __future__ import annotations

import threading
import time

from ..analysis.protocols import (
    SESSION_ACTIVE,
    SESSION_DEAD,
    SESSION_PROTOCOL,
    SESSION_QUARANTINED,
)
from ..utils import metrics
from . import blackbox
from .shm import ShmRing

TRANSPORT_SOCKET = "socket"
TRANSPORT_SHM = "shm"

# Degradation/fallback reasons (the label set of
# sidecar_transport_fallback_total).  Per-batch reasons route ONE batch
# to the socket; session reasons demote the whole session.
REASON_RING_FULL = "ring_full"            # per-batch: data ring full
REASON_OVERSIZE = "oversize"              # per-batch: frame > slot
REASON_VERDICT_RING_FULL = "verdict_ring_full"  # per-frame, service side
REASON_TORN_SLOT = "torn_slot"            # session: quarantined ring
REASON_GENERATION = "generation_mismatch"  # session: stale segment
REASON_ATTACH_REJECTED = "attach_rejected"  # session: negotiation failed
REASON_DISABLED = "disabled"              # session: service knob off
REASON_PEER_DEATH = "peer_death"          # session: peer vanished
REASON_OVERSIZE_SPREE = "oversize_spree"  # session: every frame oversized

# MSG_SHM_CREDIT flag bits.
CREDIT_FLAG_QUARANTINED = 1

# --- fan-in session containment (N shims, one sidecar) ---------------------
#
# The unit of fault isolation on the fan-in seam is the SESSION (one
# shim process's socket + optional ring pair).  Every containment
# action is scoped to exactly one session and typed with one of the
# reasons below — a misbehaving pod can be quarantined, demoted or
# shed without its neighbors losing a byte.

# SESSION_ACTIVE / SESSION_QUARANTINED / SESSION_DEAD and the declared
# transition table live in analysis/protocols.py (one definition: the
# R18 lint pass and this runtime consume the SAME edges) and are
# re-exported here for the historical import surface.

# Session-scoped shed reasons (sidecar_session_shed_total labels,
# alongside the global queue_full/deadline/stall reasons).
SHED_SESSION_QUOTA = "session_quota"          # DRR share exceeded
SHED_SESSION_QUARANTINED = "session_quarantined"  # quarantine window
SHED_FENCED = "fenced"          # zombie predecessor after handoff
SHED_RESTARTING = "restarting"  # shim-side survival window overflow

# Session quarantine reasons (sidecar_session_quarantines_total).
QUARANTINE_FLOOD = "flood"                    # sustained over-quota push
QUARANTINE_RECONNECT_STORM = "reconnect_storm"  # crash-looping shim

# Session death reasons (sidecar_session_deaths_total).
DEATH_CLOSED = "closed"            # orderly EOF (shim closed/detached)
DEATH_ABRUPT = "abrupt"            # EOF with the shm rung still live
DEATH_SEND_TIMEOUT = "send_timeout"  # shim stopped reading; write killed
DEATH_WRITE_FAILED = "write_failed"  # reply write failed mid-frame

class SessionState:
    """Per-shim-session admission, fairness and containment state —
    one instance per accepted connection, owned by its handler.

    Counter contract (the fan-in half of the exactly-once invariant):
    ``submitted`` counts entries admitted off this session's socket or
    rings; ``answered`` counts entries whose typed reply THIS session's
    handler wrote (real verdicts, SHED and error verdicts alike — the
    marking site under the handler write lock is the single counting
    point, so a stood-down racing reply never double-books); ``shed``
    breaks out the fail-closed subset by reason.  After a session
    quiesces, submitted == answered — anything else is a lost or
    double-answered entry.  All bumps are single integer ops on the
    hot path (GIL-atomic; reads are status-only)."""

    # Identities are wire-supplied: bound their length, and keep the
    # PROMETHEUS label under a separate bounded vocabulary
    # (metric_identity, assigned by the service's hello handler) so a
    # shim cycling names cannot grow label cardinality without bound.
    IDENTITY_MAX = 64

    def __init__(self, session_id: int, identity: str = ""):
        self.id = session_id
        self.identity = (
            identity[: self.IDENTITY_MAX] or f"sess-{session_id}"
        )
        self.named = bool(identity)
        self.metric_identity = "unnamed"
        self.born = time.monotonic()
        self.state = SESSION_ACTIVE
        self.death_reason: str | None = None
        self.quarantine_reason: str | None = None
        self.quarantined_until = 0.0
        self.quarantines: dict[str, int] = {}
        self.submitted = 0
        self.answered = 0
        self.shed: dict[str, int] = {}
        # DRR queue share: weight currently queued in the dispatcher on
        # this session's behalf.  Incremented at admission under the
        # dispatcher condition, zeroed wholesale when a round pops the
        # queue (the pop takes everything, so every session's unused
        # share replenishes at once — deficit-round-robin over queue
        # slots, paced by service progress).
        self.q_weight = 0
        # Byte-weighted twin of q_weight (PR 15 remainder): payload
        # bytes queued on this session's behalf, charged/drained on
        # the same dispatcher lock trips — entry count ≠ cost for
        # mixed frame sizes, so heavy-frame tenants are visible.
        self.q_bytes = 0
        # Flood strikes: over-quota sheds inside the strike window.
        self.strikes = 0
        self.strike_window_start = 0.0

    # -- containment -------------------------------------------------------

    def set_identity(self, identity: str) -> None:
        """First hello wins: a later hello on the same session is
        ignored — one connection must not cycle identities through the
        quota/metric/storm tables."""
        if identity and not self.named:
            self.identity = identity[: self.IDENTITY_MAX]
            self.named = True

    def quarantine(self, reason: str, cooldown_s: float) -> None:
        """Latch this session (and only this session) off the data
        plane for ``cooldown_s``: its submissions are answered with
        typed SHED immediately, its control plane keeps serving, and
        the latch self-heals when the window passes.  A dead session
        stays dead — quarantining a corpse is not a declared edge."""
        if self.state == SESSION_DEAD:
            return
        with blackbox.annotate(reason=reason, session=self.id):
            self.state = SESSION_PROTOCOL.advance(
                self.state, SESSION_QUARANTINED
            )
        self.quarantine_reason = reason
        self.quarantined_until = time.monotonic() + cooldown_s
        self.quarantines[reason] = self.quarantines.get(reason, 0) + 1
        metrics.SidecarSessionQuarantines.inc(self.metric_identity, reason)

    def quarantined_now(self) -> bool:
        """Lazy-heal check: True while the quarantine window is open;
        the first call past the deadline flips the session back to
        active (no timer thread — traffic drives the heal, like the
        DeviceGuard re-probe)."""
        if self.state != SESSION_QUARANTINED:
            return False
        if time.monotonic() >= self.quarantined_until:
            # Declared-silent lazy heal (protocols.py: the quarantine
            # OPEN was the counted event; the close is traffic-driven).
            with blackbox.annotate(reason="window-expired",
                                   session=self.id):
                self.state = SESSION_PROTOCOL.advance(
                    self.state, SESSION_ACTIVE
                )
            self.quarantine_reason = None
            return False
        return True

    def mark_dead(self, reason: str, counted: bool = True) -> None:
        """Terminal edge.  ``counted=False`` records the death without
        bumping the typed metric — the control-plane-session arm (a
        session that never carried data is not an operator-facing
        death), while still routing the transition through the ONE
        declared-edge mediation point."""
        if self.state != SESSION_DEAD:
            with blackbox.annotate(reason=reason, session=self.id):
                self.state = SESSION_PROTOCOL.advance(
                    self.state, SESSION_DEAD
                )
            self.death_reason = reason
            if counted:
                metrics.SidecarSessionDeaths.inc(reason)

    # -- accounting --------------------------------------------------------

    def count_shed(self, reason: str, n: int) -> None:
        self.shed[reason] = self.shed.get(reason, 0) + n
        metrics.SidecarSessionShed.inc(
            self.metric_identity, reason, amount=n
        )

    def status(self) -> dict:
        shed_total = sum(self.shed.values())
        out = {
            "session": self.id,
            "identity": self.identity,
            "state": self.state,
            "submitted": self.submitted,
            "answered": self.answered,
            "served": self.answered - shed_total,
            "shed": dict(self.shed),
            "q_weight": self.q_weight,
            "q_bytes": self.q_bytes,
        }
        if self.state == SESSION_QUARANTINED:
            out["quarantine_reason"] = self.quarantine_reason
            out["quarantine_remaining_s"] = round(
                max(self.quarantined_until - time.monotonic(), 0.0), 3
            )
        if self.quarantines:
            out["quarantines"] = dict(self.quarantines)
        if self.death_reason is not None:
            out["death_reason"] = self.death_reason
        return out


class _Counters:
    """Fallback/doorbell accounting shared by both ends (one lock-free
    integer bump per event; reads are status-path only)."""

    def __init__(self) -> None:
        self.fallbacks: dict[str, int] = {}
        self.doorbells = 0
        self.doorbell_items = 0
        self.credits = 0
        self.data_frames = 0
        self.verdict_frames = 0
        # Credit-piggybacked verdict polling (client side): drains of
        # the verdict ring driven by the post-commit tail MIRROR at a
        # natural boundary (a data push) instead of by a credit frame —
        # the elided doorbell RTTs.  Never a spin: polls happen only on
        # events the client was already performing.
        self.mirror_drains = 0
        self.mirror_frames = 0

    def fallback(self, reason: str, n: int = 1) -> None:
        self.fallbacks[reason] = self.fallbacks.get(reason, 0) + n
        metrics.SidecarTransportFallback.inc(reason, amount=n)

    def doorbell(self, items: int) -> None:
        self.doorbells += 1
        self.doorbell_items += items

    def status(self) -> dict:
        return {
            "fallbacks": dict(self.fallbacks),
            "doorbells": self.doorbells,
            "doorbell_batch_mean": round(
                self.doorbell_items / self.doorbells, 2
            ) if self.doorbells else 0.0,
            "credits": self.credits,
            "data_frames": self.data_frames,
            "verdict_frames": self.verdict_frames,
            "mirror_drains": self.mirror_drains,
            "mirror_frames": self.mirror_frames,
        }


class ShmSession:
    """Client-side shm session: data-ring producer + verdict-ring
    consumer, plus the doorbell/credit state machine.

    Push/doorbell calls are serialized by the client's write lock (the
    SPSC producer guarantee); the credit/drain side runs on the
    client's reader thread (the SPSC consumer guarantee)."""

    def __init__(self, data: ShmRing, verdict: ShmRing, generation: int):
        self.data = data
        self.verdict = verdict
        self.generation = generation
        self.active = True
        self.counters = _Counters()
        # Producer-side doorbell state (under the client write lock):
        # last doorbelled data tail, and the service's last credited
        # consume head (slots below it are free).
        self.db_tail = 0
        self.credit_head = 0
        # Verdict-ring consumer cursor and the head value last
        # piggybacked to the service.  Historically reader-thread-only
        # (SPSC); the mirror-poll path (client.poll_shm_verdicts) makes
        # the logical consumer a LOCK-SERIALIZED pair of threads —
        # every drain runs under drain_lock, so slot reads, v_head
        # advances and set_head stores never interleave.  RLock: a
        # verdict callback may push (and therefore poll) reentrantly.
        self.v_head = 0
        self.v_head_sent = 0
        self.drain_lock = threading.RLock()
        # Ring in-flight bookkeeping for zero-silent-loss demotion:
        # seq -> (ring position, conn_ids) for every data frame pushed
        # to the ring whose verdict has not come back.  GIL-atomic
        # per-key dict ops; writer = producer, eraser = reader thread.
        self.inflight: dict[int, tuple[int, object]] = {}
        # Consecutive data-ring oversize fallbacks (client half of the
        # oversize-spree demotion; reset on any successful push).
        self.oversize_run = 0
        # Lease granted by the service at attach (seconds a survivor
        # waits after abrupt peer death before unlinking the segments).
        self.lease_s = 0.0

    @classmethod
    def create(cls, generation: int, data_slots: int, data_slot_bytes: int,
               verdict_slots: int, verdict_slot_bytes: int) -> "ShmSession":
        data = ShmRing.create("data", generation, data_slots,
                              data_slot_bytes)
        try:
            verdict = ShmRing.create("verdict", generation, verdict_slots,
                                     verdict_slot_bytes)
        except Exception:
            data.close()
            data.unlink()
            raise
        return cls(data, verdict, generation)

    def attach_request(self) -> dict:
        """The MSG_SHM_ATTACH JSON payload."""
        return {
            "generation": self.generation,
            "data": self.data.seg.name,
            "verdict": self.verdict.seg.name,
        }

    def destroy(self) -> None:
        self.active = False
        for ring in (self.data, self.verdict):
            ring.close()
            ring.unlink()

    def status(self) -> dict:
        return {
            "mode": TRANSPORT_SHM if self.active else TRANSPORT_SOCKET,
            "generation": self.generation,
            "data": self.data.status(),
            "verdict": self.verdict.status(),
            "inflight": len(self.inflight),
            **self.counters.status(),
        }


class ShmPeer:
    """Service-side shm session: data-ring consumer + verdict-ring
    producer for one client handler.

    Drains run on the handler's reader thread (SPSC consumer); verdict
    pushes are serialized under the handler's write lock (SPSC
    producer).  ``_state_lock`` only guards the active/demotion latch —
    never held across blocking work."""

    def __init__(self, data: ShmRing, verdict: ShmRing, generation: int):
        self.data = data
        self.verdict = verdict
        self.generation = generation
        self.active = True
        self.counters = _Counters()
        self.head = 0            # data-ring consume cursor (reader)
        self.v_credit_head = 0   # client's last piggybacked verdict head
        self._state_lock = threading.Lock()
        self.quarantine_reason: str | None = None
        # Consecutive verdict-ring oversize fallbacks (reset on any
        # successful ring push): a spree means every frame this session
        # produces misses the ring and the per-frame fit check is pure
        # overhead — the session is demoted typed instead.
        self.oversize_run = 0
        self.attached_at = time.monotonic()

    @classmethod
    def attach(cls, req: dict) -> "ShmPeer":
        generation = int(req["generation"])
        data = ShmRing.attach(str(req["data"]), generation)
        try:
            verdict = ShmRing.attach(str(req["verdict"]), generation)
        except Exception:
            data.close()
            raise
        return cls(data, verdict, generation)

    def quarantine(self, reason: str) -> bool:
        """Latch the session off the shm rung (idempotent); True only
        for the transition so exactly one quarantined credit is sent."""
        with self._state_lock:
            if not self.active:
                return False
            self.active = False
            self.quarantine_reason = reason
        self.counters.fallback(reason)
        return True

    def close(self) -> None:
        self.active = False
        self.data.close()
        self.verdict.close()

    def reclaim(self) -> bool:
        """Survivor-side segment release: unlink BOTH segments of a
        session whose creator died without MSG_SHM_DETACH.  The creator
        owns the unlink in every orderly path; after an abrupt shim
        death nobody else ever will, and the /dev/shm files leak until
        reboot.  Safe against a shim that is actually alive behind a
        half-open socket: its own mappings stay valid (POSIX unlink
        semantics) and it reconnects with FRESH segments (generation
        bump) — its own later unlink of these names is a no-op.
        Returns True when at least one segment was actually removed."""
        a = self.data.force_unlink()
        b = self.verdict.force_unlink()
        return a or b

    def status(self) -> dict:
        return {
            "mode": TRANSPORT_SHM if self.active else TRANSPORT_SOCKET,
            "generation": self.generation,
            "quarantine_reason": self.quarantine_reason,
            "data": self.data.status(),
            "verdict": self.verdict.status(),
            **self.counters.status(),
        }
