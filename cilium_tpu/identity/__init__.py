"""Security identities: labels -> cluster-wide numeric IDs.

reference: pkg/identity — NumericIdentity with reserved values (host=1,
world=2, unmanaged=3, health=4, init=5; user IDs >= 256,
numericidentity.go), Identity{ID, Labels, SHA} (identity.go:27), and the
kvstore-backed allocator (allocator.go:73,124) whose watcher feeds a local
identity cache; the cache owner is notified to trigger policy
recalculation.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Optional

from ..kvstore import Backend, client as kvstore_client
from ..kvstore.allocator import Allocator, AllocatorEvent
from ..kvstore.backend import EventType
from ..labels import (
    ID_NAME_HEALTH,
    ID_NAME_HOST,
    ID_NAME_INIT,
    ID_NAME_UNMANAGED,
    ID_NAME_WORLD,
    SOURCE_RESERVED,
    Label,
    Labels,
)

# Reserved numeric identities (reference: numericidentity.go).
IDENTITY_UNKNOWN = 0
RESERVED_HOST = 1
RESERVED_WORLD = 2
RESERVED_UNMANAGED = 3
RESERVED_HEALTH = 4
RESERVED_INIT = 5

MIN_USER_IDENTITY = 256
MAX_IDENTITY = 65535

RESERVED_IDENTITIES = {
    ID_NAME_HOST: RESERVED_HOST,
    ID_NAME_WORLD: RESERVED_WORLD,
    ID_NAME_UNMANAGED: RESERVED_UNMANAGED,
    ID_NAME_HEALTH: RESERVED_HEALTH,
    ID_NAME_INIT: RESERVED_INIT,
}
RESERVED_IDENTITY_NAMES = {v: k for k, v in RESERVED_IDENTITIES.items()}

# Identity allocation kvstore path (reference: allocator.go IdentitiesPath).
IDENTITIES_PATH = "cilium/state/identities/v1"


@dataclass(frozen=True)
class Identity:
    """reference: pkg/identity/identity.go:27."""

    id: int
    labels: Labels

    @property
    def sha256(self) -> str:
        return self.labels.sha256_sum()

    def is_reserved(self) -> bool:
        return self.id in RESERVED_IDENTITY_NAMES

    def label_array(self):
        return self.labels.to_array()


def new_reserved_identity(name: str) -> Identity:
    lbls = Labels()
    lbls.upsert(Label(key=name, source=SOURCE_RESERVED))
    return Identity(id=RESERVED_IDENTITIES[name], labels=lbls)


ReservedIdentities = {
    name: new_reserved_identity(name) for name in RESERVED_IDENTITIES
}


def look_up_reserved_identity(numeric: int) -> Optional[Identity]:
    name = RESERVED_IDENTITY_NAMES.get(numeric)
    return ReservedIdentities[name] if name else None


def _labels_key(lbls: Labels) -> str:
    """Canonical allocator key for a label set (the reference uses the
    sorted label list as the allocator key, allocator.go GetID)."""
    return lbls.sorted_list().decode()


def _is_label_part(part: str) -> bool:
    """A serialized label is ``source:key=value`` — ':' before '='."""
    c = part.find(":")
    e = part.find("=")
    return c > 0 and e > c


def _key_labels(key: str) -> Labels:
    out = Labels()
    last: Label | None = None
    for part in key.split(";"):
        if not part:
            continue
        if not _is_label_part(part):
            # Fragment of a value that itself contained ';' — re-join onto
            # the previous label rather than crashing the watch thread.
            if last is not None:
                last = Label(key=last.key, value=last.value + ";" + part,
                             source=last.source)
                out.upsert(last)
            continue
        src, rest = part.split(":", 1)
        k, v = rest.split("=", 1)
        last = Label(key=k, value=v, source=src)
        out.upsert(last)
    return out


class IdentityAllocator:
    """Cluster identity allocation + local cache
    (reference: pkg/identity/allocator.go + cache.go)."""

    def __init__(
        self,
        owner_notify: Callable[[], None] | None = None,
        backend: Backend | None = None,
        node_name: str = "local",
        events: Callable[["IdentityChange"], None] | None = None,
    ) -> None:
        self.owner_notify = owner_notify
        self.events = events
        self._mutex = threading.RLock()
        self.allocator = Allocator(
            backend or kvstore_client(),
            IDENTITIES_PATH,
            suffix=node_name,
            min_id=MIN_USER_IDENTITY,
            max_id=MAX_IDENTITY,
            events=self._on_allocator_event,
        )
        self.allocator.start_watch()

    def _on_allocator_event(self, ev: AllocatorEvent) -> None:
        if self.events:
            self.events(
                IdentityChange(
                    kind="upsert" if ev.typ != EventType.DELETE else "delete",
                    id=ev.id,
                    labels=_key_labels(ev.key) if ev.key else Labels(),
                )
            )
        # Remote allocation changes can affect policy: notify the owner
        # (reference: identityWatcher triggering policy recalc).
        if self.owner_notify:
            self.owner_notify()

    def allocate(self, lbls: Labels) -> tuple[Identity, bool]:
        """reference: allocator.go:124 AllocateIdentity."""
        reserved = lbls.get_from_source(SOURCE_RESERVED)
        if len(reserved) == len(lbls) and len(reserved) == 1:
            name = next(iter(reserved))
            if name in RESERVED_IDENTITIES:
                return ReservedIdentities[name], False
        id_, is_new = self.allocator.allocate(_labels_key(lbls))
        return Identity(id=id_, labels=lbls), is_new

    def release(self, identity: Identity) -> bool:
        if identity.is_reserved():
            return False
        return self.allocator.release(_labels_key(identity.labels))

    def retain_cached(self, lbls: Labels) -> Optional[Identity]:
        """Degraded-mode allocation: take a refcounted LOCAL reference
        on an identity already resolved for these labels, without any
        kvstore I/O (see Allocator.retain_cached).  None if the labels
        were never resolved — a truly new identity needs the store."""
        reserved = lbls.get_from_source(SOURCE_RESERVED)
        if len(reserved) == len(lbls) and len(reserved) == 1:
            name = next(iter(reserved))
            if name in RESERVED_IDENTITIES:
                return ReservedIdentities[name]
        id_ = self.allocator.retain_cached(_labels_key(lbls))
        if id_ is None:
            return None
        return Identity(id=id_, labels=lbls)

    def lookup_by_id(self, numeric: int) -> Optional[Identity]:
        """reference: cache.go LookupIdentityByID."""
        reserved = look_up_reserved_identity(numeric)
        if reserved is not None:
            return reserved
        key = self.allocator.get_by_id(numeric)
        if key is None:
            return None
        return Identity(id=numeric, labels=_key_labels(key))

    def lookup(self, lbls: Labels) -> Optional[Identity]:
        """reference: cache.go LookupIdentity."""
        reserved = lbls.get_from_source(SOURCE_RESERVED)
        if len(reserved) == len(lbls) and len(reserved) == 1:
            name = next(iter(reserved))
            if name in RESERVED_IDENTITIES:
                return ReservedIdentities[name]
        id_ = self.allocator.get(_labels_key(lbls))
        if id_ is None:
            return None
        return Identity(id=id_, labels=lbls)

    def get_identity_cache(self) -> dict[int, Labels]:
        """reference: cache.go GetIdentityCache."""
        out: dict[int, Labels] = {
            ident.id: ident.labels for ident in ReservedIdentities.values()
        }
        with self.allocator._mutex:
            cache = dict(self.allocator.cache)
        for id_, key in cache.items():
            out[id_] = _key_labels(key)
        return out

    def gc(self) -> int:
        return self.allocator.run_gc()

    def close(self) -> None:
        self.allocator.stop_watch()


@dataclass
class IdentityChange:
    kind: str  # upsert | delete
    id: int
    labels: Labels
