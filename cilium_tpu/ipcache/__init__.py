"""IP -> identity cache with kvstore synchronization.

reference: pkg/ipcache — the agent upserts its local endpoint IPs into the
kvstore (``cilium/state/ip/v1/<cluster>/<ip>``, kvstore.go) and watches the
global prefix (InitIPIdentityWatcher kvstore.go:435); every change fans out
to listeners, the primary one writing the datapath ipcache map
(pkg/datapath/ipcache) — here cilium_tpu.maps.IpcacheMap, whose device
export answers batched identity derivation.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Callable, Optional

from ..kvstore import Backend, client as kvstore_client
from ..kvstore.backend import EventType
from ..maps.ipcache import IpcacheMap

IP_IDENTITIES_PATH = "cilium/state/ip/v1"


@dataclass
class IPIdentityPair:
    """reference: pkg/identity IPIdentityPair."""

    ip: str
    identity: int
    tunnel_endpoint: int = 0
    host_ip: str = ""


class IPIdentityCache:
    """Local authoritative IP->identity mapping + listener fan-out
    (reference: pkg/ipcache/ipcache.go:66 IPCache)."""

    def __init__(self, cluster_name: str = "default") -> None:
        self.cluster = cluster_name
        self._cache: dict[str, IPIdentityPair] = {}
        self._mutex = threading.RLock()
        self._listeners: list[Callable[[str, str, Optional[IPIdentityPair]], None]] = []

    def add_listener(
        self, listener: Callable[[str, str, Optional[IPIdentityPair]], None]
    ) -> None:
        """listener(event, ip, pair) with event in {"upsert", "delete"};
        on registration the current state replays as upserts (reference:
        ipcache.go addListener initial sync)."""
        with self._mutex:
            self._listeners.append(listener)
            current = list(self._cache.values())
        for pair in current:
            listener("upsert", pair.ip, pair)

    def upsert(self, ip: str, identity: int, tunnel_endpoint: int = 0,
               host_ip: str = "") -> bool:
        """reference: ipcache.go:217 Upsert; returns False if unchanged."""
        pair = IPIdentityPair(ip, identity, tunnel_endpoint, host_ip)
        # Notification happens under the mutex so listener (datapath map)
        # update order always matches cache mutation order.
        with self._mutex:
            old = self._cache.get(ip)
            if (old is not None and old.identity == identity
                    and old.tunnel_endpoint == tunnel_endpoint
                    and old.host_ip == host_ip):
                return False
            self._cache[ip] = pair
            for l in self._listeners:
                l("upsert", ip, pair)
        return True

    def delete(self, ip: str) -> bool:
        with self._mutex:
            pair = self._cache.pop(ip, None)
            if pair is None:
                return False
            for l in self._listeners:
                l("delete", ip, None)
        return True

    def lookup_by_ip(self, ip: str) -> Optional[int]:
        with self._mutex:
            pair = self._cache.get(ip)
            return pair.identity if pair else None

    def lookup_by_identity(self, identity: int) -> list[str]:
        with self._mutex:
            return [ip for ip, p in self._cache.items()
                    if p.identity == identity]

    def dump(self) -> list[IPIdentityPair]:
        with self._mutex:
            return sorted(self._cache.values(), key=lambda p: p.ip)


class KvstoreIPSync:
    """Bidirectional kvstore sync (reference: pkg/ipcache/kvstore.go).

    upsert_to_kvstore publishes local endpoint IPs; the watcher merges
    remote nodes' entries into the local IPIdentityCache.
    """

    def __init__(self, cache: IPIdentityCache,
                 backend: Backend | None = None) -> None:
        self.cache = cache
        self.backend = backend or kvstore_client()
        self._watcher = None

    def _path(self, ip: str) -> str:
        return f"{IP_IDENTITIES_PATH}/{self.cache.cluster}/{ip}"

    def upsert_to_kvstore(self, pair: IPIdentityPair) -> None:
        """reference: kvstore.go upsertToKVStore."""
        self.backend.set(
            self._path(pair.ip),
            json.dumps({
                "IP": pair.ip,
                "ID": pair.identity,
                "TunnelEndpoint": pair.tunnel_endpoint,
                "HostIP": pair.host_ip,
            }).encode(),
            lease=True,
        )

    def delete_from_kvstore(self, ip: str) -> None:
        self.backend.delete(self._path(ip))

    def start_watcher(self) -> None:
        """reference: kvstore.go:435 InitIPIdentityWatcher."""
        w = self.backend.list_and_watch(
            "ipcache", f"{IP_IDENTITIES_PATH}/{self.cache.cluster}/"
        )
        self._watcher = w

        prefix = f"{IP_IDENTITIES_PATH}/{self.cache.cluster}/"

        def run() -> None:
            for ev in w:
                if ev.typ == EventType.LIST_DONE:
                    continue
                # Strip the watch prefix, not rsplit: the ip may itself be a
                # CIDR prefix containing '/'.
                ip = ev.key[len(prefix):]
                if ev.typ == EventType.DELETE:
                    self.cache.delete(ip)
                else:
                    try:
                        data = json.loads(ev.value.decode())
                    except ValueError:
                        continue
                    self.cache.upsert(
                        data.get("IP", ip),
                        data.get("ID", 0),
                        data.get("TunnelEndpoint", 0),
                        data.get("HostIP", ""),
                    )

        threading.Thread(target=run, name="ipcache-watch", daemon=True).start()

    def stop(self) -> None:
        if self._watcher is not None:
            self._watcher.stop()


def datapath_listener(ipcache_map: IpcacheMap):
    """Listener mirroring the cache into the datapath map (reference:
    pkg/datapath/ipcache writing the BPF ipcache from cache updates)."""

    def on_change(event: str, ip: str, pair: Optional[IPIdentityPair]) -> None:
        prefix = ip if "/" in ip else (
            f"{ip}/128" if ":" in ip else f"{ip}/32"
        )
        if event == "upsert" and pair is not None:
            ipcache_map.upsert(prefix, pair.identity, pair.tunnel_endpoint)
        else:
            ipcache_map.delete(prefix)

    return on_change
