"""Flow-record schema: one structured record per policy decision.

The per-flow analog of the reference's Hubble flow (reference:
pkg/monitor/payload + Hubble's flow proto built from drop/trace/
policy-verdict perf events): who talked to whom, which direction, which
serving path rendered the verdict (the PR 2 degradation ladder), what
the verdict was, and — the part an opaque accelerator normally eats —
WHICH rule decided it (`rule_id`, the flattened first-match row index
shared bit-identically by the device argmax reduction and the host
oracle walk) plus the rule's compiled match kind (literal|regex|nfa).

Records are stored columnar per ROUND (see ring.py) — this module only
defines the field vocabulary and the per-record dict materialization.
"""

from __future__ import annotations

# Verdict names (shared with accesslog/record.py's vocabulary, plus the
# typed fail-closed outcomes of the PR 2 containment ladder).
VERDICT_FORWARDED = "Forwarded"
VERDICT_DENIED = "Denied"
VERDICT_SHED = "Shed"
VERDICT_ERROR = "Error"

# Integer verdict codes used in the columnar round batches.
CODE_FORWARDED = 0
CODE_DENIED = 1
CODE_SHED = 2
CODE_ERROR = 3

CODE_NAMES = (VERDICT_FORWARDED, VERDICT_DENIED, VERDICT_SHED, VERDICT_ERROR)

# Serving-path labels: the L7 ladder reuses sidecar/trace.py's path
# vocabulary (vec | oracle | host | shed); the packet layers add their
# own.
PATH_DATAPATH = "datapath"  # L3/L4 composed pipeline verdicts
PATH_XDP = "xdp"            # prefilter (XDP analog) source drops
PATH_ENGINE = "engine"      # daemon-side L7 batch engines (runtime/)

# Match kinds: how the DECIDING rule was compiled.  literal/regex/nfa
# are the device model tiers (dns maps matchName/always rows to
# literal and matchPattern/matchRegex rows to the automaton kind, so
# the legend is uniform across engine families); l3/l4 mark
# packet-layer decisions where no L7 rule row exists.
MATCH_LITERAL = "literal"
MATCH_REGEX = "regex"
MATCH_NFA = "nfa"
MATCH_L3 = "l3"
MATCH_L4 = "l4"
MATCH_NONE = ""

# Conntrack state codes for the optional per-record ct_state column.
CT_UNKNOWN = 0
CT_NEW = 1
CT_ESTABLISHED = 2
CT_NAMES = ("", "new", "established")


def verdict_name(code: int) -> str:
    return CODE_NAMES[code] if 0 <= code < len(CODE_NAMES) else VERDICT_ERROR


def materialize(
    seq: int,
    ts: float,
    path: str,
    conn_id: int,
    code: int,
    rule: int,
    kind: str,
    meta: tuple | None,
    reason: str = "",
    extra: dict | None = None,
) -> dict:
    """Build one record dict from a round batch's columns — the single
    definition of the record schema (`cilium observe --json` output,
    the MSG_OBSERVE_REPLY payload, and the tests all read this shape).
    ``meta`` is the connection metadata tuple captured at registration:
    (policy_name, ingress, src_id, dst_id, src_addr, dst_addr, proto,
    port[, session]) — the optional trailing session id is the fan-in
    shim session the conn registered through (0 = unknown/legacy)."""
    rec = {
        "seq": int(seq),
        "ts": ts,
        "path": path,
        "conn_id": int(conn_id),
        "verdict": verdict_name(code),
        "rule_id": int(rule),
        "match_kind": kind,
    }
    if meta is not None:
        (policy_name, ingress, src_id, dst_id,
         src_addr, dst_addr, proto, port) = meta[:8]
        rec.update(
            policy=policy_name,
            ingress=bool(ingress),
            src_identity=int(src_id),
            dst_identity=int(dst_id),
            src_addr=src_addr,
            dst_addr=dst_addr,
            proto=proto,
            dport=int(port),
        )
        if len(meta) > 8 and meta[8]:
            rec["session"] = int(meta[8])
    if reason:
        rec["reason"] = reason
    if extra:
        rec.update(extra)
    return rec
