"""Per-node bounded flow-record ring, populated per ROUND.

Emission contract (the R7 rule that keeps observability off the hot
path): decision layers hand the log ONE columnar batch per dispatch
round — numpy arrays of conn ids / verdict codes / rule ids — never a
per-entry append under the lock.  Per-record dicts are materialized
lazily at QUERY time (`cilium observe`, MSG_OBSERVE), so the serving
path pays O(rounds) lock trips and a few vectorized aggregations, like
sidecar/trace.py's span ring.

Side effects per round, all aggregated:

- ``flow_verdicts_total{verdict,path,match_kind}`` counter increments,
  one per distinct label tuple in the round (numpy bincount, not a
  Python loop over entries);
- bounded POLICY-VERDICT monitor events, gated by the
  ``PolicyVerdictNotification`` runtime option (the previously-dead
  ``OPTION_POLICY_VERDICT_NOTIFY``) — the reference's policy-verdict
  perf events under the same rate-limit philosophy as
  datapath/notify.py's drop sample.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ..utils import metrics
from ..utils.option import OPTION_POLICY_VERDICT_NOTIFY
from .record import (
    CODE_DENIED,
    CODE_FORWARDED,
    CODE_NAMES,
    CT_NAMES,
    MATCH_NONE,
    materialize,
)

# Per-round cap on monitor policy-verdict events (the perf-ring analog
# cap, mirroring datapath/notify.MAX_DROP_NOTIFICATIONS).
MAX_VERDICT_NOTIFICATIONS = 64


class _RoundBatch:
    """One round's worth of flow records, columnar."""

    __slots__ = ("seq0", "ts", "path", "conn_ids", "codes", "rules",
                 "kinds", "reason", "cols", "epoch")

    def __init__(self, seq0, ts, path, conn_ids, codes, rules, kinds,
                 reason, cols, epoch=-1):
        self.seq0 = seq0
        self.ts = ts
        self.path = path
        self.conn_ids = conn_ids    # [n] int64
        self.codes = codes          # [n] int8 (CODE_*)
        self.rules = rules          # [n] int32 (-1 = unattributed)
        self.kinds = kinds          # tuple[str, ...] per-rule legend
        self.reason = reason
        self.cols = cols            # extra columnar fields or None
        # Policy-table epoch the round's verdicts were decided against
        # (-1 = pre-epoch layer).  Round-wide: one serving model per
        # round batch; entrywise rounds carry a per-entry "epoch" col
        # instead, which overrides at materialize time.
        self.epoch = epoch

    @property
    def count(self) -> int:
        return len(self.conn_ids)


class FlowLog:
    """Bounded per-node flow-record ring with per-round emission.

    ``capacity`` bounds the total RECORD count (oldest rounds evicted
    whole).  ``opts`` is the runtime OptionMap consulted for the
    policy-verdict monitor gate; ``monitor`` the event sink.  Both are
    optional and may be attached after construction (the service wires
    the ring first, the daemon/test wires the sinks)."""

    def __init__(self, capacity: int = 8192, opts=None, monitor=None):
        self.capacity = max(int(capacity), 1)
        self.opts = opts
        self.monitor = monitor
        self._lock = threading.Lock()
        self._rounds: deque[_RoundBatch] = deque()
        self._records = 0  # records currently held across rounds
        self._seq = 0      # next record seq (monotonic, never reused)
        self.rounds_total = 0
        self.records_total = 0
        # conn metadata registry: conn_id -> meta tuple (record.py
        # materialize docstring).  Live conns in _meta; closed conns
        # keep their last-known meta in a bounded LRU so records
        # emitted before the close still materialize with context.
        self._meta: dict[int, tuple] = {}
        self._stale_meta: OrderedDict[int, tuple] = OrderedDict()
        self._stale_cap = 4096

    # -- conn metadata ----------------------------------------------------

    def register_conn(self, conn_id: int, policy_name: str, ingress: bool,
                      src_id: int, dst_id: int, src_addr: str,
                      dst_addr: str, proto: str, port: int,
                      session: int = 0) -> None:
        with self._lock:
            self._meta[int(conn_id)] = (
                policy_name, bool(ingress), int(src_id), int(dst_id),
                src_addr, dst_addr, proto, int(port), int(session),
            )

    def forget_conn(self, conn_id: int) -> None:
        with self._lock:
            meta = self._meta.pop(int(conn_id), None)
            if meta is not None:
                self._stale_meta[int(conn_id)] = meta
                self._stale_meta.move_to_end(int(conn_id))
                while len(self._stale_meta) > self._stale_cap:
                    self._stale_meta.popitem(last=False)

    def _meta_for(self, conn_id: int) -> tuple | None:
        return self._meta.get(conn_id) or self._stale_meta.get(conn_id)

    # -- emission (per ROUND — never per entry) ---------------------------

    def add_round(self, path: str, conn_ids, codes, rules=None,
                  kinds: tuple = (), reason: str = "",
                  cols: dict | None = None, epoch: int = -1) -> None:
        """Record one round's decisions.  ``conn_ids``/``codes`` are
        parallel arrays; ``rules`` the per-entry deciding-rule row
        (-1 = unattributed) and ``kinds`` the per-RULE match-kind
        legend of the serving model.  ``cols`` carries optional extra
        columnar fields (datapath identity/ct columns).  ``epoch`` is
        the policy-table epoch the round's verdicts were decided
        against — captured WITH the kinds legend at decision time, so a
        rule id is never resolved against a table it did not index
        (per-entry cols["epoch"] overrides for mixed rounds)."""
        conn_ids = np.asarray(conn_ids, np.int64)
        n = len(conn_ids)
        if n == 0:
            return
        codes = np.asarray(codes, np.int8)
        rules = (
            np.full(n, -1, np.int32) if rules is None
            else np.asarray(rules, np.int32)
        )
        ts = time.time()
        batch = _RoundBatch(
            0, ts, path, conn_ids, codes, rules, tuple(kinds), reason,
            cols, epoch=int(epoch),
        )
        self._count_metrics(path, codes, rules, batch.kinds, cols)
        with self._lock:
            batch.seq0 = self._seq
            self._seq += n
            self._rounds.append(batch)
            self._records += n
            self.rounds_total += 1
            self.records_total += n
            while self._records > self.capacity and len(self._rounds) > 1:
                self._records -= self._rounds.popleft().count
        # Monitor fan-out OUTSIDE the ring lock: notify() takes its own
        # mutex and must never be able to invert against ours.
        self._notify_verdicts(batch)

    def add_entries(self, path: str, entries: list, kinds: tuple = (),
                    reason: str = "") -> None:
        """Entrywise-round convenience: ``entries`` is a per-round list
        of (conn_id, code, rule) built by the caller; converted to one
        columnar batch (ONE add_round — the hot loop builds a plain
        list, the lock is taken once)."""
        if not entries:
            return
        self.add_round(
            path,
            np.fromiter((e[0] for e in entries), np.int64, len(entries)),
            np.fromiter((e[1] for e in entries), np.int8, len(entries)),
            np.fromiter((e[2] for e in entries), np.int32, len(entries)),
            kinds=kinds,
            reason=reason,
        )

    def _count_metrics(self, path, codes, rules, kinds, cols) -> None:
        """Aggregate flow_verdicts_total{verdict,path,match_kind} for
        the round: one counter inc per DISTINCT label tuple (numpy
        throughout — never a Python loop over entries)."""
        r = len(kinds)
        # Map each entry to a kind index: rule row -> its kind, -1 (or
        # out-of-range) -> the "none" slot r.  Packet-layer rounds with
        # a match_kind column override per entry.
        kind_legend = list(kinds) + [MATCH_NONE]
        if cols and "match_kind" in cols:
            legend_arr, kind_idx = np.unique(
                np.asarray(cols["match_kind"]), return_inverse=True
            )
            kind_legend = [str(k) for k in legend_arr]
        else:
            rr = np.asarray(rules, np.int64)
            kind_idx = np.where((rr >= 0) & (rr < r), rr, r)
        nk = len(kind_legend)
        flat = np.asarray(codes, np.int64) * nk + kind_idx
        counts = np.bincount(flat, minlength=len(CODE_NAMES) * nk)
        for key in np.flatnonzero(counts):
            code, ki = divmod(int(key), nk)
            metrics.FlowVerdictsTotal.inc(
                CODE_NAMES[code], path, kind_legend[ki],
                amount=int(counts[key]),
            )

    def _notify_verdicts(self, batch: _RoundBatch) -> None:
        mon = self.monitor
        opts = self.opts
        if mon is None or opts is None:
            return
        if not opts.get(OPTION_POLICY_VERDICT_NOTIFY):
            return
        try:
            from ..monitor.monitor import (
                MSG_TYPE_POLICY_VERDICT,
                MonitorEvent,
            )

            idx = np.flatnonzero(
                (batch.codes == CODE_FORWARDED) | (batch.codes == CODE_DENIED)
            )[:MAX_VERDICT_NOTIFICATIONS]
            for i in idx:
                rec = self._materialize(batch, int(i))
                allowed = batch.codes[i] == CODE_FORWARDED
                # Deny verdicts are POLICY-VERDICT events too (the
                # reference's send_policy_verdict_notify covers both
                # directions); emitting MSG_TYPE_DROP here would
                # double-count against the feeding layer's own drop
                # sample when both share a monitor.
                mon.notify(
                    MonitorEvent(
                        MSG_TYPE_POLICY_VERDICT,
                        {
                            "src_identity": rec.get("src_identity", 0),
                            "dst_identity": rec.get("dst_identity", 0),
                            "dport": rec.get("dport", 0),
                            "proto": rec.get("proto", 0),
                            "allowed": bool(allowed),
                            "verdict": rec["verdict"],
                            "path": rec["path"],
                            "rule_id": rec["rule_id"],
                            "match_kind": rec["match_kind"],
                            "policy": rec.get("policy", ""),
                        },
                    )
                )
        except Exception:  # noqa: BLE001 — sink must not poison the path
            pass

    # -- query ------------------------------------------------------------

    def _materialize(self, b: _RoundBatch, i: int) -> dict:
        rule = int(b.rules[i])
        kind = (
            b.kinds[rule] if 0 <= rule < len(b.kinds) else MATCH_NONE
        )
        extra = None
        epoch = b.epoch
        if b.cols:
            extra = {}
            for name, col in b.cols.items():
                v = col[i]
                if name == "ct_state":
                    v = CT_NAMES[int(v)] if 0 <= int(v) < len(CT_NAMES) else ""
                elif isinstance(v, np.generic):
                    v = v.item()
                extra[name] = v
            kind = extra.pop("match_kind", kind)
            epoch = int(extra.pop("epoch", epoch))
        if epoch >= 0:
            extra = dict(extra or {})
            extra["epoch"] = epoch
        return materialize(
            b.seq0 + i, b.ts, b.path, b.conn_ids[i], int(b.codes[i]),
            rule, kind, self._meta_for(int(b.conn_ids[i])),
            reason=b.reason, extra=extra,
        )

    def query(self, n: int = 100, verdict: str | None = None,
              path: str | None = None, rule: int | None = None,
              conn: int | None = None, since: int | None = None,
              epoch: int | None = None,
              session: int | None = None) -> list[dict]:
        """Filtered record dicts.  Without ``since``: the newest ``n``
        matches, newest first.  With ``since``: records with
        seq > since in ASCENDING order (the `--follow` cursor
        contract).  ``session`` filters on the fan-in shim session the
        record's conn registered through (joined via the conn-metadata
        registry at query time — the serving path stores bare conn
        ids)."""
        n = max(int(n), 0)
        if session is not None:
            session = int(session)
        if verdict is not None and verdict not in CODE_NAMES:
            # Unknown verdict name (MSG_OBSERVE is raw JSON): nothing
            # can match — returning unfiltered records here would read
            # as "everything was <verdict>".
            return []
        with self._lock:
            rounds = list(self._rounds)
        want_code = (
            CODE_NAMES.index(verdict) if verdict is not None else None
        )
        out: list[dict] = []
        it = rounds if since is not None else reversed(rounds)
        for b in it:
            if since is not None and b.seq0 + b.count <= since + 1:
                continue
            if path is not None and b.path != path:
                continue
            sel = np.arange(b.count)
            if epoch is not None:
                if b.cols is not None and "epoch" in b.cols:
                    sel = sel[
                        np.asarray(b.cols["epoch"])[sel] == epoch
                    ]
                elif b.epoch != epoch:
                    continue
            if want_code is not None:
                sel = sel[b.codes[sel] == want_code]
            if rule is not None:
                sel = sel[b.rules[sel] == rule]
            if conn is not None:
                sel = sel[b.conn_ids[sel] == conn]
            if session is not None and len(sel):
                # Query-path-only join: resolve each candidate conn's
                # registered session (cold path — the hot path never
                # touches the meta registry).
                keep = []
                for i in sel:
                    meta = self._meta_for(int(b.conn_ids[i]))
                    sid = meta[8] if meta is not None and len(meta) > 8 \
                        else 0
                    if sid == session:
                        keep.append(i)
                sel = np.asarray(keep, sel.dtype)
            if since is not None:
                sel = sel[b.seq0 + sel > since]
            idxs = sel if since is not None else sel[::-1]
            for i in idxs:
                out.append(self._materialize(b, int(i)))
                if len(out) >= n:
                    return out
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "records": self._records,
                "rounds": len(self._rounds),
                "records_total": self.records_total,
                "rounds_total": self.rounds_total,
                "next_seq": self._seq,
            }
