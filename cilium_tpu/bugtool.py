"""bugtool: one-shot support-bundle collector.

reference: bugtool/cmd/root.go:159 — archives the agent's observable
state (CLI dumps, BPF map dumps, system state, logs) into a tar for
support triage.  Here every dump comes over the agent's REST API so the
tool works exactly like an operator's CLI would; unreachable sections
are recorded as errors instead of aborting the bundle (the reference
likewise continues past failing commands).
"""

from __future__ import annotations

import io
import json
import tarfile
import time

# Route table: archive member name -> REST route.
SECTIONS = [
    ("status.json", "/v1/status"),
    ("config.json", "/v1/config"),
    ("policy.json", "/v1/policy"),
    ("endpoints.json", "/v1/endpoint"),
    ("identities.json", "/v1/identity"),
    ("ipcache.json", "/v1/ipcache"),
    ("maps.json", "/v1/map"),
    ("prefilter.json", "/v1/prefilter"),
    ("metrics.txt", "/metrics"),
    ("monitor-tail.json", "/v1/monitor/recent"),
    ("health.json", "/v1/health"),
]


def collect(client, out_path: str) -> dict:
    """Collect every section through ``client`` (ApiClient) into a
    gzipped tar at ``out_path``; returns a summary manifest."""
    manifest = {
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "sections": {},
    }
    with tarfile.open(out_path, "w:gz") as tar:
        for name, route in SECTIONS:
            try:
                data = client.get(route)
                if isinstance(data, (dict, list)):
                    blob = json.dumps(data, indent=2, default=str).encode()
                else:
                    blob = str(data).encode()
                manifest["sections"][name] = {"ok": True, "bytes": len(blob)}
            except Exception as e:  # noqa: BLE001 — best-effort bundle
                blob = f"ERROR collecting {route}: {e}\n".encode()
                manifest["sections"][name] = {"ok": False, "error": str(e)}
            _add_member(tar, name, blob)
        _add_member(
            tar, "MANIFEST.json",
            json.dumps(manifest, indent=2).encode(),
        )
    return manifest


def _add_member(tar: tarfile.TarFile, name: str, blob: bytes) -> None:
    info = tarfile.TarInfo(name=f"cilium-tpu-bugtool/{name}")
    info.size = len(blob)
    info.mtime = int(time.time())
    tar.addfile(info, io.BytesIO(blob))
