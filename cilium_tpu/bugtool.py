"""bugtool: one-shot support-bundle collector.

reference: bugtool/cmd/root.go:159 — archives the agent's observable
state (CLI dumps, BPF map dumps, system state, logs) into a tar for
support triage.  REST sections come over the agent's API exactly like
an operator's CLI would; NATIVE sections capture state outside the
agent (the reference's tc/ip/bpffs dumps): the accelerator platform
(jax devices), the verdict service's live counters over its own wire,
the kvstore failure counters, CNI interface provisioning records, and
the latest BENCH/MULTICHIP artifacts from the repo root.  Unreachable
sections are recorded as errors instead of aborting the bundle (the
reference likewise continues past failing commands).
"""

from __future__ import annotations

import glob
import io
import json
import os
import tarfile
import time

# Route table: archive member name -> REST route.
SECTIONS = [
    ("status.json", "/v1/status"),
    ("config.json", "/v1/config"),
    ("policy.json", "/v1/policy"),
    ("endpoints.json", "/v1/endpoint"),
    ("identities.json", "/v1/identity"),
    ("ipcache.json", "/v1/ipcache"),
    ("maps.json", "/v1/map"),
    ("prefilter.json", "/v1/prefilter"),
    ("metrics.txt", "/metrics"),
    ("monitor-tail.json", "/v1/monitor/recent"),
    ("health.json", "/v1/health"),
]


def _device_section() -> dict:
    """Accelerator platform state (the reference's analog: the node's
    tc/ip device dumps — here the chips the verdict engines run on)."""
    import jax

    devs = jax.devices()
    return {
        "backend": jax.default_backend(),
        "device_count": len(devs),
        "devices": [
            {
                "id": d.id,
                "kind": getattr(d, "device_kind", ""),
                "platform": d.platform,
            }
            for d in devs
        ],
    }


def _verdict_service_section(socket_path: str) -> dict:
    """Live verdict-service counters over its own wire (the shim/Envoy
    admin-state analog)."""
    from .sidecar.client import SidecarClient

    cl = SidecarClient(socket_path, timeout=5.0)
    try:
        return cl.status()
    finally:
        cl.close()


def _artifact_files(repo_root: str) -> list[str]:
    """Latest BENCH_r*/MULTICHIP_r* paths — the perf state of the
    node's engines at bundle time (read via record() so an unreadable
    artifact degrades to an error member, not an aborted bundle)."""
    out = []
    for pattern in ("BENCH_r*.json", "MULTICHIP_r*.json"):
        files = sorted(glob.glob(os.path.join(repo_root, pattern)))
        if files:
            out.append(files[-1])
    return out


def collect(
    client,
    out_path: str,
    verdict_socket: str | None = None,
    cni=None,
    repo_root: str | None = None,
    kvstore=None,
) -> dict:
    """Collect every section through ``client`` (ApiClient) plus the
    native/device sections into a gzipped tar at ``out_path``; returns
    a summary manifest."""
    manifest = {
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "sections": {},
    }

    def record(name: str, fn) -> bytes:
        try:
            data = fn()
            if isinstance(data, (dict, list)):
                blob = json.dumps(data, indent=2, default=str).encode()
            elif isinstance(data, bytes):
                blob = data
            else:
                blob = str(data).encode()
            manifest["sections"][name] = {"ok": True, "bytes": len(blob)}
        except Exception as e:  # noqa: BLE001 — best-effort bundle
            blob = f"ERROR collecting {name}: {e}\n".encode()
            manifest["sections"][name] = {"ok": False, "error": str(e)}
        return blob

    with tarfile.open(out_path, "w:gz") as tar:
        for name, route in SECTIONS:
            _add_member(tar, name, record(name, lambda r=route: client.get(r)))
        # Native/device sections (bugtool/cmd/root.go's beyond-the-agent
        # captures).
        _add_member(tar, "device.json", record("device.json", _device_section))
        _add_member(
            tar, "kvstore-counters.json",
            record(
                "kvstore-counters.json",
                lambda: (
                    kvstore.counters.snapshot()
                    if kvstore is not None
                    and hasattr(kvstore, "counters")
                    else {}
                ),
            ),
        )
        if verdict_socket:
            _add_member(
                tar, "verdict-service.json",
                record(
                    "verdict-service.json",
                    lambda: _verdict_service_section(verdict_socket),
                ),
            )
        if cni is not None:
            _add_member(
                tar, "cni-interfaces.json",
                record(
                    "cni-interfaces.json",
                    lambda: {
                        cid: vars(v)
                        for cid, v in cni.interfaces_all().items()
                    },
                ),
            )
        for fname in _artifact_files(repo_root or "."):
            base = os.path.basename(fname)
            _add_member(
                tar, f"artifacts/{base}",
                record(f"artifacts/{base}",
                       lambda f=fname: open(f, "rb").read()),
            )
        _add_member(
            tar, "MANIFEST.json",
            json.dumps(manifest, indent=2).encode(),
        )
    return manifest




def _add_member(tar: tarfile.TarFile, name: str, blob: bytes) -> None:
    info = tarfile.TarInfo(name=f"cilium-tpu-bugtool/{name}")
    info.size = len(blob)
    info.mtime = int(time.time())
    tar.addfile(info, io.BytesIO(blob))
