"""Docker libnetwork remote driver.

reference: plugins/cilium-docker/driver/driver.go — an HTTP plugin on a
unix socket speaking the libnetwork remote-driver protocol: docker POSTs
JSON to /Plugin.Activate and NetworkDriver.* endpoints; the driver
answers with capabilities, provisions endpoints against the agent, and
on Join hands libnetwork the veth + gateway configuration.

Method surface mirrors driver.go:165-181 (Listen): Plugin.Activate,
NetworkDriver.{GetCapabilities, CreateNetwork, DeleteNetwork,
CreateEndpoint, DeleteEndpoint, EndpointOperInfo, Join, Leave}.
Errors use libnetwork's {"Err": "..."} shape.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler

from ..endpoint.connector import move_to_netns, setup_veth
from ..utils.logging import get_logger
from ..utils.unixhttp import serve_unix, shutdown_unix

log = get_logger("docker-driver")


class DriverError(RuntimeError):
    pass


class LibnetworkDriver:
    """The driver state machine; serve() exposes it on a unix socket."""

    def __init__(self, daemon, ipam, mtu: int = 1500) -> None:
        self.daemon = daemon
        self.ipam = ipam
        self.mtu = mtu
        self._lock = threading.Lock()
        self._networks: set[str] = set()
        # libnetwork EndpointID -> record
        self._endpoints: dict[str, dict] = {}
        self._next_ep_id = 5000
        self._server = None

    # -- protocol methods (driver.go handler names) -----------------------

    def activate(self, _body: dict) -> dict:
        """reference: driver.go handshake — implements NetworkDriver."""
        return {"Implements": ["NetworkDriver"]}

    def get_capabilities(self, _body: dict) -> dict:
        """reference: driver.go capabilities — local scope."""
        return {"Scope": "local"}

    def create_network(self, body: dict) -> dict:
        with self._lock:
            self._networks.add(body["NetworkID"])
        return {}

    def delete_network(self, body: dict) -> dict:
        with self._lock:
            self._networks.discard(body["NetworkID"])
        return {}

    def create_endpoint(self, body: dict) -> dict:
        """reference: driver.go:278 createEndpoint — rejects duplicates
        and missing IPv4, creates the agent endpoint."""
        eid = body["EndpointID"]
        iface = body.get("Interface") or {}
        addr = iface.get("Address", "")  # "ip/prefix"
        if not addr:
            raise DriverError("No IPv4 address provided")
        ip = addr.split("/")[0]
        with self._lock:
            if eid in self._endpoints:
                raise DriverError("Endpoint already exists")
            ep_id = self._next_ep_id
            self._next_ep_id += 1
            self._endpoints[eid] = {"ep_id": ep_id, "ip": ip, "veth": None}
        try:
            self.daemon.endpoint_create(
                ep_id, ipv4=ip, labels=["container:docker"],
                container_name=eid,
            )
        except Exception as e:  # noqa: BLE001 — surface as driver error
            with self._lock:
                self._endpoints.pop(eid, None)
            raise DriverError(str(e)) from e
        # libnetwork owns the interface it described; respond empty
        # (driver.go returns an empty Interface).
        return {"Interface": {}}

    def delete_endpoint(self, body: dict) -> dict:
        eid = body["EndpointID"]
        with self._lock:
            rec = self._endpoints.pop(eid, None)
        if rec is not None:
            self.daemon.endpoint_delete(rec["ep_id"])
        return {}

    def endpoint_info(self, body: dict) -> dict:
        eid = body["EndpointID"]
        with self._lock:
            if eid not in self._endpoints:
                raise DriverError(f"unknown endpoint {eid}")
        return {"Value": {}}

    def join(self, body: dict) -> dict:
        """reference: driver.go joinEndpoint — provision the veth and
        hand libnetwork the interface name + gateway."""
        eid = body["EndpointID"]
        with self._lock:
            rec = self._endpoints.get(eid)
        if rec is None:
            raise DriverError(f"unknown endpoint {eid}")
        veth = setup_veth(eid, body.get("SandboxKey", ""), mtu=self.mtu)
        move_to_netns(veth)
        rec["veth"] = veth
        return {
            "InterfaceName": {
                "SrcName": veth.tmp_ifname,
                "DstPrefix": "eth",
            },
            "Gateway": self.ipam.router_ip,
        }

    def leave(self, body: dict) -> dict:
        eid = body["EndpointID"]
        with self._lock:
            rec = self._endpoints.get(eid)
            if rec is not None:
                rec["veth"] = None
        return {}

    ROUTES = {
        "/Plugin.Activate": "activate",
        "/NetworkDriver.GetCapabilities": "get_capabilities",
        "/NetworkDriver.CreateNetwork": "create_network",
        "/NetworkDriver.DeleteNetwork": "delete_network",
        "/NetworkDriver.CreateEndpoint": "create_endpoint",
        "/NetworkDriver.DeleteEndpoint": "delete_endpoint",
        "/NetworkDriver.EndpointOperInfo": "endpoint_info",
        "/NetworkDriver.Join": "join",
        "/NetworkDriver.Leave": "leave",
    }

    # -- unix-socket HTTP plumbing ----------------------------------------

    def serve(self, path: str) -> "LibnetworkDriver":
        driver = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    body = {}
                name = driver.ROUTES.get(self.path)
                if name is None:
                    out, status = {"Err": f"unknown {self.path}"}, 404
                else:
                    try:
                        out, status = getattr(driver, name)(body), 200
                    except DriverError as e:
                        out, status = {"Err": str(e)}, 400
                    except Exception as e:  # noqa: BLE001
                        log.with_field("err", str(e)).warning(
                            "driver method failed"
                        )
                        out, status = {"Err": str(e)}, 500
                payload = json.dumps(out).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._server = serve_unix(path, Handler)
        self.path = path
        return self

    def close(self) -> None:
        if self._server is not None:
            shutdown_unix(self._server, self.path)
