"""Orchestrator plugins (reference: plugins/ — cilium-cni and the
cilium-docker libnetwork remote driver)."""
