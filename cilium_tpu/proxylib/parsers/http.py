"""HTTP/1.x request-policy parser — the cilium.l7policy filter analog.

Reference: envoy/cilium_l7policy.cc:51 (per-request allow/deny in the
HTTP filter) + envoy/cilium_network_policy.h:50-76 (anchored regex on
path/method/host, exact header presence).  The reference serves HTTP
inside Envoy rather than proxylib; this build routes it through the
same parser seam as every other protocol so HTTP rides the sidecar
verdict service too (device model: cilium_tpu.models.http).

Framing: a request frame is the head (through CRLFCRLF) plus a
Content-Length body; the verdict covers the whole frame.  Denials
inject the reference's 403 response (envoy/cilium_l7policy.cc
AccessDenied body) and DROP the frame.  The reply direction passes
untouched — the reference's filter polices requests only.

Rule dialect: path/method/host are ANCHORED regexes evaluated with
Python ``re`` — deliberately mirroring the Envoy ``std::regex`` side of
the reference (the agent's POSIX dialect is the device compiler's
domain; the fuzz tests in tests/test_http_model.py pin the two
together on the shared corpus).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from ..accesslog import EntryType
from ..parser import parse_error, register_l7_rule_parser, register_parser_factory
from ..types import DROP, MORE, PASS

# The exact denial body the reference injects
# (envoy/cilium_l7policy.cc:91 denied_403_body_ = "Access denied").
HTTP_403 = (
    b"HTTP/1.1 403 Forbidden\r\ncontent-type: text/plain\r\n"
    b"content-length: 13\r\n\r\nAccess denied"
)
MAX_HEAD = 1 << 15  # heads beyond this are denied (engines.py MAX_WIDTH)


@dataclass
class HttpRequestData:
    method: str
    path: str
    host: str
    headers: list[str] = field(default_factory=list)


class HttpRule:
    """One PortRuleHTTP-shaped matcher (reference:
    cilium_network_policy.h HttpNetworkPolicyRule::Matches)."""

    def __init__(self, method="", path="", host="", headers=()):
        # Pattern sources are kept so the device model compiles from
        # the same strings (models/http.build_http_model_for_port).
        self.method_src = method
        self.path_src = path
        self.host_src = host
        self.method = re.compile(method) if method else None
        self.path = re.compile(path) if path else None
        self.host = re.compile(host) if host else None
        self.headers = list(headers)

    def matches(self, data) -> bool:
        if not isinstance(data, HttpRequestData):
            return False
        if self.method is not None and not self.method.fullmatch(data.method):
            return False
        if self.path is not None and not self.path.fullmatch(data.path):
            return False
        if self.host is not None and not self.host.fullmatch(data.host):
            return False
        return all(self._header_present(h, data.headers) for h in self.headers)

    @staticmethod
    def _header_present(rule_header: str, headers: list[str]) -> bool:
        """Case-insensitive name + OWS-stripped value equality — the
        same semantics the device model compiles
        (models/http.py _header_pattern)."""
        name, sep, value = rule_header.partition(":")
        if not sep:
            return rule_header in headers
        want = (name.lower(), value.strip())
        for h in headers:
            hn, hsep, hv = h.partition(":")
            if hsep and (hn.lower(), hv.strip(" \t")) == want:
                return True
        return False


def http_rule_parser(rule_config):
    """Compile the typed http_rules list (reference:
    pkg/envoy/server.go:336 getHTTPRule translation target)."""
    rules = []
    for rd in rule_config.http_rules or []:
        bad = set(rd) - {"method", "path", "host", "headers"}
        if bad:
            parse_error(f"Unsupported http rule keys: {sorted(bad)}",
                        rule_config)
        try:
            rules.append(
                HttpRule(
                    method=rd.get("method", ""),
                    path=rd.get("path", ""),
                    host=rd.get("host", ""),
                    headers=rd.get("headers", ()),
                )
            )
        except re.error as e:
            parse_error(f"invalid http rule regex: {e}", rule_config)
    return rules


def head_and_body_len(buf: bytes) -> tuple[int, int] | None:
    """(head_len, body_len) once the full frame is buffered, else None
    (the same framing as runtime/engines.py HttpBatchEngine)."""
    end = buf.find(b"\r\n\r\n")
    if end < 0:
        return None
    head_len = end + 4
    body_len = 0
    lower = buf[:head_len].lower()
    idx = lower.find(b"\r\ncontent-length:")
    if idx >= 0:
        line_end = lower.find(b"\r\n", idx + 2)
        try:
            # Clamp: a negative Content-Length must never shrink the
            # frame span (it would walk framing offsets backwards).
            body_len = max(0, int(lower[idx + 17:line_end].strip()))
        except ValueError:
            body_len = 0
    if len(buf) < head_len + body_len:
        return None
    return head_len, body_len


def parse_head(head: bytes) -> HttpRequestData | None:
    lines = head.decode("utf-8", "surrogateescape").split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) < 3:
        return None
    headers = [h for h in lines[1:] if h]
    # Host lookup mirrors the device model's pattern
    # (models/http.py: case-insensitive name, OWS-stripped value).
    host = ""
    for h in headers:
        name, sep, value = h.partition(":")
        if sep and name.lower() == "host":
            host = value.strip(" \t")
    return HttpRequestData(
        method=parts[0], path=parts[1], host=host, headers=headers
    )


class HttpParser:
    def __init__(self, connection):
        self.connection = connection

    def on_data(self, reply, end_stream, data):
        joined = b"".join(data)
        if reply:
            # Responses pass untouched (the reference's HTTP filter
            # polices the request path only).
            return (PASS, len(joined)) if joined else (MORE, 1)

        framed = head_and_body_len(joined)
        if framed is None:
            if len(joined) > MAX_HEAD:
                # Pathological unterminated head: deny what's buffered.
                self.connection.inject(True, HTTP_403)
                return DROP, len(joined)
            return MORE, 1
        head_len, body_len = framed
        req = parse_head(joined[:head_len])
        matches = req is not None and self.connection.matches(req)
        self.connection.log(
            EntryType.Request if matches else EntryType.Denied,
            proto="http",
            fields={
                "method": req.method if req else "",
                "url": req.path if req else "",
                "status": "200" if matches else "403",
            },
        )
        if not matches:
            self.connection.inject(True, HTTP_403)
            return DROP, head_len + body_len
        return PASS, head_len + body_len


class HttpParserFactory:
    def create(self, connection):
        return HttpParser(connection)


register_parser_factory("http", HttpParserFactory())
register_l7_rule_parser("http", http_rule_parser)
