"""Memcached parser (text + binary wire protocols) and L7 rules.

Reference: proxylib/memcached/{parser.go,binary/parser.go,text/parser.go,
meta/meta.go}.  The unified parser sniffs the first byte of the first
request (>= 0x80 means binary) and delegates to the protocol parser for
the rest of the connection.  Rules allow a ``command`` (a name or group
from MEMCACHE_OPCODE_MAP expanding to text commands + binary opcodes)
and optionally constrain keys with exactly one of ``keyExact`` /
``keyPrefix`` / ``keyRegex``; denials inject protocol-appropriate
"access denied" replies, kept in request order with a reply-intent
queue.

Deliberate divergence: the reference's binary parser enqueues a denial
into its inject queue even when it was already injected inline
(binary/parser.go:129-135 appends twice), permanently wedging the queue
head so later queued denials never inject; here a denial is either
injected immediately or queued, exactly once.

``keyRegex`` compiles through ``cilium_tpu.regex`` — the same NFA the
device model evaluates.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from ...regex import CompiledPattern, compile_pattern, py_search
from ...regex.parse import ParseError as RegexParseError
from ..accesslog import EntryType
from ..parser import parse_error, register_l7_rule_parser, register_parser_factory
from ..types import DROP, ERROR, INJECT, MORE, NOP, PASS, OpError


@dataclass
class MemcacheMeta:
    """Frame metadata handed to rule matching (reference: meta/meta.go)."""

    command: str = ""  # text protocol
    opcode: int = -1  # binary protocol
    keys: list[bytes] = field(default_factory=list)

    def is_binary(self) -> bool:
        return not self.command


# command name / group -> (text command set, binary opcode set)
# (reference: parser.go:214-474 MemcacheOpCodeMap)
_STORAGE_TEXT = {"add", "set", "replace", "append", "prepend", "cas", "incr", "decr"}
_STORAGE_BIN = {1, 2, 3, 5, 6, 17, 18, 19, 21, 22, 25, 26}

MEMCACHE_OPCODE_MAP: dict[str, tuple[frozenset, frozenset]] = {
    "add": (frozenset({"add"}), frozenset({2, 18})),
    "set": (frozenset({"set"}), frozenset({1, 17})),
    "replace": (frozenset({"replace"}), frozenset({3, 19})),
    "append": (frozenset({"append"}), frozenset({14, 25})),
    "prepend": (frozenset({"prepend"}), frozenset({15, 26})),
    "cas": (frozenset({"cas"}), frozenset()),
    "incr": (frozenset({"incr"}), frozenset({5, 21})),
    "decr": (frozenset({"decr"}), frozenset({6, 22})),
    "storage": (frozenset(_STORAGE_TEXT), frozenset(_STORAGE_BIN)),
    "get": (frozenset({"get", "gets"}), frozenset({0, 9, 12, 13})),
    "delete": (frozenset({"delete"}), frozenset({4, 20})),
    "touch": (frozenset({"touch"}), frozenset({28})),
    "gat": (frozenset({"gat", "gats"}), frozenset({29, 30})),
    "writeGroup": (
        frozenset(_STORAGE_TEXT | {"delete", "touch"}),
        frozenset(_STORAGE_BIN | {4, 20, 28}),
    ),
    "slabs": (frozenset({"slabs"}), frozenset()),
    "lru": (frozenset({"lru"}), frozenset()),
    "lru_crawler": (frozenset({"lru_crawler"}), frozenset()),
    "watch": (frozenset({"watch"}), frozenset()),
    "stats": (frozenset({"stats"}), frozenset({16})),
    "flush_all": (frozenset({"flush_all"}), frozenset({8, 24})),
    "cache_memlimit": (frozenset({"cache_memlimit"}), frozenset()),
    "version": (frozenset({"version"}), frozenset({11})),
    "misbehave": (frozenset({"misbehave"}), frozenset()),
    "quit": (frozenset({"quit"}), frozenset({7, 23})),
    "noop": (frozenset(), frozenset({10})),
    "verbosity": (frozenset(), frozenset({27})),
    "sasl-list-mechs": (frozenset(), frozenset({32})),
    "sasl-auth": (frozenset(), frozenset({33})),
    "sasl-step": (frozenset(), frozenset({34})),
    "rget": (frozenset(), frozenset({48})),
    "rset": (frozenset(), frozenset({49})),
    "rsetq": (frozenset(), frozenset({50})),
    "rappend": (frozenset(), frozenset({51})),
    "rappendq": (frozenset(), frozenset({52})),
    "rprepend": (frozenset(), frozenset({53})),
    "rprependq": (frozenset(), frozenset({54})),
    "rdelete": (frozenset(), frozenset({55})),
    "rdeleteq": (frozenset(), frozenset({56})),
    "rincr": (frozenset(), frozenset({57})),
    "rincrq": (frozenset(), frozenset({58})),
    "rdecr": (frozenset(), frozenset({59})),
    "rdecrq": (frozenset(), frozenset({60})),
    "set-vbucket": (frozenset(), frozenset({61})),
    "get-vbucket": (frozenset(), frozenset({62})),
    "del-vbucket": (frozenset(), frozenset({63})),
    "tap-connect": (frozenset(), frozenset({64})),
    "tap-mutation": (frozenset(), frozenset({65})),
    "tap-delete": (frozenset(), frozenset({66})),
    "tap-flush": (frozenset(), frozenset({67})),
    "tap-opaque": (frozenset(), frozenset({68})),
    "tap-vbucket-set": (frozenset(), frozenset({69})),
    "tap-checkpoint-start": (frozenset(), frozenset({70})),
    "tap-checkpoint-end": (frozenset(), frozenset({71})),
}


class MemcacheRule:
    """One allow-rule on (command set, key constraint)
    (reference: parser.go:35-100)."""

    def __init__(self, text_cmds=frozenset(), bin_opcodes=frozenset(),
                 key_exact: bytes = b"", key_prefix: bytes = b"",
                 key_regex: str = "", empty: bool = False):
        self.text_cmds = text_cmds
        self.bin_opcodes = bin_opcodes
        self.key_exact = key_exact
        self.key_prefix = key_prefix
        self.key_regex = key_regex
        self.key_compiled: CompiledPattern | None = (
            compile_pattern(key_regex) if key_regex else None
        )
        self.empty = empty

    def matches(self, data) -> bool:
        if not isinstance(data, MemcacheMeta):
            return False
        if self.empty:
            return True
        if data.is_binary():
            if data.opcode not in self.bin_opcodes:
                return False
        else:
            if data.command not in self.text_cmds:
                return False
        if self.key_exact:
            return all(k == self.key_exact for k in data.keys)
        if self.key_prefix:
            return all(k.startswith(self.key_prefix) for k in data.keys)
        if self.key_compiled is not None:
            return all(py_search(self.key_compiled, k) for k in data.keys)
        return True


def memcache_rule_parser(rule_config):
    """(reference: parser.go:114-148)."""
    rules = []
    for kv in rule_config.l7_rules or []:
        text_cmds, bin_ops = frozenset(), frozenset()
        key_exact, key_prefix, key_regex = b"", b"", ""
        command_found = False
        for k, v in kv.items():
            if k == "command":
                sets = MEMCACHE_OPCODE_MAP.get(v)
                if sets is None:
                    # Divergence: the reference leaves an unknown command
                    # name as a not-found lookup, which (without a key
                    # constraint) silently builds an allow-everything
                    # rule (parser.go:126,137-142) — a typo fails open.
                    # Reject it instead.
                    parse_error(f"Unknown command: {v}", rule_config)
                text_cmds, bin_ops = sets
                command_found = True
            elif k == "keyExact":
                key_exact = v.encode("utf-8", "surrogateescape")
            elif k == "keyPrefix":
                key_prefix = v.encode("utf-8", "surrogateescape")
            elif k == "keyRegex":
                key_regex = v
            else:
                parse_error(f"Unsupported key: {k}", rule_config)
        empty = False
        if not command_found:
            if key_exact or key_prefix or key_regex:
                parse_error(
                    "command not specified but key was provided", rule_config
                )
            else:
                empty = True
        try:
            rules.append(
                MemcacheRule(
                    text_cmds, bin_ops, key_exact, key_prefix, key_regex, empty
                )
            )
        except RegexParseError as e:
            parse_error(f"invalid keyRegex: {e}", rule_config)
    return rules


# --- binary protocol -----------------------------------------------------

BINARY_HEADER_SIZE = 24
REQUEST_MAGIC = 0x80
RESPONSE_MAGIC = 0x81

# Fixed "access denied" binary error reply (status 0x000d = busy-ish per
# reference; magic patched per request; reference: binary/parser.go:194).
BINARY_DENIED_MSG = bytes(
    [
        0x81, 0, 0, 0,
        0, 0, 0, 8,
        0, 0, 0, 0x0D,
        0, 0, 0, 0,
        0, 0, 0, 0,
        0, 0, 0, 0,
    ]
) + b"access denied"


class BinaryMemcacheParser:
    """(reference: binary/parser.go:44-191)."""

    def __init__(self, connection):
        self.connection = connection
        self.request_count = 0
        self.reply_count = 0
        # (magic, request_id) denials waiting for their in-order slot.
        self.inject_queue: list[tuple[int, int]] = []

    def _inject_denied(self, magic: int) -> None:
        msg = bytearray(BINARY_DENIED_MSG)
        msg[0] = magic
        self.connection.inject(True, bytes(msg))
        self.reply_count += 1

    def _inject_from_queue(self) -> bool:
        if self.inject_queue and self.inject_queue[0][1] == self.reply_count + 1:
            magic, _ = self.inject_queue.pop(0)
            self._inject_denied(magic)
            return True
        return False

    def on_data(self, reply, end_stream, data):
        if reply:
            if self._inject_from_queue():
                return INJECT, len(BINARY_DENIED_MSG)
            if not data:  # list emptiness, matching the reference
                return NOP, 0
        joined = b"".join(data)
        if len(joined) < BINARY_HEADER_SIZE:
            return MORE, BINARY_HEADER_SIZE - len(joined)

        (body_len,) = struct.unpack_from(">I", joined, 8)
        (key_len,) = struct.unpack_from(">H", joined, 2)
        extras_len = joined[4]
        if key_len > 0:
            needed = BINARY_HEADER_SIZE + key_len + extras_len
            if needed > len(joined):
                return MORE, needed - len(joined)

        opcode = joined[1]
        key = (
            joined[
                BINARY_HEADER_SIZE + extras_len :
                BINARY_HEADER_SIZE + extras_len + key_len
            ]
            if key_len
            else b""
        )
        fields = {"opcode": str(opcode), "key": key.decode("utf-8", "surrogateescape")}
        frame_len = BINARY_HEADER_SIZE + body_len

        # The 0x80 magic bit must be present in BOTH directions: the
        # reference validates it in getOpcodeAndKey (binary/parser.go)
        # before ever branching on reply, so a malformed reply frame is
        # an invalid-frame error, not a PASS.
        if not joined[0] & REQUEST_MAGIC:
            return ERROR, int(OpError.ERROR_INVALID_FRAME_TYPE)

        if reply:
            self.connection.log(
                EntryType.Response, proto="binarymemcached", fields=fields
            )
            self.reply_count += 1
            return PASS, frame_len

        self.request_count += 1
        meta = MemcacheMeta(opcode=opcode, keys=[key])
        if self.connection.matches(meta):
            self.connection.log(
                EntryType.Request, proto="binarymemcached", fields=fields
            )
            return PASS, frame_len

        magic = RESPONSE_MAGIC | joined[0]
        # In-order denial replies: inject now only if every earlier
        # request has been answered, else queue (exactly once — see the
        # divergence note in the module docstring).
        if self.request_count == self.reply_count + 1:
            self._inject_denied(magic)
        else:
            self.inject_queue.append((magic, self.request_count))
        self.connection.log(
            EntryType.Denied, proto="binarymemcached", fields=fields
        )
        return DROP, frame_len


# --- text protocol -------------------------------------------------------

TEXT_DENIED_MSG = b"CLIENT_ERROR access denied\r\n"
_PAYLOAD_END = b"\r\nEND\r\n"

# token counts that indicate a trailing "noreply" (reference:
# text/parser.go:63-69)
_CAS_NOREPLY = 7
_STORAGE_NOREPLY = 6
_DELETE_NOREPLY = 3
_INCR_NOREPLY = 4
_TOUCH_NOREPLY = 4

_FLAT_COMMANDS = (
    b"slabs", b"lru", b"lru_crawler", b"stats", b"version", b"misbehave",
)


def _is_retrieval(cmd: bytes) -> bool:
    return cmd.startswith(b"get") or cmd.startswith(b"gat")


def _is_storage(cmd: bytes) -> bool:
    return cmd in (b"set", b"add", b"replace", b"append", b"prepend", b"cas")


def _is_incr_decr(cmd: bytes) -> bool:
    return cmd in (b"incr", b"decr")


def _is_error_reply(tok: bytes) -> bool:
    return tok in (b"ERROR", b"CLIENT_ERROR", b"SERVER_ERROR")


class TextMemcacheParser:
    """(reference: text/parser.go:45-302)."""

    def __init__(self, connection):
        self.connection = connection
        # (command, denied) intents, one per reply expected in order.
        self.reply_queue: list[tuple[bytes, bool]] = []
        self.watching = False

    def _inject_from_queue(self) -> int:
        injected = 0
        for cmd, denied in self.reply_queue:
            if denied:
                injected += 1
                self.connection.inject(True, TEXT_DENIED_MSG)
            else:
                break
        if injected:
            del self.reply_queue[:injected]
        return injected * len(TEXT_DENIED_MSG)

    def on_data(self, reply, end_stream, data):
        if reply:
            injected = self._inject_from_queue()
            if injected > 0:
                return INJECT, injected
            if not data:  # list emptiness, matching the reference
                return NOP, 0
        joined = b"".join(data)
        linefeed = joined.find(b"\r\n")
        if linefeed < 0:
            if joined and joined[-1:] == b"\r":
                return MORE, 1
            return MORE, 2
        tokens = joined[:linefeed].split()

        if not reply:
            return self._on_request(joined, linefeed, tokens)
        return self._on_reply(joined, linefeed, tokens)

    def _on_request(self, joined, linefeed, tokens):
        if not tokens:
            return ERROR, 0
        command = tokens[0]
        meta = MemcacheMeta(command=command.decode("ascii", "replace"))
        frame_len = linefeed + 2
        has_noreply = False

        if _is_retrieval(command):
            if command.startswith(b"get"):
                meta.keys = tokens[1:]
            else:
                meta.keys = tokens[2:]
        elif _is_storage(command):
            meta.keys = tokens[1:2]
            try:
                n_bytes = int(tokens[4])
            except (IndexError, ValueError):
                return ERROR, 0
            frame_len += n_bytes + 2  # data block + terminating CRLF
            if command[:1] == b"c":  # cas
                has_noreply = len(tokens) == _CAS_NOREPLY
            else:
                has_noreply = len(tokens) == _STORAGE_NOREPLY
        elif command == b"delete":
            meta.keys = tokens[1:2]
            has_noreply = len(tokens) == _DELETE_NOREPLY
        elif _is_incr_decr(command):
            meta.keys = tokens[1:2]
            has_noreply = len(tokens) == _INCR_NOREPLY
        elif command == b"touch":
            meta.keys = tokens[1:2]
            has_noreply = len(tokens) == _TOUCH_NOREPLY
        elif command in _FLAT_COMMANDS:
            meta.keys = []
        elif command in (b"flush_all", b"cache_memlimit"):
            meta.keys = []
            has_noreply = tokens[-1] == b"noreply"
        elif command == b"quit":
            meta.keys = []
            has_noreply = True
        elif command == b"watch":
            meta.keys = []
            self.watching = True
        else:
            return ERROR, 0

        fields = {
            "command": meta.command,
            "keys": b", ".join(meta.keys).decode("utf-8", "surrogateescape"),
        }
        if self.connection.matches(meta):
            if not has_noreply:
                self.reply_queue.append((command, False))
            self.connection.log(
                EntryType.Request, proto="textmemcached", fields=fields
            )
            return PASS, frame_len

        if not has_noreply:
            if not self.reply_queue:
                self.connection.inject(True, TEXT_DENIED_MSG)
            else:
                self.reply_queue.append((command, True))
        self.connection.log(
            EntryType.Denied, proto="textmemcached", fields=fields
        )
        return DROP, frame_len

    def _on_reply(self, joined, linefeed, tokens):
        if not self.reply_queue:
            # Unsolicited reply line (or reply to a noreply command):
            # nothing to correlate — protocol error (the reference
            # panics here and recovers to PARSER_ERROR).
            return ERROR, 0
        command, _denied = self.reply_queue[0]
        fields = {"command": command.decode("utf-8", "surrogateescape")}

        if self.watching:
            return PASS, linefeed + 2  # watch mode: pass every line

        if (
            (tokens and _is_error_reply(tokens[0]))
            or _is_storage(command)
            or command == b"delete"
            or _is_incr_decr(command)
            or command
            in (
                b"touch", b"slabs", b"lru", b"flush_all",
                b"cache_memlimit", b"version", b"misbehave",
            )
        ):
            self.connection.log(
                EntryType.Response, proto="textmemcached", fields=fields
            )
            self.reply_queue.pop(0)
            return PASS, linefeed + 2
        if _is_retrieval(command) or command == b"stats":
            op, n = self._until_end(joined)
            if op == PASS:
                self.connection.log(
                    EntryType.Response, proto="textmemcached", fields=fields
                )
                self.reply_queue.pop(0)
            return op, n
        if command == b"lru_crawler":
            if tokens and tokens[0] in (b"OK", b"BUSY", b"BADCLASS"):
                self.connection.log(
                    EntryType.Response, proto="textmemcached", fields=fields
                )
                self.reply_queue.pop(0)
                return PASS, linefeed + 2
            op, n = self._until_end(joined)
            if op == PASS:
                self.connection.log(
                    EntryType.Response, proto="textmemcached", fields=fields
                )
                self.reply_queue.pop(0)
            return op, n
        return ERROR, 0

    @staticmethod
    def _until_end(data: bytes):
        # A miss reply is the bare terminator line "END\r\n" — the
        # reference only searches for "\r\nEND\r\n" (text/parser.go:
        # 264-273) and would buffer a miss reply forever; divergence:
        # accept the terminator at offset 0.
        if data.startswith(_PAYLOAD_END[2:]):
            return PASS, len(_PAYLOAD_END) - 2
        end = data.find(_PAYLOAD_END)
        if end > 0:
            return PASS, end + len(_PAYLOAD_END)
        return MORE, 1


# --- unified sniffing parser (reference: parser.go:176-202) --------------

class MemcacheParser:
    def __init__(self, connection):
        self.connection = connection
        self.parser = None

    def on_data(self, reply, end_stream, data):
        if self.parser is None:
            first = b""
            for chunk in data:
                if chunk:
                    first = chunk[:1]
                    break
            if not first:
                return NOP, 0
            if first[0] >= 128:
                self.parser = BinaryMemcacheParser(self.connection)
            else:
                self.parser = TextMemcacheParser(self.connection)
        return self.parser.on_data(reply, end_stream, data)


class MemcacheParserFactory:
    def create(self, connection):
        return MemcacheParser(connection)


register_parser_factory("memcache", MemcacheParserFactory())
register_l7_rule_parser("memcache", memcache_rule_parser)
