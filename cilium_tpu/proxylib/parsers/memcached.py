"""Memcached parser — implemented in cilium_tpu.proxylib.parsers.memcached (phase 4).

Reference: proxylib/memcached/parser.go.
"""
