"""Test parsers: passer, line, block, header.

Byte-exact reimplementations of the reference's test parsers
(reference: proxylib/testparsers/{passer,lineparser,blockparser,
headerparser}.go) — they anchor the OnData op-sequence oracle tests.
"""

from __future__ import annotations

from ..accesslog import EntryType
from ..parser import parse_error, register_l7_rule_parser, register_parser_factory
from ..types import DROP, ERROR, INJECT, MORE, NOP, PASS, OpError


class PasserParser:
    """Passes everything (reference: testparsers/passer.go)."""

    def on_data(self, reply, end_stream, data):
        n = sum(len(s) for s in data)
        if n == 0:
            return NOP, 0
        return PASS, n


class PasserParserFactory:
    def create(self, connection):
        if connection.policy_name == "invalid-policy":
            return None  # reject based on connection metadata
        return PasserParser()


def get_line(data: list[bytes]) -> tuple[bytes, bool]:
    """First '\\n'-terminated line across chunks
    (reference: testparsers/lineparser.go getLine)."""
    line = bytearray()
    for s in data:
        idx = s.find(b"\n")
        if idx < 0:
            line += s
        else:
            line += s[: idx + 1]
            return bytes(line), True
    return bytes(line), False


class LineParser:
    """PASS/DROP/INJECT/INSERT line protocol
    (reference: testparsers/lineparser.go)."""

    def __init__(self, connection):
        self.connection = connection
        self.inserted = False

    def on_data(self, reply, end_stream, data):
        line, ok = get_line(data)
        line_len = len(line)
        if self.inserted:
            self.inserted = False
            return DROP, line_len
        if not ok:
            if line_len > 0:
                return MORE, 1
            return NOP, 0
        if line.startswith(b"PASS"):
            return PASS, line_len
        if line.startswith(b"DROP"):
            return DROP, line_len
        if line.startswith(b"INJECT"):
            self.connection.inject(not reply, line)
            return DROP, line_len
        if line.startswith(b"INSERT"):
            self.connection.inject(reply, line)
            self.inserted = True
            return INJECT, line_len
        return ERROR, int(OpError.ERROR_INVALID_FRAME_TYPE)


class LineParserFactory:
    def create(self, connection):
        return LineParser(connection)


def get_block(data: list[bytes]) -> tuple[bytes, int, int]:
    """Length-prefixed 'N:...' frame reassembly
    (reference: testparsers/blockparser.go getBlock).
    Returns (block, block_len, missing); raises ValueError on bad length."""
    block = bytearray()
    offset = 0
    block_len = 0
    have_length = False
    missing = 0
    for s in data:
        if not have_length:
            idx = s[offset:].find(b":")
            if idx < 0:
                block += s[offset:]
                if len(block) > 0:
                    missing = 1
            else:
                block += s[offset : offset + idx]
                offset += idx
                n = int(bytes(block))  # may raise ValueError
                block_len = n
                if block_len <= len(block):
                    raise ValueError("Block length too short")
                have_length = True
                missing = block_len - len(block)
        if have_length:
            s_len = len(s) - offset
            if missing <= s_len:
                block += s[offset : offset + missing]
                return bytes(block), block_len, 0
            block += s[offset:]
            missing -= s_len
        offset = 0
    return bytes(block), block_len, missing


class BlockParser:
    """(reference: testparsers/blockparser.go)."""

    def __init__(self, connection):
        self.connection = connection
        self.inserted = False

    def on_data(self, reply, end_stream, data):
        try:
            block, block_len, missing = get_block(data)
        except ValueError:
            return ERROR, int(OpError.ERROR_INVALID_FRAME_LENGTH)
        if self.inserted:
            self.inserted = False
            return DROP, block_len
        if missing == 0 and block_len == 0:
            return NOP, 0
        if b"PASS" in block:
            self.connection.log(EntryType.Request, proto="http", fields={"status": 200})
            return PASS, block_len
        if b"DROP" in block:
            self.connection.log(EntryType.Denied, proto="http", fields={"status": 201})
            return DROP, block_len
        if missing > 0:
            return MORE, missing
        if b"INJECT" in block:
            self.connection.inject(not reply, block)
            return DROP, block_len
        if b"INSERT" in block:
            self.connection.inject(reply, block)
            self.inserted = True
            return INJECT, block_len
        return ERROR, int(OpError.ERROR_INVALID_FRAME_TYPE)


class BlockParserFactory:
    def create(self, connection):
        return BlockParser(connection)


class HeaderRule:
    """prefix/contains/suffix rule on a whitespace-trimmed line
    (reference: testparsers/headerparser.go HeaderRule)."""

    def __init__(self, prefix=b"", contains=b"", suffix=b""):
        self.prefix, self.contains, self.suffix = prefix, contains, suffix

    def matches(self, data) -> bool:
        bs = bytes(data).strip()
        if self.prefix and not bs.startswith(self.prefix):
            return False
        if self.contains and self.contains not in bs:
            return False
        if self.suffix and not bs.endswith(self.suffix):
            return False
        return True


def header_rule_parser(rule_config):
    rules = []
    for kv in rule_config.l7_rules or []:
        hr = HeaderRule()
        for k, v in kv.items():
            if k == "prefix":
                hr.prefix = v.encode()
            elif k == "contains":
                hr.contains = v.encode()
            elif k == "suffix":
                hr.suffix = v.encode()
            else:
                parse_error(f"Unsupported key: {k}", rule_config)
        rules.append(hr)
    return rules


class HeaderParser:
    """(reference: testparsers/headerparser.go)."""

    def __init__(self, connection):
        self.connection = connection

    def on_data(self, reply, end_stream, data):
        line, ok = get_line(data)
        line_len = len(line)
        if not ok:
            if line_len > 0:
                return MORE, 1
            return NOP, 0
        if reply or self.connection.matches(line):
            self.connection.log(
                EntryType.Request,
                proto="test.headerparser",
                fields={"status": "PASS"},
            )
            return PASS, line_len
        self.connection.inject(not reply, b"Line dropped: " + line)
        self.connection.log(
            EntryType.Denied,
            proto="test.headerparser",
            fields={"status": "DROP"},
        )
        return DROP, line_len


class HeaderParserFactory:
    def create(self, connection):
        return HeaderParser(connection)


register_parser_factory("test.passer", PasserParserFactory())
register_parser_factory("test.lineparser", LineParserFactory())
register_parser_factory("test.blockparser", BlockParserFactory())
register_parser_factory("test.headerparser", HeaderParserFactory())
register_l7_rule_parser("test.headerparser", header_rule_parser)
