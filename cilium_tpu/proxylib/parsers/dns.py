"""DNS-over-TCP name-policy parser — the streaming oracle of the DNS
engine family (models/dns.py is the device twin).

Wire format (RFC 1035 §4.2.2): each message rides a 2-byte big-endian
length prefix; the message itself is a 12-byte header followed by the
question section — a QNAME label sequence (length-prefixed labels,
terminated by a zero byte) plus QTYPE/QCLASS.  This parser frames
requests on the length prefix, extracts the FIRST question's name, and
matches it against compiled name rules:

- ``matchName``   — exact name, case-insensitive (0x20-folded), trailing
                    dot stripped;
- ``matchPattern``— wildcard name: a leading ``*.`` matches one or MORE
                    whole labels; ``*`` anywhere else matches a run of
                    zero or more non-dot bytes; everything else literal.
                    Lowered onto the shared regex automaton;
- ``matchRegex``  — raw regex over the dotted, 0x20-folded name
                    (search semantics, like the r2d2 ``file`` rule).

Name canonicalization is deliberately byte-exact with the device model:
only bytes 0x41-0x5A fold (+0x20); labels join with ``.``; no trailing
dot; the root name is the empty string.  Queries the engine cannot
prove well-formed (compression pointers in QNAME, label > 63 bytes,
more than MAX_LABELS labels, truncated question, QDCOUNT == 0) can
never satisfy a name-CONSTRAINED rule, but a byte-free always-match
row ("allow these peers' DNS") still admits them — host and device
alike.  That asymmetry is load-bearing: it is what makes a byte-free
row genuinely byte-INVARIANT, so DNS flows ride the PR 12 verdict
cache (policy/invariance.reduce_dns_rows).

Deny semantics: DROP the frame with NO reply inject (unlike r2d2's
``ERROR\\r\\n``): a synthesized DNS response would need the query id and
question echoed per frame, which the batched/columnar tiers cannot do
from a fixed template; the reference dnsproxy's REFUSED synthesis is
future work and noted in README.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...regex import CompiledPattern, compile_pattern, py_search
from ...regex.parse import ParseError as RegexParseError
from ..accesslog import EntryType
from ..parser import parse_error, register_l7_rule_parser, register_parser_factory
from ..types import DROP, MORE, PASS

# Structural bounds shared with the device model (models/dns.py): a
# name outside them is INVALID — it matches nothing, on both rungs.
DNS_HEADER_LEN = 12  # id, flags, qd/an/ns/ar counts
DNS_PREFIX_LEN = 2  # the TCP length prefix
DNS_QNAME_OFF = DNS_PREFIX_LEN + DNS_HEADER_LEN  # first label-length byte
MAX_LABEL = 63  # RFC 1035 label bound; >=64 means pointer/garbage
MAX_LABELS = 40  # engine bound (device walk iterations); legal names
#                  rarely exceed ~10 labels — deeper ones deny typed

_RX_ESCAPE = set(".\\+*?()[]{}|^$")


def fold_name_bytes(raw: bytes) -> bytes:
    """0x20-fold ASCII A-Z only — BYTE-EXACT with the device model's
    fold (str.lower would also fold latin-1 0xC0-0xDE)."""
    return bytes(b + 0x20 if 0x41 <= b <= 0x5A else b for b in raw)


def pattern_to_regex(pattern: str) -> str:
    """Lower a ``matchPattern`` wildcard onto the shared regex dialect,
    anchored: leading ``*.`` -> one or more whole labels; other ``*`` ->
    zero or more non-dot bytes; literals escaped."""
    body = pattern
    head = ""
    if body.startswith("*."):
        head = "([^.]+[.])+"
        body = body[2:]
    out = []
    for ch in body:
        if ch == "*":
            out.append("[^.]*")
        elif ch in _RX_ESCAPE:
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "^" + head + "".join(out) + "$"


@dataclass
class DnsRequestData:
    name: str  # dotted, 0x20-folded, no trailing dot ("" = root)
    # False = the engine could not prove the question well-formed:
    # name-CONSTRAINED rules can never match, but byte-free
    # always-match rules still do — the invariance contract the
    # verdict cache's byte-free claim rests on (see
    # policy/invariance.reduce_dns_rows and the device twin's gate).
    valid: bool = True


class DnsRule:
    """One compiled name matcher.  At most one of (name, pattern,
    regex) is set; none set = always-match (the byte-free row the
    verdict cache's invariance claim keys on)."""

    def __init__(self, name: str = "", pattern: str = "", regex: str = ""):
        self.name = name.rstrip(".").lower()
        self.pattern = pattern.rstrip(".").lower()
        self.regex = regex
        rx = None
        if self.pattern:
            rx = pattern_to_regex(self.pattern)
        elif regex:
            rx = regex
        self.compiled: CompiledPattern | None = (
            compile_pattern(rx) if rx is not None else None
        )

    def device_pattern(self) -> str:
        """The regex this row contributes to the device automaton
        ("" for exact/always rows — their automaton slot is dead)."""
        if self.pattern:
            return pattern_to_regex(self.pattern)
        return self.regex

    def matches(self, data) -> bool:
        if not isinstance(data, DnsRequestData):
            return False
        if self.name:
            return data.valid and data.name == self.name
        if self.compiled is not None:
            return data.valid and py_search(
                self.compiled, data.name.encode("latin-1", "replace")
            )
        return True  # byte-free row: any complete frame


def dns_rule_parser(rule_config):
    """Compile ``l7_rules`` kv dicts ({matchName|matchPattern|
    matchRegex: value}; empty dict = always-match) into DnsRule rows."""
    rules = []
    for kv in rule_config.l7_rules or []:
        name, pattern, regex = "", "", ""
        for k, v in kv.items():
            if k == "matchName":
                name = v
            elif k == "matchPattern":
                pattern = v
            elif k == "matchRegex":
                regex = v
            else:
                parse_error(f"Unsupported key: {k}", rule_config)
        if sum(1 for v in (name, pattern, regex) if v) > 1:
            parse_error(
                "DNS rule takes at most one of matchName/matchPattern/"
                "matchRegex", rule_config,
            )
        try:
            rules.append(DnsRule(name, pattern, regex))
        except RegexParseError as e:
            parse_error(f"invalid DNS regex: {e}", rule_config)
    return rules


def encode_dns_query(name: str, qtype: int = 1, qid: int = 0,
                     qdcount: int = 1) -> bytes:
    """One prefixed DNS-over-TCP query frame for ``name`` (probe grids,
    benches and tests share this single encoder)."""
    labels = [l for l in name.encode("latin-1", "replace").split(b".") if l]
    qn = b"".join(bytes([len(l)]) + l for l in labels) + b"\x00"
    msg = (
        qid.to_bytes(2, "big") + b"\x01\x00"
        + qdcount.to_bytes(2, "big") + b"\x00" * 6
        + qn + qtype.to_bytes(2, "big") + b"\x00\x01"
    )
    return len(msg).to_bytes(2, "big") + msg


def frame_len(buf: bytes) -> int:
    """Total length (prefix included) of the first DNS-over-TCP frame
    in ``buf``, or -1 while the 2-byte prefix is incomplete."""
    if len(buf) < DNS_PREFIX_LEN:
        return -1
    return DNS_PREFIX_LEN + ((buf[0] << 8) | buf[1])


def parse_dns_query(frame: bytes) -> str | None:
    """First-question name of one COMPLETE prefixed frame (dotted,
    0x20-folded, no trailing dot), or None when the engine cannot
    prove the question well-formed.  Walk order and every structural
    bound here are mirrored by the device model's label scan — parity
    tests pin the two bit-identical."""
    if len(frame) < DNS_PREFIX_LEN + DNS_HEADER_LEN + 1 + 4:
        return None
    end = frame_len(frame)
    if end > len(frame):
        return None
    qdcount = (frame[6] << 8) | frame[7]
    if qdcount < 1:
        return None
    pos = DNS_PREFIX_LEN + DNS_HEADER_LEN
    labels: list[bytes] = []
    for _ in range(MAX_LABELS + 1):
        if pos >= end:
            return None
        lb = frame[pos]
        if lb == 0:
            if pos + 5 > end:  # QTYPE + QCLASS must fit
                return None
            return fold_name_bytes(b".".join(labels)).decode("latin-1")
        if lb > MAX_LABEL or len(labels) >= MAX_LABELS:
            return None  # compression pointer / oversized / too deep
        if pos + 1 + lb > end:
            return None
        labels.append(frame[pos + 1 : pos + 1 + lb])
        pos += 1 + lb
    return None


class DnsParser:
    """Streaming oracle: frame on the length prefix, judge the query
    name, PASS/DROP whole frames (replies always pass — response
    policy is out of scope, like the r2d2 reply direction)."""

    def __init__(self, connection):
        self.connection = connection

    def on_data(self, reply, end_stream, data):
        joined = b"".join(data)
        need = frame_len(joined)
        if need < 0 or len(joined) < need:
            return MORE, 1
        if reply:
            return PASS, need

        name = parse_dns_query(joined[:need])
        req = DnsRequestData(
            name=name if name is not None else "",
            valid=name is not None,
        )
        matches = self.connection.matches(req)
        self.connection.log(
            EntryType.Request if matches else EntryType.Denied,
            proto="dns",
            fields={"query": req.name if name is not None else "<invalid>"},
        )
        if not matches:
            return DROP, need  # no inject (see module docstring)
        return PASS, need


class DnsParserFactory:
    def create(self, connection):
        return DnsParser(connection)


register_parser_factory("dns", DnsParserFactory())
register_l7_rule_parser("dns", dns_rule_parser)
