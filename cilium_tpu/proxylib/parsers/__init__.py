"""Protocol parser registrations (import side effects).

Like the reference's per-parser ``init()`` functions
(reference: proxylib/r2d2/r2d2parser.go:133-137).
"""

from . import testparsers  # noqa: F401
from . import r2d2  # noqa: F401
from . import cassandra  # noqa: F401
from . import memcached  # noqa: F401
from . import http  # noqa: F401
from . import dns  # noqa: F401
