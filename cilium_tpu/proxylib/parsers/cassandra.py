"""Cassandra CQL parser — implemented in cilium_tpu.proxylib.parsers.cassandra (phase 4).

Reference: proxylib/cassandra/cassandraparser.go.
"""
