"""Cassandra CQL native-protocol (v3/v4) parser and L7 rules.

Reference: proxylib/cassandra/cassandraparser.go.  Frames are
9-byte-header binary messages; requests with a query-like opcode
(query/prepare/batch/execute) are matched on ``query_action`` (exact)
and ``query_table`` (regex, search semantics) extracted from the CQL
text; other opcodes always pass the L7 rules.  Prepared statements are
tracked: PREPARE stashes the parsed path by stream-id, the server's
RESULT(prepared) reply binds it to the prepared-id, and EXECUTE/batch
entries look the path up by prepared-id — an unknown id injects an
``unprepared`` error so the client re-prepares
(reference: cassandraparser.go:586-601).

Deliberate divergences from the reference, after analysis:
- Batch frames: the reference reads the query count as a 16-bit int
  from a 1-byte slice and walks entries from offset 11
  (cassandraparser.go:519-522), which can never execute without a
  runtime panic; this parser follows the protocol spec — count is a
  big-endian u16 at bytes 10..12, entries start at offset 12.
- The per-frame inject of the unprepared error and its prepared-id
  trailer are emitted as one buffer write instead of two consecutive
  Inject calls (byte stream identical).

The ``query_table`` regex compiles through ``cilium_tpu.regex`` — the
same NFA the device model evaluates — so the streaming oracle and the
TPU path share one compiled semantics.
"""

from __future__ import annotations

import struct

from ...regex import CompiledPattern, compile_pattern, py_search
from ...regex.parse import ParseError as RegexParseError
from ..accesslog import EntryType
from ..parser import parse_error, register_l7_rule_parser, register_parser_factory
from ..types import DROP, ERROR, MORE, PASS, OpError

CASS_HDR_LEN = 9
CASS_MAX_LEN = 268435456  # 256 MB, per spec

OPCODE_MAP = {
    0x00: "error",
    0x01: "startup",
    0x02: "ready",
    0x03: "authenticate",
    0x05: "options",
    0x06: "supported",
    0x07: "query",
    0x08: "result",
    0x09: "prepare",
    0x0A: "execute",
    0x0B: "register",
    0x0C: "event",
    0x0D: "batch",
    0x0E: "auth_challenge",
    0x0F: "auth_response",
    0x10: "auth_success",
}

# query_action validity (reference: cassandraparser.go:315-366)
INVALID_ACTION = 0
ACTION_WITH_TABLE = 1
ACTION_NO_TABLE = 2

QUERY_ACTION_MAP = {
    "select": ACTION_WITH_TABLE,
    "delete": ACTION_WITH_TABLE,
    "insert": ACTION_WITH_TABLE,
    "update": ACTION_WITH_TABLE,
    "create-table": ACTION_WITH_TABLE,
    "drop-table": ACTION_WITH_TABLE,
    "alter-table": ACTION_WITH_TABLE,
    "truncate-table": ACTION_WITH_TABLE,
    "use": ACTION_WITH_TABLE,
    "create-keyspace": ACTION_WITH_TABLE,
    "alter-keyspace": ACTION_WITH_TABLE,
    "drop-keyspace": ACTION_WITH_TABLE,
    "drop-index": ACTION_NO_TABLE,
    "create-index": ACTION_NO_TABLE,
    "create-materialized-view": ACTION_NO_TABLE,
    "drop-materialized-view": ACTION_NO_TABLE,
    "create-role": ACTION_NO_TABLE,
    "alter-role": ACTION_NO_TABLE,
    "drop-role": ACTION_NO_TABLE,
    "grant-role": ACTION_NO_TABLE,
    "revoke-role": ACTION_NO_TABLE,
    "list-roles": ACTION_NO_TABLE,
    "grant-permission": ACTION_NO_TABLE,
    "revoke-permission": ACTION_NO_TABLE,
    "list-permissions": ACTION_NO_TABLE,
    "create-user": ACTION_NO_TABLE,
    "alter-user": ACTION_NO_TABLE,
    "drop-user": ACTION_NO_TABLE,
    "list-users": ACTION_NO_TABLE,
    "create-function": ACTION_NO_TABLE,
    "drop-function": ACTION_NO_TABLE,
    "create-aggregate": ACTION_NO_TABLE,
    "drop-aggregate": ACTION_NO_TABLE,
    "create-type": ACTION_NO_TABLE,
    "alter-type": ACTION_NO_TABLE,
    "drop-type": ACTION_NO_TABLE,
    "create-trigger": ACTION_NO_TABLE,
    "drop-trigger": ACTION_NO_TABLE,
}

# Fixed "Request Unauthorized" error frame; version and stream-id are
# patched per request before injection (reference: cassandraparser.go:269).
UNAUTH_MSG_BASE = bytes(
    [
        0x0,  # version - patched
        0x0,  # flags
        0x0, 0x0,  # stream-id - patched
        0x0,  # opcode error
        0x0, 0x0, 0x0, 0x1A,  # body length
        0x0, 0x0, 0x21, 0x00,  # unauthorized error code 0x2100
        0x0, 0x14,  # error message length
    ]
) + b"Request Unauthorized"

# "Unprepared" error prefix; the prepared-id in [short bytes] form is
# appended per request (reference: cassandraparser.go:284).
UNPREPARED_MSG_BASE = bytes(
    [
        0x0,  # version - patched
        0x0,  # flags
        0x0, 0x0,  # stream-id - patched
        0x0,  # opcode error
        0x0, 0x0, 0x0, 0x1A,  # body length
        0x0, 0x0, 0x25, 0x00,  # unprepared error code 0x2500
    ]
)


class CassandraRule:
    """One allow-rule on (query_action, query_table)
    (reference: cassandraparser.go:50-95)."""

    def __init__(self, query_action_exact: str = "", table_regex: str = ""):
        self.query_action_exact = query_action_exact
        self.table_regex = table_regex
        self.table_compiled: CompiledPattern | None = (
            compile_pattern(table_regex) if table_regex else None
        )

    def matches(self, data) -> bool:
        if not isinstance(data, str):
            return False
        parts = data.split("/")
        if len(parts) <= 2:
            return True  # not a query-like request: allow
        if len(parts) < 4:
            return False  # malformed internal path
        if self.query_action_exact and self.query_action_exact != parts[2]:
            return False
        if (
            parts[3]
            and self.table_compiled is not None
            and not py_search(
                self.table_compiled,
                parts[3].encode("utf-8", "surrogateescape"),
            )
        ):
            return False
        return True


def cassandra_rule_parser(rule_config):
    """(reference: cassandraparser.go:99-134, incl. validation)."""
    rules = []
    for kv in rule_config.l7_rules or []:
        action, table = "", ""
        for k, v in kv.items():
            if k == "query_action":
                action = v
            elif k == "query_table":
                table = v
            else:
                parse_error(f"Unsupported key: {k}", rule_config)
        if action:
            res = QUERY_ACTION_MAP.get(action, INVALID_ACTION)
            if res == INVALID_ACTION:
                parse_error(
                    "Unable to parse L7 cassandra rule with invalid "
                    f"query_action: '{action}'",
                    rule_config,
                )
            elif res == ACTION_NO_TABLE and table:
                parse_error(
                    f"query_action '{action}' is not compatible with a "
                    "query_table match",
                    rule_config,
                )
        try:
            rules.append(CassandraRule(action, table))
        except RegexParseError as e:
            parse_error(f"invalid query_table regex: {e}", rule_config)
    return rules


def parse_query(parser: "CassandraParser", query: str) -> tuple[str, str]:
    """CQL text -> (action, table); ('', '') when unparseable
    (reference: cassandraparser.go:368-469)."""
    query = query.rstrip(";")
    fields = query.lower().split()

    # Comment tokens make the table extraction unsafe: fail parsing
    # (reference: cassandraparser.go:383-392).
    for f in fields:
        if len(f) >= 2 and (f[:2] == "--" or f[:2] == "/*" or f[:2] == "//"):
            return "", ""
    if len(fields) < 2:
        return "", ""

    action = fields[0]
    table = ""
    if action in ("select", "delete"):
        for i in range(1, len(fields)):
            if fields[i] == "from" and i + 1 < len(fields):
                table = fields[i + 1].lower()
        if not table:
            return "", ""
    elif action == "insert":
        if len(fields) < 3:
            return "", ""
        table = fields[2].lower()
    elif action == "update":
        table = fields[1].lower()
    elif action == "use":
        parser.keyspace = fields[1].strip("\"\\'")
        table = parser.keyspace
    elif action in ("alter", "create", "drop", "truncate", "list"):
        action = f"{action}-{fields[1]}"
        if fields[1] in ("table", "keyspace"):
            if len(fields) < 3:
                return "", ""
            table = fields[2]
            if table == "if":
                if action == "create-table":
                    if len(fields) < 6:
                        return "", ""
                    table = fields[5]  # skip "IF NOT EXISTS"
                elif action in ("drop-table", "drop-keyspace"):
                    if len(fields) < 5:
                        return "", ""
                    table = fields[4]  # skip "IF EXISTS"
        # NOTE: bare "TRUNCATE <t>" yields action "truncate-<t>" with no
        # table — the reference's special case for it is unreachable
        # (action already rewritten; cassandraparser.go:424,447-450) and
        # that behavior is preserved here.
        if fields[1] == "materialized":
            action += "-view"
        elif fields[1] == "custom":
            action = "create-index"
    else:
        return "", ""

    if table and "." not in table and action != "use":
        table = f"{parser.keyspace}.{table}"
    return action, table


class CassandraParser:
    """(reference: cassandraparser.go:146-262)."""

    def __init__(self, connection):
        self.connection = connection
        self.keyspace = ""
        # PREPARE path stashed by stream-id until the server's
        # RESULT(prepared) reply binds it to the prepared-id.
        self.prepared_path_by_stream_id: dict[int, str] = {}
        self.prepared_path_by_prepared_id: dict[bytes, str] = {}

    def on_data(self, reply, end_stream, data):
        joined = b"".join(data)
        if len(joined) < CASS_HDR_LEN:
            return MORE, CASS_HDR_LEN - len(joined)
        request_len = struct.unpack_from(">I", joined, 5)[0]
        if request_len > CASS_MAX_LEN:
            return ERROR, int(OpError.ERROR_INVALID_FRAME_LENGTH)
        missing = CASS_HDR_LEN + request_len - len(joined)
        if missing > 0:
            return MORE, missing
        frame = joined[: CASS_HDR_LEN + request_len]

        if reply:
            self._parse_reply(frame)
            return PASS, len(frame)

        err, paths = self._parse_request(frame)
        if err:
            return ERROR, int(err)

        matches = True
        entry_type = EntryType.Request
        for path in paths:
            if not self.connection.matches(path):
                matches = False
                entry_type = EntryType.Denied

        for path in paths:
            parts = path.split("/")
            if len(parts) == 4:
                self.connection.log(
                    entry_type,
                    proto="cassandra",
                    fields={
                        "query_action": parts[2],
                        "query_table": parts[3],
                    },
                )

        if not matches:
            unauth = bytearray(UNAUTH_MSG_BASE)
            unauth[0] = 0x80 | (frame[0] & 0x07)
            unauth[2] = frame[2]
            unauth[3] = frame[3]
            self.connection.inject(True, bytes(unauth))
            return DROP, len(frame)
        return PASS, len(frame)

    # -- request/reply body parsing --------------------------------------

    def _send_unprepared(self, version: int, stream_id: bytes,
                         prepared_id_short_bytes: bytes) -> None:
        msg = bytearray(UNPREPARED_MSG_BASE)
        msg[0] = 0x80 | (version & 0x07)
        msg[2] = stream_id[0]
        msg[3] = stream_id[1]
        # Divergence: the reference leaves the body-length field at the
        # hardcoded 0x1A regardless of the appended prepared-id length
        # (cassandraparser.go:284-292), producing a malformed frame for
        # any id length other than 20; patch the real length.
        body_len = 4 + len(prepared_id_short_bytes)  # error code + id
        struct.pack_into(">I", msg, 5, body_len)
        self.connection.inject(True, bytes(msg) + prepared_id_short_bytes)

    def _parse_request(self, data: bytes):
        """Returns (OpError | 0, [path...]) (reference:
        cassandraparser.go:471-581)."""
        if data[0] & 0x80:
            return OpError.ERROR_INVALID_FRAME_TYPE, None
        if data[1] & 0x01:
            return OpError.ERROR_INVALID_FRAME_TYPE, None  # compressed

        opcode = data[4]
        path = OPCODE_MAP.get(opcode, "")
        if opcode in (0x07, 0x09):  # query | prepare
            (query_len,) = struct.unpack_from(">I", data, 9)
            query = data[13 : 13 + query_len].decode("utf-8", "surrogateescape")
            action, table = parse_query(self, query)
            if not action:
                return OpError.ERROR_INVALID_FRAME_TYPE, None
            path = f"/{path}/{action}/{table}"
            if opcode == 0x09:
                (stream_id,) = struct.unpack_from(">H", data, 2)
                self.prepared_path_by_stream_id[stream_id] = path.replace(
                    "prepare", "execute", 1
                )
            return 0, [path]
        if opcode == 0x0D:  # batch (spec-correct framing, see module doc)
            (num_queries,) = struct.unpack_from(">H", data, 10)
            paths = []
            offset = 12
            for _ in range(num_queries):
                kind = data[offset]
                if kind == 0:  # inline query string
                    (query_len,) = struct.unpack_from(">I", data, offset + 1)
                    query = data[offset + 5 : offset + 5 + query_len].decode(
                        "utf-8", "surrogateescape"
                    )
                    action, table = parse_query(self, query)
                    if not action:
                        return OpError.ERROR_INVALID_FRAME_TYPE, None
                    paths.append(f"/batch/{action}/{table}")
                    offset += 5 + query_len
                elif kind == 1:  # prepared query id
                    (id_len,) = struct.unpack_from(">H", data, offset + 1)
                    prepared_id = data[offset + 3 : offset + 3 + id_len]
                    cached = self.prepared_path_by_prepared_id.get(prepared_id)
                    if not cached:
                        self._send_unprepared(
                            data[0], data[2:4],
                            data[offset + 1 : offset + 3 + id_len],
                        )
                        return OpError.ERROR_INVALID_FRAME_TYPE, None
                    paths.append(cached)
                    offset += 3 + id_len
                else:
                    return OpError.ERROR_INVALID_FRAME_TYPE, None
            return 0, paths
        if opcode == 0x0A:  # execute
            (id_len,) = struct.unpack_from(">H", data, 9)
            prepared_id = data[11 : 11 + id_len]
            cached = self.prepared_path_by_prepared_id.get(prepared_id)
            if not cached:
                self._send_unprepared(data[0], data[2:4], data[9 : 11 + id_len])
                return OpError.ERROR_INVALID_FRAME_TYPE, None
            return 0, [cached]
        return 0, [f"/{path}"]

    def _parse_reply(self, data: bytes) -> None:
        """Associates RESULT(prepared) ids with stashed PREPARE paths
        (reference: cassandraparser.go:605-642)."""
        if not data[0] & 0x80:
            return
        if data[1] & 0x01:
            return  # compressed
        (stream_id,) = struct.unpack_from(">H", data, 2)
        if data[4] == 0x08:  # RESULT
            (result_kind,) = struct.unpack_from(">I", data, 9)
            if result_kind == 0x0004:  # prepared
                (id_len,) = struct.unpack_from(">H", data, 13)
                prepared_id = data[15 : 15 + id_len]
                path = self.prepared_path_by_stream_id.get(stream_id)
                if path:
                    self.prepared_path_by_prepared_id[prepared_id] = path


class CassandraParserFactory:
    def create(self, connection):
        return CassandraParser(connection)


register_parser_factory("cassandra", CassandraParserFactory())
register_l7_rule_parser("cassandra", cassandra_rule_parser)
