"""r2d2 line-protocol parser — the minimum end-to-end protocol family.

Reference: proxylib/r2d2/r2d2parser.go.  Protocol:
  "READ <filename>\\r\\n" | "WRITE <filename>\\r\\n" | "HALT\\r\\n" | "RESET\\r\\n"
Rules are key/value pairs {cmd: exact, file: regex}; the ``file`` regex uses
search semantics (Go regexp.MatchString, reference: r2d2parser.go:79).

The rule matcher compiles ``file`` through ``cilium_tpu.regex`` — the SAME
NFA the TPU batch pipeline (cilium_tpu.models.r2d2) evaluates — so the
streaming oracle and the device path share one compiled semantics.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...regex import CompiledPattern, compile_pattern, py_search
from ...regex.parse import ParseError as RegexParseError
from ..accesslog import EntryType
from ..parser import parse_error, register_l7_rule_parser, register_parser_factory
from ..types import DROP, ERROR, MORE, PASS, OpError

VALID_CMDS = ("READ", "WRITE", "HALT", "RESET")
FILE_CMDS = ("", "READ", "WRITE")


@dataclass
class R2d2RequestData:
    cmd: str
    file: str


class R2d2Rule:
    def __init__(self, cmd_exact: str = "", file_regex: str = ""):
        self.cmd_exact = cmd_exact
        self.file_regex = file_regex
        self.file_compiled: CompiledPattern | None = (
            compile_pattern(file_regex) if file_regex else None
        )

    def matches(self, data) -> bool:
        if not isinstance(data, R2d2RequestData):
            return False
        if self.cmd_exact and self.cmd_exact != data.cmd:
            return False
        if self.file_compiled is not None and not py_search(
            self.file_compiled, data.file.encode("utf-8", "surrogateescape")
        ):
            return False
        return True


def r2d2_rule_parser(rule_config):
    """(reference: r2d2parser.go:89-127, incl. validation)."""
    rules = []
    for kv in rule_config.l7_rules or []:
        cmd, file_ = "", ""
        for k, v in kv.items():
            if k == "cmd":
                cmd = v
            elif k == "file":
                file_ = v
            else:
                parse_error(f"Unsupported key: {k}", rule_config)
        if cmd and cmd not in VALID_CMDS:
            parse_error(
                f"Unable to parse L7 r2d2 rule with invalid cmd: '{cmd}'", rule_config
            )
        if file_ and cmd not in FILE_CMDS:
            parse_error(
                f"Unable to parse L7 r2d2 rule, cmd '{cmd}' is not compatible with 'file'",
                rule_config,
            )
        try:
            rules.append(R2d2Rule(cmd, file_))
        except RegexParseError as e:
            parse_error(f"invalid file regex: {e}", rule_config)
    return rules


class R2d2Parser:
    """(reference: r2d2parser.go:151-214)."""

    def __init__(self, connection):
        self.connection = connection

    def on_data(self, reply, end_stream, data):
        joined = b"".join(data)
        idx = joined.find(b"\r\n")
        if idx < 0:
            return MORE, 1
        msg = joined[:idx]
        msg_len = idx + 2

        if reply:
            return PASS, msg_len

        fields = msg.decode("utf-8", "surrogateescape").split(" ")
        if len(fields) == 0:
            return ERROR, int(OpError.ERROR_INVALID_FRAME_TYPE)
        file_ = fields[1] if len(fields) == 2 else ""
        req = R2d2RequestData(cmd=fields[0], file=file_)

        matches = self.connection.matches(req)
        self.connection.log(
            EntryType.Request if matches else EntryType.Denied,
            proto="r2d2",
            fields={"cmd": req.cmd, "file": req.file},
        )
        if not matches:
            self.connection.inject(True, b"ERROR\r\n")
            return DROP, msg_len
        return PASS, msg_len


class R2d2ParserFactory:
    def create(self, connection):
        return R2d2Parser(connection)


register_parser_factory("r2d2", R2d2ParserFactory())
register_l7_rule_parser("r2d2", r2d2_rule_parser)
