"""Filter op and result codes — numerically identical to the reference ABI.

Values mirror proxylib/proxylib/types.h so the native C++ datapath shim
(``native/``) shares the enum encoding with the reference's Envoy-side
consumer (reference: envoy/cilium_proxylib.cc:201-260 applies these ops).
"""

from __future__ import annotations

import enum


class OpType(enum.IntEnum):
    MORE = 0
    PASS = 1
    DROP = 2
    INJECT = 3
    ERROR = 4
    # Internal only, never exposed to the datapath caller
    # (reference: proxylib/proxylib/types.go:36)
    NOP = 256


MORE = OpType.MORE
PASS = OpType.PASS
DROP = OpType.DROP
INJECT = OpType.INJECT
ERROR = OpType.ERROR
NOP = OpType.NOP


class OpError(enum.IntEnum):
    ERROR_INVALID_OP_LENGTH = 1
    ERROR_INVALID_FRAME_TYPE = 2
    ERROR_INVALID_FRAME_LENGTH = 3


class FilterResult(enum.IntEnum):
    OK = 0
    POLICY_DROP = 1
    PARSER_ERROR = 2
    UNKNOWN_PARSER = 3
    UNKNOWN_CONNECTION = 4
    INVALID_ADDRESS = 5
    INVALID_INSTANCE = 6
    UNKNOWN_ERROR = 7
    # Extensions beyond the reference ABI range — fail-closed overload /
    # fault containment verdicts for the sidecar seam.  Any non-OK
    # result is treated as a connection error by the datapath consumer
    # (including the native shim, which needs no knowledge of the new
    # codes), so these stay fail-closed on old clients by construction.
    SHED = 8  # admission queue over capacity / entry deadline passed
    SERVICE_UNAVAILABLE = 9  # verdict service unreachable (client-side)
    RESTARTING = 10  # sidecar restart window: queued-then-shed, typed
    FENCED = 11  # fenced zombie predecessor rejected a late write
