"""Compiled policy-match cascade.

Bit-for-bit the reference's verdict semantics
(reference: proxylib/proxylib/policymap.go):

- rule level (:91-111): remote id must be in the allowed set if non-empty;
  any L7 rule matching allows; an empty L7 rule list allows any payload.
- rules level (:150-171): no L7 rules at all -> allow (BPF verdict final);
  empty rule list -> allow; otherwise first matching rule allows.
- port level (:208-236): exact port, then wildcard port 0; a port with a
  policy that matches nothing -> drop; NO policy for the port -> drop.
- unknown L7 parser (:128-133): drop-all for that port.
- UDP port policies are ignored (:182-184); non-TCP otherwise rejected.
- duplicate port numbers rejected (:188-190); mismatched L7 types on one
  port rejected (:138-144).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from .npds import TCP, UDP, NetworkPolicy, PortNetworkPolicy, PortNetworkPolicyRule
from .parser import get_l7_rule_parser, parse_error


@dataclass
class CompiledRule:
    allowed_remotes: frozenset[int]
    l7_matchers: list[Any]  # objects with .matches(l7_data) -> bool

    def matches(self, remote_id: int, l7_data) -> bool:
        if self.allowed_remotes and remote_id not in self.allowed_remotes:
            return False
        if self.l7_matchers:
            return any(m.matches(l7_data) for m in self.l7_matchers)
        return True  # empty set matches any payload


@dataclass
class CompiledPortRules:
    rules: list[CompiledRule] = field(default_factory=list)
    have_l7_rules: bool = False

    def matches(self, remote_id: int, l7_data) -> bool:
        if not self.have_l7_rules:
            # No L7 rules: the datapath's L3/L4 verdict is final; emulate by
            # allowing (reference: policymap.go:151-158).
            return True
        if not self.rules:
            return True
        return any(r.matches(remote_id, l7_data) for r in self.rules)


def _compile_rule(config: PortNetworkPolicyRule) -> tuple[CompiledRule | None, bool]:
    """Returns (compiled, ok).  ok=False => unknown L7 parser: the whole
    port becomes drop-all (reference: policymap.go:128-133)."""
    rule = CompiledRule(
        allowed_remotes=frozenset(config.remote_policies), l7_matchers=[]
    )
    kind = config.l7_kind()
    if kind:
        parser = get_l7_rule_parser(kind)
        if parser is None:
            return rule, False
        rule.l7_matchers = parser(config)
    return rule, True


@dataclass
class CompiledPortPolicies:
    by_port: dict[int, CompiledPortRules] = field(default_factory=dict)

    def matches(self, port: int, remote_id: int, l7_data) -> bool:
        rules = self.by_port.get(port)
        if rules is not None and rules.matches(remote_id, l7_data):
            return True
        wc = self.by_port.get(0)
        if wc is not None and wc.matches(remote_id, l7_data):
            return True
        return False


def _compile_port_policies(configs: list[PortNetworkPolicy]) -> CompiledPortPolicies:
    out = CompiledPortPolicies()
    for pp in configs:
        if pp.protocol == UDP:
            continue  # ignored (reference: policymap.go:182-184)
        if pp.protocol != TCP:
            parse_error(f"Invalid transport protocol {pp.protocol}", pp)
        if pp.port in out.by_port:
            parse_error(f"Duplicate port number {pp.port}", configs)

        compiled = CompiledPortRules()
        ok = True
        first_kind = ""
        for rc in pp.rules:
            rule, rule_ok = _compile_rule(rc)
            if not rule_ok:
                # Unknown L7 parser: the port is SKIPPED, so lookups find no
                # policy and drop (reference: policymap.go:196-203 only
                # installs the port when rules compiled ok).
                ok = False
                break
            if rule.l7_matchers:
                compiled.have_l7_rules = True
            kind = rc.l7_kind()
            if kind:
                if not first_kind:
                    first_kind = kind
                elif kind != first_kind:
                    parse_error("Mismatching L7 types on the same port", configs)
            compiled.rules.append(rule)
        if ok:
            out.by_port[pp.port] = compiled
    return out


@dataclass
class PolicyInstance:
    config: NetworkPolicy
    ingress: CompiledPortPolicies
    egress: CompiledPortPolicies

    def matches(self, ingress: bool, port: int, remote_id: int, l7_data) -> bool:
        side = self.ingress if ingress else self.egress
        return side.matches(port, remote_id, l7_data)


PolicyMap = dict[str, PolicyInstance]


def compile_policy(config: NetworkPolicy) -> PolicyInstance:
    config.validate()
    return PolicyInstance(
        config=config,
        ingress=_compile_port_policies(config.ingress_per_port_policies),
        egress=_compile_port_policies(config.egress_per_port_policies),
    )


def build_policy_map(configs: list[NetworkPolicy]) -> PolicyMap:
    return {c.name: compile_policy(c) for c in configs}
