"""Compiled policy-match cascade.

Bit-for-bit the reference's verdict semantics
(reference: proxylib/proxylib/policymap.go):

- rule level (:91-111): remote id must be in the allowed set if non-empty;
  any L7 rule matching allows; an empty L7 rule list allows any payload.
- rules level (:150-171): no L7 rules at all -> allow (BPF verdict final);
  empty rule list -> allow; otherwise first matching rule allows.
- port level (:208-236): exact port, then wildcard port 0; a port with a
  policy that matches nothing -> drop; NO policy for the port -> drop.
- unknown L7 parser (:128-133): drop-all for that port.
- UDP port policies are ignored (:182-184); non-TCP otherwise rejected.
- duplicate port numbers rejected (:188-190); mismatched L7 types on one
  port rejected (:138-144).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from .npds import TCP, UDP, NetworkPolicy, PortNetworkPolicy, PortNetworkPolicyRule
from .parser import get_l7_rule_parser, parse_error


@dataclass
class CompiledRule:
    allowed_remotes: frozenset[int]
    l7_matchers: list[Any]  # objects with .matches(l7_data) -> bool

    def matches(self, remote_id: int, l7_data) -> bool:
        if self.allowed_remotes and remote_id not in self.allowed_remotes:
            return False
        if self.l7_matchers:
            return any(m.matches(l7_data) for m in self.l7_matchers)
        return True  # empty set matches any payload

    def n_rows(self) -> int:
        """Flattened (rule, matcher) device rows this rule contributes
        (a matcherless rule is one always-match row) — mirrors
        models/r2d2.collect_policy_rows and models/http's
        build_http_model_for_port flattening."""
        return max(len(self.l7_matchers), 1)

    def matches_with_rule(self, remote_id: int, l7_data) -> tuple[bool, int]:
        """(allow, row): ``row`` is the rule-local index of the FIRST
        matching matcher row, or -1 when nothing matches.  Walk order
        is declaration order — the same priority the device models'
        argmax reduction uses, so host and device attribute the same
        row bit-identically."""
        if self.allowed_remotes and remote_id not in self.allowed_remotes:
            return False, -1
        if not self.l7_matchers:
            return True, 0  # the single always-match row
        for j, m in enumerate(self.l7_matchers):
            if m.matches(l7_data):
                return True, j
        return False, -1


@dataclass
class CompiledPortRules:
    rules: list[CompiledRule] = field(default_factory=list)
    have_l7_rules: bool = False

    def matches(self, remote_id: int, l7_data) -> bool:
        if not self.have_l7_rules:
            # No L7 rules: the datapath's L3/L4 verdict is final; emulate by
            # allowing (reference: policymap.go:151-158).
            return True
        if not self.rules:
            return True
        return any(r.matches(remote_id, l7_data) for r in self.rules)

    def n_rows(self) -> int:
        return sum(r.n_rows() for r in self.rules)

    def matches_with_rule(
        self, remote_id: int, l7_data, base: int = 0
    ) -> tuple[bool, int]:
        """The attribution twin of matches(): (allow, rule_id) where
        ``rule_id`` indexes the flattened (rule, matcher) rows starting
        at ``base`` (the port cascade offsets the wildcard set past the
        exact-port rows), or -1 for L4-final/empty-set allows and for
        deny.  Bit-identical allow to matches() by construction: the
        same rule walk, the same matcher order."""
        if not self.have_l7_rules or not self.rules:
            return self.matches(remote_id, l7_data), -1
        row = base
        for r in self.rules:
            ok, j = r.matches_with_rule(remote_id, l7_data)
            if ok:
                return True, row + j
            row += r.n_rows()
        return False, -1


def _compile_rule(config: PortNetworkPolicyRule) -> tuple[CompiledRule | None, bool]:
    """Returns (compiled, ok).  ok=False => unknown L7 parser: the whole
    port becomes drop-all (reference: policymap.go:128-133)."""
    rule = CompiledRule(
        allowed_remotes=frozenset(config.remote_policies), l7_matchers=[]
    )
    kind = config.l7_kind()
    if kind:
        parser = get_l7_rule_parser(kind)
        if parser is None:
            return rule, False
        rule.l7_matchers = parser(config)
    return rule, True


@dataclass
class CompiledPortPolicies:
    by_port: dict[int, CompiledPortRules] = field(default_factory=dict)

    def matches(self, port: int, remote_id: int, l7_data) -> bool:
        rules = self.by_port.get(port)
        if rules is not None and rules.matches(remote_id, l7_data):
            return True
        wc = self.by_port.get(0)
        if wc is not None and wc.matches(remote_id, l7_data):
            return True
        return False

    def matches_at(
        self, port: int, remote_id: int, l7_data
    ) -> tuple[bool, int]:
        """(allow, rule_id) over the port cascade's flattened rows:
        exact-port rules first, wildcard-port rules offset past them —
        exactly the device builders' row order (collect_policy_rows /
        build_http_model_for_port iterate ``(port, 0)``), so the id
        here and the device argmax name the same row.  Degenerate
        allows (L4-final / empty rule list) attribute -1; the device is
        never consulted there (ConstVerdict)."""
        rules = self.by_port.get(port)
        base = 0
        if rules is not None:
            ok, row = rules.matches_with_rule(remote_id, l7_data, 0)
            if ok:
                return True, row
            base = rules.n_rows()
        wc = self.by_port.get(0)
        if wc is not None and wc is not rules:
            ok, row = wc.matches_with_rule(remote_id, l7_data, base)
            if ok:
                return True, row
        return False, -1


def _compile_port_policies(configs: list[PortNetworkPolicy]) -> CompiledPortPolicies:
    out = CompiledPortPolicies()
    for pp in configs:
        if pp.protocol == UDP:
            continue  # ignored (reference: policymap.go:182-184)
        if pp.protocol != TCP:
            parse_error(f"Invalid transport protocol {pp.protocol}", pp)
        if pp.port in out.by_port:
            parse_error(f"Duplicate port number {pp.port}", configs)

        compiled = CompiledPortRules()
        ok = True
        first_kind = ""
        for rc in pp.rules:
            rule, rule_ok = _compile_rule(rc)
            if not rule_ok:
                # Unknown L7 parser: the port is SKIPPED, so lookups find no
                # policy and drop (reference: policymap.go:196-203 only
                # installs the port when rules compiled ok).
                ok = False
                break
            if rule.l7_matchers:
                compiled.have_l7_rules = True
            kind = rc.l7_kind()
            if kind:
                if not first_kind:
                    first_kind = kind
                elif kind != first_kind:
                    parse_error("Mismatching L7 types on the same port", configs)
            compiled.rules.append(rule)
        if ok:
            out.by_port[pp.port] = compiled
    return out


@dataclass
class PolicyInstance:
    config: NetworkPolicy
    ingress: CompiledPortPolicies
    egress: CompiledPortPolicies

    def matches(self, ingress: bool, port: int, remote_id: int, l7_data) -> bool:
        side = self.ingress if ingress else self.egress
        return side.matches(port, remote_id, l7_data)

    def matches_at(
        self, ingress: bool, port: int, remote_id: int, l7_data
    ) -> tuple[bool, int]:
        """matches() plus the deciding flattened rule row (-1 when
        denied or decided without an L7 rule walk) — the host oracle
        half of rule attribution; the device half is the models'
        ``verdicts_attr`` argmax over the same row order."""
        side = self.ingress if ingress else self.egress
        return side.matches_at(port, remote_id, l7_data)


PolicyMap = dict[str, PolicyInstance]


def compile_policy(config: NetworkPolicy) -> PolicyInstance:
    config.validate()
    return PolicyInstance(
        config=config,
        ingress=_compile_port_policies(config.ingress_per_port_policies),
        egress=_compile_port_policies(config.egress_per_port_policies),
    )


def build_policy_map(configs: list[NetworkPolicy]) -> PolicyMap:
    return {c.name: compile_policy(c) for c in configs}
