"""Network-policy wire model (the NPDS ``cilium.NetworkPolicy`` analog).

The reference distributes per-endpoint L7 policy as protobuf over a gRPC
xDS channel (reference: pkg/envoy/cilium/npds.pb.go, pushed by
pkg/envoy/server.go:628).  This framework's equivalent wire model is a plain
dataclass tree (serialized as JSON/dict over the control channel); the
fields mirror the proto so the policy compiler and test policies translate
one-to-one.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

TCP = "TCP"
UDP = "UDP"


@dataclass
class PortNetworkPolicyRule:
    """One allow-rule on a port.

    remote_policies: allowed remote identities (empty = any remote)
    l7_proto:        which registered L7 rule parser interprets l7_rules
                     (reference: policymap.go:70-76 — falls back to the
                     rule-kind name, here 'http'/'kafka' when those typed
                     rule lists are used)
    l7_rules:        generic key/value rules (r2d2, cassandra, memcached)
    http_rules:      typed HTTP rules (dicts with path/method/host/headers)
    kafka_rules:     typed Kafka rules (dicts with apikey/topic/clientid...)
    """

    remote_policies: list[int] = field(default_factory=list)
    l7_proto: str = ""
    l7_rules: list[dict[str, str]] | None = None
    http_rules: list[dict[str, Any]] | None = None
    kafka_rules: list[dict[str, Any]] | None = None

    def l7_kind(self) -> str:
        """The effective L7 parser name (proto 'oneof' name fallback)."""
        if self.l7_proto:
            return self.l7_proto
        if self.http_rules is not None:
            return "http"
        if self.kafka_rules is not None:
            return "kafka"
        return ""

    def has_l7(self) -> bool:
        return (
            self.l7_kind() != ""
            or self.l7_rules is not None
            or self.http_rules is not None
            or self.kafka_rules is not None
        )


@dataclass
class PortNetworkPolicy:
    port: int = 0  # 0 = wildcard port
    protocol: str = TCP
    rules: list[PortNetworkPolicyRule] = field(default_factory=list)


@dataclass
class NetworkPolicy:
    name: str = ""  # endpoint policy name (IP in the reference)
    policy: int = 0  # endpoint identity
    ingress_per_port_policies: list[PortNetworkPolicy] = field(default_factory=list)
    egress_per_port_policies: list[PortNetworkPolicy] = field(default_factory=list)

    def validate(self) -> None:
        if not self.name:
            raise ValueError("NetworkPolicy requires a name")
        for pp in list(self.ingress_per_port_policies) + list(
            self.egress_per_port_policies
        ):
            if not (0 <= pp.port <= 65535):
                raise ValueError(f"invalid port {pp.port}")
            if pp.protocol not in (TCP, UDP):
                raise ValueError(f"invalid protocol {pp.protocol}")


def policy_from_dict(d: dict) -> NetworkPolicy:
    """Build a NetworkPolicy from a plain dict (the JSON wire form)."""

    def rule(rd: dict) -> PortNetworkPolicyRule:
        return PortNetworkPolicyRule(
            remote_policies=list(rd.get("remote_policies", [])),
            l7_proto=rd.get("l7_proto", ""),
            l7_rules=rd.get("l7_rules"),
            http_rules=rd.get("http_rules"),
            kafka_rules=rd.get("kafka_rules"),
        )

    def port_policy(pd: dict) -> PortNetworkPolicy:
        return PortNetworkPolicy(
            port=pd.get("port", 0),
            protocol=pd.get("protocol", TCP),
            rules=[rule(r) for r in pd.get("rules", [])],
        )

    return NetworkPolicy(
        name=d.get("name", ""),
        policy=d.get("policy", 0),
        ingress_per_port_policies=[
            port_policy(p) for p in d.get("ingress_per_port_policies", [])
        ],
        egress_per_port_policies=[
            port_policy(p) for p in d.get("egress_per_port_policies", [])
        ],
    )
