"""Access-log entries and loggers.

Reference: the proxylib access logger sends protobuf ``cilium.LogEntry``
over a unix socket to the agent (reference: proxylib/accesslog/client.go,
received by pkg/envoy/accesslog_server.go:90).  Here the canonical record is
a dataclass; ``MemoryAccessLogger`` is the in-process sink used by tests and
the oracle harness, and ``cilium_tpu.runtime.accesslog`` provides the
socket-backed sink that feeds the monitor stream.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Any


class EntryType(enum.IntEnum):
    Request = 0
    Response = 1
    Denied = 2


@dataclass
class LogEntry:
    timestamp: int = 0
    is_ingress: bool = False
    entry_type: EntryType = EntryType.Request
    policy_name: str = ""
    source_security_id: int = 0
    destination_security_id: int = 0
    source_address: str = ""
    destination_address: str = ""
    proto: str = ""
    fields: dict[str, Any] = field(default_factory=dict)


class MemoryAccessLogger:
    """In-memory logger with the AccessLogger interface
    (reference: proxylib/proxylib/instance.go:34-38)."""

    def __init__(self, path: str = ""):
        self._path = path
        self.entries: list[LogEntry] = []

    def log(self, entry: LogEntry) -> None:
        if not entry.timestamp:
            entry.timestamp = time.time_ns()
        self.entries.append(entry)

    def close(self) -> None:
        pass

    def path(self) -> str:
        return self._path

    def counts(self) -> tuple[int, int]:
        """(passes, drops) — drop = Denied entries, like the reference's
        checkAccessLogs (reference: proxylib/proxylib_test.go:119-139)."""
        drops = sum(1 for e in self.entries if e.entry_type == EntryType.Denied)
        return len(self.entries) - drops, drops

    def clear(self) -> None:
        self.entries.clear()
