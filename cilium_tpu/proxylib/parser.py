"""Parser and L7-rule-parser registries.

Reference: proxylib/proxylib/parserfactory.go (Parser/ParserFactory,
RegisterParserFactory) and proxylib/proxylib/policymap.go:35-51
(L7RuleParser, RegisterL7RuleParser, ParseError).
"""

from __future__ import annotations

from typing import Callable, Protocol, runtime_checkable

from .types import OpType


@runtime_checkable
class Parser(Protocol):
    """Per-connection streaming protocol parser.

    ``on_data(reply, end_stream, data)`` sees the currently buffered data
    for one direction (a list of byte chunks, always starting on a frame
    boundary) and returns one ``(op, n_bytes)`` decision:

      MORE n   — keep the data buffered; call again once >= n more bytes
      PASS n   — allow n bytes
      DROP n   — drop n bytes; called again with the remainder
      INJECT n — splice n bytes from the inject buffer into this direction
      NOP      — nothing to do (no more input expected)
      ERROR    — unparseable protocol; connection will be closed

    Reference: proxylib/proxylib/parserfactory.go:22-57.
    """

    def on_data(self, reply: bool, end_stream: bool, data: list[bytes]) -> tuple[OpType, int]:
        ...


class ParserFactory(Protocol):
    def create(self, connection) -> Parser | None:
        """Create a parser for a new connection; None rejects it (POLICY_DROP)."""
        ...


class PolicyParseError(Exception):
    """Raised while compiling a pushed policy; the whole policy update is
    rejected without touching the active policy map (reference:
    proxylib/proxylib/policymap.go:49-51, instance.go:168-176)."""


def parse_error(reason: str, config=None):
    raise PolicyParseError(f"NPDS: {reason} (config: {config!r})")


_parser_factories: dict[str, ParserFactory] = {}
# l7 rule parser: (rule_kv_list, full_rule_config) -> list of matcher objects
# with a .matches(l7_data) -> bool method.
_l7_rule_parsers: dict[str, Callable] = {}


def register_parser_factory(name: str, factory: ParserFactory) -> None:
    _parser_factories[name] = factory


def get_parser_factory(name: str) -> ParserFactory | None:
    return _parser_factories.get(name)


def register_l7_rule_parser(name: str, fn: Callable) -> None:
    _l7_rule_parsers[name] = fn


def get_l7_rule_parser(name: str):
    return _l7_rule_parsers.get(name)
