"""Per-connection OnData dispatch loop.

Reference: proxylib/proxylib/connection.go.  The loop semantics are the
op/byte-exact oracle every TPU batch pipeline is validated against:

- loop until the op list reaches capacity or the parser yields NOP/MORE
- a zero byte count from the parser is a parser error
- PASS/DROP advance the input chunk list; INJECT does not
- stop after INJECT if the inject buffer filled up
- parser exceptions produce a Denied access-log entry and PARSER_ERROR
  (reference: connection.go:119-135)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import logging

from ..utils import flowdebug
from .accesslog import EntryType, LogEntry
from .types import DROP, ERROR, INJECT, MORE, NOP, PASS, FilterResult, OpType

_flow_log = logging.getLogger("cilium_tpu.proxylib.flow")

# Default op-list capacity, matching the Envoy-side caller's array
# (reference: envoy/cilium_proxylib.cc:201 — max 16 ops per OnIO call).
FILTER_OPS_CAPACITY = 16


class InjectBuf:
    """Fixed-capacity inject buffer (the caller-owned C buffer analog,
    reference: connection.go:36-44,190-209)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.data = bytearray()

    def inject(self, data: bytes) -> int:
        n = min(len(data), self.capacity - len(self.data))
        self.data += data[:n]
        return n

    def is_full(self) -> bool:
        return len(self.data) >= self.capacity

    def take(self) -> bytes:
        out = bytes(self.data)
        self.data.clear()
        return out


def advance_input(chunks: list[bytes], nbytes: int) -> list[bytes]:
    """Skip ``nbytes`` over a chunk list (reference: connection.go:104-116)."""
    chunks = list(chunks)
    while nbytes > 0 and chunks:
        if nbytes < len(chunks[0]):
            chunks[0] = chunks[0][nbytes:]
            nbytes = 0
        else:
            nbytes -= len(chunks[0])
            chunks.pop(0)
    return chunks


@dataclass
class Connection:
    instance: Any  # Instance (duck-typed to avoid circular import)
    conn_id: int
    ingress: bool
    src_id: int
    dst_id: int
    src_addr: str
    dst_addr: str
    policy_name: str
    port: int
    parser_name: str = ""
    parser: Any = None
    orig_buf: InjectBuf = field(default_factory=lambda: InjectBuf(1024))
    reply_buf: InjectBuf = field(default_factory=lambda: InjectBuf(1024))
    # Rule attribution of the most recent policy decision on this
    # connection (flattened first-match row, -1 = denied/unattributed):
    # stamped by matches() below and by the device-assisted engines'
    # precomputed-verdict hook, read by the flow-record emission.
    last_rule_id: int = -1

    def on_data(
        self,
        reply: bool,
        end_stream: bool,
        data: list[bytes],
        ops: list[tuple[OpType, int]],
        ops_capacity: int = FILTER_OPS_CAPACITY,
    ) -> FilterResult:
        try:
            input_ = list(data)
            while len(ops) < ops_capacity:
                op, nbytes = self.parser.on_data(reply, end_stream, input_)
                if op == NOP:
                    break
                if nbytes == 0:
                    return FilterResult.PARSER_ERROR
                # Per-flow op tracing rides the flowdebug gate so the
                # hot loop pays one boolean when disabled (reference:
                # pkg/flowdebug consumers in pkg/proxy).
                flowdebug.log(
                    _flow_log, "conn %d %s %s op=%s n=%d",
                    self.conn_id, self.parser_name,
                    "reply" if reply else "orig", op.name, nbytes,
                )
                ops.append((op, nbytes))
                if op == MORE:
                    break
                if op in (PASS, DROP):
                    input_ = advance_input(input_, nbytes)
                    # loop back even with no data left: parser may inject
                    # frames at the end of the input
                if op == INJECT and self.inject_buf(reply).is_full():
                    break
            return FilterResult.OK
        except Exception as exc:  # parser "panic" recovery
            self.log(
                EntryType.Denied,
                proto=self.parser_name,
                fields={"status": f"Panic: {exc}"},
            )
            return FilterResult.PARSER_ERROR

    def matches(self, l7_data) -> bool:
        at = getattr(self.instance, "policy_matches_at", None)
        if at is not None:
            ok, rule = at(
                self.policy_name, self.ingress, self.port, self.src_id,
                l7_data,
            )
            self.last_rule_id = rule
            return ok
        return self.instance.policy_matches(
            self.policy_name, self.ingress, self.port, self.src_id, l7_data
        )

    def inject_buf(self, reply: bool) -> InjectBuf:
        return self.reply_buf if reply else self.orig_buf

    def inject(self, reply: bool, data: bytes) -> int:
        return self.inject_buf(reply).inject(data)

    def log(self, entry_type: EntryType, proto: str = "", fields: dict | None = None) -> None:
        self.instance.log(
            LogEntry(
                is_ingress=self.ingress,
                entry_type=entry_type,
                policy_name=self.policy_name,
                source_security_id=self.src_id,
                destination_security_id=self.dst_id,
                source_address=self.src_addr,
                destination_address=self.dst_addr,
                proto=proto or self.parser_name,
                fields=dict(fields or {}),
            )
        )
