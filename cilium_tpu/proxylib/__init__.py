"""Streaming L7 parser framework with the reference proxylib's contracts.

This is the host-side verdict oracle and streaming front-end of the
framework.  It reproduces, in Python, the exact observable behavior of the
reference's cgo shared library (reference: proxylib/proxylib.go,
proxylib/proxylib/{connection,policymap,instance,parserfactory}.go):

- the per-connection ``OnData`` loop emitting ``PASS/DROP/INJECT/MORE/NOP``
  ops with byte counts (reference: proxylib/proxylib/connection.go:118-174)
- the policy-match cascade PolicyMap -> PolicyInstance ->
  PortNetworkPolicies -> PortNetworkPolicyRules -> PortNetworkPolicyRule
  (reference: proxylib/proxylib/policymap.go)
- the module/instance lifecycle keyed on (node-id, xds-path,
  access-log-path) (reference: proxylib/proxylib/instance.go:85-116)

Protocol parsers registered here are *also* the host halves of the TPU batch
pipelines in ``cilium_tpu.models``: both consume the same compiled rule
artifacts, so batch verdicts can be checked bit-identical against this
in-process oracle (the strategy of the reference's own op/byte-exact test
harness, reference: proxylib/proxylib/test_util.go:95-120).
"""

from .types import (
    OpType,
    OpError,
    FilterResult,
    MORE,
    PASS,
    DROP,
    INJECT,
    ERROR,
    NOP,
)
from .parser import (
    Parser,
    ParserFactory,
    register_parser_factory,
    get_parser_factory,
    register_l7_rule_parser,
    get_l7_rule_parser,
    PolicyParseError,
    parse_error,
)
from .npds import (
    NetworkPolicy,
    PortNetworkPolicy,
    PortNetworkPolicyRule,
    TCP,
    UDP,
)
from .policy import PolicyInstance, PolicyMap, build_policy_map
from .connection import Connection, FILTER_OPS_CAPACITY
from .instance import (
    Instance,
    open_instance,
    find_instance,
    close_instance,
    open_module,
    close_module,
    reset_module_registry,
)
from .accesslog import LogEntry, EntryType, MemoryAccessLogger

# Parser registrations (import side effects, like the reference's init()).
from . import parsers as _parsers  # noqa: F401

__all__ = [
    "OpType", "OpError", "FilterResult",
    "MORE", "PASS", "DROP", "INJECT", "ERROR", "NOP",
    "Parser", "ParserFactory",
    "register_parser_factory", "get_parser_factory",
    "register_l7_rule_parser", "get_l7_rule_parser",
    "PolicyParseError", "parse_error",
    "NetworkPolicy", "PortNetworkPolicy", "PortNetworkPolicyRule", "TCP", "UDP",
    "PolicyInstance", "PolicyMap", "build_policy_map",
    "Connection", "FILTER_OPS_CAPACITY",
    "Instance", "open_instance", "find_instance", "close_instance",
    "open_module", "close_module", "reset_module_registry",
    "LogEntry", "EntryType", "MemoryAccessLogger",
]
