"""Instance/module lifecycle and connection registry.

Reference: proxylib/proxylib/instance.go (instance registry keyed on
node-id/xds-path/access-log-path, refcounted open/close, atomic policy-map
swap) and proxylib/proxylib.go:57-153 (the cgo module surface: OpenModule /
OnNewConnection / OnData / Close / CloseModule, with the global connection
map).  The same surface is exported to the native C++ shim via
``cilium_tpu.runtime.capi``.
"""

from __future__ import annotations

import threading
from typing import Callable

from .accesslog import MemoryAccessLogger
from .connection import FILTER_OPS_CAPACITY, Connection, InjectBuf
from .npds import NetworkPolicy
from .parser import PolicyParseError, get_parser_factory
from .policy import PolicyMap, compile_policy
from .types import FilterResult, OpType


class Instance:
    def __init__(self, instance_id: int, node_id: str, access_logger=None):
        self.id = instance_id
        self.open_count = 1
        self.node_id = node_id or f"host~127.0.0.1~libcilium-{instance_id}~localdomain"
        self.access_logger = access_logger
        self.policy_client = None
        self._policy_map: PolicyMap = {}
        self._lock = threading.Lock()

    # -- policy ----------------------------------------------------------
    def policy_matches(
        self, policy_name: str, ingress: bool, port: int, remote_id: int, l7_data
    ) -> bool:
        policy = self._policy_map.get(policy_name)
        return policy is not None and policy.matches(ingress, port, remote_id, l7_data)

    def policy_matches_at(
        self, policy_name: str, ingress: bool, port: int, remote_id: int, l7_data
    ) -> tuple[bool, int]:
        """policy_matches plus the deciding flattened rule row (-1 for
        deny/unattributed) — the attribution walk Connection.matches
        records onto ``last_rule_id`` for flow-record emission."""
        policy = self._policy_map.get(policy_name)
        if policy is None:
            return False, -1
        return policy.matches_at(ingress, port, remote_id, l7_data)

    def has_policy(self, policy_name: str) -> bool:
        return policy_name in self._policy_map

    def policy_map(self) -> PolicyMap:
        return self._policy_map

    def policy_prepare(self, configs: list[NetworkPolicy]) -> PolicyMap:
        """Compile a STAGED policy map without publishing it: the active
        map keeps serving while compilation runs, and a compile error
        leaves nothing half-applied (the staged map is simply dropped).
        Unchanged policies are re-used from the old map.  The sidecar's
        epoch swap builds device tables against the staged map and
        publishes both in one pointer flip (policy_commit)."""
        old = self._policy_map
        new: PolicyMap = {}
        for config in configs:
            existing = old.get(config.name)
            if existing is not None and existing.config == config:
                new[config.name] = existing
                continue
            new[config.name] = compile_policy(config)  # may raise
        return new

    def policy_commit(self, new: PolicyMap) -> None:
        """Publish a staged map (atomic plain store; maps are never
        mutated after construction)."""
        self._policy_map = new

    def policy_update(self, configs: list[NetworkPolicy]) -> None:
        """Atomically replace the policy map; an error while compiling any
        policy leaves the active map untouched (reference: instance.go:168-219)."""
        self.policy_commit(self.policy_prepare(configs))

    def log(self, entry) -> None:
        if self.access_logger is not None:
            self.access_logger.log(entry)


# --- module-level registries (the cgo export surface) -------------------

_mutex = threading.Lock()
_instances: dict[int, Instance] = {}
_next_instance_id = 0
_connections: dict[int, Connection] = {}


def open_instance(
    node_id: str,
    xds_path: str = "",
    access_log_path: str = "",
    new_access_logger: Callable = MemoryAccessLogger,
    new_policy_client: Callable | None = None,
) -> int:
    """Open (or ref) an instance with these parameters
    (reference: instance.go:85-116)."""
    global _next_instance_id
    with _mutex:
        for iid, old in _instances.items():
            old_xds = old.policy_client.path() if old.policy_client else ""
            old_log = old.access_logger.path() if old.access_logger else ""
            if (
                (node_id == "" or old.node_id == node_id)
                and xds_path == old_xds
                and access_log_path == old_log
            ):
                old.open_count += 1
                return iid
        _next_instance_id += 1
        ins = Instance(
            _next_instance_id, node_id, new_access_logger(access_log_path)
        )
        if new_policy_client is not None:
            ins.policy_client = new_policy_client(xds_path, ins.node_id, ins)
        _instances[_next_instance_id] = ins
        return _next_instance_id


def find_instance(instance_id: int) -> Instance | None:
    with _mutex:
        return _instances.get(instance_id)


def close_instance(instance_id: int) -> int:
    with _mutex:
        ins = _instances.get(instance_id)
        if ins is None:
            return 0
        ins.open_count -= 1
        if ins.open_count <= 0:
            if ins.policy_client is not None:
                ins.policy_client.close()
            if ins.access_logger is not None:
                ins.access_logger.close()
            del _instances[instance_id]
            return 0
        return ins.open_count


_KNOWN_MODULE_PARAMS = ("node-id", "xds-path", "access-log-path")


def open_module(params: list[tuple[str, str]], debug: bool = False) -> int:
    """The OpenModule surface (reference: proxylib/proxylib.go:124-153).
    Unknown params fail with 0."""
    kv = {}
    for k, v in params:
        if k not in _KNOWN_MODULE_PARAMS:
            return 0
        kv[k] = v
    return open_instance(
        kv.get("node-id", ""),
        xds_path=kv.get("xds-path", ""),
        access_log_path=kv.get("access-log-path", ""),
    )


def close_module(module_id: int) -> int:
    return close_instance(module_id)


def reset_module_registry() -> None:
    """Test hook: drop all instances/connections."""
    global _next_instance_id
    with _mutex:
        _instances.clear()
        _connections.clear()
        _next_instance_id = 0


# --- connection surface (reference: proxylib/proxylib.go:57-122) --------

def on_new_connection(
    instance_id: int,
    proto: str,
    connection_id: int,
    ingress: bool,
    src_id: int,
    dst_id: int,
    src_addr: str,
    dst_addr: str,
    policy_name: str,
    orig_buf_capacity: int = 1024,
    reply_buf_capacity: int = 1024,
) -> tuple[FilterResult, Connection | None]:
    ins = find_instance(instance_id)
    if ins is None:
        return FilterResult.INVALID_INSTANCE, None
    factory = get_parser_factory(proto)
    if factory is None:
        return FilterResult.UNKNOWN_PARSER, None
    port = _parse_port(dst_addr)
    if port is None:
        return FilterResult.INVALID_ADDRESS, None
    conn = Connection(
        instance=ins,
        conn_id=connection_id,
        ingress=ingress,
        src_id=src_id,
        dst_id=dst_id,
        src_addr=src_addr,
        dst_addr=dst_addr,
        policy_name=policy_name,
        port=port,
        parser_name=proto,
        orig_buf=InjectBuf(orig_buf_capacity),
        reply_buf=InjectBuf(reply_buf_capacity),
    )
    parser = factory.create(conn)
    if parser is None:
        return FilterResult.POLICY_DROP, None
    conn.parser = parser
    with _mutex:
        _connections[connection_id] = conn
    return FilterResult.OK, conn


def on_data(
    connection_id: int,
    reply: bool,
    end_stream: bool,
    data: list[bytes],
    ops: list[tuple[OpType, int]],
    ops_capacity: int = FILTER_OPS_CAPACITY,
) -> FilterResult:
    with _mutex:
        conn = _connections.get(connection_id)
    if conn is None:
        return FilterResult.UNKNOWN_CONNECTION
    return conn.on_data(reply, end_stream, data, ops, ops_capacity)


def close_connection(connection_id: int) -> int:
    with _mutex:
        _connections.pop(connection_id, None)
        return len(_connections)


def _parse_port(addr: str) -> int | None:
    """Destination port from 'a.b.c.d:port' / '[v6]:port'; 0 is reserved
    for wildcarding and invalid here (reference: connection.go:71-78)."""
    host, sep, port_s = addr.rpartition(":")
    if not sep or not host:
        return None
    try:
        port = int(port_s)
    except ValueError:
        return None
    if not (0 < port <= 65535):
        return None
    return port
