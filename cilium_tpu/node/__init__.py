"""Node registry: local node addressing + cluster node discovery.

reference: pkg/node — local node identity/CIDR config (node.go:40,
address.go) and discovery of remote nodes through a kvstore SharedStore
(``cilium/state/nodes/v1``), installing per-node state (the reference
installs routes; here the tunnel/ipcache state used by the datapath ops).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Optional

from ..kvstore import Backend, client as kvstore_client
from ..kvstore.store import SharedStore

NODES_PATH = "cilium/state/nodes/v1"


@dataclass
class Node:
    """reference: pkg/node/node.go Node."""

    name: str
    cluster: str = "default"
    ipv4_address: str = ""
    ipv6_address: str = ""
    ipv4_alloc_cidr: str = ""
    ipv6_alloc_cidr: str = ""
    ipv4_health_ip: str = ""

    def to_dict(self) -> dict:
        return {
            "Name": self.name,
            "Cluster": self.cluster,
            "IPv4Address": self.ipv4_address,
            "IPv6Address": self.ipv6_address,
            "IPv4AllocCIDR": self.ipv4_alloc_cidr,
            "IPv6AllocCIDR": self.ipv6_alloc_cidr,
            "IPv4HealthIP": self.ipv4_health_ip,
        }

    @staticmethod
    def from_dict(d: dict) -> "Node":
        return Node(
            name=d.get("Name", ""),
            cluster=d.get("Cluster", "default"),
            ipv4_address=d.get("IPv4Address", ""),
            ipv6_address=d.get("IPv6Address", ""),
            ipv4_alloc_cidr=d.get("IPv4AllocCIDR", ""),
            ipv6_alloc_cidr=d.get("IPv6AllocCIDR", ""),
            ipv4_health_ip=d.get("IPv4HealthIP", ""),
        )

    def fullname(self) -> str:
        return f"{self.cluster}/{self.name}"


class NodeDiscovery:
    """Publishes the local node and tracks remote nodes
    (reference: pkg/node manager + kvstore store)."""

    def __init__(
        self,
        local: Node,
        backend: Backend | None = None,
        on_node_update: Callable[[Node], None] | None = None,
        on_node_delete: Callable[[str], None] | None = None,
    ) -> None:
        self.local = local
        self.nodes: dict[str, Node] = {}
        self._mutex = threading.RLock()
        self._on_update = on_node_update
        self._on_delete = on_node_delete
        self.store = SharedStore(
            backend or kvstore_client(),
            NODES_PATH,
            node_name=local.fullname(),
            on_update=self._store_update,
            on_delete=self._store_delete,
        )
        self.store.update_local_key_sync(local.fullname(), local.to_dict())

    def _store_update(self, name: str, value: dict) -> None:
        node = Node.from_dict(value)
        with self._mutex:
            self.nodes[name] = node
        if self._on_update:
            self._on_update(node)

    def _store_delete(self, name: str) -> None:
        with self._mutex:
            self.nodes.pop(name, None)
        if self._on_delete:
            self._on_delete(name)

    def get_nodes(self) -> dict[str, Node]:
        with self._mutex:
            return dict(self.nodes)

    def update_local(self, **kwargs) -> None:
        for k, v in kwargs.items():
            setattr(self.local, k, v)
        self.store.update_local_key_sync(self.local.fullname(),
                                         self.local.to_dict())

    def close(self) -> None:
        self.store.close()
