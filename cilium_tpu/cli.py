"""cilium-tpu CLI — the operator interface.

reference: cilium/cmd (cobra command tree: status, policy, endpoint,
identity, bpf map dumps, monitor, prefilter, config, metrics).  Speaks the
REST API on the agent's unix socket; `monitor` attaches to the monitor
socket.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .api import ApiClient, ApiError
from .utils import defaults

VERSION = "0.1.0"


def _client(args) -> ApiClient:
    return ApiClient(args.socket)


def _print(obj, as_json: bool) -> None:
    if as_json or isinstance(obj, str):
        print(obj if isinstance(obj, str) else json.dumps(obj, indent=2))
    else:
        print(json.dumps(obj, indent=2))


def cmd_status(args):
    st = _client(args).get("/v1/status")
    if args.json:
        _print(st, True)
        return 0
    print(f"KVStore:        {st['kvstore']['state']}  "
          f"({st['kvstore']['status']})")
    print(f"Cilium:         {st['cilium']['state']}  "
          f"uptime {st['cilium']['uptime_s']}s")
    print(f"Cluster:        {st['cluster']} node {st['node']}")
    print(f"Policy:         revision {st['policy']['revision']}, "
          f"{st['policy']['rules']} rules")
    eps = st["endpoints"]
    states = " ".join(f"{k}={v}" for k, v in eps["by_state"].items())
    print(f"Endpoints:      {eps['total']} ({states})")
    print(f"Identities:     {st['identity']['allocated']}")
    print(f"IPCache:        {st['ipcache']['entries']} entries")
    print(f"Proxy:          {st['proxy']['redirects']} redirects on "
          f"{st['proxy']['port_range']}")
    if args.all_controllers:
        print("Controllers:")
        for c in st["controllers"]:
            mark = "OK " if not c["last_error"] else "ERR"
            print(f"  {mark} {c['name']} success={c['success']} "
                  f"failure={c['failure']} {c['last_error']}")
    return 0


def cmd_policy_get(args):
    _print(_client(args).get("/v1/policy"), args.json)
    return 0


def cmd_policy_import(args):
    text = (
        sys.stdin.read() if args.file == "-" else open(args.file).read()
    )
    out = _client(args).put("/v1/policy", text)
    print(f"Revision: {out['revision']}")
    return 0


def cmd_policy_delete(args):
    out = _client(args).delete("/v1/policy", args.labels)
    print(f"Revision: {out['revision']}, deleted {out['deleted']} rules")
    return 0


def cmd_policy_trace(args):
    route = f"/v1/policy/resolve?from={args.src}&to={args.dst}"
    if args.dport:
        route += f"&dport={args.dport}"
    out = _client(args).get(route)
    if args.verbose and out.get("trace"):
        print(out["trace"])
    print(f"Verdict: {out['verdict']}")
    return 0 if out["verdict"] == "allowed" else 1


def cmd_endpoint_list(args):
    eps = _client(args).get("/v1/endpoint")
    if args.json:
        _print(eps, True)
        return 0
    print(f"{'ID':<8}{'STATE':<24}{'IDENTITY':<10}{'IPV4':<16}LABELS")
    for ep in eps:
        print(f"{ep['id']:<8}{ep['state']:<24}{ep['identity']:<10}"
              f"{ep['ipv4']:<16}{','.join(ep['labels'])}")
    return 0


def cmd_endpoint_get(args):
    _print(_client(args).get(f"/v1/endpoint/{args.id}"), args.json)
    return 0


def cmd_endpoint_create(args):
    spec = {"ipv4": args.ipv4, "labels": args.label or []}
    out = _client(args).put(f"/v1/endpoint/{args.id}", spec)
    _print(out, args.json)
    return 0


def cmd_endpoint_delete(args):
    _client(args).delete(f"/v1/endpoint/{args.id}")
    print(f"Endpoint {args.id} deleted")
    return 0


def cmd_endpoint_regenerate(args):
    _client(args).post(f"/v1/endpoint/{args.id}/regenerate")
    print(f"Endpoint {args.id} regeneration queued")
    return 0


def cmd_identity_list(args):
    _print(_client(args).get("/v1/identity"), args.json)
    return 0


def cmd_identity_get(args):
    _print(_client(args).get(f"/v1/identity/{args.id}"), args.json)
    return 0


def cmd_ipcache(args):
    _print(_client(args).get("/v1/ipcache"), args.json)
    return 0


def cmd_map_list(args):
    for name in _client(args).get("/v1/map"):
        print(name)
    return 0


def cmd_map_get(args):
    _print(_client(args).get(f"/v1/map/{args.name}"), args.json)
    return 0


def cmd_prefilter_list(args):
    _print(_client(args).get("/v1/prefilter"), args.json)
    return 0


def cmd_prefilter_update(args):
    out = _client(args).patch(
        "/v1/prefilter", {"revision": args.revision, "cidrs": args.cidr}
    )
    print(f"Revision: {out['revision']}")
    return 0


def cmd_prefilter_delete(args):
    out = _client(args).delete(
        "/v1/prefilter", {"revision": args.revision, "cidrs": args.cidr}
    )
    print(f"Revision: {out['revision']}")
    return 0


def cmd_config(args):
    c = _client(args)
    if args.option:
        changes = {}
        for opt in args.option:
            k, _, v = opt.partition("=")
            changes[k] = v or "true"
        out = c.patch("/v1/config", {"options": changes})
        _print(out, args.json)
    else:
        _print(c.get("/v1/config"), args.json)
    return 0


def _filter_metrics(text: str, prefix: str) -> str:
    """Name-prefix filter over Prometheus text exposition: keeps the
    HELP/TYPE/sample lines of metrics whose name starts with ``prefix``
    (with or without the ``cilium_tpu_`` namespace), including their
    ``_bucket``/``_sum``/``_count`` series."""
    if not prefix:
        return text
    from .utils.metrics import NAMESPACE

    prefixes = (prefix, f"{NAMESPACE}_{prefix}")
    out = []
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            name = line.split(" ", 3)[2]
        else:
            name = line.split("{", 1)[0].split(" ", 1)[0]
        if name.startswith(prefixes):
            out.append(line)
    return "\n".join(out) + ("\n" if out else "")


def cmd_metrics(args):
    text = _client(args).get("/metrics")
    print(_filter_metrics(text, args.prefix), end="")
    return 0


def cmd_monitor(args):
    from .monitor import MonitorClient, format_event

    client = MonitorClient(args.monitor_socket, version=args.protocol)
    print("Listening for events...", file=sys.stderr)
    try:
        while True:
            ev = client.next_event(timeout=1.0)
            if ev is None:
                continue
            if args.json:
                print(json.dumps(ev.to_dict()))
            else:
                print(format_event(ev))
            sys.stdout.flush()
    except KeyboardInterrupt:
        return 0
    finally:
        client.close()


def cmd_health(args):
    """reference: cilium-health status."""
    _print(_client(args).get("/v1/health"), args.json)
    return 0


def cmd_bugtool(args):
    """reference: bugtool/cmd/root.go:159 — support bundle."""
    from .bugtool import collect

    manifest = collect(_client(args), args.output)
    failed = [k for k, v in manifest["sections"].items() if not v["ok"]]
    print(f"wrote {args.output} ({len(manifest['sections'])} sections"
          + (f", {len(failed)} failed: {failed}" if failed else "") + ")")
    return 1 if failed else 0


def cmd_version(args):
    print(f"cilium-tpu {VERSION}")
    return 0


def _parse_l3n4(spec: str) -> dict:
    """'ip:port' or '[v6]:port' -> address dict (reference: cilium
    service update --frontend)."""
    host, _, port = spec.rpartition(":")
    host = host.strip("[]")
    try:
        port_n = int(port)
    except ValueError:
        port_n = 0
    if not host or not port_n:
        raise SystemExit(f"invalid address {spec!r}; want IP:PORT")
    return {"ip": host, "port": port_n, "protocol": "TCP"}


def cmd_service_list(args):
    """reference: cilium service list (cilium/cmd/service_list.go)."""
    data = _client(args).get("/v1/service")
    if args.json:
        print(json.dumps(data, indent=2))
        return 0
    for svc in data:
        fe = svc["frontend-address"]
        bes = ", ".join(
            f"{b['ip']}:{b['port']}" for b in svc["backend-addresses"]
        ) or "-"
        print(f"{svc['id']} {fe['ip']}:{fe['port']}/{fe['protocol']} -> {bes}")
    return 0


def cmd_service_get(args):
    _print(_client(args).get(f"/v1/service/{args.id}"), args.json)
    return 0


def cmd_service_update(args):
    """reference: cilium service update --id --frontend --backends."""
    body = {
        "frontend-address": _parse_l3n4(args.frontend),
        "backend-addresses": [
            _parse_l3n4(b) for b in (args.backends or "").split(",") if b
        ],
    }
    out = _client(args).put(f"/v1/service/{args.id}", body)
    _print(out, args.json)
    return 0


def cmd_service_delete(args):
    _client(args).delete(f"/v1/service/{args.id}")
    print(f"service {args.id} deleted")
    return 0


def cmd_node_list(args):
    """reference: cilium node list — local node + kvstore-discovered
    peers."""
    data = _client(args).get("/v1/node")
    if args.json:
        print(json.dumps(data, indent=2))
        return 0
    local = data["local"]
    print(f"local: {local['Cluster']}/{local['Name']} "
          f"{local['IPv4Address'] or '-'}")
    for name, n in sorted(data["nodes"].items()):
        print(f"{name} {n['IPv4Address'] or '-'}")
    return 0


def _run_kvstore(args, fn) -> int:
    """Direct store connection + error handling (reference:
    cilium/cmd/kvstore.go — these commands bypass the agent and dial
    the store, so failures name the STORE, not the agent socket)."""
    from .kvstore.backend import KvstoreError
    from .kvstore.net import NetBackend

    try:
        b = NetBackend(args.address)
    except (OSError, ValueError) as e:
        print(f"Error: cannot reach kvstore at {args.address}: {e}",
              file=sys.stderr)
        return 1
    try:
        return fn(b)
    except KvstoreError as e:
        print(f"Error: kvstore at {args.address}: {e}", file=sys.stderr)
        return 1
    finally:
        b.close()


def cmd_kvstore_get(args):
    def go(b):
        if args.recursive:
            items = b.list_prefix(args.key)
            for k in sorted(items):
                print(f"{k} => {items[k].decode(errors='replace')}")
            return 0
        v = b.get(args.key)
        if v is None:
            print(f"key {args.key} not found", file=sys.stderr)
            return 1
        print(v.decode(errors="replace"))
        return 0

    return _run_kvstore(args, go)


def cmd_kvstore_set(args):
    def go(b):
        b.set(args.key, args.value.encode())
        return 0

    return _run_kvstore(args, go)


def cmd_kvstore_delete(args):
    def go(b):
        if args.recursive:
            b.delete_prefix(args.key)
        else:
            b.delete(args.key)
        return 0

    return _run_kvstore(args, go)


def cmd_kvstore_status(args):
    """Fencing/arbitration view of one store server: role, epoch,
    whether it has been fenced by a newer primary, and the failure
    counters on both ends (reference: cilium kvstore + the etcd
    cluster-health probes in `cilium status --all-health`)."""

    def go(b):
        info = b.server_info()
        if args.json:
            print(json.dumps(info, indent=2))
            return 0
        fenced = (
            f"FENCED by epoch {info['fenced_by']}" if info["fenced"]
            else "writable" if info["role"] == "primary"
            else "read-only (replicating)"
        )
        print(f"{info['address']}: role={info['role']} "
              f"epoch={info['epoch']} {fenced}")
        print(f"backend: {info['backend']}")
        if info["replicating"]:
            print("replication: streaming from primary")
        for side in ("server", "client"):
            counters = info[f"{side}_counters"]
            if counters:
                joined = " ".join(
                    f"{k}={v}" for k, v in sorted(counters.items())
                )
                print(f"{side} counters: {joined}")
        return 0

    return _run_kvstore(args, go)


def cmd_sidecar_status(args):
    """Verdict-service health: throughput counters plus the overload/
    fault-containment ladder (queue depth, shed counts, quarantine,
    host-fallback) — the L7 analog of `cilium kvstore status`."""
    from .sidecar import SidecarClient, SidecarUnavailable

    try:
        cl = SidecarClient(args.address, timeout=3.0)
    except OSError as e:
        print(f"Error: cannot reach verdict service at {args.address}: {e}",
              file=sys.stderr)
        return 1
    try:
        st = cl.status()
    except (SidecarUnavailable, TimeoutError) as e:
        print(f"Error: verdict service at {args.address}: {e}",
              file=sys.stderr)
        return 1
    finally:
        cl.close()
    if args.json:
        print(json.dumps(st, indent=2))
        return 0
    cont = st.get("containment", {})
    disp = st.get("dispatcher", {})
    health = (
        "QUARANTINED (host fallback active)" if cont.get("quarantined")
        else "Ok"
    )
    print(f"{args.address}: {health}")
    print(f"connections: {st['connections']}  engines: {st['engines']}  "
          f"dispatch={st['dispatch_mode']}")
    print(f"verdicts: {st['requests']} requests, {st['denied']} denied, "
          f"{st['vec_entries']} vectorized")
    print(f"queue: depth={disp.get('queue_depth', 0)} "
          f"oldest={disp.get('queue_oldest_ms', 0)}ms "
          f"shed_submits={disp.get('shed_submits', 0)} "
          f"stall_deposals={disp.get('stall_deposals', 0)}")
    print(f"containment: shed={cont.get('shed_entries', 0)} "
          f"errors={cont.get('error_entries', 0)} "
          f"crashes={cont.get('batch_crashes', 0)} "
          f"fallback={cont.get('fallback_entries', 0)} "
          f"stalls={cont.get('stalls', 0)} "
          f"quarantine_events={cont.get('quarantine_events', 0)}")
    rst = st.get("restart") or {}
    if rst:
        refused = " ".join(
            f"{k}={v}"
            for k, v in sorted((rst.get("handoff_refused") or {}).items())
        )
        age = rst.get("handoff_age_s")
        print(f"restart: generation={rst.get('generation', 1)}"
              + (" FENCED(zombie predecessor)" if rst.get("fenced") else "")
              + (f" handoff_age={age}s" if age is not None else "")
              + f" restores: sessions={rst.get('session_restores', 0)}"
              + f" conns={rst.get('conn_restores', 0)}"
              + f" grants={rst.get('grant_restores', 0)}"
              + f" residue={rst.get('residue_restores', 0)}"
              + f" warm_shapes={rst.get('warm_shapes', 0)}"
              + (f" fence_rejects={rst.get('fence_rejects', 0)}"
                 if rst.get("fence_rejects") else "")
              + (f" stale_segments_swept={rst.get('stale_segments_swept', 0)}"
                 if rst.get("stale_segments_swept") else "")
              + (f" refused: {refused}" if refused else ""))
    pol = st.get("policy") or {}
    if pol:
        fails = " ".join(
            f"{k}={v}"
            for k, v in sorted((pol.get("swap_failures") or {}).items())
        )
        print(f"policy: epoch={pol.get('epoch', 0)} "
              f"swaps={pol.get('swaps', 0)} "
              f"last_swap={pol.get('last_swap_ms', 0)}ms "
              f"pending_builds={pol.get('pending_builds', 0)}"
              + (f" failures: {fails}" if fails else ""))
    mesh = st.get("mesh") or {}
    if mesh:
        dem = " ".join(
            f"{k}={v}"
            for k, v in sorted((mesh.get("demotions") or {}).items())
        )
        rung = mesh.get("rung") or (
            "full" if mesh.get("active") else "fallback"
        )
        lost = mesh.get("lost_devices") or []
        rfails = " ".join(
            f"{k}={v}"
            for k, v in sorted(
                (mesh.get("reshape_failures") or {}).items()
            )
        )
        print(f"mesh: devices={mesh.get('devices', 0)} "
              f"(flows={mesh.get('flow_shards', 0)}, "
              f"rules={mesh.get('rule_shards', 0)}) "
              f"{'ACTIVE' if mesh.get('active') else 'DEMOTED'} "
              f"rung={rung}"
              + (f" serving={mesh.get('serving_devices')}"
                 f"/{mesh.get('devices', 0)} "
                 f"capacity={mesh.get('capacity_frac', 1.0):.2f}"
                 if rung != "full" else "")
              + (f" lost={','.join(str(x) for x in lost)}"
                 if lost else "")
              + (f" reason={mesh.get('demoted')}" if mesh.get("demoted")
                 else "")
              + (f" demotions: {dem}" if dem else "")
              + (f" reshapes={mesh.get('reshapes', 0)}"
                 if mesh.get("reshapes") else "")
              + (f" reshape_window={mesh.get('reshape_window_ms', 0):.0f}ms"
                 if mesh.get("reshape_window_ms") else "")
              + (f" reshape_failures: {rfails}" if rfails else "")
              + (f" repromotions={mesh.get('repromotions', 0)}"
                 if mesh.get("repromotions") else "")
              + (f" rebind_rebuilds={mesh.get('rebind_rebuilds', 0)}"
                 if mesh.get("rebind_rebuilds") else ""))
    fc = st.get("flow_cache") or {}
    if fc:
        print(f"flow_cache: armed={fc.get('armed', 0)}/"
              f"{fc.get('cap', 0)} "
              f"hits={fc.get('hits', 0)} "
              f"misses={fc.get('misses', 0)} "
              f"invalidations={fc.get('invalidations', 0)} "
              f"evictions={fc.get('evictions', 0)}")
    def _fmt_shed(row):
        return " ".join(
            f"{k}={v}"
            for k, v in sorted((row.get("shed") or {}).items())
        )

    sessions = st.get("sessions") or {}
    if sessions:
        print(f"sessions: {len(sessions.get('live', []))} live, "
              f"{len(sessions.get('dead', []))} recently dead "
              f"(fair_share={sessions.get('fair_share', 0)})")
        for row in sessions.get("live", []):
            shed = _fmt_shed(row)
            q = ""
            if row.get("state") == "quarantined":
                q = (f" QUARANTINED({row.get('quarantine_reason')}, "
                     f"{row.get('quarantine_remaining_s', 0)}s left)")
            print(
                f"  [{row.get('session')}] {row.get('identity')} "
                f"{row.get('state')}{q} "
                f"submitted={row.get('submitted', 0)} "
                f"answered={row.get('answered', 0)} "
                f"served={row.get('served', 0)} "
                f"q={row.get('q_weight', 0)}"
                + (f" shed: {shed}" if shed else "")
            )
        for row in sessions.get("dead", []):
            shed = _fmt_shed(row)
            print(
                f"  [{row.get('session')}] {row.get('identity')} "
                f"dead({row.get('death_reason', '?')}) "
                f"submitted={row.get('submitted', 0)} "
                f"answered={row.get('answered', 0)}"
                + (f" shed: {shed}" if shed else "")
            )
    tr = st.get("transport") or {}
    if tr:
        rejects = " ".join(
            f"{k}={v}" for k, v in sorted((tr.get("rejects") or {}).items())
        )
        print(f"transport: shm_entries={tr.get('shm_entries', 0)} "
              f"shm_reclaims={tr.get('shm_reclaims', 0)}"
              + (f" rejects: {rejects}" if rejects else ""))
        for sess in tr.get("sessions", []):
            mode = sess.get("mode", "socket")
            tag = (f"session={sess.get('session', '?')} "
                   f"{sess.get('identity', '')}")
            if mode != "shm" and not sess.get("fallbacks"):
                print(f"  [{tag}] mode={mode}")
                continue
            data = sess.get("data") or {}
            verdict = sess.get("verdict") or {}
            fb = " ".join(
                f"{k}={v}"
                for k, v in sorted((sess.get("fallbacks") or {}).items())
            )
            print(
                f"  [{tag}] mode={mode} gen={sess.get('generation')} "
                f"data={data.get('occupancy', 0)}/{data.get('slots', 0)} "
                f"verdict={verdict.get('occupancy', 0)}"
                f"/{verdict.get('slots', 0)} "
                f"doorbells={sess.get('doorbells', 0)} "
                f"(batch~{sess.get('doorbell_batch_mean', 0)}) "
                f"credits={sess.get('credits', 0)}"
                + (f" fallbacks: {fb}" if fb else "")
            )
    rs = st.get("reasm") or {}
    if rs:
        arena = rs.get("arena") or {}
        fb = " ".join(
            f"{k}={v}"
            for k, v in sorted((rs.get("fallbacks") or {}).items())
        )
        by_f = " ".join(
            f"{k}={v}"
            for k, v in sorted((rs.get("rounds_by_framing") or {}).items())
        )
        print(f"reasm: rounds={rs.get('rounds', 0)}"
              + (f" ({by_f})" if by_f else "") + " "
              f"entries={rs.get('entries', 0)} "
              f"frames={rs.get('frames', 0)} "
              f"overflows={rs.get('overflows', 0)} "
              f"arena={arena.get('live_bytes', 0)}B/"
              f"{arena.get('capacity', 0)}B "
              f"({arena.get('slots', 0)} conns, "
              f"{arena.get('compactions', 0)} compactions)"
              + (f" fallbacks: {fb}" if fb else ""))
    if cont.get("quarantined"):
        print(f"quarantine: {cont.get('reason', '')} "
              f"for {cont.get('quarantined_for_s', 0)}s "
              f"(probes: {cont.get('probes', 0)})")
    lat = st.get("latency") or {}
    if lat.get("rounds"):
        print(f"latency: {lat['rounds']} rounds, "
              f"{lat.get('spans_sampled', 0)} sampled spans, "
              f"{lat.get('slow_exemplars', 0)} slow exemplars "
              f"(threshold {lat.get('slow_threshold_ms', 0)}ms, "
              f"sample 1/{lat.get('sample_every', 0)})")
        for path, stages in sorted((lat.get("stages") or {}).items()):
            cells = " ".join(
                f"{stage}={rec['mean_us']:.0f}us"
                + (f"/p99<={rec['p99_us']:.0f}us"
                   if rec.get("p99_us") is not None else "")
                for stage, rec in stages.items()
            )
            print(f"  [{path}] {cells}")
    tl = st.get("timeline") or {}
    if tl:
        tiers = " ".join(
            f"{k}={v}" for k, v in sorted((tl.get("tiers") or {}).items())
        )
        last = tl.get("last_postmortem") or {}
        print(f"timeline: {tl.get('events', 0)}/{tl.get('ring', 0)} events "
              f"(seq {tl.get('seq', 0)}), "
              f"{tl.get('fail_closed_events', 0)} fail-closed, "
              f"{tl.get('postmortems', 0)} postmortem(s)"
              + (f" tiers: {tiers}" if tiers else ""))
        if last:
            print(f"  last postmortem: {last.get('trigger', '?')} "
                  f"seq={last.get('seq')} events={last.get('events')}"
                  + (f" -> {last['path']}" if last.get("path") else ""))
    led = st.get("ledger") or {}
    if led:
        causes = " ".join(
            f"{k}={v}" for k, v in sorted((led.get("by_cause") or {}).items())
        )
        print(f"ledger: {led.get('compiles', 0)} compile(s) "
              f"({led.get('compile_seconds', 0.0):.3f}s total), "
              f"{led.get('executables_resident', 0)} executable(s) "
              f"resident, {led.get('dispatch_path_compiles', 0)} on "
              f"dispatch path"
              + (f" causes: {causes}" if causes else ""))
        for trig, rec in sorted((led.get("formation") or {}).items()):
            print(f"  [{trig}] rounds={rec.get('rounds', 0)} "
                  f"occ={rec.get('occ_mean', 0.0):.2f} "
                  f"age_mean={rec.get('age_mean_s', 0.0) * 1e6:.0f}us "
                  f"age_max={rec.get('age_max_s', 0.0) * 1e6:.0f}us "
                  f"depth_max={rec.get('depth_max', 0)} "
                  f"bytes={rec.get('bytes', 0)}")
    return 0


def cmd_sidecar_trace(args):
    """Dump the verdict service's latency-trace ring: sampled per-entry
    spans plus every slow-verdict exemplar, with per-stage breakdowns
    (the forensic half of the always-on stage histograms)."""
    from .sidecar import SidecarClient, SidecarUnavailable

    try:
        cl = SidecarClient(args.address, timeout=3.0)
    except OSError as e:
        print(f"Error: cannot reach verdict service at {args.address}: {e}",
              file=sys.stderr)
        return 1
    try:
        out = cl.trace(n=args.n, kind=args.kind, session=args.session)
    except (SidecarUnavailable, TimeoutError) as e:
        print(f"Error: verdict service at {args.address}: {e}",
              file=sys.stderr)
        return 1
    finally:
        cl.close()
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    spans = out.get("spans", [])
    lat = out.get("latency", {})
    print(f"{args.address}: {len(spans)} span(s) "
          f"({lat.get('spans_sampled', 0)} sampled, "
          f"{lat.get('slow_exemplars', 0)} slow, "
          f"{lat.get('shed_spans', 0)} shed)")
    from .sidecar.trace import format_stages_us

    for s in spans:
        stages = format_stages_us(s.get("stages_us", {}))
        reason = f" reason={s['reason']}" if s.get("reason") else ""
        sess = f" session={s['session']}" if s.get("session") else ""
        print(f"  {s['kind']:<6} path={s['path']:<6} seq={s['seq']:<8} "
              f"conn={s['conn_id']:<6} n={s['entries']:<5} "
              f"e2e={s['e2e_us'] / 1e3:.3f}ms{sess}{reason} {stages}")
    return 0


_TIMELINE_ID_KEYS = ("reason", "session", "conn", "epoch", "device", "n")


def _format_timeline_event(ev: dict) -> str:
    """One human line per flight-recorder event: seq, wall clock,
    table, edge, and whatever correlation ids the transition site
    annotated (reason/session/conn/epoch/device)."""
    import time as _time

    ts = _time.strftime("%H:%M:%S", _time.localtime(ev.get("t", 0)))
    frm, to = (ev.get("edge") or ["?", "?"])[:2]
    ids = " ".join(
        f"{k}={ev[k]}" for k in _TIMELINE_ID_KEYS if ev.get(k) is not None
    )
    flag = " FAIL-CLOSED" if ev.get("fail_closed") else ""
    return (f"  {ev.get('seq', 0):<7} {ts} {ev.get('table', '?'):<12} "
            f"{frm}->{to}{flag}" + (f" {ids}" if ids else ""))


def cmd_sidecar_timeline(args):
    """Dump the verdict service's flight recorder: the declared-edge
    incident timeline, windowed occupancy samples, and postmortem
    bundle summaries from every fail-closed transition."""
    from .sidecar import SidecarClient, SidecarUnavailable

    try:
        cl = SidecarClient(args.address, timeout=3.0)
    except OSError as e:
        print(f"Error: cannot reach verdict service at {args.address}: {e}",
              file=sys.stderr)
        return 1
    try:
        out = cl.timeline(n=args.n, since=args.since, table=args.table)
    except (SidecarUnavailable, TimeoutError) as e:
        print(f"Error: verdict service at {args.address}: {e}",
              file=sys.stderr)
        return 1
    finally:
        cl.close()
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    events = out.get("events", [])
    tl = out.get("timeline", {})
    tiers = " ".join(
        f"{k}={v}" for k, v in sorted((tl.get("tiers") or {}).items())
    )
    print(f"{args.address}: {len(events)} event(s) of "
          f"{tl.get('events', 0)} ringed (seq {tl.get('seq', 0)}, "
          f"{tl.get('fail_closed_events', 0)} fail-closed)"
          + (f" tiers: {tiers}" if tiers else ""))
    for ev in events:
        print(_format_timeline_event(ev))
    occ = out.get("occupancy", [])
    if occ:
        recent = occ[-5:]
        cells = " ".join(
            f"[busy={b.get('busy', 0):.2f} occ={b.get('occupancy', 0):.2f} "
            f"q={b.get('queue_max', 0)}]" for b in recent
        )
        print(f"occupancy ({len(occ)} bucket(s), newest last): {cells}")
    for pm in out.get("postmortems", []):
        print(f"postmortem: {pm.get('trigger', '?')} seq={pm.get('seq')} "
              f"events={pm.get('events')}"
              + (f" reason={pm['reason']}" if pm.get("reason") else "")
              + (f" -> {pm['path']}" if pm.get("path") else ""))
    return 0


_LEDGER_ID_KEYS = ("rules", "mesh", "epoch", "kind", "on_dispatch_path")


def _format_ledger_event(ev: dict) -> str:
    """One human line per compile-ledger event: seq, wall clock, cause,
    engine family, compile seconds, and the shape/correlation ids the
    recording site attached."""
    import time as _time

    ts = _time.strftime("%H:%M:%S", _time.localtime(ev.get("t", 0)))
    ids = " ".join(
        f"{k}={ev[k]}" for k in _LEDGER_ID_KEYS if ev.get(k) not in (None,
                                                                     False)
    )
    shape = f" shape={ev['shape']}" if ev.get("shape") else ""
    return (f"  {ev.get('seq', 0):<7} {ts} {ev.get('cause', '?'):<16} "
            f"{ev.get('family', '?'):<18} {ev.get('seconds', 0.0):.3f}s"
            + (f" {ids}" if ids else "") + shape)


def cmd_sidecar_ledger(args):
    """Dump the verdict service's device-economics ledger: per-cause
    trace/compile events, per-trigger batch-formation provenance, and
    the resident-executable census."""
    from .sidecar import SidecarClient, SidecarUnavailable

    try:
        cl = SidecarClient(args.address, timeout=3.0)
    except OSError as e:
        print(f"Error: cannot reach verdict service at {args.address}: {e}",
              file=sys.stderr)
        return 1
    try:
        out = cl.ledger(n=args.n, since=args.since, cause=args.cause)
    except (SidecarUnavailable, TimeoutError) as e:
        print(f"Error: verdict service at {args.address}: {e}",
              file=sys.stderr)
        return 1
    finally:
        cl.close()
    if args.json:
        print(json.dumps(out, indent=2))
        return 0
    events = out.get("compiles", [])
    led = out.get("ledger", {})
    causes = " ".join(
        f"{k}={v}" for k, v in sorted((led.get("by_cause") or {}).items())
    )
    print(f"{args.address}: {len(events)} compile(s) of "
          f"{led.get('compiles', 0)} recorded (seq {led.get('seq', 0)}, "
          f"{led.get('executables_resident', 0)} resident, "
          f"{led.get('dispatch_path_compiles', 0)} on dispatch path)"
          + (f" causes: {causes}" if causes else ""))
    for ev in events:
        print(_format_ledger_event(ev))
    form = out.get("formation", {})
    for trig, rec in sorted(form.items()):
        print(f"formation [{trig}]: rounds={rec.get('rounds', 0)} "
              f"items={rec.get('items', 0)} "
              f"occ={rec.get('occ_mean', 0.0):.2f} "
              f"age_mean={rec.get('age_mean_s', 0.0) * 1e6:.0f}us "
              f"age_max={rec.get('age_max_s', 0.0) * 1e6:.0f}us "
              f"depth_max={rec.get('depth_max', 0)} "
              f"bytes={rec.get('bytes', 0)}")
    return 0


def _format_flow_record(rec: dict) -> str:
    """One human line per flow record: who -> whom, verdict, serving
    path, and the deciding rule (`rule=<row> (<match kind>)`)."""
    import time as _time

    ts = _time.strftime("%H:%M:%S", _time.localtime(rec.get("ts", 0)))
    arrow = "->" if rec.get("ingress", True) else "<-"
    src = rec.get("src_identity", "?")
    dst = rec.get("dst_identity", "?")
    where = (
        f"{rec.get('proto', '?')}:{rec.get('dport', '?')}"
        + (f" policy={rec['policy']}" if rec.get("policy") else "")
    )
    rule = rec.get("rule_id", -1)
    attr = (
        f" rule={rule} ({rec.get('match_kind') or '?'})"
        if rule >= 0 else ""
    )
    if rec.get("epoch") is not None:
        attr += f" epoch={rec['epoch']}"
    if rec.get("session"):
        attr += f" session={rec['session']}"
    reason = f" reason={rec['reason']}" if rec.get("reason") else ""
    return (
        f"{ts} [{rec.get('path', '?')}] {rec.get('verdict', '?').upper()}: "
        f"identity {src} {arrow} {dst} conn={rec.get('conn_id')} "
        f"{where}{attr}{reason}"
    )


def cmd_observe(args):
    """Per-flow verdict records from the verdict service's flow log:
    why did flow X get verdict Y, and which rule decided it — the
    `cilium observe` / Hubble analog over MSG_OBSERVE."""
    from .sidecar import SidecarClient, SidecarUnavailable

    try:
        cl = SidecarClient(args.address, timeout=3.0)
    except OSError as e:
        print(f"Error: cannot reach verdict service at {args.address}: {e}",
              file=sys.stderr)
        return 1
    filters = dict(
        verdict=args.verdict, path=args.path,
        rule=args.rule, conn=args.conn, epoch=args.epoch,
        session=args.session,
    )
    try:
        if not args.follow:
            out = cl.observe(n=args.last, **filters)
            records = out.get("records", [])
            if args.json:
                print(json.dumps(out, indent=2))
                return 0
            stats = out.get("stats", {})
            if stats.get("disabled"):
                print("flow observability is disabled "
                      "(flow_observe=False)", file=sys.stderr)
                return 1
            for rec in reversed(records):  # oldest first for reading
                print(_format_flow_record(rec))
            print(f"{len(records)} record(s) "
                  f"({stats.get('records_total', 0)} total, ring "
                  f"{stats.get('records', 0)}/{stats.get('capacity', 0)})")
            return 0
        # Follow mode: poll with the seq cursor; records stream in
        # ascending order, each printed exactly once.
        cursor = None
        try:
            while True:
                out = cl.observe(n=args.last, since=cursor, **filters)
                if cursor is None and out.get("stats", {}).get("disabled"):
                    print("flow observability is disabled "
                          "(flow_observe=False)", file=sys.stderr)
                    return 1
                if cursor is None:
                    # Start at the CURRENT tail: follow shows new
                    # records, not history (use a plain query for that).
                    cursor = out.get("stats", {}).get("next_seq", 0) - 1
                    continue
                for rec in out.get("records", []):
                    if args.json:
                        print(json.dumps(rec))
                    else:
                        print(_format_flow_record(rec))
                    cursor = max(cursor, rec["seq"])
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
    except (SidecarUnavailable, TimeoutError) as e:
        print(f"Error: verdict service at {args.address}: {e}",
              file=sys.stderr)
        return 1
    finally:
        cl.close()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cilium-tpu",
        description="CLI for the TPU-native cilium agent",
    )
    p.add_argument("--socket", default=defaults.SOCK_PATH,
                   help="agent API unix socket")
    p.add_argument("--json", action="store_true", help="JSON output")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("status", help="daemon status")
    s.add_argument("--all-controllers", action="store_true")
    s.set_defaults(fn=cmd_status)

    pol = sub.add_parser("policy", help="policy management").add_subparsers(
        dest="sub", required=True
    )
    x = pol.add_parser("get")
    x.set_defaults(fn=cmd_policy_get)
    x = pol.add_parser("import")
    x.add_argument("file", help="policy JSON file, or - for stdin")
    x.set_defaults(fn=cmd_policy_import)
    x = pol.add_parser("delete")
    x.add_argument("labels", nargs="+")
    x.set_defaults(fn=cmd_policy_delete)
    x = pol.add_parser("trace")
    x.add_argument("--src", required=True, help="comma-separated labels")
    x.add_argument("--dst", required=True)
    x.add_argument("--dport", default="")
    x.add_argument("-v", "--verbose", action="store_true")
    x.set_defaults(fn=cmd_policy_trace)

    ep = sub.add_parser("endpoint", help="endpoints").add_subparsers(
        dest="sub", required=True
    )
    x = ep.add_parser("list")
    x.set_defaults(fn=cmd_endpoint_list)
    x = ep.add_parser("get")
    x.add_argument("id", type=int)
    x.set_defaults(fn=cmd_endpoint_get)
    x = ep.add_parser("create")
    x.add_argument("id", type=int)
    x.add_argument("--ipv4", default="")
    x.add_argument("-l", "--label", action="append")
    x.set_defaults(fn=cmd_endpoint_create)
    x = ep.add_parser("delete")
    x.add_argument("id", type=int)
    x.set_defaults(fn=cmd_endpoint_delete)
    x = ep.add_parser("regenerate")
    x.add_argument("id", type=int)
    x.set_defaults(fn=cmd_endpoint_regenerate)

    ident = sub.add_parser("identity", help="identities").add_subparsers(
        dest="sub", required=True
    )
    x = ident.add_parser("list")
    x.set_defaults(fn=cmd_identity_list)
    x = ident.add_parser("get")
    x.add_argument("id", type=int)
    x.set_defaults(fn=cmd_identity_get)

    x = sub.add_parser("ipcache", help="IP to identity mappings")
    x.set_defaults(fn=cmd_ipcache)

    mp = sub.add_parser("map", help="datapath maps").add_subparsers(
        dest="sub", required=True
    )
    x = mp.add_parser("list")
    x.set_defaults(fn=cmd_map_list)
    x = mp.add_parser("get")
    x.add_argument("name")
    x.set_defaults(fn=cmd_map_get)

    pf = sub.add_parser("prefilter", help="CIDR prefilter").add_subparsers(
        dest="sub", required=True
    )
    x = pf.add_parser("list")
    x.set_defaults(fn=cmd_prefilter_list)
    x = pf.add_parser("update")
    x.add_argument("--revision", type=int, required=True)
    x.add_argument("--cidr", action="append", required=True)
    x.set_defaults(fn=cmd_prefilter_update)
    x = pf.add_parser("delete")
    x.add_argument("--revision", type=int, required=True)
    x.add_argument("--cidr", action="append", required=True)
    x.set_defaults(fn=cmd_prefilter_delete)

    x = sub.add_parser("config", help="get/set daemon options")
    x.add_argument("option", nargs="*", help="Option=value pairs")
    x.set_defaults(fn=cmd_config)

    x = sub.add_parser("metrics", help="Prometheus metrics")
    x.add_argument("prefix", nargs="?", default="",
                   help="only metrics whose name starts with this "
                        "prefix (namespace optional)")
    x.set_defaults(fn=cmd_metrics)

    x = sub.add_parser("monitor", help="live event stream")
    x.add_argument("--monitor-socket", default=defaults.MONITOR_SOCK_PATH)
    # Listener protocol generation (reference: monitor/listener1_0.go
    # vs listener1_2.go — both served simultaneously).
    x.add_argument("--protocol", choices=["1.0", "1.2"], default="1.2")
    x.set_defaults(fn=cmd_monitor)

    x = sub.add_parser("health", help="node connectivity status")
    x.set_defaults(fn=cmd_health)

    x = sub.add_parser("bugtool", help="collect a support bundle")
    x.add_argument("-o", "--output", default="cilium-tpu-bugtool.tar.gz")
    x.set_defaults(fn=cmd_bugtool)

    nd = sub.add_parser("node", help="cluster nodes").add_subparsers(
        dest="node_cmd", required=True
    )
    x = nd.add_parser("list")
    x.set_defaults(fn=cmd_node_list)

    # reference: cilium service list/get/update/delete
    # (cilium/cmd/service*.go)
    svc = sub.add_parser(
        "service", help="load-balancer services"
    ).add_subparsers(dest="svc_cmd", required=True)
    x = svc.add_parser("list")
    x.set_defaults(fn=cmd_service_list)
    x = svc.add_parser("get")
    x.add_argument("id", type=int)
    x.set_defaults(fn=cmd_service_get)
    x = svc.add_parser("update")
    x.add_argument("--id", type=int, required=True)
    x.add_argument("--frontend", required=True, help="VIP as IP:PORT")
    x.add_argument("--backends", default="",
                   help="comma-separated backend IP:PORT list")
    x.set_defaults(fn=cmd_service_update)
    x = svc.add_parser("delete")
    x.add_argument("id", type=int)
    x.set_defaults(fn=cmd_service_delete)

    kv = sub.add_parser(
        "kvstore", help="direct kvstore access (reference: cilium kvstore)"
    ).add_subparsers(dest="kv_cmd", required=True)
    for name, fn, val in (
        ("get", cmd_kvstore_get, False),
        ("set", cmd_kvstore_set, True),
        ("delete", cmd_kvstore_delete, False),
    ):
        x = kv.add_parser(name)
        x.add_argument("key")
        if val:
            x.add_argument("value")
        else:
            x.add_argument("--recursive", action="store_true")
        x.add_argument("--address", required=True,
                       help="kvstore server host:port")
        x.set_defaults(fn=fn)
    x = kv.add_parser(
        "status", help="store role/epoch/fencing state + counters"
    )
    x.add_argument("--address", required=True,
                   help="kvstore server host:port")
    x.add_argument("--json", action="store_true")
    x.set_defaults(fn=cmd_kvstore_status)

    sc = sub.add_parser(
        "sidecar", help="verdict-service status (overload/containment)"
    ).add_subparsers(dest="sc_cmd", required=True)
    x = sc.add_parser(
        "status",
        help="verdict counters + shed/quarantine/fallback ladder",
    )
    x.add_argument("--address", required=True,
                   help="verdict service unix socket path")
    x.add_argument("--json", action="store_true")
    x.set_defaults(fn=cmd_sidecar_status)
    x = sc.add_parser(
        "trace",
        help="latency-trace ring: sampled spans + slow-verdict "
             "exemplars with stage breakdowns",
    )
    x.add_argument("--address", required=True,
                   help="verdict service unix socket path")
    x.add_argument("-n", type=int, default=50, help="max spans")
    x.add_argument("--kind", choices=["sample", "slow", "shed"],
                   default=None, help="only spans of this kind")
    x.add_argument("--session", type=int, default=None,
                   help="only spans attributed to this fan-in session "
                        "id (see `cilium sidecar status` sessions)")
    x.add_argument("--json", action="store_true")
    x.set_defaults(fn=cmd_sidecar_trace)
    x = sc.add_parser(
        "timeline",
        help="flight-recorder ring: declared-edge incident timeline, "
             "occupancy buckets, and postmortem bundle summaries",
    )
    x.add_argument("--address", required=True,
                   help="verdict service unix socket path")
    x.add_argument("-n", type=int, default=100, help="max events")
    x.add_argument("--since", type=int, default=0,
                   help="only events with seq strictly greater "
                        "(incremental tail cursor)")
    x.add_argument("--table", default=None,
                   help="typestate table filter (session, device_guard, "
                        "mesh_device, mesh_ladder, flow_cache, "
                        "epoch_swap, mark, overload)")
    x.add_argument("--json", action="store_true")
    x.set_defaults(fn=cmd_sidecar_timeline)
    x = sc.add_parser(
        "ledger",
        help="device-economics ledger: per-cause compile events, "
             "batch-formation provenance, resident-executable census",
    )
    x.add_argument("--address", required=True,
                   help="verdict service unix socket path")
    x.add_argument("-n", type=int, default=100,
                   help="max compile events")
    x.add_argument("--since", type=int, default=0,
                   help="only events with seq strictly greater "
                        "(incremental tail cursor)")
    x.add_argument("--cause", default=None,
                   help="compile-cause filter (cold, prewarm, "
                        "churn-new-shape, churn-vocab, mesh-reshape, "
                        "repromotion, heal-rebind)")
    x.add_argument("--json", action="store_true")
    x.set_defaults(fn=cmd_sidecar_ledger)

    x = sub.add_parser(
        "observe",
        help="per-flow verdict records with rule attribution "
             "(verdict service flow log)",
    )
    x.add_argument("--address", required=True,
                   help="verdict service unix socket path")
    x.add_argument("--last", type=int, default=20,
                   help="max records per query")
    x.add_argument("--verdict",
                   choices=["Forwarded", "Denied", "Shed", "Error"],
                   default=None)
    x.add_argument("--path", default=None,
                   help="serving path filter (vec|oracle|host|shed|...)")
    x.add_argument("--rule", type=int, default=None,
                   help="deciding rule row filter")
    x.add_argument("--conn", type=int, default=None,
                   help="connection id filter")
    x.add_argument("--epoch", type=int, default=None,
                   help="policy-table epoch filter (the epoch the "
                        "verdict was decided against)")
    x.add_argument("--session", type=int, default=None,
                   help="fan-in session filter (the shim session the "
                        "conn registered through)")
    x.add_argument("--follow", "-f", action="store_true",
                   help="stream new records (poll with a seq cursor)")
    x.add_argument("--interval", type=float, default=0.5,
                   help="follow poll interval seconds")
    x.add_argument("--json", action="store_true")
    x.set_defaults(fn=cmd_observe)

    x = sub.add_parser("version")
    x.set_defaults(fn=cmd_version)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except (ConnectionRefusedError, FileNotFoundError):
        print(
            f"Error: cannot reach the agent on {args.socket} "
            "(is cilium-tpu-agent running?)",
            file=sys.stderr,
        )
        return 1


if __name__ == "__main__":
    sys.exit(main())
