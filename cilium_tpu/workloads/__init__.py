"""Container-runtime integration (reference: pkg/workloads)."""

from .runtime import (  # noqa: F401
    Workload,
    WorkloadRuntime,
    get_runtime,
    register_runtime,
    registered_runtimes,
)
from .watcher import EventType, WorkloadWatcher  # noqa: F401
