"""Workload runtime abstraction + module registry.

reference: pkg/workloads/{runtimes.go,client.go,docker.go,crio.go,
containerd.go} — each container runtime registers a named module
exposing the same client operations; the daemon picks one at bootstrap
(``--container-runtime``).  The operations the watcher needs:

- ``inspect(workload_id)``   — name, labels, IP for a container
  (reference: docker.go retrieveDockerLabels)
- ``list_workloads()``       — ids of currently-running containers
  (reference: watcher_state.go syncWithRuntime's source)
- ``is_running(workload_id)``
- ``status()``               — runtime connectivity for `status`
  (reference: docker.go Status)

Concrete runtimes talk to a local socket (docker/crio/containerd); the
module factories take the socket path via opts, and tests inject a fake
runtime the same way the reference wires ``newDockerClientMock``.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Callable

# Label source for labels learned from the container runtime
# (reference: pkg/labels LabelSourceContainer).
LABEL_SOURCE = "container"


@dataclass
class Workload:
    """What ``inspect`` returns for one container."""

    id: str
    name: str = ""
    labels: dict = field(default_factory=dict)  # raw runtime labels
    ipv4: str = ""
    running: bool = True

    def identity_labels(self) -> list[str]:
        """Runtime labels -> cilium label models (reference:
        docker.go retrieveDockerLabels filters through the label
        prefix config; everything rides the container: source)."""
        return [
            f"{LABEL_SOURCE}:{k}={v}" for k, v in sorted(self.labels.items())
        ]


class WorkloadRuntime(ABC):
    """reference: workloads.WorkloadRuntime interface."""

    name = "unknown"

    @abstractmethod
    def inspect(self, workload_id: str) -> Workload | None:
        ...

    @abstractmethod
    def list_workloads(self) -> list[str]:
        ...

    def is_running(self, workload_id: str) -> bool:
        w = self.inspect(workload_id)
        return w is not None and w.running

    def status(self) -> dict:
        try:
            n = len(self.list_workloads())
            return {"state": "ok", "msg": f"{self.name}: {n} workloads"}
        except Exception as e:  # noqa: BLE001 — runtime unreachable
            return {"state": "failure", "msg": f"{self.name}: {e}"}


_registry: dict[str, Callable[..., WorkloadRuntime]] = {}


def register_runtime(name: str, factory: Callable[..., WorkloadRuntime]) -> None:
    """reference: runtimes.go registerWorkload (modules self-register)."""
    _registry[name] = factory


def registered_runtimes() -> list[str]:
    return sorted(_registry)


def get_runtime(name: str, **opts) -> WorkloadRuntime:
    if name not in _registry:
        raise ValueError(
            f"unknown container runtime {name!r} (have {registered_runtimes()})"
        )
    return _registry[name](**opts)


class _SocketRuntime(WorkloadRuntime):
    """Shared shape of the real runtime clients: each talks a local
    socket protocol (docker HTTP, CRI gRPC).  The protocol drivers are
    per-module; in environments without the runtime socket the client
    reports failure status instead of raising at construction
    (reference: docker.go newDockerClient probes lazily too)."""

    def __init__(self, endpoint: str):
        self.endpoint = endpoint

    def inspect(self, workload_id: str) -> Workload | None:
        raise ConnectionError(
            f"{self.name} runtime socket {self.endpoint} not reachable"
        )

    def list_workloads(self) -> list[str]:
        raise ConnectionError(
            f"{self.name} runtime socket {self.endpoint} not reachable"
        )


class DockerRuntime(_SocketRuntime):
    """reference: docker.go (endpoint default unix:///var/run/docker.sock)."""

    name = "docker"

    def __init__(self, endpoint: str = "unix:///var/run/docker.sock"):
        super().__init__(endpoint)


class CrioRuntime(_SocketRuntime):
    """reference: crio.go (CRI gRPC over /var/run/crio/crio.sock)."""

    name = "crio"

    def __init__(self, endpoint: str = "unix:///var/run/crio/crio.sock"):
        super().__init__(endpoint)


class ContainerdRuntime(_SocketRuntime):
    """reference: containerd.go."""

    name = "containerd"

    def __init__(self, endpoint: str = "unix:///var/run/containerd/containerd.sock"):
        super().__init__(endpoint)


register_runtime("docker", DockerRuntime)
register_runtime("crio", CrioRuntime)
register_runtime("containerd", ContainerdRuntime)
