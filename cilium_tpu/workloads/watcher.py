"""Workload event watcher: runtime events -> endpoint label sync.

reference: pkg/workloads/watcher_state.go + docker.go processEvent/
handleCreateWorkload — container start/delete events are serialized
PER CONTAINER (one handler queue each, so a start/delete pair for one
container can never race, while different containers proceed in
parallel), correlated with the endpoint the CNI/plugin created, and the
runtime's labels become the endpoint's identity labels.  A periodic
sync lists running workloads and enqueues start events for any the
watcher has not seen (reference: watcher_state.go syncWithRuntime),
catching containers started while the listener was down.
"""

from __future__ import annotations

import enum
import logging
import queue
import threading
import time
from dataclasses import dataclass

from ..utils.controller import ControllerManager, ControllerParams
from .runtime import WorkloadRuntime

log = logging.getLogger(__name__)

# reference: docker.go EndpointCorrelationMaxRetries and the backoff
# sleep between correlation attempts.
CORRELATION_MAX_RETRIES = 3
CORRELATION_SLEEP = 0.05
PERIODIC_SYNC_INTERVAL = 30.0  # reference: defaults.go periodicSyncRate


class EventType(enum.Enum):
    START = "start"  # reference: watcher_state.go EventTypeStart
    DELETE = "delete"  # EventTypeDelete


@dataclass
class EventMessage:
    workload_id: str
    event_type: EventType


class WorkloadWatcher:
    """Drives daemon endpoint state from a WorkloadRuntime's events."""

    def __init__(
        self,
        daemon,
        runtime: WorkloadRuntime,
        max_retries: int = CORRELATION_MAX_RETRIES,
        sync_interval: float = PERIODIC_SYNC_INTERVAL,
        controllers: ControllerManager | None = None,
    ) -> None:
        self.daemon = daemon
        self.runtime = runtime
        self.max_retries = max_retries
        self.sync_interval = sync_interval
        self._mutex = threading.Lock()
        self._queues: dict[str, queue.Queue] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._controllers = controllers or ControllerManager()
        self._own_controllers = controllers is None
        self._started = False
        self.events_handled = 0

    # -- event intake ------------------------------------------------------

    def start(self) -> "WorkloadWatcher":
        """Begin periodic runtime sync (event feeds call enqueue)."""
        if not self._started:
            self._started = True
            self._controllers.update_controller(
                "workload-sync",
                ControllerParams(
                    do_func=self.sync_with_runtime,
                    run_interval=self.sync_interval,
                ),
            )
        return self

    def enqueue(self, workload_id: str, event_type: EventType) -> None:
        """Serialized per container (reference: enqueueByContainerID)."""
        with self._mutex:
            q = self._queues.get(workload_id)
            if q is None:
                q = queue.Queue(maxsize=256)
                self._queues[workload_id] = q
                t = threading.Thread(
                    target=self._handler, args=(workload_id, q),
                    name=f"workload-{workload_id[:12]}", daemon=True,
                )
                self._threads[workload_id] = t
                t.start()
        q.put(EventMessage(workload_id, event_type))

    def _handler(self, workload_id: str, q: queue.Queue) -> None:
        while True:
            msg = q.get()
            if msg is None:
                return
            try:
                self._process_event(msg)
            except Exception:  # noqa: BLE001 — one event must not kill
                log.exception("workload event failed: %s", msg)
            finally:
                self.events_handled += 1

    # -- event handling ----------------------------------------------------

    def _process_event(self, msg: EventMessage) -> None:
        if msg.event_type is EventType.START:
            self._handle_create(msg.workload_id)
        elif msg.event_type is EventType.DELETE:
            ep = self.daemon.endpoint_manager.lookup_container(
                msg.workload_id
            )
            if ep is not None:
                self.daemon.endpoint_delete(ep.id)

    def _handle_create(self, workload_id: str) -> None:
        """Correlate the endpoint and apply the runtime's labels
        (reference: docker.go handleCreateWorkload retry loop)."""
        for attempt in range(1, self.max_retries + 1):
            if attempt > 1:
                time.sleep(CORRELATION_SLEEP * attempt)
            w = self.runtime.inspect(workload_id)
            if w is None or not w.running:
                return  # died before correlation — nothing to label
            ep = self.daemon.endpoint_manager.lookup_container(workload_id)
            if ep is None and w.ipv4:
                ep = self.daemon.endpoint_manager.lookup_ipv4(w.ipv4)
            if ep is None:
                continue  # endpoint not created yet; retry
            self.daemon.endpoint_update_labels(ep.id, w.identity_labels())
            return
        log.warning(
            "no endpoint for workload %s after %d tries",
            workload_id[:12], self.max_retries,
        )

    # -- periodic sync -----------------------------------------------------

    def sync_with_runtime(self) -> None:
        """Enqueue START for running workloads without a handler yet
        (reference: watcher_state.go syncWithRuntime)."""
        try:
            ids = self.runtime.list_workloads()
        except Exception:  # noqa: BLE001 — runtime down; retry next tick
            log.debug("workload runtime unreachable during sync")
            return
        with self._mutex:
            unknown = [i for i in ids if i not in self._queues]
        for workload_id in unknown:
            self.enqueue(workload_id, EventType.START)

    def flush(self, timeout: float = 5.0) -> None:
        """Wait until all queued events are handled (tests)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._mutex:
                empty = all(q.empty() for q in self._queues.values())
            if empty:
                # Settle OUTSIDE the mutex: in-flight handlers need it
                # to drain, so sleeping while holding it stalled the
                # very completion this poll is waiting for (lint R2).
                time.sleep(0.02)
                with self._mutex:
                    if all(q.empty() for q in self._queues.values()):
                        return
            else:
                time.sleep(0.01)

    def close(self) -> None:
        if self._own_controllers:
            self._controllers.remove_all()
        else:
            self._controllers.remove_controller("workload-sync")
        with self._mutex:
            for q in self._queues.values():
                q.put(None)
