"""Services / load-balancer control plane.

reference: pkg/service/id_kvstore.go (cluster-wide service-ID
allocation over the kvstore), daemon/loadbalancer.go:34 addSVC2BPFMap
/ :56 SVCAdd / svcAdd / svcDelete (frontend+backends -> LB map
programming with RevNAT), pkg/loadbalancer/loadbalancer.go (L3n4Addr
and LBSVC models).

The ServiceManager is the daemon-side owner of the LbMap: every
frontend gets a cluster-wide numeric service ID from the kvstore (used
as the RevNAT index, as in the reference), backends land in the slave
slots, and the service model is queryable by ID for the REST/CLI
surface.  The k8s watcher drives it from Service+Endpoints objects;
the REST API drives it directly (PUT/GET/DELETE /v1/service).
"""

from __future__ import annotations

import ipaddress
import json
import threading
from dataclasses import dataclass, field

from ..kvstore.backend import Backend, EpochFencedError, KvstoreError

# reference: common/const.go FirstFreeServiceID = 1
FIRST_FREE_SERVICE_ID = 1
MAX_SERVICE_ID = 0xFFFF  # RevNAT indices are u16 in the BPF maps

SERVICE_ID_PATH = "cilium/state/services/v1"


class ServiceError(RuntimeError):
    pass


@dataclass(frozen=True)
class L3n4Addr:
    """Frontend/backend address (reference: pkg/loadbalancer L3n4Addr)."""

    ip: str
    port: int
    protocol: str = "TCP"

    @property
    def family(self) -> int:
        return ipaddress.ip_address(self.ip).version

    @property
    def ip_int(self) -> int:
        return int(ipaddress.ip_address(self.ip))

    def key(self) -> str:
        """Canonical identity string (the reference's SHA256Sum role:
        one ID per distinct frontend)."""
        return f"{ipaddress.ip_address(self.ip)}:{self.port}/{self.protocol.upper()}"

    def to_dict(self) -> dict:
        return {"ip": self.ip, "port": self.port, "protocol": self.protocol}

    @classmethod
    def from_dict(cls, d: dict) -> "L3n4Addr":
        try:
            ip = str(ipaddress.ip_address(d["ip"]))
            port = int(d["port"])
        except (KeyError, ValueError, TypeError) as e:
            raise ServiceError(f"invalid address {d!r}: {e}") from e
        if not 0 < port <= 0xFFFF:
            raise ServiceError(f"invalid port {port}")
        return cls(ip=ip, port=port, protocol=d.get("protocol", "TCP").upper())


class ServiceIDAllocator:
    """Cluster-wide service-ID allocation (reference:
    pkg/service/id_kvstore.go AcquireID/GetID/DeleteID).

    Layout: ``<base>/id/<n>`` -> frontend JSON, ``<base>/next`` -> the
    free-ID hint the reference keeps in its FreeID key.  All mutation
    happens under a kvstore lock so concurrent agents converge on one
    ID per frontend.
    """

    def __init__(self, backend: Backend, base_path: str = SERVICE_ID_PATH):
        self.backend = backend
        self.base = base_path.rstrip("/")

    def _id_key(self, id_: int) -> str:
        return f"{self.base}/id/{id_}"

    def _find_by_frontend(self, fe_key: str) -> tuple[int, dict] | None:
        for k, v in self.backend.list_prefix(f"{self.base}/id/").items():
            try:
                data = json.loads(v.decode())
                id_ = int(k.rsplit("/", 1)[1])
            except (ValueError, KeyError):
                continue
            if data.get("key") == fe_key:
                return id_, data
        return None

    def acquire_id(self, frontend: L3n4Addr, desired: int = 0) -> int:
        """Allocate (or reuse) the cluster-wide ID for a frontend
        (reference: id_kvstore.go acquireGlobalID).  With ``desired``
        nonzero, bind exactly that ID or fail — the SVCAdd contract
        (daemon/loadbalancer.go:56): a frontend already registered
        under a different ID, or an ID bound to a different frontend,
        is an error surfaced to the caller.

        Epoch-aware: an EPOCH_FENCED rejection mid-sequence means the
        store failed over.  Every fenced op was rejected before being
        applied, so the whole lock + find + CAS sequence re-runs
        cleanly against the new primary (which re-resolves the
        frontend->ID binding from ITS key space)."""
        try:
            return self._acquire_id(frontend, desired)
        except EpochFencedError:
            return self._acquire_id(frontend, desired)

    def _acquire_id(self, frontend: L3n4Addr, desired: int = 0) -> int:
        if desired and not 0 < desired <= MAX_SERVICE_ID:
            raise ServiceError(
                f"service ID {desired} outside [1, {MAX_SERVICE_ID}] "
                f"(RevNAT indices are u16)"
            )
        fe_key = frontend.key()
        lock = self.backend.lock_path(f"{self.base}/lock")
        try:
            existing = self._find_by_frontend(fe_key)
            if existing is not None:
                id_, _ = existing
                if desired and id_ != desired:
                    raise ServiceError(
                        f"frontend {fe_key} already registered with ID "
                        f"{id_}, requested {desired}"
                    )
                return id_
            value = json.dumps(
                {"key": fe_key, "frontend": frontend.to_dict()}
            ).encode()
            if desired:
                if self.backend.get(self._id_key(desired)) is not None:
                    raise ServiceError(
                        f"service ID {desired} is already registered to a "
                        f"different frontend"
                    )
                self.backend.set(self._id_key(desired), value)
                self._bump_next(desired + 1)
                return desired
            next_id = self._read_next()
            for _ in range(MAX_SERVICE_ID):
                if next_id > MAX_SERVICE_ID:
                    next_id = FIRST_FREE_SERVICE_ID
                if self.backend.create_only(self._id_key(next_id), value):
                    self._bump_next(next_id + 1)
                    return next_id
                next_id += 1
            raise ServiceError("service ID space exhausted")
        finally:
            lock.unlock()

    def _read_next(self) -> int:
        raw = self.backend.get(f"{self.base}/next")
        if raw is None:
            return FIRST_FREE_SERVICE_ID
        try:
            return max(FIRST_FREE_SERVICE_ID, int(raw.decode()))
        except ValueError:
            return FIRST_FREE_SERVICE_ID

    def _bump_next(self, value: int) -> None:
        # Hint only (reference: setMaxID) — correctness comes from the
        # atomic create_only on the id key.  Only ever raised: moving it
        # backwards would make auto-allocation re-scan taken IDs.
        if value > self._read_next():
            self.backend.set(f"{self.base}/next", str(value).encode())

    def get_id(self, id_: int) -> L3n4Addr | None:
        """reference: id_kvstore.go GetID."""
        raw = self.backend.get(self._id_key(id_))
        if raw is None:
            return None
        try:
            return L3n4Addr.from_dict(json.loads(raw.decode())["frontend"])
        except (ValueError, KeyError, ServiceError):
            return None

    def delete_id(self, id_: int) -> bool:
        """reference: id_kvstore.go DeleteID.  Same fenced-retry
        contract as acquire_id: rejected-before-apply, so the lock +
        delete re-runs whole against the post-failover primary."""
        try:
            return self._delete_id(id_)
        except EpochFencedError:
            return self._delete_id(id_)

    def _delete_id(self, id_: int) -> bool:
        lock = self.backend.lock_path(f"{self.base}/lock")
        try:
            if self.backend.get(self._id_key(id_)) is None:
                return False
            self.backend.delete(self._id_key(id_))
            return True
        finally:
            lock.unlock()


@dataclass
class LBService:
    """Stored service model (reference: pkg/loadbalancer LBSVC)."""

    id: int
    frontend: L3n4Addr
    backends: list[L3n4Addr] = field(default_factory=list)

    def to_model(self) -> dict:
        """REST model (reference: api/v1 Service/ServiceSpec)."""
        return {
            "id": self.id,
            "frontend-address": self.frontend.to_dict(),
            "backend-addresses": [b.to_dict() for b in self.backends],
        }


class ServiceManager:
    """Owner of the LB maps (reference: daemon/loadbalancer.go's
    d.loadBalancer + addSVC2BPFMap).  All map programming for services
    funnels through here so the REST, CLI, and k8s paths share one
    bookkeeping surface."""

    def __init__(self, lb_map, backend: Backend) -> None:
        self.lb_map = lb_map
        self.id_allocator = ServiceIDAllocator(backend)
        # id -> LBSVC and frontend-key -> id (reference: SVCMapID + SVCMap)
        self._services: dict[int, LBService] = {}
        self._by_frontend: dict[str, int] = {}
        # (ip_int, port, family) -> protocol, for the O(1) map-slot
        # collision check (the LB map key carries no protocol).
        self._slot_proto: dict[tuple, str] = {}
        self._mutex = threading.RLock()  # reference: BPFMapMU

    # -- core add/delete (reference: SVCAdd / svcAdd / svcDelete) ---------

    def upsert(
        self,
        frontend: L3n4Addr,
        backends: list[L3n4Addr],
        id: int = 0,
    ) -> tuple[int, bool]:
        """Install or update a service; returns (service_id, created).
        The service ID doubles as the RevNAT index, exactly as the
        reference programs RevNAT with feCilium.ID
        (daemon/loadbalancer.go:34)."""
        for be in backends:
            if be.family != frontend.family:
                raise ServiceError(
                    f"backend {be.key()} address family does not match "
                    f"frontend {frontend.key()}"
                )
        with self._mutex:
            # The datapath service key is (vip, port) without protocol —
            # same as the reference's lb4_key (bpf/lib/common.h:427),
            # where two services differing only in protocol would
            # silently share one map slot.  Reject that instead of
            # desyncing the manager from the map.
            slot = (frontend.ip_int, frontend.port, frontend.family)
            other_proto = self._slot_proto.get(slot)
            if other_proto is not None and other_proto != frontend.protocol:
                raise ServiceError(
                    f"frontend {frontend.key()} collides with an "
                    f"existing {other_proto} service on the same "
                    f"VIP:port: the LB map key has no protocol"
                )
            # Local cache first (reference: SVCMap in front of the
            # kvstore): the k8s endpoint-churn hot path must not pay a
            # kvstore lock + scan for a frontend whose ID is known.
            known = self._by_frontend.get(frontend.key())
            if known is not None and id in (0, known):
                svc_id = known
            else:
                svc_id = self.id_allocator.acquire_id(frontend, desired=id)
            created = svc_id not in self._services
            pairs = [(b.ip_int, b.port) for b in backends]
            if frontend.family == 4:
                self.lb_map.upsert_service(
                    frontend.ip_int, frontend.port, pairs,
                    rev_nat_index=svc_id,
                )
            else:
                self.lb_map.upsert_service6(
                    frontend.ip_int, frontend.port, pairs,
                    rev_nat_index=svc_id,
                )
            self._services[svc_id] = LBService(
                id=svc_id, frontend=frontend, backends=list(backends)
            )
            self._by_frontend[frontend.key()] = svc_id
            self._slot_proto[slot] = frontend.protocol
            return svc_id, created

    def delete_by_id(self, id_: int) -> bool:
        """reference: DELETE /service/{id} handler
        (daemon/loadbalancer.go:183) — drops the kvstore ID, the map
        entries, and the model."""
        with self._mutex:
            svc = self._services.pop(id_, None)
            if svc is None:
                return False
            self._by_frontend.pop(svc.frontend.key(), None)
            self._slot_proto.pop(
                (svc.frontend.ip_int, svc.frontend.port,
                 svc.frontend.family), None,
            )
            self.id_allocator.delete_id(id_)
            self._delete_from_map(svc.frontend)
            return True

    def delete_by_frontend(self, frontend: L3n4Addr) -> bool:
        """reference: svcDeleteByFrontend (k8s teardown path)."""
        with self._mutex:
            id_ = self._by_frontend.get(frontend.key())
            if id_ is None:
                return False
            return self.delete_by_id(id_)

    def _delete_from_map(self, frontend: L3n4Addr) -> None:
        if frontend.family == 4:
            self.lb_map.delete_service(frontend.ip_int, frontend.port)
        else:
            self.lb_map.delete_service6(frontend.ip_int, frontend.port)

    def resync(self, desired: list[tuple[L3n4Addr, list[L3n4Addr]]]) -> dict:
        """Converge the LB maps onto the FULL desired frontend set —
        the k8s relist path under churn (reference: the watcher's
        replaceCiliumService resync after an apiserver reconnect).
        Upserts every desired service and prunes frontends that
        vanished from the desired set, so a burst of missed
        add/update/delete events cannot leave stale map slots serving
        dead backends.  Returns {"upserted", "created", "pruned"}."""
        created = 0
        keep: set[str] = set()
        for frontend, backends in desired:
            keep.add(frontend.key())
            _, was_created = self.upsert(frontend, backends)
            if was_created:
                created += 1
        pruned = 0
        with self._mutex:
            stale = [
                svc.frontend for svc in self._services.values()
                if svc.frontend.key() not in keep
            ]
            for frontend in stale:
                if self.delete_by_frontend(frontend):
                    pruned += 1
        return {
            "upserted": len(desired),
            "created": created,
            "pruned": pruned,
        }

    # -- queries (reference: GET /service, GET /service/{id}) -------------

    def get(self, id_: int) -> LBService | None:
        with self._mutex:
            return self._services.get(id_)

    def get_by_frontend(self, frontend: L3n4Addr) -> LBService | None:
        with self._mutex:
            id_ = self._by_frontend.get(frontend.key())
            return self._services.get(id_) if id_ is not None else None

    def list(self) -> list[LBService]:
        with self._mutex:
            return [self._services[i] for i in sorted(self._services)]

    def __len__(self) -> int:
        return len(self._services)
