"""Pack a set of compiled patterns into dense NFA transition tables.

The packed form is what the TPU verdict kernel consumes
(``cilium_tpu.ops.nfa``): one boolean transition matrix per *byte class*
(bytes with identical transition behavior share a class, which typically
compresses 256 columns to a handful for real policy rule sets — cf. the
reference's rule corpus in examples/policies and proxylib test policies),
a start-state vector, and one accept vector per pattern.

Pure function rules -> arrays, mirroring how the reference compiles policy
into packed BPF map entries (reference: pkg/maps/policymap/policymap.go:64)
— except the "map" here is a dense matrix the MXU can multiply through.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .nfa import CompiledPattern, compile_pattern
from .parse import ParseError

MAX_TOTAL_STATES = 8192


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@dataclass
class NfaTables:
    """Dense multi-pattern NFA tables.

    classmap: [256] int32   byte -> byte-class id
    delta:    [C, S, S] uint8   delta[c, s, t] = 1 iff s -(class c)-> t
    start:    [S] bool          post-BEGIN start state set
    accept:   [R, S] bool       per-pattern sticky-accept states
    accept_final: [R, S] bool   accept | accept-via-END (checked on the
                                final carried state only)
    matches_empty: [R] bool     pattern matches the empty string
    """

    n_states: int
    n_classes: int
    n_patterns: int
    classmap: np.ndarray
    delta: np.ndarray
    start: np.ndarray
    accept: np.ndarray
    accept_final: np.ndarray
    matches_empty: np.ndarray
    patterns: list[str] = field(default_factory=list)

    def pad_states(self, multiple: int = 8) -> "NfaTables":
        """Pad the state axis (dead padding states) for friendlier matmul
        tiling; padding states have no transitions and are never set."""
        s_pad = _round_up(max(self.n_states, 1), multiple)
        if s_pad == self.n_states:
            return self
        d = np.zeros((self.n_classes, s_pad, s_pad), dtype=np.uint8)
        d[:, : self.n_states, : self.n_states] = self.delta
        st = np.zeros((s_pad,), dtype=bool)
        st[: self.n_states] = self.start
        acc = np.zeros((self.n_patterns, s_pad), dtype=bool)
        acc[:, : self.n_states] = self.accept
        accf = np.zeros((self.n_patterns, s_pad), dtype=bool)
        accf[:, : self.n_states] = self.accept_final
        return NfaTables(
            n_states=s_pad,
            n_classes=self.n_classes,
            n_patterns=self.n_patterns,
            classmap=self.classmap,
            delta=d,
            start=st,
            accept=acc,
            accept_final=accf,
            matches_empty=self.matches_empty,
            patterns=self.patterns,
        )


def compile_patterns(patterns: list[str], pad_to: int = 8) -> NfaTables:
    """Compile ``patterns`` into one packed multi-pattern table set.

    Patterns are united into a single NFA with disjoint state spaces (plus a
    shared dense numbering); per-pattern accept vectors let one device pass
    answer "which rules matched" for a whole rule set at once.
    """
    compiled: list[CompiledPattern] = [compile_pattern(p) for p in patterns]

    total = sum(c.n_states for c in compiled)
    if total > MAX_TOTAL_STATES:
        raise ParseError(
            f"rule set compiles to {total} NFA states (max {MAX_TOTAL_STATES})"
        )
    n_r = len(compiled)
    offsets = np.cumsum([0] + [c.n_states for c in compiled])[:-1]

    start = np.zeros((max(total, 1),), dtype=bool)
    accept = np.zeros((n_r, max(total, 1)), dtype=bool)
    accept_final = np.zeros((n_r, max(total, 1)), dtype=bool)
    matches_empty = np.zeros((n_r,), dtype=bool)

    # trans_by_byte[b] : list of (src, dst) global pairs for byte b
    # Build a [256, S, S] dense relation incrementally but memory-safely by
    # first collecting per-byte edge lists.
    edge_lists: list[list[tuple[int, int]]] = [[] for _ in range(256)]
    for r, c in enumerate(compiled):
        off = int(offsets[r])
        for s in c.start:
            start[off + s] = True
        for s in c.accept:
            accept[r, off + s] = True
        for s in c.accept | c.accept_via_end:
            accept_final[r, off + s] = True
        matches_empty[r] = c.matches_empty()
        for s, edges in enumerate(c.transitions):
            for byteset, d in edges:
                for byte in byteset:
                    edge_lists[byte].append((off + s, off + d))

    # Byte classes: bytes with identical edge sets share a class.
    sig_to_class: dict[tuple, int] = {}
    classmap = np.zeros((256,), dtype=np.int32)
    class_edges: list[list[tuple[int, int]]] = []
    for byte in range(256):
        sig = tuple(sorted(set(edge_lists[byte])))
        cls = sig_to_class.get(sig)
        if cls is None:
            cls = len(sig_to_class)
            sig_to_class[sig] = cls
            class_edges.append(sorted(set(edge_lists[byte])))
        classmap[byte] = cls

    n_classes = len(class_edges)
    s_dim = max(total, 1)
    delta = np.zeros((n_classes, s_dim, s_dim), dtype=np.uint8)
    for cls, edges in enumerate(class_edges):
        if edges:
            src, dst = zip(*edges)
            delta[cls, list(src), list(dst)] = 1

    tables = NfaTables(
        n_states=s_dim,
        n_classes=n_classes,
        n_patterns=n_r,
        classmap=classmap,
        delta=delta,
        start=start,
        accept=accept,
        accept_final=accept_final,
        matches_empty=matches_empty,
        patterns=list(patterns),
    )
    if pad_to > 1:
        tables = tables.pad_states(pad_to)
    return tables
