"""CPU reference evaluation of compiled NFAs and packed tables.

Two evaluators:

* ``py_search``     — walks the ``CompiledPattern`` state sets directly;
                      the semantic oracle for the regex compiler itself.
* ``tables_search`` — numpy evaluation of the packed ``NfaTables`` using the
                      exact update rule the TPU kernel uses (boolean matvec +
                      sticky accept), so device results can be checked
                      bit-identical against it.
"""

from __future__ import annotations

import numpy as np

from .nfa import CompiledPattern
from .tables import NfaTables


def py_search(c: CompiledPattern, data: bytes) -> bool:
    """True iff ``data`` contains a match ("search" semantics)."""
    state = set(c.start)
    if state & c.accept:
        return True
    for byte in data:
        nxt: set[int] = set()
        for s in state:
            for byteset, d in c.transitions[s]:
                if byte in byteset:
                    nxt.add(d)
        state = nxt
        if state & c.accept:
            return True
        if not state:
            return False
    return bool(state & c.accept_via_end)


def tables_search(t: NfaTables, data: bytes) -> np.ndarray:
    """Evaluate all patterns in ``t`` against ``data``.

    Returns a [R] bool array: pattern matched somewhere in ``data``.
    Mirrors the device scan: state' = (state @ delta[cls]) > 0, with sticky
    accept per step and the END-folded accept on the final state.
    """
    state = t.start.astype(np.int32)
    accepted = (t.accept @ state) > 0  # [R]
    for byte in data:
        cls = int(t.classmap[byte])
        state = (state @ t.delta[cls].astype(np.int32)) > 0
        state = state.astype(np.int32)
        accepted |= (t.accept @ state) > 0
    accepted |= (t.accept_final @ state) > 0
    return accepted
