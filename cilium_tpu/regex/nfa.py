"""AST -> epsilon-free byte NFA with search ("contains match") semantics.

Matches the observable behavior of Go ``regexp.MatchString`` as used by the
reference's proxylib rule matchers (reference: proxylib/r2d2/r2d2parser.go:79,
proxylib/cassandra/cassandraparser.go rule matching) and the agent-side
validation of HTTP rules (reference: pkg/policy/api/http.go:66).

Design notes (TPU-first):

* Anchors are compiled via two virtual symbols, BEGIN and END, conceptually
  processed before the first and after the last input byte.  Both are folded
  out of the device loop at compile time: the exported ``start`` set is the
  post-BEGIN state set, and ``accept_via_end`` marks states that reach an
  accepting state by consuming END.  The device kernel therefore advances the
  state set exactly once per real input byte.
* A wrapper start state with a self-loop over every byte provides unanchored
  search; acceptance is *sticky* (recorded per step), so "contains a match"
  is an OR-reduction the kernel folds into its scan carry.
"""

from __future__ import annotations

from dataclasses import dataclass

from .parse import ALL_BYTES, ParseError, parse

END = 256
BEGIN = 257

# Hard cap on epsilon-free states for one compiled pattern set; transition
# tables are dense [C, S, S] so S bounds both HBM footprint and matmul cost.
MAX_STATES = 4096


class _Builder:
    """Thompson construction over (byteset | BEGIN | EOL | eps) edges."""

    def __init__(self):
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[frozenset, int]]] = []

    def new_state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def add_eps(self, a: int, b: int) -> None:
        self.eps[a].append(b)

    def add_edge(self, a: int, syms: frozenset, b: int) -> None:
        self.edges[a].append((syms, b))

    # Each build returns (entry, exit) state pair.
    def build(self, node) -> tuple[int, int]:
        kind = node[0]
        if kind == "empty":
            s = self.new_state()
            return s, s
        if kind == "lit":
            a, b = self.new_state(), self.new_state()
            self.add_edge(a, node[1], b)
            return a, b
        if kind == "bol":
            a, b = self.new_state(), self.new_state()
            self.add_edge(a, frozenset([BEGIN]), b)
            return a, b
        if kind == "eol":
            a, b = self.new_state(), self.new_state()
            self.add_edge(a, frozenset([END]), b)
            return a, b
        if kind == "cat":
            items = node[1]
            entry, cur = None, None
            for item in items:
                a, b = self.build(item)
                if entry is None:
                    entry = a
                else:
                    self.add_eps(cur, a)
                cur = b
            return entry, cur
        if kind == "alt":
            a, b = self.new_state(), self.new_state()
            for branch in node[1]:
                x, y = self.build(branch)
                self.add_eps(a, x)
                self.add_eps(y, b)
            return a, b
        if kind == "star":
            a, b = self.new_state(), self.new_state()
            x, y = self.build(node[1])
            self.add_eps(a, x)
            self.add_eps(a, b)
            self.add_eps(y, x)
            self.add_eps(y, b)
            return a, b
        if kind == "plus":
            x, y = self.build(node[1])
            b = self.new_state()
            self.add_eps(y, x)
            self.add_eps(y, b)
            return x, b
        if kind == "opt":
            a, b = self.new_state(), self.new_state()
            x, y = self.build(node[1])
            self.add_eps(a, x)
            self.add_eps(y, b)
            self.add_eps(a, b)
            return a, b
        if kind == "rep":
            _, inner, m, n = node
            a = self.new_state()
            cur = a
            for _ in range(m):
                x, y = self.build(inner)
                self.add_eps(cur, x)
                cur = y
            if n is None:
                x, y = self.build(inner)
                self.add_eps(cur, x)
                self.add_eps(y, x)
                self.add_eps(y, cur)
                b = self.new_state()
                self.add_eps(cur, b)
                return a, b
            b = self.new_state()
            self.add_eps(cur, b)
            for _ in range(n - m):
                x, y = self.build(inner)
                self.add_eps(cur, x)
                cur = y
                self.add_eps(cur, b)
            return a, b
        raise ParseError(f"unknown AST node {kind}")


@dataclass
class CompiledPattern:
    """Epsilon-free NFA over bytes 0..255.

    transitions: per-state list of (byteset, target-state) pairs
    start: state set after the virtual BEGIN step
    accept: states whose epsilon-closure is accepting
    accept_via_end: states reaching acceptance by consuming the virtual END
    """

    n_states: int
    transitions: list[list[tuple[frozenset, int]]]
    start: frozenset
    accept: frozenset
    accept_via_end: frozenset

    def matches_empty(self) -> bool:
        return bool(self.start & (self.accept | self.accept_via_end))


def compile_pattern(pattern: str) -> CompiledPattern:
    """Compile ``pattern`` to an epsilon-free search NFA."""
    ast = parse(pattern)

    b = _Builder()
    # Unanchored-search wrapper: self-loop over every byte and BEGIN.
    wrapper = b.new_state()
    b.add_edge(wrapper, ALL_BYTES | frozenset([BEGIN]), wrapper)
    entry, exit_ = b.build(ast)
    b.add_eps(wrapper, entry)
    final = exit_

    n = len(b.eps)

    # epsilon closures (iterative DFS per state)
    closures: list[frozenset] = []
    for s in range(n):
        seen = {s}
        stack = [s]
        while stack:
            q = stack.pop()
            for d in b.eps[q]:
                if d not in seen:
                    seen.add(d)
                    stack.append(d)
        closures.append(frozenset(seen))

    def closure_of(states) -> frozenset:
        out: set[int] = set()
        for s in states:
            out |= closures[s]
        return frozenset(out)

    # Raw symbol move: from closed state s, on symbol sym.
    def move(states: frozenset, pred) -> frozenset:
        out: set[int] = set()
        for s in states:
            for syms, d in b.edges[s]:
                if pred(syms):
                    out |= closures[d]
        return frozenset(out)

    def anchor_fixpoint(states: frozenset, sym: int) -> frozenset:
        """Anchors are zero-width assertions: asserting ^ (or $) twice at the
        same position is legal (``^(^a)``, ``(a$)$``), but our encoding
        consumes a virtual symbol per anchor edge — so take the transitive
        closure over anchor moves."""
        cur = states
        while True:
            nxt = cur | move(cur, lambda syms: sym in syms)
            if nxt == cur:
                return cur
            cur = nxt

    raw_start = closures[wrapper]
    # Post-BEGIN state set.  The wrapper's BEGIN self-loop keeps unanchored
    # starts alive; the fixpoint admits stacked ^ anchors across groups.
    start = anchor_fixpoint(
        move(raw_start, lambda syms: BEGIN in syms), BEGIN
    )

    accepting_raw = frozenset([final])

    def is_accepting(cl: frozenset) -> bool:
        return bool(cl & accepting_raw)

    # Restrict to states reachable over byte transitions from `start`.
    reachable = set(start)
    frontier = list(start)
    while frontier:
        s = frontier.pop()
        for syms, d in b.edges[s]:
            if syms & ALL_BYTES:
                for t in closures[d]:
                    if t not in reachable:
                        reachable.add(t)
                        frontier.append(t)
    if len(reachable) > MAX_STATES:
        raise ParseError(
            f"pattern compiles to {len(reachable)} NFA states (max {MAX_STATES})"
        )

    # Renumber reachable states densely.
    order = sorted(reachable)
    index = {s: i for i, s in enumerate(order)}

    transitions: list[list[tuple[frozenset, int]]] = [[] for _ in order]
    accept: set[int] = set()
    accept_via_end: set[int] = set()
    for s in order:
        cl = closures[s]
        if is_accepting(cl):
            accept.add(index[s])
        # END moves to fixpoint from the closure of s (stacked $ anchors)
        end_set = anchor_fixpoint(move(cl, lambda syms: END in syms), END)
        if any(is_accepting(closures[t]) for t in end_set):
            accept_via_end.add(index[s])
        # byte transitions from the closure of s
        for q in cl:
            for syms, d in b.edges[q]:
                byte_syms = syms & ALL_BYTES
                if byte_syms:
                    transitions[index[s]].append((byte_syms, index[d]))

    return CompiledPattern(
        n_states=len(order),
        transitions=transitions,
        start=frozenset(index[s] for s in start),
        accept=frozenset(accept),
        accept_via_end=frozenset(accept_via_end),
    )
