"""Regex -> packed NFA transition tables for TPU evaluation.

The reference enforces L7 rules with three regex engines: Go ``regexp`` (RE2)
in proxylib parsers (reference: proxylib/r2d2/r2d2parser.go:103), POSIX
extended regex declared for agent-side HTTP rules (reference:
pkg/policy/api/http.go:22-27), and ``std::regex`` inside Envoy (reference:
envoy/cilium_network_policy.h:50-76).  This package implements the common
subset of those dialects, compiled to a byte-level epsilon-free NFA whose
transition relation is packed into dense per-byte-class matrices so a batch of
flows can be advanced with one MXU matmul per input byte.

Semantics: *search* ("contains a match"), matching Go ``regexp.MatchString``,
which is what proxylib rule matching uses.  ``^``/``$`` anchor to string
start/end.  ``.`` matches any byte except ``\n`` (RE2 default).
"""

from .parse import ParseError, parse
from .nfa import CompiledPattern, compile_pattern
from .tables import NfaTables, compile_patterns
from .pymatch import py_search, tables_search

__all__ = [
    "ParseError",
    "parse",
    "CompiledPattern",
    "compile_pattern",
    "NfaTables",
    "compile_patterns",
    "py_search",
    "tables_search",
]
