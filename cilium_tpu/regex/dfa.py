"""Per-pattern DFA compilation for gather-based batch matching.

The dense multi-pattern NFA tables (tables.py) are MXU-friendly but their
transition matrix is O(S²·C) for S TOTAL states across all patterns — at
policy-set scale (hundreds of rules) that is gigabytes of HBM and
teraflops per scan.  The union NFA is block-diagonal (patterns' states
never interact), so large rule sets compile instead to one SMALL DFA per
pattern: the batch step becomes a per-(flow, pattern) table gather,
O(F·R) loads per byte with per-pattern tables of a few hundred bytes.

Semantics are bit-identical to the NFA path (same CompiledPattern input,
same search/anchor/sticky-accept contract as ops/nfa.py); subset
construction runs over the pattern's own byte classes.  Acceptance is
encoded in the state ORDER — accepting states get the highest ids — so
the device's sticky-accept check is one integer compare per step instead
of a second gather.

Reference counterpart: envoy/cilium_network_policy.h:50-76 compiles one
std::regex per rule; here each rule's pattern becomes a packed DFA row.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .nfa import CompiledPattern, compile_pattern

MAX_DFA_STATES = 512  # per pattern; policy-rule regexes are literal-heavy


class DfaBlowupError(ValueError):
    """Subset construction exceeded MAX_DFA_STATES (pathological pattern);
    callers fall back to the NFA path."""


@dataclass
class PatternDfa:
    n_states: int
    n_classes: int
    classmap: np.ndarray  # [256] int32
    delta: np.ndarray  # [S, C] int32
    start: int
    accept_thresh: int  # state >= thresh  <=>  sticky-accepting
    accept_final: np.ndarray  # [S] bool (accept | accept-via-END)
    matches_empty: bool


def pattern_dfa(c: CompiledPattern, max_states: int = MAX_DFA_STATES) -> PatternDfa:
    """Subset-construct a DFA from an epsilon-free search NFA.

    CompiledPattern invariants (regex/nfa.py): transitions from state s
    already enumerate closure(s)'s edges and targets are raw states, so
    the subset move needs no closure step; accept membership is by raw
    state."""
    byte_edges: list[list[tuple[int, int]]] = [[] for _ in range(256)]
    for s, edges in enumerate(c.transitions):
        for byteset, d in edges:
            for byte in byteset:
                byte_edges[byte].append((s, d))

    sig_to_class: dict[tuple, int] = {}
    classmap = np.zeros((256,), np.int32)
    class_moves: list[dict[int, set[int]]] = []
    for byte in range(256):
        sig = tuple(sorted(set(byte_edges[byte])))
        cls = sig_to_class.get(sig)
        if cls is None:
            cls = len(sig_to_class)
            sig_to_class[sig] = cls
            mv: dict[int, set[int]] = {}
            for s, d in sig:
                mv.setdefault(s, set()).add(d)
            class_moves.append(mv)
        classmap[byte] = cls
    n_classes = len(class_moves)

    start_set = frozenset(c.start)
    index: dict[frozenset, int] = {start_set: 0}
    order: list[frozenset] = [start_set]
    trans: dict[int, list[int]] = {}
    queue = [start_set]
    while queue:
        st = queue.pop()
        row = []
        for mv in class_moves:
            out: set[int] = set()
            for s in st:
                out |= mv.get(s, set())
            nxt = frozenset(out)
            idx = index.get(nxt)
            if idx is None:
                idx = len(order)
                if idx >= max_states:
                    raise DfaBlowupError(
                        f"DFA exceeds {max_states} states for pattern"
                    )
                index[nxt] = idx
                order.append(nxt)
                queue.append(nxt)
            row.append(idx)
        trans[index[st]] = row
    delta = np.zeros((len(order), n_classes), np.int32)
    for src, row in trans.items():
        delta[src] = row

    accept = np.array([bool(st & c.accept) for st in order])
    accept_final_raw = np.array(
        [bool(st & (c.accept | c.accept_via_end)) for st in order]
    )

    # Reorder: non-accepting states first, so sticky accept is a
    # threshold compare on the state id.
    n = len(order)
    perm = np.concatenate(
        [np.flatnonzero(~accept), np.flatnonzero(accept)]
    ).astype(np.int64)
    remap = np.empty((n,), np.int64)
    remap[perm] = np.arange(n)
    delta = remap[delta[perm]].astype(np.int32)
    accept_final = accept_final_raw[perm]
    thresh = int((~accept).sum())
    return PatternDfa(
        n_states=n,
        n_classes=n_classes,
        classmap=classmap,
        delta=delta,
        start=int(remap[0]),
        accept_thresh=thresh,
        accept_final=accept_final,
        matches_empty=bool(
            start_set & (c.accept | c.accept_via_end)
        ),
    )


@dataclass
class DfaTables:
    """Per-pattern DFAs packed to common [R, S, C] shapes over ONE
    shared byte-class map (bytes equivalent iff they behave identically
    in EVERY pattern of the set), so the device step needs no per-pattern
    class lookup — the class one-hot comes from a single [256, C]
    matmul and the transition is a block-diagonal batched matmul
    (ops/dfa.py)."""

    n_states: int
    n_classes: int
    n_patterns: int
    classmap: np.ndarray  # [256] int32 — SHARED across patterns
    delta: np.ndarray  # [R, S, C] int32
    start: np.ndarray  # [R] int32
    n_states_per: np.ndarray  # [R] int32 — real (unpadded) state count
    accept: np.ndarray  # [R, S] bool — sticky accept
    accept_final: np.ndarray  # [R, S] bool
    matches_empty: np.ndarray  # [R] bool
    patterns: list[str]


def pad_dfa_tables(t: DfaTables, s: int, c: int) -> DfaTables:
    """Pad the state/class axes (e.g. to share one jit shape across many
    policies' tables).  Padding states are unreachable (delta never
    points at them) and padding classes are never produced by classmap."""
    assert s >= t.n_states and c >= t.n_classes
    if s == t.n_states and c == t.n_classes:
        return t
    r = t.n_patterns
    delta = np.zeros((r, s, c), np.int32)
    delta[:, : t.n_states, : t.n_classes] = t.delta
    accept = np.zeros((r, s), bool)
    accept[:, : t.n_states] = t.accept
    accept_final = np.zeros((r, s), bool)
    accept_final[:, : t.n_states] = t.accept_final
    return DfaTables(
        n_states=s,
        n_classes=c,
        n_patterns=r,
        classmap=t.classmap,
        delta=delta,
        start=t.start,
        n_states_per=t.n_states_per,
        accept=accept,
        accept_final=accept_final,
        matches_empty=t.matches_empty,
        patterns=list(t.patterns),
    )


def compile_pattern_dfas(
    patterns: list[str], max_states: int = MAX_DFA_STATES
) -> DfaTables:
    """Compile each pattern to its own DFA over a shared byte-class map
    and pack them.  Raises DfaBlowupError if any pattern's DFA exceeds
    ``max_states``."""
    dfas = [pattern_dfa(compile_pattern(p), max_states) for p in patterns]
    r = len(dfas)
    s = max((d.n_states for d in dfas), default=1)

    # Shared classes: two bytes are equivalent iff every pattern puts
    # them in the same per-pattern class.
    sig_to_class: dict[tuple, int] = {}
    classmap = np.zeros((256,), np.int32)
    reps: list[int] = []  # representative byte per shared class
    for byte in range(256):
        sig = tuple(int(d.classmap[byte]) for d in dfas)
        cls = sig_to_class.get(sig)
        if cls is None:
            cls = len(sig_to_class)
            sig_to_class[sig] = cls
            reps.append(byte)
        classmap[byte] = cls
    c = max(len(reps), 1)

    delta = np.zeros((r, s, c), np.int32)
    start = np.zeros((r,), np.int32)
    n_states_per = np.zeros((r,), np.int32)
    accept = np.zeros((r, s), bool)
    accept_final = np.zeros((r, s), bool)
    matches_empty = np.zeros((r,), bool)
    for i, d in enumerate(dfas):
        # Re-index the pattern's transitions by shared class via a
        # representative byte (all bytes of a shared class share the
        # pattern-local class by construction).
        local_cls = d.classmap[reps]  # [C] pattern-local class ids
        delta[i, : d.n_states, :] = d.delta[:, local_cls]
        start[i] = d.start
        n_states_per[i] = d.n_states
        accept[i, d.accept_thresh : d.n_states] = True
        accept_final[i, : d.n_states] = d.accept_final
        matches_empty[i] = d.matches_empty
    return DfaTables(
        n_states=s,
        n_classes=c,
        n_patterns=r,
        classmap=classmap,
        delta=delta,
        start=start,
        n_states_per=n_states_per,
        accept=accept,
        accept_final=accept_final,
        matches_empty=matches_empty,
        patterns=list(patterns),
    )
