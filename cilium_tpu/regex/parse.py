"""Regex parser: RE2/POSIX-ERE common subset -> AST over the byte alphabet.

Supported syntax (the subset exercised by the reference's policy rules —
HTTP path/method/host regexes, proxylib ``file``/``query_table``/key-prefix
rules): literals (UTF-8 encoded to bytes), ``.``, character classes
``[...]``/``[^...]`` with ranges, POSIX classes ``[[:alpha:]]`` etc.,
perl classes ``\\d \\w \\s`` (+ negations), escapes, grouping ``( )`` and
``(?: )``, alternation ``|``, quantifiers ``* + ? {m} {m,} {m,n}`` (with an
optional non-greedy ``?`` suffix, which is irrelevant for accept/reject
semantics and ignored), and anchors ``^ $``.

AST nodes are plain tuples:
  ("empty",)                  - matches empty string
  ("lit", frozenset[int])     - one byte drawn from the set
  ("cat", [node, ...])
  ("alt", [node, ...])
  ("star", node)              - zero or more
  ("plus", node)              - one or more
  ("opt", node)               - zero or one
  ("rep", node, m, n)         - m..n repetitions (n may be None = unbounded)
  ("bol",)                    - ^ anchor
  ("eol",)                    - $ anchor
"""

from __future__ import annotations

DOT_EXCLUDES_NEWLINE = True

# Maximum counted-repetition bound: keeps Thompson state counts sane for
# adversarial rules ({1000} would otherwise explode the transition table).
MAX_REPEAT = 256


class ParseError(ValueError):
    """Raised when a pattern is outside the supported dialect subset."""


_PERL_CLASSES = {
    "d": frozenset(range(0x30, 0x3A)),
    "w": frozenset(
        list(range(0x30, 0x3A))
        + list(range(0x41, 0x5B))
        + list(range(0x61, 0x7B))
        + [0x5F]
    ),
    # RE2 \s is [\t\n\f\r ] — no vertical tab, unlike POSIX [[:space:]].
    "s": frozenset([0x20, 0x09, 0x0A, 0x0C, 0x0D]),
}

_POSIX_CLASSES = {
    "alpha": frozenset(list(range(0x41, 0x5B)) + list(range(0x61, 0x7B))),
    "digit": frozenset(range(0x30, 0x3A)),
    "alnum": frozenset(
        list(range(0x30, 0x3A)) + list(range(0x41, 0x5B)) + list(range(0x61, 0x7B))
    ),
    "upper": frozenset(range(0x41, 0x5B)),
    "lower": frozenset(range(0x61, 0x7B)),
    "space": frozenset([0x20, 0x09, 0x0A, 0x0B, 0x0C, 0x0D]),
    "blank": frozenset([0x20, 0x09]),
    "punct": frozenset(
        b for b in range(0x21, 0x7F) if not (chr(b).isalnum())
    ),
    "xdigit": frozenset(
        list(range(0x30, 0x3A)) + list(range(0x41, 0x47)) + list(range(0x61, 0x67))
    ),
    "print": frozenset(range(0x20, 0x7F)),
    "graph": frozenset(range(0x21, 0x7F)),
    "cntrl": frozenset(list(range(0x00, 0x20)) + [0x7F]),
}

_ESCAPE_LITERALS = {
    "n": 0x0A,
    "r": 0x0D,
    "t": 0x09,
    "f": 0x0C,
    "v": 0x0B,
    "a": 0x07,
    "0": 0x00,
}

ALL_BYTES = frozenset(range(256))
DOT_BYTES = frozenset(b for b in range(256) if b != 0x0A) if DOT_EXCLUDES_NEWLINE else ALL_BYTES


class _Parser:
    def __init__(self, pattern: str):
        # Patterns arrive as str; operate on their UTF-8 bytes so multi-byte
        # literals match byte streams exactly.
        self.data = pattern.encode("utf-8")
        self.pos = 0

    def error(self, msg: str) -> ParseError:
        return ParseError(f"{msg} at offset {self.pos} in pattern {self.data!r}")

    def peek(self) -> int | None:
        return self.data[self.pos] if self.pos < len(self.data) else None

    def next(self) -> int:
        b = self.data[self.pos]
        self.pos += 1
        return b

    def eof(self) -> bool:
        return self.pos >= len(self.data)

    # --- grammar: alt -> cat ('|' cat)* ; cat -> rep* ; rep -> atom quant*
    def parse_alt(self):
        branches = [self.parse_cat()]
        while self.peek() == 0x7C:  # '|'
            self.next()
            branches.append(self.parse_cat())
        if len(branches) == 1:
            return branches[0]
        return ("alt", branches)

    def parse_cat(self):
        items = []
        while not self.eof() and self.peek() not in (0x7C, 0x29):  # '|' ')'
            items.append(self.parse_rep())
        if not items:
            return ("empty",)
        if len(items) == 1:
            return items[0]
        return ("cat", items)

    def parse_rep(self):
        atom = self.parse_atom()
        quantified = self._parse_one_quantifier(atom)
        if quantified is atom:
            return atom
        # swallow a non-greedy marker: greediness can't change whether a
        # search succeeds, only which span it reports.
        if self.peek() == 0x3F:
            self.next()
        # Go/RE2 reject stacked quantifiers (a**, a*+, a{2}{3}); silently
        # reinterpreting them as nested greedy repetition would change match
        # semantics vs the reference, so reject them too.
        if self._parse_one_quantifier(quantified) is not quantified:
            raise self.error("nested repetition operator")
        return quantified

    def _parse_one_quantifier(self, atom):
        """Apply at most one quantifier to ``atom``; returns ``atom``
        unchanged if no quantifier follows."""
        c = self.peek()
        if c == 0x2A:  # '*'
            self.next()
            self._check_quantifiable(atom)
            return ("star", atom)
        if c == 0x2B:  # '+'
            self.next()
            self._check_quantifiable(atom)
            return ("plus", atom)
        if c == 0x3F:  # '?'
            self.next()
            self._check_quantifiable(atom)
            return ("opt", atom)
        if c == 0x7B:  # '{'
            saved = self.pos
            rep = self._try_parse_counted()
            if rep is None:
                self.pos = saved
                return atom
            self._check_quantifiable(atom)
            m, n = rep
            return ("rep", atom, m, n)
        return atom

    def _check_quantifiable(self, atom):
        if atom[0] in ("bol", "eol", "empty"):
            raise self.error("quantifier applied to anchor or empty expression")

    def _try_parse_counted(self):
        """Parse {m}, {m,}, {m,n} after consuming nothing.  Returns (m, n)
        with n=None for unbounded, or None if not a valid counted repetition
        (in which case '{' is treated as a literal, matching Go/RE2)."""
        assert self.peek() == 0x7B
        self.next()
        digits = bytearray()
        while self.peek() is not None and 0x30 <= self.peek() <= 0x39:
            digits.append(self.next())
        if not digits:
            return None
        m = int(digits.decode())
        n = m
        if self.peek() == 0x2C:  # ','
            self.next()
            digits2 = bytearray()
            while self.peek() is not None and 0x30 <= self.peek() <= 0x39:
                digits2.append(self.next())
            n = int(digits2.decode()) if digits2 else None
        if self.peek() != 0x7D:  # '}'
            return None
        self.next()
        if n is not None and n < m:
            raise self.error(f"invalid repetition bound {{{m},{n}}}")
        if m > MAX_REPEAT or (n is not None and n > MAX_REPEAT):
            raise self.error(f"repetition bound exceeds {MAX_REPEAT}")
        return (m, n)

    def parse_atom(self):
        c = self.peek()
        if c is None:
            return ("empty",)
        if c == 0x28:  # '('
            self.next()
            if self.peek() == 0x3F:  # '(?'
                self.next()
                if self.peek() == 0x3A:  # '(?:'
                    self.next()
                else:
                    raise self.error("unsupported group flag (only (?: supported)")
            inner = self.parse_alt()
            if self.peek() != 0x29:
                raise self.error("missing )")
            self.next()
            return inner
        if c == 0x5B:  # '['
            return self.parse_class()
        if c == 0x2E:  # '.'
            self.next()
            return ("lit", DOT_BYTES)
        if c == 0x5E:  # '^'
            self.next()
            return ("bol",)
        if c == 0x24:  # '$'
            self.next()
            return ("eol",)
        if c == 0x5C:  # backslash
            self.next()
            return ("lit", self.parse_escape(in_class=False))
        if c in (0x2A, 0x2B, 0x3F):
            raise self.error("quantifier with nothing to repeat")
        if c == 0x29:
            raise self.error("unmatched )")
        self.next()
        return ("lit", frozenset([c]))

    def parse_escape(self, in_class: bool) -> frozenset:
        if self.eof():
            raise self.error("trailing backslash")
        c = self.next()
        ch = chr(c)
        if ch in _PERL_CLASSES:
            return _PERL_CLASSES[ch]
        if ch.lower() in _PERL_CLASSES and ch.isupper():
            return ALL_BYTES - _PERL_CLASSES[ch.lower()]
        if ch in _ESCAPE_LITERALS:
            return frozenset([_ESCAPE_LITERALS[ch]])
        if ch == "x":
            hex_digits = bytearray()
            if self.peek() == 0x7B:  # \x{...}
                self.next()
                while self.peek() is not None and self.peek() != 0x7D:
                    hex_digits.append(self.next())
                if self.peek() != 0x7D:
                    raise self.error("missing } in \\x{}")
                self.next()
                try:
                    cp = int(hex_digits.decode(), 16)
                except ValueError:
                    raise self.error("invalid \\x{} escape")
                if cp > 0x10FFFF:
                    raise self.error("codepoint out of range")
                # Multi-byte codepoints in \x{} would need a 'cat' result;
                # restrict to single-byte values (covers policy rule corpus).
                if cp > 0xFF:
                    raise self.error("\\x{} above 0xFF unsupported")
                return frozenset([cp])
            for _ in range(2):
                if self.peek() is None:
                    raise self.error("truncated \\x escape")
                hex_digits.append(self.next())
            try:
                return frozenset([int(hex_digits.decode(), 16)])
            except ValueError:
                raise self.error("invalid \\x escape")
        if ch.isalnum():
            raise self.error(f"unsupported escape \\{ch}")
        # escaped punctuation is the literal byte
        return frozenset([c])

    def parse_class(self) -> tuple:
        assert self.next() == 0x5B
        negate = False
        if self.peek() == 0x5E:
            negate = True
            self.next()
        members: set[int] = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise self.error("missing ]")
            if c == 0x5D and not first:  # ']'
                self.next()
                break
            first = False
            # POSIX class [[:name:]]
            if c == 0x5B and self.data[self.pos : self.pos + 2] == b"[:":
                end = self.data.find(b":]", self.pos + 2)
                if end < 0:
                    raise self.error("unterminated [:class:]")
                name = self.data[self.pos + 2 : end].decode()
                if name not in _POSIX_CLASSES:
                    raise self.error(f"unknown POSIX class [:{name}:]")
                members |= _POSIX_CLASSES[name]
                self.pos = end + 2
                continue
            if c == 0x5C:
                self.next()
                esc = self.parse_escape(in_class=True)
                if len(esc) > 1:
                    members |= esc
                    continue
                lo = next(iter(esc))
            else:
                lo = self.next()
            # possible range lo-hi
            if (
                self.peek() == 0x2D
                and self.pos + 1 < len(self.data)
                and self.data[self.pos + 1] != 0x5D
            ):
                self.next()  # '-'
                if self.peek() == 0x5C:
                    self.next()
                    esc = self.parse_escape(in_class=True)
                    if len(esc) != 1:
                        raise self.error("class shorthand cannot end a range")
                    hi = next(iter(esc))
                else:
                    hi = self.next()
                if hi < lo:
                    raise self.error("inverted class range")
                members |= set(range(lo, hi + 1))
            else:
                members.add(lo)
        result = frozenset(members)
        if negate:
            result = ALL_BYTES - result
        if not result:
            raise self.error("empty character class")
        return ("lit", result)


def parse(pattern: str):
    """Parse ``pattern`` into an AST; raises ParseError outside the subset."""
    p = _Parser(pattern)
    ast = p.parse_alt()
    if not p.eof():
        raise p.error("unexpected trailing input")
    return ast
