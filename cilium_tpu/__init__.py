"""cilium-tpu: a TPU-native policy-enforcement framework.

A from-scratch re-design of the capabilities of Cilium (reference:
yandooo/cilium v1.2.90) with the L7 policy-verdict hot path executed on TPU:

- ``cilium_tpu.policy``   — rule AST + policy compiler (reference: pkg/policy)
- ``cilium_tpu.regex``    — POSIX-ERE/RE2-subset -> packed NFA transition tables
- ``cilium_tpu.ops``      — JAX/Pallas device ops (NFA step, LPM, tokenizers)
- ``cilium_tpu.models``   — per-protocol verdict pipelines (r2d2, HTTP, Kafka,
                            Cassandra, memcached) — the "model families"
- ``cilium_tpu.parallel`` — mesh/sharding helpers (data-parallel flow sharding)
- ``cilium_tpu.proxylib`` — streaming parser framework with the reference's
                            OnData PASS/DROP/INJECT/MORE contract
                            (reference: proxylib/proxylib)
- ``cilium_tpu.runtime``  — batching engine feeding fixed-size frame batches
                            to the device
- ``cilium_tpu.datapath`` — packed L4 policy tables + CIDR prefilter arrays
                            (reference: pkg/maps/policymap, daemon/prefilter)
"""

__version__ = "0.1.0"
