"""Monitor: the event stream for datapath and agent notifications.

reference: monitor/ + pkg/monitor — BPF trace/drop/debug events flow
through per-CPU perf rings into the cilium-node-monitor process, which
fans them out to unix-socket subscribers; agent events (policy updates,
endpoint regenerations, access logs) are pushed into the same stream
(daemon/daemon.go:1647 SendNotification).  Here the datapath events come
from the batch engines' verdict paths instead of a kernel perf ring: a
bounded in-process ring buffer feeds unix-socket subscribers with
length-prefixed JSON payloads (the 1.2 payload protocol analog,
monitor/listener1_2.go).
"""

from .monitor import (
    AGENT_NOTIFY_ENDPOINT_REGENERATE_SUCCESS,
    AGENT_NOTIFY_KVSTORE_DEGRADED,
    AGENT_NOTIFY_KVSTORE_RESTORED,
    AGENT_NOTIFY_POLICY_UPDATED,
    AGENT_NOTIFY_START,
    MSG_TYPE_ACCESS_LOG,
    MSG_TYPE_AGENT,
    MSG_TYPE_DEBUG,
    MSG_TYPE_DROP,
    MSG_TYPE_POLICY_VERDICT,
    MSG_TYPE_TRACE,
    Monitor,
    MonitorEvent,
)
from .server import MonitorClient, MonitorServer
from .format import format_event

__all__ = [
    "AGENT_NOTIFY_ENDPOINT_REGENERATE_SUCCESS",
    "AGENT_NOTIFY_KVSTORE_DEGRADED",
    "AGENT_NOTIFY_KVSTORE_RESTORED",
    "AGENT_NOTIFY_POLICY_UPDATED",
    "AGENT_NOTIFY_START",
    "MSG_TYPE_ACCESS_LOG",
    "MSG_TYPE_AGENT",
    "MSG_TYPE_DEBUG",
    "MSG_TYPE_DROP",
    "MSG_TYPE_POLICY_VERDICT",
    "MSG_TYPE_TRACE",
    "Monitor",
    "MonitorClient",
    "MonitorEvent",
    "MonitorServer",
    "format_event",
]
