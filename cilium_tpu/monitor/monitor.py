"""Monitor core: bounded event ring with subscriber fan-out.

reference: monitor/monitor.go:106 (Monitor owning the perf reader and the
listener set) + pkg/monitor message types (messages.go MessageType*).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from ..utils import defaults

# Message types (reference: pkg/monitor/messages.go).
MSG_TYPE_DROP = 1
MSG_TYPE_DEBUG = 2
MSG_TYPE_CAPTURE = 3
MSG_TYPE_TRACE = 4
MSG_TYPE_POLICY_VERDICT = 5
MSG_TYPE_ACCESS_LOG = 6
MSG_TYPE_AGENT = 7
# Flight-recorder postmortem bundle (sidecar/blackbox.py): emitted on a
# fail-closed typestate transition so `cilium monitor` surfaces the
# incident without polling the timeline RPC.
MSG_TYPE_POSTMORTEM = 8

# Agent notification codes (reference: pkg/monitor AgentNotify*).
AGENT_NOTIFY_START = 2
AGENT_NOTIFY_ENDPOINT_REGENERATE_SUCCESS = 3
AGENT_NOTIFY_ENDPOINT_REGENERATE_FAIL = 4
AGENT_NOTIFY_POLICY_UPDATED = 5
AGENT_NOTIFY_POLICY_DELETED = 6
# Cluster-store degradation (fenced/unreachable kvstore): the agent
# keeps serving on cached identities and announces both edges.
AGENT_NOTIFY_KVSTORE_DEGRADED = 7
AGENT_NOTIFY_KVSTORE_RESTORED = 8


@dataclass
class MonitorEvent:
    type: int
    payload: dict
    timestamp: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "timestamp": self.timestamp,
            "payload": self.payload,
        }

    @staticmethod
    def from_dict(d: dict) -> "MonitorEvent":
        return MonitorEvent(
            type=d.get("type", 0),
            payload=d.get("payload", {}),
            timestamp=d.get("timestamp", 0.0),
        )


class _ListenerQueue:
    """Per-listener bounded queue + delivery thread: a slow or blocking
    listener loses ITS OWN events (counted) instead of stalling the
    publishing thread (reference: the per-CPU perf rings feeding each
    consumer independently, pkg/bpf/perf.go:341, and listener queues in
    monitor/listener1_2.go)."""

    def __init__(self, callback, maxlen: int) -> None:
        from collections import deque

        self.callback = callback
        self.lost = 0
        self._q: deque = deque()
        self.maxlen = maxlen
        self._cond = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="monitor-listener"
        )
        self._thread.start()

    def put(self, event: "MonitorEvent") -> None:
        with self._cond:
            if len(self._q) >= self.maxlen:
                self._q.popleft()
                self.lost += 1
            self._q.append(event)
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stopped:
                    self._cond.wait(timeout=0.5)
                if self._stopped and not self._q:
                    return
                event = self._q.popleft()
            try:
                self.callback(event)
            except Exception:  # noqa: BLE001 — a bad listener never
                pass  # stalls the stream

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify()


class Monitor:
    """Bounded ring + per-listener queued fan-out
    (reference: monitor/monitor.go).

    Lost events are counted, not blocked on — the perf-ring overflow
    behavior (monitor.go lost-event accounting); each listener has its
    own bounded queue so backpressure is per-consumer.
    """

    def __init__(self, queue_size: int = defaults.MONITOR_QUEUE_SIZE) -> None:
        self.queue_size = queue_size
        self._ring: list[MonitorEvent] = []
        # (callback, queue-or-None) pairs; removal is by == so bound
        # methods (a fresh object per attribute access) still match.
        self._listeners: list = []
        self._mutex = threading.RLock()
        self.events_seen = 0
        self.events_lost = 0

    def add_listener(self, listener: Callable[[MonitorEvent], None],
                     queued: bool = True) -> None:
        """``queued=False`` delivers synchronously on the publishing
        thread — for listeners that are already non-blocking (e.g. a
        put_nowait fan-out with its own per-subscriber queues)."""
        lq = _ListenerQueue(listener, self.queue_size) if queued else None
        with self._mutex:
            self._listeners.append((listener, lq))

    def remove_listener(self, listener) -> None:
        with self._mutex:
            lq = None
            for i, (cb, q) in enumerate(self._listeners):
                if cb == listener:
                    del self._listeners[i]
                    lq = q
                    break
            if lq is not None:
                # Keep the cumulative loss counter monotonic.
                self.events_lost += lq.lost
        if lq is not None:
            lq.stop()

    def notify(self, event: MonitorEvent) -> None:
        with self._mutex:
            self.events_seen += 1
            self._ring.append(event)
            if len(self._ring) > self.queue_size:
                overflow = len(self._ring) - self.queue_size
                self._ring = self._ring[overflow:]
                self.events_lost += overflow
            listeners = list(self._listeners)
        for cb, lq in listeners:
            if lq is not None:
                lq.put(event)
            else:
                try:
                    cb(event)
                except Exception:  # noqa: BLE001 — a bad listener never
                    pass  # stalls the stream

    # Convenience emitters -------------------------------------------------

    def send_agent_notification(self, code: int, text: str, **payload) -> None:
        """reference: daemon/daemon.go:1647 SendNotification."""
        self.notify(
            MonitorEvent(
                MSG_TYPE_AGENT, {"code": code, "text": text, **payload}
            )
        )

    def send_verdict(
        self, *, src_identity: int, dst_identity: int, dport: int, proto: int,
        allowed: bool, proxy_port: int = 0, l7: dict | None = None,
    ) -> None:
        """Policy verdict event from the datapath ops/batch engines."""
        self.notify(
            MonitorEvent(
                MSG_TYPE_POLICY_VERDICT if allowed else MSG_TYPE_DROP,
                {
                    "src_identity": src_identity,
                    "dst_identity": dst_identity,
                    "dport": dport,
                    "proto": proto,
                    "allowed": allowed,
                    "proxy_port": proxy_port,
                    **({"l7": l7} if l7 else {}),
                },
            )
        )

    def recent(self, n: int = 100) -> list[MonitorEvent]:
        with self._mutex:
            return self._ring[-n:]

    def status(self) -> dict:
        with self._mutex:
            return {
                "seen": self.events_seen,
                "lost": self.events_lost
                + sum(lq.lost for _, lq in self._listeners if lq is not None),
                "listeners": len(self._listeners),
                "queued": len(self._ring),
            }
