"""Monitor core: bounded event ring with subscriber fan-out.

reference: monitor/monitor.go:106 (Monitor owning the perf reader and the
listener set) + pkg/monitor message types (messages.go MessageType*).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from ..utils import defaults

# Message types (reference: pkg/monitor/messages.go).
MSG_TYPE_DROP = 1
MSG_TYPE_DEBUG = 2
MSG_TYPE_CAPTURE = 3
MSG_TYPE_TRACE = 4
MSG_TYPE_POLICY_VERDICT = 5
MSG_TYPE_ACCESS_LOG = 6
MSG_TYPE_AGENT = 7

# Agent notification codes (reference: pkg/monitor AgentNotify*).
AGENT_NOTIFY_START = 2
AGENT_NOTIFY_ENDPOINT_REGENERATE_SUCCESS = 3
AGENT_NOTIFY_ENDPOINT_REGENERATE_FAIL = 4
AGENT_NOTIFY_POLICY_UPDATED = 5
AGENT_NOTIFY_POLICY_DELETED = 6


@dataclass
class MonitorEvent:
    type: int
    payload: dict
    timestamp: float = field(default_factory=time.time)

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "timestamp": self.timestamp,
            "payload": self.payload,
        }

    @staticmethod
    def from_dict(d: dict) -> "MonitorEvent":
        return MonitorEvent(
            type=d.get("type", 0),
            payload=d.get("payload", {}),
            timestamp=d.get("timestamp", 0.0),
        )


class Monitor:
    """Bounded ring + listener fan-out (reference: monitor/monitor.go).

    Lost events are counted, not blocked on — the perf-ring overflow
    behavior (monitor.go lost-event accounting).
    """

    def __init__(self, queue_size: int = defaults.MONITOR_QUEUE_SIZE) -> None:
        self.queue_size = queue_size
        self._ring: list[MonitorEvent] = []
        self._listeners: list[Callable[[MonitorEvent], None]] = []
        self._mutex = threading.RLock()
        self.events_seen = 0
        self.events_lost = 0

    def add_listener(self, listener: Callable[[MonitorEvent], None]) -> None:
        with self._mutex:
            self._listeners.append(listener)

    def remove_listener(self, listener) -> None:
        with self._mutex:
            try:
                self._listeners.remove(listener)
            except ValueError:
                pass

    def notify(self, event: MonitorEvent) -> None:
        with self._mutex:
            self.events_seen += 1
            self._ring.append(event)
            if len(self._ring) > self.queue_size:
                overflow = len(self._ring) - self.queue_size
                self._ring = self._ring[overflow:]
                self.events_lost += overflow
            listeners = list(self._listeners)
        for l in listeners:
            try:
                l(event)
            except Exception:  # noqa: BLE001 — a bad listener never stalls
                pass  # the stream

    # Convenience emitters -------------------------------------------------

    def send_agent_notification(self, code: int, text: str, **payload) -> None:
        """reference: daemon/daemon.go:1647 SendNotification."""
        self.notify(
            MonitorEvent(
                MSG_TYPE_AGENT, {"code": code, "text": text, **payload}
            )
        )

    def send_verdict(
        self, *, src_identity: int, dst_identity: int, dport: int, proto: int,
        allowed: bool, proxy_port: int = 0, l7: dict | None = None,
    ) -> None:
        """Policy verdict event from the datapath ops/batch engines."""
        self.notify(
            MonitorEvent(
                MSG_TYPE_POLICY_VERDICT if allowed else MSG_TYPE_DROP,
                {
                    "src_identity": src_identity,
                    "dst_identity": dst_identity,
                    "dport": dport,
                    "proto": proto,
                    "allowed": allowed,
                    "proxy_port": proxy_port,
                    **({"l7": l7} if l7 else {}),
                },
            )
        )

    def recent(self, n: int = 100) -> list[MonitorEvent]:
        with self._mutex:
            return self._ring[-n:]

    def status(self) -> dict:
        with self._mutex:
            return {
                "seen": self.events_seen,
                "lost": self.events_lost,
                "listeners": len(self._listeners),
                "queued": len(self._ring),
            }
