"""Human-readable event formatting for the monitor CLI.

reference: pkg/monitor/{format,dissect}.go + cilium/cmd/monitor.go output.
"""

from __future__ import annotations

import time

from .monitor import (
    MSG_TYPE_ACCESS_LOG,
    MSG_TYPE_AGENT,
    MSG_TYPE_DEBUG,
    MSG_TYPE_DROP,
    MSG_TYPE_POLICY_VERDICT,
    MSG_TYPE_POSTMORTEM,
    MSG_TYPE_TRACE,
    MonitorEvent,
)

_PROTO = {6: "tcp", 17: "udp", 0: "any"}


def _rule_attribution(p: dict) -> str:
    """Render the deciding-rule fields a flow-record-fed event carries
    (flowlog/ring.py): ` rule=<row> (<match kind>) policy=<name>` —
    THE one rendering shared by the DROP and POLICY-VERDICT lines (an
    operator correlates one against the other)."""
    if "rule_id" not in p:
        return ""
    rule = p.get("rule_id", -1)
    out = ""
    if rule is not None and rule >= 0:
        kind = p.get("match_kind") or "?"
        out = f" rule={rule} ({kind})"
    if p.get("policy"):
        out += f" policy={p['policy']}"
    return out


def format_event(ev: MonitorEvent) -> str:
    ts = time.strftime("%H:%M:%S", time.localtime(ev.timestamp))
    p = ev.payload
    if ev.type == MSG_TYPE_DROP:
        return (
            f"{ts} DROP: identity {p.get('src_identity')} -> "
            f"{p.get('dst_identity')} dport {p.get('dport')}"
            f"/{_PROTO.get(p.get('proto'), p.get('proto'))}"
            + (f" ({p['l7']})" if p.get("l7") else "")
            + _rule_attribution(p)
        )
    if ev.type == MSG_TYPE_POLICY_VERDICT:
        redirect = (
            f" redirect :{p['proxy_port']}" if p.get("proxy_port") else ""
        )
        word = "ALLOW" if p.get("allowed", True) else "DENY"
        return (
            f"{ts} POLICY-VERDICT: {word} identity "
            f"{p.get('src_identity')} -> "
            f"{p.get('dst_identity')} dport {p.get('dport')}"
            f"/{_PROTO.get(p.get('proto'), p.get('proto'))}{redirect}"
            + (f" ({p['l7']})" if p.get("l7") else "")
            + _rule_attribution(p)
        )
    if ev.type == MSG_TYPE_AGENT:
        return f"{ts} AGENT: {p.get('text', '')}"
    if ev.type == MSG_TYPE_ACCESS_LOG:
        return (
            f"{ts} L7: {p.get('verdict', '?')} "
            f"{p.get('l7_protocol', '?')} {p.get('info', '')}"
        )
    if ev.type == MSG_TYPE_TRACE:
        sv = p.get("slow_verdict") if isinstance(p, dict) else None
        if sv:
            # Slow-verdict exemplar from the sidecar latency tracer
            # (sidecar/trace.py): name the request and where its time
            # went, largest stage first.
            from ..sidecar.trace import format_stages_us

            stages = format_stages_us(sv.get("stages_us", {}))
            reason = f" reason={sv['reason']}" if sv.get("reason") else ""
            return (
                f"{ts} SLOW-VERDICT: path={sv.get('path', '?')} "
                f"seq={sv.get('seq')} conn={sv.get('conn_id')} "
                f"n={sv.get('entries')} "
                f"e2e={sv.get('e2e_us', 0) / 1e3:.2f}ms{reason} {stages}"
            )
        return f"{ts} TRACE: {p}"
    if ev.type == MSG_TYPE_POSTMORTEM:
        # Flight-recorder bundle (sidecar/blackbox.py): the fail-closed
        # edge that fired it, how deep the captured ring is, and where
        # the full bundle landed (if a bundle_dir was configured).
        reason = f" reason={p['reason']}" if p.get("reason") else ""
        path = f" bundle={p['path']}" if p.get("path") else ""
        return (
            f"{ts} POSTMORTEM: trigger={p.get('trigger', '?')} "
            f"seq={p.get('seq')} events={p.get('events')}{reason}{path}"
        )
    if ev.type == MSG_TYPE_DEBUG:
        return f"{ts} DEBUG: {p}"
    return f"{ts} UNKNOWN({ev.type}): {p}"
