"""Monitor unix-socket pub/sub, both listener protocol versions.

reference: monitor/listener1_2.go + listener1_0.go — the node monitor
serves BOTH protocol generations simultaneously on sibling sockets so
old and new consumers coexist across upgrades:

- **1.2** (``<path>``): 4-byte big-endian length + JSON event — the
  payload framing (reference: listener1_2.go gob payload.Payload).
- **1.0** (``<path>.1_0``): newline-delimited JSON, one event per line
  — the legacy framing analog (reference: listener1_0.go raw encoding).

Slow subscribers drop events rather than stalling the stream on either
version.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import struct
import threading
from typing import Callable, Optional

from ..utils.logging import get_logger
from ..utils.sockutil import shutdown_close
from .monitor import Monitor, MonitorEvent

log = get_logger("monitor-server")


class _Subscriber:
    def __init__(self, conn: socket.socket, version: str = "1.2") -> None:
        self.conn = conn
        self.version = version
        self.queue: "queue.Queue[MonitorEvent]" = queue.Queue(maxsize=4096)
        self.lost = 0


class MonitorServer:
    """reference: monitor/monitor.go serve loop + listener registry."""

    def __init__(self, monitor: Monitor, path: str) -> None:
        self.monitor = monitor
        self.path = path
        self.path_1_0 = path + ".1_0"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._socks: dict[str, socket.socket] = {}
        for p, version in ((path, "1.2"), (self.path_1_0, "1.0")):
            if os.path.exists(p):
                os.unlink(p)
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.bind(p)
            s.listen(16)
            self._socks[version] = s
        self._subs: list[_Subscriber] = []
        self._mutex = threading.Lock()
        self._stop = threading.Event()
        # Direct (unqueued) delivery: _fan_out only put_nowaits into
        # per-subscriber bounded queues, so the per-listener queue layer
        # would just double-buffer and hide subscriber loss accounting.
        monitor.add_listener(self._fan_out, queued=False)
        for version, s in self._socks.items():
            threading.Thread(
                target=self._accept_loop, args=(s, version),
                name=f"monitor-server-{version}", daemon=True,
            ).start()

    def _fan_out(self, ev: MonitorEvent) -> None:
        with self._mutex:
            subs = list(self._subs)
        for s in subs:
            try:
                s.queue.put_nowait(ev)
            except queue.Full:
                s.lost += 1  # slow subscriber: drop, don't stall

    def _accept_loop(self, sock: socket.socket, version: str) -> None:
        while not self._stop.is_set():
            try:
                sock.settimeout(0.2)
                conn, _ = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sub = _Subscriber(conn, version=version)
            with self._mutex:
                self._subs.append(sub)
            threading.Thread(
                target=self._send_loop, args=(sub,), daemon=True
            ).start()

    def _send_loop(self, sub: _Subscriber) -> None:
        try:
            while not self._stop.is_set():
                try:
                    ev = sub.queue.get(timeout=0.2)
                except queue.Empty:
                    continue
                data = json.dumps(ev.to_dict()).encode()
                if sub.version == "1.0":
                    sub.conn.sendall(data + b"\n")
                else:
                    sub.conn.sendall(struct.pack(">I", len(data)) + data)
        except OSError:
            pass
        finally:
            with self._mutex:
                try:
                    self._subs.remove(sub)
                except ValueError:
                    pass
            shutdown_close(sub.conn)

    def subscriber_count(self) -> int:
        with self._mutex:
            return len(self._subs)

    def close(self) -> None:
        self._stop.set()
        # shutdown-then-close: a bare close while an accept thread
        # holds the fd defers the kernel teardown, and the listener
        # keeps accepting into a dead server until the thread's next
        # timeout tick (the PR 2 zombie-service bug class).
        for s in self._socks.values():
            shutdown_close(s)
        for p in (self.path, self.path_1_0):
            if os.path.exists(p):
                os.unlink(p)


class MonitorClient:
    """Subscriber side (the `monitor` CLI command's transport).

    ``version="1.0"`` dials the legacy line-framed socket (the path the
    server exposes as ``<path>.1_0``); default is the 1.2 payload
    framing."""

    def __init__(self, path: str, version: str = "1.2") -> None:
        self.version = version
        if version == "1.0" and not path.endswith(".1_0"):
            path = path + ".1_0"
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.connect(path)
        self._linebuf = b""

    def next_event(self, timeout: float | None = None) -> Optional[MonitorEvent]:
        self._sock.settimeout(timeout)
        try:
            if self.version == "1.0":
                while b"\n" not in self._linebuf:
                    chunk = self._sock.recv(65536)
                    if not chunk:
                        return None
                    self._linebuf += chunk
                line, self._linebuf = self._linebuf.split(b"\n", 1)
                return MonitorEvent.from_dict(json.loads(line.decode()))
            hdr = self._recv_exact(4)
            if hdr is None:
                return None
            (n,) = struct.unpack(">I", hdr)
            body = self._recv_exact(n)
            if body is None:
                return None
            return MonitorEvent.from_dict(json.loads(body.decode()))
        except socket.timeout:
            return None

    def _recv_exact(self, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def close(self) -> None:
        # shutdown first: a consumer thread blocked in next_event's
        # recv holds the fd, so a bare close would never wake it — the
        # reader lingered to process exit (the sidecar-client PR 2 bug,
        # here on the monitor consumer side).  After shutdown the recv
        # returns b"" and next_event reports end-of-stream with None.
        shutdown_close(self._sock)
