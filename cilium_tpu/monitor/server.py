"""Monitor unix-socket pub/sub.

reference: monitor/listener1_2.go — subscribers connect to the monitor
socket and receive every event; slow subscribers drop events rather than
stalling the stream.  Framing: 4-byte big-endian length + JSON event.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import struct
import threading
from typing import Callable, Optional

from ..utils.logging import get_logger
from .monitor import Monitor, MonitorEvent

log = get_logger("monitor-server")


class _Subscriber:
    def __init__(self, conn: socket.socket) -> None:
        self.conn = conn
        self.queue: "queue.Queue[MonitorEvent]" = queue.Queue(maxsize=4096)
        self.lost = 0


class MonitorServer:
    """reference: monitor/monitor.go serve loop + listener registry."""

    def __init__(self, monitor: Monitor, path: str) -> None:
        self.monitor = monitor
        self.path = path
        if os.path.exists(path):
            os.unlink(path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(path)
        self._sock.listen(16)
        self._subs: list[_Subscriber] = []
        self._mutex = threading.Lock()
        self._stop = threading.Event()
        # Direct (unqueued) delivery: _fan_out only put_nowaits into
        # per-subscriber bounded queues, so the per-listener queue layer
        # would just double-buffer and hide subscriber loss accounting.
        monitor.add_listener(self._fan_out, queued=False)
        threading.Thread(
            target=self._accept_loop, name="monitor-server", daemon=True
        ).start()

    def _fan_out(self, ev: MonitorEvent) -> None:
        with self._mutex:
            subs = list(self._subs)
        for s in subs:
            try:
                s.queue.put_nowait(ev)
            except queue.Full:
                s.lost += 1  # slow subscriber: drop, don't stall

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sub = _Subscriber(conn)
            with self._mutex:
                self._subs.append(sub)
            threading.Thread(
                target=self._send_loop, args=(sub,), daemon=True
            ).start()

    def _send_loop(self, sub: _Subscriber) -> None:
        try:
            while not self._stop.is_set():
                try:
                    ev = sub.queue.get(timeout=0.2)
                except queue.Empty:
                    continue
                data = json.dumps(ev.to_dict()).encode()
                sub.conn.sendall(struct.pack(">I", len(data)) + data)
        except OSError:
            pass
        finally:
            with self._mutex:
                try:
                    self._subs.remove(sub)
                except ValueError:
                    pass
            try:
                sub.conn.close()
            except OSError:
                pass

    def subscriber_count(self) -> int:
        with self._mutex:
            return len(self._subs)

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        finally:
            if os.path.exists(self.path):
                os.unlink(self.path)


class MonitorClient:
    """Subscriber side (the `monitor` CLI command's transport)."""

    def __init__(self, path: str) -> None:
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.connect(path)

    def next_event(self, timeout: float | None = None) -> Optional[MonitorEvent]:
        self._sock.settimeout(timeout)
        try:
            hdr = self._recv_exact(4)
            if hdr is None:
                return None
            (n,) = struct.unpack(">I", hdr)
            body = self._recv_exact(n)
            if body is None:
                return None
            return MonitorEvent.from_dict(json.loads(body.decode()))
        except socket.timeout:
            return None

    def _recv_exact(self, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
