"""Flow-batching engine: buffered streams -> device batches -> filter ops.

Maps the streaming OnData contract (reference: proxylib/proxylib/
connection.go:118-174) onto fixed-shape device dispatch:

- each flow keeps a byte buffer (the datapath's retained-data buffer in the
  reference, see parserfactory.go:34-40)
- one engine step packs the first unconsumed frame of every active flow
  into a [F, L] batch, runs the model once, and converts per-flow verdicts
  into (PASS n | DROP n + inject) ops, consuming the frame
- flows whose buffer holds no complete frame get MORE (retain bytes)
- steps repeat until no flow has a complete frame (multi-frame buffers
  drain across steps, preserving per-flow op order)

Verdict-op mapping is the r2d2 parser's (reference: r2d2parser.go:188-213):
allow -> PASS msg_len; deny -> inject b"ERROR\\r\\n" into the reply
direction + DROP msg_len; reply direction always passes.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import numpy as np

from ..models.base import ConstVerdict
from ..policy.invariance import InvariantClaimEngine
from ..proxylib.accesslog import EntryType, LogEntry
from ..proxylib.types import DROP, ERROR, MORE, PASS, OpError, OpType
from ..utils import flowdebug

# Per-flow debug stream, flowdebug-gated (one boolean when disabled).
_flow_log = logging.getLogger("cilium_tpu.runtime.flow")


@dataclass
class FlowState:
    flow_id: int
    remote_id: int
    policy_name: str = ""
    ingress: bool = True
    dst_id: int = 0
    src_addr: str = ""
    dst_addr: str = ""
    buffer: bytearray = field(default_factory=bytearray)
    ops: list[tuple[OpType, int]] = field(default_factory=list)
    reply_inject: bytearray = field(default_factory=bytearray)
    # Mirrors the streaming path's caller-owned inject buffer capacity
    # (reference: connection.go:190-209): injected bytes beyond this are
    # truncated, never buffered unboundedly.
    inject_capacity: int = 1024
    # Set when the flow exceeded the retained-bytes cap: the buffer was
    # dropped with a typed protocol-error op sequence and the flow is
    # dead (the caller closes the connection on the ERROR result).
    overflowed: bool = False
    # Rule attribution of the most recent device verdict on this flow
    # (flattened first-match row, -1 = denied/unattributed) — read by
    # the service's flow-record emission for pump-path entries.
    last_rule_id: int = -1


class R2d2BatchEngine(InvariantClaimEngine):
    """Batch engine for the r2d2 model (the flagship end-to-end slice).

    Framing is parameterized through four class hooks so length-
    prefixed families (runtime/dnsengine.DnsBatchEngine) reuse the
    whole feed/feed_extract/settle_entry/pump machinery:
    ``_frame_split`` (first complete frame length), ``_frame_msg``
    (the judged/logged message slice), ``frame_row`` (the device-row
    bytes the async slow path reconstructs from a settled message),
    and ``DENY_INJECT`` (the per-denied-frame reply inject)."""

    proto = "r2d2"

    # Reply bytes injected per denied frame (byte-exact with the
    # streaming oracle; reference: r2d2parser.go:211).
    DENY_INJECT = b"ERROR\r\n"

    # Columnar feed contract (sidecar/reasm.py): the service's
    # reassembler may own this engine's carry state in its byte arena
    # and judge whole rounds of frames columnar — the scalar
    # feed/feed_extract/settle_entry path below stays the oracle/
    # fallback rung and must never drift from it.
    reasm_columnar = True

    @staticmethod
    def reasm_spec() -> str:
        """Framing kind of the columnar feed contract
        (reasm.FRAMINGS): r2d2 frames on the first CRLF."""
        return "crlf"

    @staticmethod
    def _frame_split(buf) -> int:
        """Length of the first COMPLETE frame in ``buf`` (delimiter/
        header included), or -1."""
        idx = buf.find(b"\r\n")
        return -1 if idx < 0 else idx + 2

    @staticmethod
    def _frame_msg(buf, msg_len: int) -> bytes:
        """The message slice judged/logged for one complete frame
        (r2d2: the line without its CRLF)."""
        return bytes(buf[: msg_len - 2])

    @staticmethod
    def frame_row(msg: bytes) -> bytes:
        """Reconstruct the device-row bytes from a ``feed_extract``
        message (the async slow path packs judged frames from these)."""
        return msg + b"\r\n"

    def __init__(self, model, capacity: int = 2048, width: int = 256,
                 logger=None, max_buffer: int = 1 << 20,
                 attr_enabled: bool = True):
        self.model = model
        self.capacity = capacity
        self.width = width
        self.logger = logger
        # Rule attribution gate: False (flow_observe off) keeps the
        # pump on the PLAIN model call — no argmax, no extra readback
        # (the flow_observe_overhead bench's disabled baseline).
        self.attr_enabled = attr_enabled
        # Per-flow retained-bytes cap: a flow that buffers more than
        # this without a frame delimiter is dropped with a typed
        # protocol-error (bounded retained-data contract; the streaming
        # reference bounds its buffer the same way).  0 = unbounded.
        self.max_buffer = max_buffer
        self.buffer_overflows = 0
        self.flows: dict[int, FlowState] = {}

    def flow(
        self,
        flow_id: int,
        remote_id: int,
        policy_name: str = "",
        ingress: bool = True,
        dst_id: int = 0,
        src_addr: str = "",
        dst_addr: str = "",
    ) -> FlowState:
        st = self.flows.get(flow_id)
        if st is None:
            st = FlowState(
                flow_id=flow_id,
                remote_id=remote_id,
                policy_name=policy_name,
                ingress=ingress,
                dst_id=dst_id,
                src_addr=src_addr,
                dst_addr=dst_addr,
            )
            self.flows[flow_id] = st
        return st

    def _overflow(self, st: FlowState, incoming: int) -> None:
        """Enforce the retained-bytes cap: drop everything buffered plus
        the incoming bytes with a typed protocol-error op pair — the
        shim consumes the DROP then surfaces PARSER_ERROR on the ERROR
        op and closes the connection.  Nothing is silently retained."""
        dropped = len(st.buffer) + incoming
        st.buffer.clear()
        st.overflowed = True
        self.buffer_overflows += 1
        st.ops.append((DROP, dropped))
        st.ops.append((ERROR, int(OpError.ERROR_INVALID_FRAME_LENGTH)))

    def feed(self, flow_id: int, data: bytes, remote_id: int = 0, policy_name: str = "", **flow_kwargs) -> None:
        st = self.flow(flow_id, remote_id, policy_name, **flow_kwargs)
        if st.overflowed:
            if not st.ops:  # dead flow: every further feed errors out
                st.ops.append(
                    (ERROR, int(OpError.ERROR_INVALID_FRAME_LENGTH))
                )
            return
        if self.max_buffer and len(st.buffer) + len(data) > self.max_buffer:
            self._overflow(st, len(data))
            return
        st.buffer += data

    # -- async round API (one readback per round) --------------------------
    #
    # CRLF framing is host-knowable, so frame extraction never needs the
    # device — only the per-frame allow verdict does.  The service feeds
    # every slow entry of a round through feed_extract, judges ALL
    # extracted frames in one model call, and emits ops at completion
    # time; the wave path's one-readback-per-pump (a ~100ms link RTT on
    # the tunneled bench chip) collapses to one readback per round.

    def feed_extract(
        self, flow_id: int, data: bytes, remote_id: int = 0,
        policy_name: str = "", **flow_kwargs,
    ) -> list[tuple[bytes, int]]:
        """Append data and drain every now-complete frame host-side.
        Returns [(msg_bytes, msg_len)] completed by THIS feed, in
        stream order.  Ops are NOT emitted here — the caller judges the
        frames (batched across flows) and settles each entry with
        settle_entry, which keeps MORE parity with pump()."""
        st = self.flows.get(flow_id)  # fast path: metadata kwargs only
        if st is None:  # matter at creation
            st = self.flow(flow_id, remote_id, policy_name, **flow_kwargs)
        if st.overflowed:
            if not st.ops:
                st.ops.append(
                    (ERROR, int(OpError.ERROR_INVALID_FRAME_LENGTH))
                )
            return []
        if self.max_buffer and len(st.buffer) + len(data) > self.max_buffer:
            self._overflow(st, len(data))
            return []
        st.buffer += data
        frames: list[tuple[bytes, int]] = []
        while True:
            msg_len = self._frame_split(st.buffer)
            if msg_len < 0:
                break
            frames.append((self._frame_msg(st.buffer, msg_len), msg_len))
            del st.buffer[:msg_len]
        return frames

    def adopt_residue(self, flow_id: int, data: bytes, overflowed: bool,
                      remote_id: int = 0, policy_name: str = "",
                      **flow_kwargs) -> None:
        """Lane-exit half of the columnar feed contract: the service's
        reassembler hands back a conn's arena carry (and its
        dead/overflowed latch) when the conn leaves the columnar lane,
        so the scalar feed/pump path resumes from exactly the retained
        bytes — no byte lost or replayed across the transition."""
        st = self.flow(flow_id, remote_id, policy_name, **flow_kwargs)
        if data:
            st.buffer = bytearray(data) + st.buffer
        st.overflowed = st.overflowed or overflowed

    def settle_entry(self, flow_id: int, frames: list, more: bool):
        """The finish half of one async entry in ONE dict lookup (the
        per-entry hot path — three separate emit/finish/take calls
        measured ~10µs/entry): emit ops for the entry's judged frames,
        append the trailing MORE, and drain.  ``frames`` is
        [(msg, msg_len, allow)]; ``more`` is the caller's decision
        CAPTURED AT FEED TIME (frames completed or residue left), so a
        later round draining the buffer cannot retroactively change
        this entry's ops.  Returns (ops, inject) exactly as take_ops
        would."""
        st = self.flows[flow_id]
        for frame in frames:
            # (msg, msg_len, allow) or (msg, msg_len, allow, rule) —
            # the attributed variant stamps the deciding rule row.
            msg, msg_len, allow = frame[0], frame[1], frame[2]
            st.last_rule_id = frame[3] if len(frame) > 3 else -1
            self._emit(st, msg, allow, msg_len, drain=False)
        if more and (not st.ops or st.ops[-1][0] != MORE):
            st.ops.append((MORE, 1))
        ops, inject = st.ops, bytes(st.reply_inject)
        st.ops = []
        st.reply_inject = bytearray()
        return ops, inject

    def pump(self) -> None:
        """Run device steps until no flow has a complete frame; appends ops
        to each flow's op list."""
        ops_before = {fid: len(st.ops) for fid, st in self.flows.items()}
        while self._step():
            pass
        # The streaming parser is re-invoked on the remainder after every
        # PASS/DROP and answers MORE 1 when no CRLF is left (reference:
        # r2d2parser.go:158-161) — flows that saw activity or still hold
        # bytes end the round with MORE 1 for op-sequence parity.
        for fid, st in self.flows.items():
            if st.overflowed:
                continue  # ops already end in the typed error pair
            grew = len(st.ops) > ops_before.get(fid, 0)
            if (st.buffer or grew) and (not st.ops or st.ops[-1][0] != MORE):
                st.ops.append((MORE, 1))

    def _step(self) -> bool:
        # Group flows with a complete frame by the batch width needed to
        # hold it (power-of-two buckets >= the configured width), so frames
        # longer than the default width still get verdicts instead of
        # buffering forever — the streaming parser sees its whole buffer
        # (reference: r2d2parser.go:154 joins all buffered data).
        buckets: dict[int, list[FlowState]] = {}
        for st in self.flows.values():
            msg_len = self._frame_split(st.buffer)
            if msg_len < 0:
                continue
            w = self.width
            while msg_len > w:
                w *= 2
            buckets.setdefault(w, []).append(st)
        if not buckets:
            return False
        any_work = False
        for w, active in sorted(buckets.items()):
            for chunk_start in range(0, len(active), self.capacity):
                chunk = active[chunk_start : chunk_start + self.capacity]
                any_work |= self._run_chunk(chunk, w)
        return any_work

    def _run_chunk(self, chunk: list[FlowState], width: int | None = None) -> bool:
        width = width or self.width
        f = len(chunk)
        if isinstance(self.model, ConstVerdict):
            for st in chunk:
                msg_len = self._frame_split(st.buffer)
                self._emit(
                    st, self._frame_msg(st.buffer, msg_len),
                    bool(self.model.allow), msg_len,
                )
            return True

        # Pad the flow axis to a power of two so the jitted model sees a
        # small fixed set of shapes instead of recompiling per chunk size;
        # padding rows have length 0 -> incomplete -> ignored on emit.
        f_pad = 1
        while f_pad < f:
            f_pad *= 2
        data = np.zeros((f_pad, width), dtype=np.uint8)
        lengths = np.zeros((f_pad,), dtype=np.int32)
        remotes = np.zeros((f_pad,), dtype=np.int32)
        for i, st in enumerate(chunk):
            n = min(len(st.buffer), width)
            data[i, :n] = np.frombuffer(bytes(st.buffer[:n]), dtype=np.uint8)
            lengths[i] = n
            remotes[i] = st.remote_id

        attr = (
            getattr(self.model, "verdicts_attr", None)
            if self.attr_enabled else None
        )
        if attr is not None:
            complete, msg_len, allow, rule = attr(data, lengths, remotes)
            rule = np.asarray(rule)
        else:
            complete, msg_len, allow = self.model(data, lengths, remotes)
            rule = None
        complete = np.asarray(complete)
        msg_len = np.asarray(msg_len)
        allow = np.asarray(allow)

        for i, st in enumerate(chunk):
            if not complete[i]:
                continue
            n = int(msg_len[i])
            st.last_rule_id = int(rule[i]) if rule is not None else -1
            self._emit(st, self._frame_msg(st.buffer, n), bool(allow[i]), n)
        return True

    def _log_frame(self, st: FlowState, msg: bytes, allow: bool) -> None:
        """Access-log hook for one judged frame (protocol-specific
        field extraction; overridden by non-r2d2 subclasses)."""
        fields = msg.decode("utf-8", "surrogateescape").split(" ")
        file_ = fields[1] if len(fields) == 2 else ""
        self.logger.log(
            LogEntry(
                is_ingress=st.ingress,
                entry_type=EntryType.Request if allow else EntryType.Denied,
                policy_name=st.policy_name,
                source_security_id=st.remote_id,
                destination_security_id=st.dst_id,
                source_address=st.src_addr,
                destination_address=st.dst_addr,
                proto=self.proto,
                fields={"cmd": fields[0] if fields else "", "file": file_},
            )
        )

    def _emit(self, st: FlowState, msg: bytes, allow: bool, msg_len: int,
              drain: bool = True) -> None:
        flowdebug.log(
            _flow_log, "flow %d %s %s n=%d rule=%d",
            st.flow_id, self.proto, "PASS" if allow else "DROP", msg_len,
            st.last_rule_id,
        )
        if self.logger is not None:
            self._log_frame(st, msg, allow)
        if allow:
            st.ops.append((PASS, msg_len))
        else:
            room = st.inject_capacity - len(st.reply_inject)
            st.reply_inject += self.DENY_INJECT[: max(room, 0)]
            st.ops.append((DROP, msg_len))
        if drain:
            del st.buffer[:msg_len]

    def take_ops(self, flow_id: int) -> tuple[list[tuple[OpType, int]], bytes]:
        st = self.flows[flow_id]
        ops, inject = st.ops, bytes(st.reply_inject)
        st.ops = []
        st.reply_inject = bytearray()
        return ops, inject

    def close_flow(self, flow_id: int) -> None:
        """Drop a closed connection's flow state (same contract as the
        l7/device-assisted engines — close_connection calls this on
        whichever engine is bound, and a conn churned onto an r2d2
        engine must not crash the round that closes it)."""
        self.flows.pop(flow_id, None)
