"""Host-side runtime: batching engine, sidecar service, access-log plumbing.

The reference's per-connection hot loop lives in Envoy's GoFilter::OnIO
(reference: envoy/cilium_proxylib.cc:125-260) which calls into proxylib
per connection.  Here the runtime instead aggregates many connections'
buffered frames into fixed-shape [flows, bytes] batches, dispatches one
device computation, and fans the verdicts back out into per-connection op
lists that honor the same PASS/DROP/INJECT/MORE contract.
"""
