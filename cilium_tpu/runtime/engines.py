"""Per-protocol runtime batch engines and the redirect -> engine factory.

The runtime analog of the reference's proxy dispatch (reference:
pkg/proxy/proxy.go:229-236 — HTTP and proxylib protocols to Envoy, Kafka
to the Go proxy): every redirect gets an engine that buffers flow bytes,
frames complete requests, runs the batched device verdict model, and
converts verdicts into filter ops (PASS/DROP + inject), preserving the
OnIO contract (reference: envoy/cilium_proxylib.cc:125).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field

import numpy as np

from ..accesslog import (
    HttpLogEntry,
    KafkaLogEntry,
    LogRecord,
    VERDICT_DENIED,
    VERDICT_FORWARDED,
)
from ..kafka import matches_rule, parse_request
from ..kafka.request import KafkaParseError, frame_length
from ..models.base import ConstVerdict
from ..models.builder import build_model_for_filter
from ..models.kafka import encode_requests
from ..policy.invariance import InvariantClaimEngine
from ..policy.l4 import PARSER_TYPE_HTTP, PARSER_TYPE_KAFKA
from ..proxylib.types import DROP, MORE, PASS, OpType
from ..utils import flowdebug, metrics

log = logging.getLogger(__name__)
# Per-flow debug stream, flowdebug-gated (one boolean when disabled).
_flow_log = logging.getLogger("cilium_tpu.runtime.flow")

# Shared with the streaming parser so both HTTP paths inject the
# reference's exact denial (envoy/cilium_l7policy.cc:91).
from ..proxylib.parsers.http import HTTP_403  # noqa: E402


@dataclass
class EngineFlow:
    flow_id: int
    remote_id: int
    dst_id: int = 0
    ingress: bool = True
    buffer: bytearray = field(default_factory=bytearray)
    ops: list[tuple[OpType, int]] = field(default_factory=list)
    reply_inject: bytearray = field(default_factory=bytearray)
    inject_capacity: int = 4096
    # Set when the engine decides the connection must die (unparseable
    # framing); every subsequent byte drops without re-parsing
    # (reference: the kafka proxy closes the connection on parse errors,
    # pkg/proxy/kafka.go handleRequest error path).
    closed: bool = False


class BaseBatchEngine(InvariantClaimEngine):
    """Shared flow/buffer management (the OnIO byte accounting)."""

    proto = ""

    def __init__(self, capacity: int = 2048, logger=None, monitor=None,
                 flowlog=None):
        self.capacity = capacity
        self.logger = logger
        self.monitor = monitor
        # Flow-record sink (flowlog/ring.py): subclasses emit ONE
        # columnar round per _step — never per-request appends.
        self.flowlog = flowlog
        self.flows: dict[int, EngineFlow] = {}

    def flow(self, flow_id: int, remote_id: int = 0, **kw) -> EngineFlow:
        st = self.flows.get(flow_id)
        if st is None:
            st = EngineFlow(flow_id=flow_id, remote_id=remote_id, **kw)
            self.flows[flow_id] = st
        return st

    def _judge_dispatch(self, call):
        """Mesh rung for the daemon-side engines (the sidecar service
        has its own in _mesh_guarded): a raising SHARDED dispatch
        flips this engine to the wrapper's single-chip fallback —
        typed mesh_demotions_total{engine-judge} — and reissues the
        round on it, never a crashed step.  Single-chip models have
        no fallback and re-raise unchanged."""
        try:
            return call(self.model)
        except Exception:
            fb = getattr(self.model, "fallback", None)
            if fb is None:
                raise
            log.exception(
                "sharded judge failed; engine demoted to single-chip"
            )
            metrics.MeshDemotions.inc("engine-judge")
            self.model = fb
            return call(fb)

    def feed(self, flow_id: int, data: bytes, remote_id: int = 0, **kw) -> None:
        self.flow(flow_id, remote_id, **kw).buffer += data

    def close_flow(self, flow_id: int) -> None:
        self.flows.pop(flow_id, None)

    def take_ops(self, flow_id: int):
        st = self.flows[flow_id]
        ops, inject = st.ops, bytes(st.reply_inject)
        st.ops = []
        st.reply_inject = bytearray()
        return ops, inject

    def pump(self) -> None:
        while self._step():
            pass
        for st in self.flows.values():
            if st.buffer and (not st.ops or st.ops[-1][0] != MORE):
                st.ops.append((MORE, 1))

    # to implement: _step() -> bool

    def _record_round(self, entries: list, kinds: tuple = ()) -> None:
        """One flow-record batch per engine step; ``entries`` is
        [(flow_id, allow, rule)] built by the step's hot loop."""
        if self.flowlog is None or not entries:
            return
        from ..flowlog import CODE_DENIED, CODE_FORWARDED, PATH_ENGINE

        self.flowlog.add_entries(
            PATH_ENGINE,
            [
                (fid, CODE_FORWARDED if allow else CODE_DENIED, rule)
                for fid, allow, rule in entries
            ],
            kinds=kinds,
        )

    def _emit(self, st: EngineFlow, allow: bool, n: int,
              inject: bytes = b"", record: LogRecord | None = None) -> None:
        flowdebug.log(
            _flow_log, "flow %d %s %s n=%d",
            st.flow_id, self.proto or type(self).__name__,
            "PASS" if allow else "DROP", n,
        )
        if allow:
            st.ops.append((PASS, n))
        else:
            room = st.inject_capacity - len(st.reply_inject)
            st.reply_inject += inject[: max(room, 0)]
            st.ops.append((DROP, n))
        del st.buffer[:n]
        if record is not None and self.logger is not None:
            record.verdict = VERDICT_FORWARDED if allow else VERDICT_DENIED
            record.source.identity = st.remote_id
            record.destination.identity = st.dst_id
            self.logger.log(record)


class HttpBatchEngine(BaseBatchEngine):
    """HTTP request-head framing + device verdicts + 403 injection
    (reference: envoy/cilium_l7policy.cc request path)."""

    proto = "http"

    # Fixed width/row buckets: padded shapes are drawn from these sets
    # so XLA compiles each (width, rows) pair once — one oversized head
    # must not widen (and recompile) the whole batch.
    MIN_WIDTH = 512
    MAX_WIDTH = 1 << 15  # heads beyond this are judged as DENY (absurd)
    MIN_ROWS = 64

    def __init__(self, model, cache_enabled: bool = False, **kw):
        super().__init__(**kw)
        self.model = model
        # Verdict-cache offload tier (gated — cache-off is the true
        # baseline): heads of an identity whose claim is byte-invariant
        # are judged host-side with the claimed rule row, never encoded
        # into the device batch.
        self.cache_enabled = cache_enabled

    def _width_bucket(self, head_len: int) -> int:
        w = self.MIN_WIDTH
        while w < head_len:
            w *= 2
        return w

    def prewarm(self, widths: tuple[int, ...] = (512, 1024)) -> None:
        """Compile the model for the common bucket shapes up front so
        first requests never pay a compile."""
        if isinstance(self.model, ConstVerdict):
            return
        for w in widths:
            out = self.model(
                np.zeros((self.MIN_ROWS, w), np.uint8),
                np.zeros((self.MIN_ROWS,), np.int32),
                np.zeros((self.MIN_ROWS,), np.int32),
            )
            np.asarray(out[-1])

    def _head_and_body_len(self, buf: bytes) -> tuple[int, int] | None:
        # One framing implementation for both HTTP paths (streaming
        # parser + this engine) so fixes cannot diverge.
        from ..proxylib.parsers.http import head_and_body_len

        return head_and_body_len(buf)

    def _step(self) -> bool:
        active: list[tuple[EngineFlow, int, int]] = []
        for st in self.flows.values():
            r = self._head_and_body_len(bytes(st.buffer))
            if r is not None:
                active.append((st, r[0], r[1]))
        if not active:
            return False
        active = active[: self.capacity]

        if isinstance(self.model, ConstVerdict):
            for st, head_len, body_len in active:
                self._emit_http(st, bool(self.model.allow), head_len, body_len)
            self._record_round(
                [(st.flow_id, bool(self.model.allow), -1)
                 for st, _, _ in active]
            )
            return True

        recs: list[tuple[int, bool, int]] = []
        # Group flows into per-width buckets so one oversized head does
        # not force a wide (and freshly compiled) scan for everyone.
        buckets: dict[int, list[tuple[EngineFlow, int, int]]] = {}
        cache_hits = 0
        for st, head_len, body_len in active:
            if head_len > self.MAX_WIDTH:
                # Pathological request head: deny without a device pass.
                self._emit_http(st, False, head_len, body_len)
                recs.append((st.flow_id, False, -1))
                continue
            if self.cache_enabled:
                claim = self.verdict_invariant(st.remote_id)
                if claim is not None and claim[0]:
                    # Byte-invariant allow: the verdict AND the
                    # first-match row are independent of the head's
                    # bytes — judged host-side, no device row (the
                    # verdict-cache offload tier; deny claims keep the
                    # normal path so per-frame 403 injection framing
                    # is never skipped).
                    self._emit_http(st, True, head_len, body_len)
                    recs.append((st.flow_id, True, claim[1]))
                    cache_hits += 1
                    continue
            buckets.setdefault(
                self._width_bucket(head_len), []
            ).append((st, head_len, body_len))
        for width, group in sorted(buckets.items()):
            f_pad = self.MIN_ROWS
            while f_pad < len(group):
                f_pad *= 2
            data = np.zeros((f_pad, width), np.uint8)
            lengths = np.zeros((f_pad,), np.int32)
            remotes = np.zeros((f_pad,), np.int32)
            for i, (st, head_len, _) in enumerate(group):
                data[i, :head_len] = np.frombuffer(
                    bytes(st.buffer[:head_len]), np.uint8
                )
                lengths[i] = head_len
                remotes[i] = st.remote_id
            # Attribution only when a record sink is wired: without a
            # flowlog the rule index would be computed, read back, and
            # dropped (the flow_observe=False cost contract).
            if self.flowlog is not None:
                _, _, allow, rule = self._judge_dispatch(
                    lambda m: m.verdicts_attr(data, lengths, remotes)
                )
                rule = np.asarray(rule)
            else:
                # Model-object dispatch (not the module-level jitted
                # fn): a mesh-resident ShardedVerdictModel routes its
                # shard_map step here transparently.
                _, _, allow = self._judge_dispatch(
                    lambda m: m(data, lengths, remotes)
                )
                rule = None
            allow = np.asarray(allow)
            for i, (st, head_len, body_len) in enumerate(group):
                self._emit_http(st, bool(allow[i]), head_len, body_len)
                recs.append((
                    st.flow_id, bool(allow[i]),
                    int(rule[i]) if rule is not None else -1,
                ))
        if cache_hits:  # one batched inc per step, never per entry
            metrics.VerdictCacheHits.inc("engine", amount=cache_hits)
        self._record_round(recs, getattr(self.model, "match_kinds", ()))
        return True

    def _emit_http(self, st: EngineFlow, allow: bool, head_len: int,
                   body_len: int) -> None:
        head = bytes(st.buffer[:head_len])
        line = head.split(b"\r\n", 1)[0].decode("utf-8", "replace")
        parts = line.split(" ")
        method = parts[0] if parts else ""
        url = parts[1] if len(parts) > 1 else ""
        rec = LogRecord(
            http=HttpLogEntry(
                code=200 if allow else 403, method=method, url=url
            )
        )
        self._emit(st, allow, head_len + body_len, HTTP_403, rec)


class KafkaBatchEngine(BaseBatchEngine):
    """Kafka frame parse + device topic-ACL verdicts + error injection
    (reference: pkg/proxy/kafka.go:233 handleRequest)."""

    proto = "kafka"

    def __init__(self, model, host_rows=None, **kw):
        super().__init__(**kw)
        self.model = model
        # (remotes, PortRuleKafka) rows for host fallback on overflow.
        self.host_rows = host_rows or []

    def _host_allow(self, req, remote_id: int) -> bool:
        rules = [
            rule for remotes, rule in self.host_rows
            if not remotes or remote_id in remotes
        ]
        return matches_rule(req, rules)

    def _step(self) -> bool:
        active = []
        for st in self.flows.values():
            if st.closed:
                # Connection condemned by an earlier framing error: every
                # byte drops unparsed until the datapath tears it down.
                if st.buffer:
                    self._emit(st, False, len(st.buffer))
                continue
            buf = bytes(st.buffer)
            try:
                n = frame_length(buf)
            except KafkaParseError:
                # Unparseable framing: drop the buffer AND condemn the
                # connection (reference: the kafka proxy closes the
                # connection on parse errors, kafka.go handleRequest) —
                # subsequent bytes are misframed garbage.
                st.closed = True
                self._emit(st, False, len(buf))
                continue
            if n is None or len(buf) < n:
                continue
            try:
                req = parse_request(buf[:n])
            except KafkaParseError:
                self._emit(st, False, n)
                continue
            active.append((st, n, req))
        if not active:
            return False
        active = active[: self.capacity]

        if isinstance(self.model, ConstVerdict):
            for st, n, req in active:
                self._emit_kafka(st, bool(self.model.allow), n, req)
            return True

        batch = encode_requests([req for _, _, req in active])
        remotes = np.asarray(
            [st.remote_id for st, _, _ in active], np.int32
        )
        allow = np.asarray(
            self._judge_dispatch(lambda m: m(batch, remotes))
        )
        recs = []
        for i, (st, n, req) in enumerate(active):
            a = bool(allow[i])
            if batch.overflow[i]:
                # Device refused to judge: exact host-oracle decision.
                a = self._host_allow(req, st.remote_id)
            self._emit_kafka(st, a, n, req)
            recs.append((st.flow_id, a, -1))
        self._record_round(recs)
        return True

    def _emit_kafka(self, st: EngineFlow, allow: bool, n: int, req) -> None:
        from ..policy.api import KAFKA_REVERSE_API_KEY_MAP

        rec = LogRecord(
            kafka=KafkaLogEntry(
                error_code=0 if allow else 29,
                api_version=req.api_version,
                api_key=KAFKA_REVERSE_API_KEY_MAP.get(
                    req.api_key, str(req.api_key)
                ),
                correlation_id=req.correlation_id,
                topics=list(req.topics),
            )
        )
        inject = b"" if allow else req.create_response().raw
        self._emit(st, allow, n, inject, rec)


def _daemon_mesh(daemon):
    """The daemon's (flows, rules) verdict mesh: honors a pre-set
    ``daemon.verdict_mesh`` (tests/embedders), otherwise resolves
    ONCE from the daemon's DaemonConfig mesh knobs (same resolution
    as the sidecar service — parallel/mesh.serving_mesh) and caches
    the answer on the daemon.  None = single-chip builds."""
    mesh = getattr(daemon, "verdict_mesh", None)
    if mesh is not None or getattr(daemon, "_verdict_mesh_resolved",
                                   False):
        return mesh
    cfg = getattr(daemon, "config", None)
    if cfg is not None and getattr(cfg, "mesh", "off") != "off":
        from ..parallel.mesh import serving_mesh

        try:
            mesh = serving_mesh(
                cfg.mesh, getattr(cfg, "mesh_rule_shards", 0),
                getattr(cfg, "mesh_flow_shards", 0),
            )
        except Exception:  # noqa: BLE001 — fail to single-chip, typed
            log.exception("verdict mesh resolution failed; "
                          "single-chip builds")
            mesh = None
    try:
        daemon.verdict_mesh = mesh
        daemon._verdict_mesh_resolved = True
    except Exception:  # noqa: BLE001 — slotted/frozen daemon doubles
        pass
    return mesh


def create_engine_for_redirect(daemon, redirect):
    """Factory wired into ProxyManager (reference dispatch:
    pkg/proxy/proxy.go:229-236)."""
    f = redirect.l4_filter
    if f is None:
        return None
    identity_cache = daemon.get_identity_cache()
    t0 = time.perf_counter()
    model = build_model_for_filter(
        f, identity_cache, mesh=_daemon_mesh(daemon)
    )
    # Daemon-side engine builds land in any installed device ledger by
    # broadcast (the daemon holds no service handle); cause rides the
    # enclosing scope, cold by default.
    try:
        from ..sidecar import ledger as _ledger

        _ledger.broadcast_compile(
            str(f.l7_parser or "l7"), time.perf_counter() - t0,
            kind="engine-build",
        )
    except Exception:  # noqa: BLE001 — ledger must not cost the build
        pass
    common = dict(
        logger=daemon.access_logger,
        monitor=daemon.monitor,
        flowlog=getattr(daemon, "flowlog", None),
    )
    if f.l7_parser == PARSER_TYPE_HTTP:
        return HttpBatchEngine(
            model,
            cache_enabled=getattr(
                getattr(daemon, "config", None), "flow_cache", False
            ),
            **common,
        )
    if f.l7_parser == PARSER_TYPE_KAFKA:
        from .engines_util import kafka_host_rows

        return KafkaBatchEngine(
            model, host_rows=kafka_host_rows(f, identity_cache), **common
        )
    # Generic L7 (r2d2/cassandra/memcached/...): served by the proxylib
    # pipeline (cilium_tpu.proxylib + runtime.batch for r2d2).
    return None
