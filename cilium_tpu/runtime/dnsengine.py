"""DNS batch engine — the first length-prefixed family on the scalar
engine rung AND the columnar reassembly lane.

Reuses the whole R2d2BatchEngine machinery (feed/feed_extract/
settle_entry/pump/adopt_residue — the flagship scalar contract the
columnar lane falls back to and parity-tests against) with the framing
hooks rebound to DNS-over-TCP: frames split on the 2-byte big-endian
length prefix (reasm FRAMINGS["dns"] is the columnar twin), the judged
message is the WHOLE prefixed frame, and denied frames inject nothing
(a synthesized DNS response would need the query id echoed per frame —
see proxylib/parsers/dns.py).

This file is deliberately on the lint hot-module list (R7/R12/R13):
it sits on the dispatch path via the service's slow/async lanes, so
per-entry feed loops, hot compiles and epoch-unkeyed caches here are
the same hazards they are in service.py.
"""

from __future__ import annotations

from ..proxylib.accesslog import EntryType, LogEntry
from ..proxylib.parsers.dns import frame_len, parse_dns_query
from .batch import FlowState, R2d2BatchEngine


class DnsBatchEngine(R2d2BatchEngine):
    """Batch engine for the DNS name-policy model (models/dns.py)."""

    proto = "dns"

    # Denied queries DROP with no reply inject (module docstring).
    DENY_INJECT = b""

    reasm_columnar = True

    @staticmethod
    def reasm_spec() -> str:
        """Columnar feed contract framing kind (reasm.FRAMINGS):
        DNS-over-TCP frames on a 2-byte big-endian length prefix."""
        return "dns"

    @staticmethod
    def _frame_split(buf) -> int:
        need = frame_len(bytes(buf[:2]))
        return need if 0 <= need <= len(buf) else -1

    @staticmethod
    def _frame_msg(buf, msg_len: int) -> bytes:
        """The judged message IS the whole prefixed frame (the device
        model reads the prefix itself)."""
        return bytes(buf[:msg_len])

    @staticmethod
    def frame_row(msg: bytes) -> bytes:
        """feed_extract messages already carry the full frame."""
        return msg

    def _log_frame(self, st: FlowState, msg: bytes, allow: bool) -> None:
        name = parse_dns_query(msg)
        self.logger.log(
            LogEntry(
                is_ingress=st.ingress,
                entry_type=EntryType.Request if allow else EntryType.Denied,
                policy_name=st.policy_name,
                source_security_id=st.remote_id,
                destination_security_id=st.dst_id,
                source_address=st.src_addr,
                destination_address=st.dst_addr,
                proto="dns",
                fields={"query": name if name is not None else "<invalid>"},
            )
        )
