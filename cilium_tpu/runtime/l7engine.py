"""Device-assisted L7 engines for stateful protocols (cassandra, memcached).

The r2d2/HTTP/Kafka engines re-implement framing and emission around a
pure device model.  Cassandra and memcached have deeply stateful
connection semantics (prepared-statement caches, keyspace tracking,
reply-intent queues with in-order denial injection), so this engine
keeps the streaming oracle parser as the single source of framing/state
truth and batches only the decision:

1. **Peek**: extract match inputs for every complete frame in each
   flow's buffer WITHOUT mutating parser state (clones for the
   keyspace-tracking tokenizer).
2. **Judge**: one device pass over the collected frames (cassandra
   (action, table) ACL / memcached (command, key) ACL).
3. **Drive**: run the oracle parser exactly as in-process proxylib —
   its ``Connection.matches`` is answered from the precomputed device
   verdicts (host fallback for overflow frames), so the op/byte/inject
   stream is bit-identical to the oracle by construction.

Reference seams: proxylib/proxylib/connection.go:118 (op loop),
proxylib/cassandra/cassandraparser.go, proxylib/memcached/*.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from ..models.base import ConstVerdict
from ..models.cassandra import cassandra_verdicts, encode_cassandra_batch
from ..policy.invariance import InvariantClaimEngine
from ..models.memcached import encode_memcache_batch, memcache_verdicts
from ..proxylib.connection import Connection, InjectBuf
from ..proxylib.parsers.cassandra import (
    CASS_HDR_LEN,
    CassandraParser,
)
from ..proxylib.parsers.memcached import (
    BINARY_HEADER_SIZE,
    BinaryMemcacheParser,
    MemcacheMeta,
    MemcacheParser,
    TextMemcacheParser,
)
import logging

from ..proxylib.types import MORE, DROP, ERROR, PASS, FilterResult, OpError
from ..utils import flowdebug, metrics

log = logging.getLogger(__name__)
# Per-flow debug stream: every per-frame/per-op message in this module
# rides the flowdebug gate (one boolean when disabled) — never a bare
# log.debug on the verdict hot path.
_flow_log = logging.getLogger("cilium_tpu.runtime.flow")


class _EngineInstance:
    """Duck-typed proxylib Instance: policy decisions come from the
    engine's precomputed device verdicts, logging from its logger."""

    def __init__(self, engine):
        self.engine = engine

    def policy_matches_at(self, policy_name, ingress, port, remote_id, l7):
        """(allow, rule) — Connection.matches stamps the rule onto the
        connection's ``last_rule_id`` for flow-record emission.  Device
        rounds answer from the precomputed (verdict, rule) queue; host
        fallback walks the oracle's matches_at (the same flattened row
        order the device argmax uses)."""
        q = self.engine._pending_verdicts.get(self.engine._driving_flow)
        if q:
            allow, rule = q.popleft()
            return bool(allow), int(rule)
        # Host fallback: overflow frames, frames beyond the peek
        # horizon, or a quarantined device — exact oracle decision.
        self.engine.host_judged += 1
        policy = self.engine.policy
        if policy is None:
            return False, -1
        return policy.matches_at(ingress, port, remote_id, l7)

    def policy_matches(self, policy_name, ingress, port, remote_id, l7):
        return self.policy_matches_at(
            policy_name, ingress, port, remote_id, l7
        )[0]

    def log(self, entry) -> None:
        if self.engine.logger is not None:
            self.engine.logger.log(entry)


class _EngineFlow:
    __slots__ = ("conn", "parser", "bufs", "ops", "stalled", "skip",
                 "overflowed")

    def __init__(self, conn, parser):
        self.conn = conn
        self.parser = parser
        self.bufs = {False: bytearray(), True: bytearray()}
        self.ops = {False: [], True: []}
        # Per-direction need-more marker: don't re-drive until new bytes.
        self.stalled = {False: False, True: False}
        # Bytes already covered by a PASS/DROP that overshot the buffered
        # input (a parser may decide on a frame prefix — e.g. memcached
        # binary bodies); consumed on arrival without re-parsing.
        self.skip = {False: 0, True: 0}
        # Retained-bytes cap exceeded: buffers dropped with a typed
        # protocol-error, flow is dead.
        self.overflowed = False


class DeviceAssistedEngine(InvariantClaimEngine):
    """Common pump for peek/judge/drive engines.

    Subclasses implement ``_peek(flow, buf)`` returning the list of
    device-encodable frame descriptors for complete request frames at
    the head of ``buf`` (in order), or [] when none/fallback.
    """

    proto = ""
    handles_reply = True

    def __init__(self, policy, ingress: bool, port: int, model,
                 logger=None, capacity: int = 2048,
                 max_buffer: int = 1 << 20, attr_enabled: bool = True):
        self.policy = policy  # PolicyInstance for host fallback
        # Rule attribution gate: False (flow_observe off) keeps the
        # judge on the plain verdict call — no argmax, no extra
        # readback.
        self.attr_enabled = attr_enabled
        # Verdict-cache offload tier gate (service config flow_cache):
        # when on, judge steps may answer byte-invariant identities
        # host-side from the claim instead of encoding device rows.
        self.cache_enabled = False
        self.ingress = ingress
        self.port = port
        self.model = model
        self.logger = logger
        self.capacity = capacity
        # Per-flow retained-bytes cap across both direction buffers
        # (0 = unbounded) — see runtime/batch.py FlowState.
        self.max_buffer = max_buffer
        self.buffer_overflows = 0
        self.flows: dict[int, _EngineFlow] = {}
        self.instance = _EngineInstance(self)
        self._pending_verdicts: dict[int, deque] = {}
        self._driving_flow: int | None = None
        self.device_judged = 0  # frames decided on device (telemetry)
        self.host_judged = 0  # frames decided by host fallback (telemetry)
        # Containment hooks set by the service: device_gate() -> bool
        # answers "may this round use the device?" (False while the
        # device is quarantined — the judge step is skipped and every
        # frame falls through to the host ``policy.matches`` fallback,
        # which is bit-identical by construction).  device_fail_hook(exc)
        # reports a crashed judge so the service can count it toward the
        # poisoned-engine threshold.
        self.device_gate = None
        self.device_fail_hook = None
        # Optional service-owned judge dispatch: (data, lengths,
        # remotes) -> (complete, len, allow, rule-or-None) routed
        # through the service's jit caches AND its mesh demotion rung
        # — a raising sharded dispatch demotes to the single-chip
        # fallback instead of host-judging every round forever.
        self.judge_dispatch = None

    # -- flow management --------------------------------------------------

    def flow(self, flow_id: int, remote_id: int = 0, policy_name: str = "",
             dst_id: int = 0, src_addr: str = "", dst_addr: str = "",
             **_kw) -> _EngineFlow:
        st = self.flows.get(flow_id)
        if st is None:
            conn = Connection(
                instance=self.instance,
                conn_id=flow_id,
                ingress=self.ingress,
                src_id=remote_id,
                dst_id=dst_id,
                src_addr=src_addr,
                dst_addr=dst_addr or f"0.0.0.0:{self.port}",
                policy_name=policy_name,
                port=self.port,
                parser_name=self.proto,
                orig_buf=InjectBuf(4096),
                reply_buf=InjectBuf(4096),
            )
            conn.parser = self._make_parser(conn)
            st = _EngineFlow(conn, conn.parser)
            self.flows[flow_id] = st
        return st

    def feed(self, flow_id: int, data: bytes, reply: bool = False,
             remote_id: int = 0, **kw) -> None:
        st = self.flow(flow_id, remote_id, **kw)
        if st.overflowed:
            if not st.ops[reply]:  # dead flow: every further feed errors
                st.ops[reply].append(
                    (ERROR, int(OpError.ERROR_INVALID_FRAME_LENGTH))
                )
            return
        if st.skip[reply]:
            take = min(st.skip[reply], len(data))
            st.skip[reply] -= take
            data = data[take:]
            if not data:
                return
        retained = len(st.bufs[False]) + len(st.bufs[True])
        if self.max_buffer and retained + len(data) > self.max_buffer:
            # Retained-bytes cap: drop everything buffered in THIS
            # direction plus the incoming bytes with a typed
            # protocol-error pair; the flow is dead (caller closes on
            # the ERROR result).  The opposite direction's buffer is
            # left intact — the shim still mirrors those retained
            # bytes, and clearing them here with no covering op would
            # desync that mirror; they die with the flow (the next
            # entry in that direction gets the overflowed ERROR above).
            dropped = len(st.bufs[reply]) + len(data)
            st.bufs[reply].clear()
            st.overflowed = True
            st.stalled[False] = st.stalled[True] = True
            self.buffer_overflows += 1
            st.ops[reply].append((DROP, dropped))
            st.ops[reply].append(
                (ERROR, int(OpError.ERROR_INVALID_FRAME_LENGTH))
            )
            return
        st.bufs[reply] += data
        st.stalled[reply] = False

    def close_flow(self, flow_id: int) -> None:
        self.flows.pop(flow_id, None)
        self._pending_verdicts.pop(flow_id, None)

    def take_ops(self, flow_id: int, reply: bool = False):
        st = self.flows[flow_id]
        ops = st.ops[reply]
        st.ops[reply] = []
        inject_orig = st.conn.orig_buf.take()
        inject_reply = st.conn.reply_buf.take()
        return ops, inject_orig, inject_reply

    # -- the pump ---------------------------------------------------------

    def pump(self) -> None:
        while self._round():
            pass

    def _round(self) -> bool:
        # 1. peek request-direction frames across flows
        batch_entries: list[tuple[int, object]] = []
        for fid, st in self.flows.items():
            if st.stalled[False] or not st.bufs[False]:
                continue
            for desc in self._peek(st, bytes(st.bufs[False])):
                batch_entries.append((fid, desc))
        # 2. judge on device — skipped entirely while the device is
        # quarantined (device_gate False): every frame then falls
        # through to the host ``policy.matches`` fallback inside the
        # drive phase, which is bit-identical by construction.  A judge
        # that CRASHES takes the same fallback (and reports the failure
        # so the service can quarantine a poisoned engine).
        self._pending_verdicts = {}
        device_ok = self.device_gate is None or self.device_gate()
        if (
            batch_entries
            and device_ok
            and not isinstance(self.model, ConstVerdict)
        ):
            try:
                judged = self._judge(
                    [d for _, d in batch_entries],
                    np.asarray(
                        [self.flows[fid].conn.src_id
                         for fid, _ in batch_entries],
                        np.int32,
                    ),
                )
                # Engines with device-side rule attribution return a
                # third per-frame array of first-match rule rows; the
                # rest attribute -1 (the queue always carries pairs).
                if len(judged) == 3:
                    verdicts, overflow, rules = judged
                else:
                    verdicts, overflow = judged
                    rules = None
            except Exception as exc:  # noqa: BLE001 — host fallback
                log.exception("device judge failed; host fallback")
                if self.device_fail_hook is not None:
                    try:
                        self.device_fail_hook(exc)
                    except Exception:  # noqa: BLE001
                        pass
                verdicts, overflow, rules = None, None, None
            if verdicts is not None:
                stopped: set[int] = set()
                for i, (fid, _) in enumerate(batch_entries):
                    if fid in stopped:
                        continue
                    if overflow[i]:
                        # host fallback from this frame on, for THIS
                        # flow only
                        stopped.add(fid)
                        continue
                    self._pending_verdicts.setdefault(fid, deque()).append(
                        (bool(verdicts[i]),
                         int(rules[i]) if rules is not None else -1)
                    )
                    self.device_judged += 1
        elif batch_entries and isinstance(self.model, ConstVerdict):
            for fid, _ in batch_entries:
                self._pending_verdicts.setdefault(fid, deque()).append(
                    (bool(self.model.allow), -1)
                )

        # 3. drive the oracle op loop per (flow, direction)
        progress = False
        for fid, st in self.flows.items():
            for reply in (False, True):
                if st.stalled[reply] or not st.bufs[reply]:
                    continue
                self._driving_flow = fid if not reply else None
                ops: list = []
                res = st.conn.on_data(
                    reply, False, [bytes(st.bufs[reply])], ops
                )
                self._driving_flow = None
                flowdebug.log(
                    _flow_log, "flow %d %s %s drive: %d op(s) rule=%d",
                    fid, self.proto, "reply" if reply else "orig",
                    len(ops), st.conn.last_rule_id,
                )
                consumed = 0
                for op, n in ops:
                    st.ops[reply].append((op, n))
                    if op in (PASS, DROP):
                        take = min(n, len(st.bufs[reply]) - consumed)
                        consumed += take
                        st.skip[reply] += n - take  # decide-on-prefix
                if consumed:
                    del st.bufs[reply][:consumed]
                    progress = True
                if res != FilterResult.OK:
                    # parser error: ops carry ERROR; connection is dead
                    st.stalled[False] = st.stalled[True] = True
                elif not ops or ops[-1][0] == MORE or not st.bufs[reply]:
                    st.stalled[reply] = True
            # discard unused verdicts: next round re-peeks
        self._pending_verdicts = {}
        return progress

    # -- subclass hooks ---------------------------------------------------

    def _make_parser(self, conn):
        raise NotImplementedError

    def _peek(self, st: _EngineFlow, buf: bytes) -> list:
        raise NotImplementedError

    def _judge(self, descs: list, remotes: np.ndarray):
        raise NotImplementedError


class CassandraBatchEngine(DeviceAssistedEngine):
    proto = "cassandra"

    @staticmethod
    def reasm_spec() -> str:
        """Columnar feed contract framing kind (sidecar/reasm.py):
        cassandra frames are length-prefixed — a 9-byte v3/v4 header
        with the u32 body length at offset 5
        (reasm.scan_length_prefixed / length_prefix_reader(9, 5)).
        Declared for the columnar lane's engine inventory; the kind
        has no reasm.FRAMINGS entry yet (and reasm_columnar stays
        unset — the per-direction parser state here is not
        arena-portable), so the per-framing dispatch serves this
        engine scalar.  Registering the Framing is ROADMAP item 2's
        remaining half; the DNS engine is the template."""
        return "length_prefix"

    def _make_parser(self, conn):
        return CassandraParser(conn)

    class _PeekState:
        """Non-mutating tokenizer context: keyspace evolves across the
        peeked frames without touching the live parser.  The unprepared
        error inject is swallowed by the null connection — the real
        inject happens when the oracle drives the frame."""

        _send_unprepared = CassandraParser._send_unprepared

        def __init__(self, parser):
            self.keyspace = parser.keyspace
            self.prepared_path_by_stream_id = dict(
                parser.prepared_path_by_stream_id
            )
            self.prepared_path_by_prepared_id = (
                parser.prepared_path_by_prepared_id
            )
            self.connection = _NullConn()

    def _peek(self, st, buf):
        import struct

        parser = st.parser
        clone = self._PeekState(parser)
        descs = []
        off = 0
        while True:
            if len(buf) - off < CASS_HDR_LEN:
                break
            (request_len,) = struct.unpack_from(">I", buf, off + 5)
            end = off + CASS_HDR_LEN + request_len
            if end > len(buf):
                break
            frame = buf[off:end]
            err, paths = CassandraParser._parse_request(clone, frame)
            if err:
                break  # oracle will ERROR on this frame; stop peeking
            # All paths of the frame must match (batch opcode): encode
            # each as a device row; the drive phase consumes one verdict
            # per path in order (the oracle matches() per path).
            for path in paths:
                parts = path.split("/")
                if len(parts) >= 4:
                    descs.append((parts[2], parts[3], False))
                else:
                    descs.append(("", "", True))
            off = end
        return descs

    def _judge(self, descs, remotes):
        data, alen, tlen, nq, overflow = encode_cassandra_batch(descs)
        allow = np.asarray(
            cassandra_verdicts(self.model, data, alen, tlen, nq, remotes)
        )
        return allow, overflow


class _NullConn:
    """Inject sink for the peek pass (the real inject happens when the
    oracle processes the frame)."""

    def inject(self, reply, data):
        return len(data)


class MemcacheBatchEngine(DeviceAssistedEngine):
    proto = "memcache"

    @staticmethod
    def reasm_spec() -> str:
        """Columnar feed contract framing kind (sidecar/reasm.py):
        memcached is SNIFFED per conn — text frames on CRLF, binary
        frames length-prefixed — so the kind is deliberately NOT
        "crlf": the per-framing dispatch (reasm.FRAMINGS has no entry
        for this kind) would otherwise CRLF-scan binary conns into
        garbage frames the moment this engine grew reasm_columnar.
        A future lane must split on the sniffed protocol first."""
        return "crlf_or_length_prefix"

    def _make_parser(self, conn):
        return MemcacheParser(conn)

    def _peek(self, st, buf):
        import struct

        # Resolve the sniffed protocol (same rule as the unified parser).
        inner = st.parser.parser
        if inner is None:
            if not buf:
                return []
            binary = buf[0] >= 128
        else:
            binary = isinstance(inner, BinaryMemcacheParser)

        descs = []
        off = 0
        while True:
            rest = buf[off:]
            if binary:
                if len(rest) < BINARY_HEADER_SIZE:
                    break
                (body_len,) = struct.unpack_from(">I", rest, 8)
                (key_len,) = struct.unpack_from(">H", rest, 2)
                extras_len = rest[4]
                if key_len and len(rest) < BINARY_HEADER_SIZE + key_len + extras_len:
                    break  # oracle asks MORE for the key
                if not rest[0] & 0x80:
                    break  # oracle errors out
                key = rest[
                    BINARY_HEADER_SIZE + extras_len :
                    BINARY_HEADER_SIZE + extras_len + key_len
                ]
                descs.append((True, rest[1], "", [key]))
                # The oracle decides once header+key are in, with
                # pre-pass/drop for the body (decide-on-prefix).
                off += BINARY_HEADER_SIZE + body_len
                if off > len(buf):
                    break
            else:
                linefeed = rest.find(b"\r\n")
                if linefeed < 0:
                    break
                tokens = rest[:linefeed].split()
                if not tokens:
                    break
                command = tokens[0]
                cmd = command.decode("ascii", "replace")
                keys: list[bytes] = []
                frame_len = linefeed + 2
                if command.startswith(b"get"):
                    keys = tokens[1:]
                elif command.startswith(b"gat"):
                    keys = tokens[2:]
                elif command in (b"set", b"add", b"replace", b"append",
                                 b"prepend", b"cas"):
                    keys = tokens[1:2]
                    try:
                        frame_len += int(tokens[4]) + 2
                    except (IndexError, ValueError):
                        break  # oracle errors
                elif command in (b"delete", b"incr", b"decr", b"touch"):
                    keys = tokens[1:2]
                descs.append((False, 0, cmd, keys))
                off += frame_len
                if off > len(buf):
                    break
        return descs

    def _judge(self, descs, remotes):
        key_data, key_len, has_key, is_bin, opcode, cmd_id, overflow = (
            encode_memcache_batch(descs)
        )
        allow = np.asarray(
            memcache_verdicts(
                self.model, key_data, key_len, has_key, is_bin, opcode,
                cmd_id, remotes,
            )
        )
        return allow, overflow


class HttpSidecarEngine(DeviceAssistedEngine):
    """HTTP through the sidecar seam — the cilium.l7policy filter
    served by the verdict service (reference: envoy/cilium_l7policy.cc
    request path): complete request frames are judged on device via the
    HTTP batch model; partial frames, replies, and oversized heads ride
    the streaming HttpParser oracle."""

    proto = "http"
    MIN_WIDTH = 512
    MAX_WIDTH = 1 << 15  # beyond this: host fallback (parser denies)
    MIN_ROWS = 64

    def _make_parser(self, conn):
        from ..proxylib.parsers.http import HttpParser

        return HttpParser(conn)

    def _peek(self, st, buf):
        from ..proxylib.parsers.http import head_and_body_len, parse_head

        descs = []
        off = 0
        while True:
            framed = head_and_body_len(buf[off:])
            if framed is None:
                break
            head_len, body_len = framed
            head = buf[off : off + head_len]
            if parse_head(head) is None:
                # The oracle denies malformed request lines WITHOUT
                # consuming a device verdict — stop peeking here so the
                # per-flow verdict queue stays aligned (the cassandra
                # peek breaks on parse errors for the same reason).
                break
            descs.append(head)
            off += head_len + body_len
        return descs

    def _judge(self, descs, remotes):
        n = len(descs)
        allow = np.zeros(n, bool)
        overflow = np.zeros(n, bool)
        rules = np.full(n, -1, np.int32)
        buckets: dict[int, list[int]] = {}
        cache_hits = 0
        for i, head in enumerate(descs):
            if len(head) > self.MAX_WIDTH:
                overflow[i] = True
                continue
            if self.cache_enabled:
                claim = self.verdict_invariant(int(remotes[i]))
                if claim is not None and claim[0]:
                    # Byte-invariant allow (the verdict-cache offload
                    # tier): answer from the claim — verdict AND rule
                    # row are bytes-independent — and keep the head out
                    # of the device batch.  Deny claims stay on the
                    # normal path (the oracle owns 403 framing).
                    allow[i] = True
                    rules[i] = claim[1]
                    cache_hits += 1
                    continue
            w = self.MIN_WIDTH
            while w < len(head):
                w *= 2
            buckets.setdefault(w, []).append(i)
        if cache_hits:  # one batched inc per judge step, never per frame
            metrics.VerdictCacheHits.inc("engine", amount=cache_hits)
        for w, idxs in sorted(buckets.items()):
            f_pad = self.MIN_ROWS
            while f_pad < len(idxs):
                f_pad *= 2
            data = np.zeros((f_pad, w), np.uint8)
            lengths = np.zeros((f_pad,), np.int32)
            rem = np.zeros((f_pad,), np.int32)
            for j, i in enumerate(idxs):
                h = descs[i]
                data[j, : len(h)] = np.frombuffer(h, np.uint8)
                lengths[j] = len(h)
                rem[j] = remotes[i]
            if self.judge_dispatch is not None:
                # Service-owned dispatch: shared jit caches + the
                # mesh demotion rung (a lost mesh device reissues on
                # the single-chip fallback and demotes typed).
                _, _, a, r = self.judge_dispatch(data, lengths, rem)
                r = np.asarray(r) if r is not None else None
            elif self.attr_enabled:
                # Model-object dispatch so a mesh-resident sharded
                # model (with its global-argmax attribution) serves
                # this judge step transparently.
                _, _, a, r = self.model.verdicts_attr(
                    data, lengths, rem
                )
                r = np.asarray(r)
            else:
                _, _, a = self.model(data, lengths, rem)
                r = None
            a = np.asarray(a)
            for j, i in enumerate(idxs):
                allow[i] = bool(a[j])
                if r is not None:
                    rules[i] = int(r[j])
        return allow, overflow, rules
