"""Engine construction helpers."""

from __future__ import annotations

from ..models.builder import _remote_rows
from ..policy.api import PortRuleKafka
from ..policy.l4 import L4Filter


def kafka_host_rows(
    f: L4Filter, identity_cache: dict
) -> list[tuple[frozenset, PortRuleKafka]]:
    """(remotes, rule) rows for the host-oracle fallback path, mirroring
    build_model_for_filter's expansion."""
    rows: list[tuple[frozenset, PortRuleKafka]] = []
    for sel, l7 in f.l7_rules_per_ep.items():
        remote_chunks = _remote_rows(sel, identity_cache)
        if remote_chunks is None:
            continue
        for remotes in remote_chunks:
            if len(l7) == 0:
                wildcard = PortRuleKafka()
                wildcard.sanitize()
                rows.append((remotes, wildcard))
            for k in l7.kafka:
                rows.append((remotes, k))
    return rows
