"""Local endpoint map (reference: pkg/maps/lxcmap + bpf/lib/common.h:164
endpoint_info): endpoint IP/ID -> interface + MAC info for local delivery."""

from __future__ import annotations

import struct
from dataclasses import dataclass

# ifindex, unused, lxc_id, flags, 4 alignment-pad bytes (mac_t is __u64),
# mac, node_mac, pad[4] (reference: common.h:164-173, mac_t at :59).
_ENDPOINT_INFO_FMT = "<IHHI4xQQ16x"
ENDPOINT_INFO_SIZE = struct.calcsize(_ENDPOINT_INFO_FMT)  # 48

ENDPOINT_F_HOST = 1


@dataclass
class EndpointInfo:
    ifindex: int = 0
    lxc_id: int = 0
    flags: int = 0
    mac: int = 0
    node_mac: int = 0

    def pack(self) -> bytes:
        return struct.pack(
            _ENDPOINT_INFO_FMT, self.ifindex, 0, self.lxc_id, self.flags,
            self.mac, self.node_mac,
        )

    @property
    def is_host(self) -> bool:
        return bool(self.flags & ENDPOINT_F_HOST)


class LxcMap:
    """Host map of local endpoints keyed by IP string or endpoint ID."""

    def __init__(self) -> None:
        self.by_ip: dict[str, EndpointInfo] = {}
        self.by_id: dict[int, EndpointInfo] = {}

    def upsert(self, ip: str, ep_id: int, info: EndpointInfo) -> None:
        # Clear any stale index entries from a previous IP or ID of this
        # endpoint so neither index dangles.
        old_by_id = self.by_id.get(ep_id)
        if old_by_id is not None:
            for old_ip, i in list(self.by_ip.items()):
                if i is old_by_id and old_ip != ip:
                    del self.by_ip[old_ip]
        old_by_ip = self.by_ip.get(ip)
        if old_by_ip is not None and old_by_ip.lxc_id != ep_id:
            self.by_id.pop(old_by_ip.lxc_id, None)
        info.lxc_id = ep_id
        self.by_ip[ip] = info
        self.by_id[ep_id] = info

    def delete_ip(self, ip: str) -> bool:
        info = self.by_ip.pop(ip, None)
        if info is not None:
            self.by_id.pop(info.lxc_id, None)
            return True
        return False

    def lookup_ip(self, ip: str) -> EndpointInfo | None:
        return self.by_ip.get(ip)

    def lookup_id(self, ep_id: int) -> EndpointInfo | None:
        return self.by_id.get(ep_id)

    def dump(self):
        return sorted(self.by_ip.items())

    def to_device(self, pad_to: int | None = None):
        """Pack the v4 endpoint IPs into an exact-match DeviceTable
        (key: addr; values: [lxc_id, flags]) — the batched analog of
        lookup_ip4_endpoint (reference: bpf/lib/eps.h, consumed by
        bpf_netdev.c handle_ipv4 for local delivery demux)."""
        import ipaddress

        import numpy as np

        from ..ops.maplookup import pack_table, u32_to_i32

        rows = []
        vals = []
        for ip, info in self.by_ip.items():
            addr = ipaddress.ip_address(ip)
            if addr.version != 4:
                continue
            rows.append([int(addr) & 0xFFFFFFFF])
            vals.append([info.lxc_id, info.flags])
        keys = u32_to_i32(np.array(rows or np.zeros((0, 1)), np.int64))
        return pack_table(
            keys.reshape(-1, 1),
            np.array(vals or np.zeros((0, 2)), np.int64).astype(np.int32),
            pad_to=pad_to,
        )
