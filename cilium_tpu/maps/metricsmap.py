"""Datapath metrics map: per-(reason, direction) packet/byte counters.

reference: bpf/lib/metrics.h (update_metrics) + pkg/maps/metricsmap
(metrics_key {reason, dir}, metrics_value {count, bytes}); reason 0 is
"forwarded", >0 are drop reasons.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.metrics import DropCount, ForwardCount

REASON_FORWARDED = 0

# The metrics map's direction encoding differs from policy_key's 0/1 bit
# (reference: bpf/lib/common.h metrics_key dir 1=ingress 2=egress vs
# policy_key egress bit) — distinct names to prevent cross-map mixups.
METRIC_DIR_INGRESS = 1
METRIC_DIR_EGRESS = 2

_DIR_NAMES = {METRIC_DIR_INGRESS: "INGRESS", METRIC_DIR_EGRESS: "EGRESS"}


@dataclass
class MetricsValue:
    count: int = 0
    bytes: int = 0


class MetricsMap:
    """Host metrics counters (reference: pkg/maps/metricsmap)."""

    def __init__(self) -> None:
        self.values: dict[tuple[int, int], MetricsValue] = {}

    def update(self, reason: int, direction: int, count: int = 1,
               nbytes: int = 0) -> None:
        v = self.values.setdefault((reason, direction), MetricsValue())
        v.count += count
        v.bytes += nbytes
        # Bridge into the Prometheus registry (reference: pkg/metrics
        # drop_count_total/forward_count_total are fed from this map).
        d = _DIR_NAMES.get(direction, str(direction))
        if reason == REASON_FORWARDED:
            ForwardCount.inc(d, amount=count)
        else:
            DropCount.inc(str(reason), d, amount=count)

    def get(self, reason: int, direction: int) -> MetricsValue:
        return self.values.get((reason, direction), MetricsValue())

    def dump(self):
        return sorted(
            (
                (_DIR_NAMES.get(d, str(d)), reason, v.count, v.bytes)
                for (reason, d), v in self.values.items()
            )
        )
