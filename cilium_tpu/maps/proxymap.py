"""Proxy redirect map: proxied 5-tuple -> original destination + identities.

reference: pkg/maps/proxymap (proxy4_tbl) + bpf/lib/lxc.h:103-138
(proxy4_create/update writes on redirect) + envoy/proxymap.cc (the proxy
reading back the original destination on accept).  Entries expire after
PROXY_DEFAULT_LIFETIME unless refreshed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

# reference: bpf/lib/common.h PROXY_DEFAULT_LIFETIME
PROXY_DEFAULT_LIFETIME = 720


@dataclass(frozen=True)
class ProxyKey4:
    """From the source's perspective; dport is the local proxy port
    (reference: pkg/maps/proxymap/ipv4.go:32)."""

    saddr: int
    daddr: int
    sport: int
    dport: int
    nexthdr: int


@dataclass
class ProxyValue4:
    orig_daddr: int
    orig_dport: int
    identity: int
    lifetime: int = 0


class ProxyMap:
    """Host proxy map (reference: pkg/maps/proxymap)."""

    def __init__(self, clock=time.monotonic) -> None:
        self.entries: dict[ProxyKey4, ProxyValue4] = {}
        self.clock = clock

    def create(self, key: ProxyKey4, orig_daddr: int, orig_dport: int,
               identity: int) -> None:
        self.entries[key] = ProxyValue4(
            orig_daddr=orig_daddr,
            orig_dport=orig_dport,
            identity=identity,
            lifetime=int(self.clock()) + PROXY_DEFAULT_LIFETIME,
        )

    def lookup(self, key: ProxyKey4) -> ProxyValue4 | None:
        """Lookup + lifetime refresh (proxies keep entries alive via
        TCP keepalive in the reference)."""
        v = self.entries.get(key)
        if v is None:
            return None
        now = int(self.clock())
        if v.lifetime < now:
            del self.entries[key]
            return None
        v.lifetime = now + PROXY_DEFAULT_LIFETIME
        return v

    def gc(self) -> int:
        now = int(self.clock())
        dead = [k for k, v in self.entries.items() if v.lifetime < now]
        for k in dead:
            del self.entries[k]
        return len(dead)

    def flush(self) -> int:
        n = len(self.entries)
        self.entries.clear()
        return n

    def save(self, path: str) -> int:
        """Write a binary snapshot for the native proxy side (the
        pinned-BPF-map analog; reader: native/shim.cc
        cilium_tpu_proxymap_open / envoy/proxymap.cc counterpart).
        Layout: b"CTPM" + uint32 count + count * 8 LE uint32s
        (saddr, daddr, sport, dport, proto, orig_daddr, orig_dport,
        identity).  Expired entries are skipped; the write is atomic
        (tmp + rename) so the reader never sees a torn file.
        Returns the number of entries written."""
        import os
        import struct

        now = int(self.clock())
        live = [
            (k, v) for k, v in self.entries.items() if v.lifetime >= now
        ]
        blob = b"CTPM" + struct.pack("<I", len(live))
        for k, v in live:
            blob += struct.pack(
                "<8I",
                k.saddr & 0xFFFFFFFF, k.daddr & 0xFFFFFFFF,
                k.sport, k.dport, k.nexthdr,
                v.orig_daddr & 0xFFFFFFFF, v.orig_dport, v.identity,
            )
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return len(live)

    def dump(self):
        return sorted(
            self.entries.items(),
            key=lambda kv: (kv[0].saddr, kv[0].sport, kv[0].dport),
        )
