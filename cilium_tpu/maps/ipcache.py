"""IP -> identity cache map with LPM semantics.

reference: pkg/maps/ipcache (BPF ipcache LPM/hash map) + bpf/lib/eps.h
(lookup_ip4_remote_endpoint).  Host-authoritative prefix -> identity table;
``to_device`` exports a DeviceLpm so identity derivation for F source
addresses is one batched longest-prefix sweep (the bpf_netdev.c ingress
identity path, reference: bpf/bpf_netdev.c identity from ipcache).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass

from ..ops.lpm import DeviceLpm, build_lpm


@dataclass
class RemoteEndpointInfo:
    """reference: bpf/lib/common.h:175 remote_endpoint_info."""

    sec_label: int
    tunnel_endpoint: int = 0


class IpcacheMap:
    """Host IP->identity map (reference: pkg/maps/ipcache/ipcache.go)."""

    def __init__(self) -> None:
        # key -> (parsed network, info); networks parsed once on upsert.
        self.v4: dict[str, tuple] = {}
        self.v6: dict[str, tuple] = {}

    def upsert(self, prefix: str, sec_label: int, tunnel_endpoint: int = 0) -> None:
        net = ipaddress.ip_network(prefix, strict=False)
        key = str(net)
        info = RemoteEndpointInfo(sec_label, tunnel_endpoint)
        (self.v4 if net.version == 4 else self.v6)[key] = (net, info)

    def delete(self, prefix: str) -> bool:
        net = ipaddress.ip_network(prefix, strict=False)
        key = str(net)
        table = self.v4 if net.version == 4 else self.v6
        return table.pop(key, None) is not None

    def lookup(self, ip: str) -> RemoteEndpointInfo | None:
        """Host-side LPM lookup."""
        addr = ipaddress.ip_address(ip)
        table = self.v4 if addr.version == 4 else self.v6
        best = None
        best_len = -1
        for net, info in table.values():
            if addr in net and net.prefixlen > best_len:
                best, best_len = info, net.prefixlen
        return best

    def dump(self):
        return sorted((k, v[1]) for k, v in self.v4.items()) + sorted(
            (k, v[1]) for k, v in self.v6.items()
        )

    def save(self, path: str) -> int:
        """Write a v4 binary snapshot for the native datapath process
        (the PolicyHostMap analog; reader: native/shim.cc
        cilium_tpu_hostmap_open — reference: envoy/cilium_host_map.cc
        PolicyHostMap, which subscribes the same IP->identity data via
        NPHDS).  Layout: b"CTHM" + uint32 count + count * 4 LE uint32s
        (network address host-order, prefix_len, sec_label,
        tunnel_endpoint).  Atomic via tmp + rename.  Returns the entry
        count."""
        import os
        import struct

        recs = []
        for net, info in self.v4.values():
            recs.append(
                struct.pack(
                    "<4I",
                    int(net.network_address), net.prefixlen,
                    info.sec_label & 0xFFFFFFFF,
                    info.tunnel_endpoint & 0xFFFFFFFF,
                )
            )
        blob = b"CTHM" + struct.pack("<I", len(recs)) + b"".join(recs)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return len(recs)

    def to_device(
        self,
        v6: bool = False,
        pad_to: int | None = None,
        value: str = "sec_label",
    ) -> DeviceLpm:
        """Export one value column as a DeviceLpm: 'sec_label' (identity
        derivation) or 'tunnel_endpoint' (overlay forwarding, reference:
        bpf_netdev.c encap_and_redirect_with_nodeid on
        info->tunnel_endpoint)."""
        from ..ops.maplookup import u32_to_i32

        table = self.v6 if v6 else self.v4
        # Values ride int32 lanes as bit patterns (tunnel endpoints are
        # full uint32 addresses).
        return build_lpm(
            [
                (prefix, int(u32_to_i32(getattr(info, value))))
                for prefix, (_, info) in table.items()
            ],
            v6=v6,
            pad_to=pad_to,
        )
