"""Connection-tracking table with TCP state and garbage collection.

reference: bpf/lib/conntrack.h (5-tuple CT with per-direction TCP flag
tracking, lifetime refresh) + pkg/maps/ctmap (dump/GC driver).  The table
is host-authoritative; the batched device lookup answers "is this flow
established" for replay/analysis workloads in one [F, N] sweep.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

import numpy as np

# Tuple flags (reference: pkg/maps/ctmap/ctmap.go:74-78).
TUPLE_F_OUT = 0
TUPLE_F_IN = 1
TUPLE_F_RELATED = 2
TUPLE_F_SERVICE = 4

# Lifetimes in seconds (reference: bpf/lib/conntrack.h:31-50).
CT_STATE_UNKNOWN = 0  # flowlog ct_state codes (flowlog/record.CT_NAMES)
CT_STATE_NEW = 1
CT_STATE_ESTABLISHED = 2

CT_DEFAULT_LIFETIME = 21600  # TCP, 6 hours
CT_DEFAULT_LIFETIME_NONTCP = 60
TCP_CLOSING_LIFETIME = 10  # CT_DEFAULT_CLOSE_TIMEOUT

PROTO_TCP = 6
PROTO_UDP = 17

# Packed tuple layout (reference: bpf/lib/common.h:359-367 ipv4_ct_tuple).
_TUPLE4_FMT = "<IIHHBB"
TUPLE4_SIZE = struct.calcsize(_TUPLE4_FMT)  # 14 (packed)

# reference: bpf/lib/common.h ipv6_ct_tuple (two 16-byte addresses).
_TUPLE6_FMT = "<16s16sHHBB"
TUPLE6_SIZE = struct.calcsize(_TUPLE6_FMT)  # 38 (packed)


@dataclass(frozen=True)
class CtKey4:
    """IPv4 CT tuple (reference: common.h ipv4_ct_tuple)."""

    daddr: int
    saddr: int
    dport: int
    sport: int
    nexthdr: int
    flags: int = TUPLE_F_OUT

    def pack(self) -> bytes:
        return struct.pack(
            _TUPLE4_FMT, self.daddr, self.saddr, self.dport, self.sport,
            self.nexthdr, self.flags,
        )


@dataclass(frozen=True)
class CtKey6:
    """IPv6 CT tuple (reference: common.h ipv6_ct_tuple).  Addresses
    are 128-bit ints; the device table splits them into four 32-bit
    words with the same word order as ops/lpm.ipv6_to_words."""

    daddr: int
    saddr: int
    dport: int
    sport: int
    nexthdr: int
    flags: int = TUPLE_F_OUT

    def pack(self) -> bytes:
        return struct.pack(
            _TUPLE6_FMT,
            self.daddr.to_bytes(16, "big"), self.saddr.to_bytes(16, "big"),
            self.dport, self.sport, self.nexthdr, self.flags,
        )

    @staticmethod
    def words(addr: int) -> tuple[int, int, int, int]:
        return tuple(
            (addr >> (128 - 32 * (w + 1))) & 0xFFFFFFFF for w in range(4)
        )


@dataclass
class CtEntry:
    """reference: bpf/lib/common.h:380-401 ct_entry."""

    rx_packets: int = 0
    rx_bytes: int = 0
    tx_packets: int = 0
    tx_bytes: int = 0
    lifetime: int = 0  # absolute expiry, seconds
    rx_closing: bool = False
    tx_closing: bool = False
    seen_non_syn: bool = False
    rev_nat_index: int = 0
    slave: int = 0
    tx_flags_seen: int = 0
    rx_flags_seen: int = 0
    src_sec_id: int = 0

    @property
    def closing(self) -> bool:
        return self.rx_closing or self.tx_closing


# TCP flag bits
TCP_FIN = 0x01
TCP_SYN = 0x02
TCP_RST = 0x04
TCP_ACK = 0x10


class CtMap:
    """Host conntrack table (reference: pkg/maps/ctmap + lib/conntrack.h)."""

    def __init__(self, max_entries: int = 65536, clock=time.monotonic) -> None:
        self.entries: dict[CtKey4, CtEntry] = {}
        self.max_entries = max_entries
        self.clock = clock

    def _lifetime_for(self, proto: int, closing: bool) -> int:
        if closing:
            return TCP_CLOSING_LIFETIME
        return CT_DEFAULT_LIFETIME if proto == PROTO_TCP else (
            CT_DEFAULT_LIFETIME_NONTCP
        )

    def create(self, key: CtKey4, src_sec_id: int = 0,
               rev_nat_index: int = 0, slave: int = 0) -> CtEntry:
        """reference: conntrack.h ct_create4."""
        if key in self.entries:
            # Re-establishing an existing flow needs no new slot.
            pass
        elif len(self.entries) >= self.max_entries:
            # Emergency GC then retry once (reference agent behavior).
            self.gc()
            if len(self.entries) >= self.max_entries:
                raise OverflowError("CT table full")
        e = CtEntry(
            lifetime=int(self.clock()) + self._lifetime_for(key.nexthdr, False),
            src_sec_id=src_sec_id,
            rev_nat_index=rev_nat_index,
            slave=slave,
        )
        self.entries[key] = e
        return e

    def lookup(self, key: CtKey4, tcp_flags: int = 0,
               is_reply: bool = False) -> CtEntry | None:
        """Lookup + lifetime refresh + TCP state update
        (reference: conntrack.h ct_lookup4/__ct_lookup)."""
        e = self.entries.get(key)
        if e is None:
            return None
        now = int(self.clock())
        if e.lifetime < now:
            del self.entries[key]
            return None
        if key.nexthdr == PROTO_TCP:
            if tcp_flags & (TCP_FIN | TCP_RST):
                if is_reply:
                    e.rx_closing = True
                else:
                    e.tx_closing = True
            if not (tcp_flags & TCP_SYN):
                e.seen_non_syn = True
            if is_reply:
                e.rx_flags_seen |= tcp_flags
            else:
                e.tx_flags_seen |= tcp_flags
        if is_reply:
            e.rx_packets += 1
        else:
            e.tx_packets += 1
        e.lifetime = now + self._lifetime_for(key.nexthdr, e.closing)
        return e

    def gc(self, filter_fn=None) -> int:
        """Remove expired entries (+ entries matching filter_fn); returns
        number deleted (reference: ctmap.go doGC4)."""
        now = int(self.clock())
        dead = [
            k for k, e in self.entries.items()
            if e.lifetime < now or (filter_fn is not None and filter_fn(k, e))
        ]
        for k in dead:
            del self.entries[k]
        return len(dead)

    def flush(self) -> int:
        n = len(self.entries)
        self.entries.clear()
        return n

    def dump(self) -> list[tuple[CtKey4, CtEntry]]:
        """Human-ordered dump (reference: ctmap.go:240 DumpToSlice)."""
        return sorted(
            self.entries.items(),
            key=lambda kv: (kv[0].daddr, kv[0].saddr, kv[0].dport, kv[0].sport),
        )

    @staticmethod
    def state_codes(established) -> np.ndarray:
        """[F] int8 flowlog ct_state codes from a pipeline batch's
        ``established`` column: the CT half of a flow record (a verdict
        on an established flow was admitted at connect time, reference:
        handle_ipv4 CT_ESTABLISHED path)."""
        est = np.asarray(established)
        return np.where(
            est, CT_STATE_ESTABLISHED, CT_STATE_NEW
        ).astype(np.int8)

    def to_device_arrays(self):
        """Export tuples as column arrays for batched established-checks."""
        n = max(len(self.entries), 1)
        cols = np.zeros((5, n), np.int64)
        valid = np.zeros((n,), bool)
        for i, k in enumerate(self.entries):
            cols[:, i] = (k.daddr, k.saddr, k.dport, k.sport, k.nexthdr)
            valid[i] = True
        return cols, valid
