"""Load-balancer service/backend maps and batched backend selection.

reference: bpf/lib/lb.h (lb4_lookup_service :604, lb4_lookup_slave :637,
lb4_select_slave :158 — hash-based slave pick) and pkg/maps/lbmap (service
+ RevNAT bookkeeping).  Services are keyed {vip, dport, slave}; slave 0 is
the master entry holding the backend count; slaves 1..count are backends.
Backend selection for F flows is one device pass: hash the flow 5-tuple,
``slave = hash % count + 1``, gather the backend.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

# Packed layouts (reference: bpf/lib/common.h:427-445).
_LB4_KEY_FMT = "<IHH"  # address, dport, slave
_LB4_SERVICE_FMT = "<IHHHH"  # target, port, count, rev_nat_index, weight
LB4_KEY_SIZE = struct.calcsize(_LB4_KEY_FMT)  # 8
LB4_SERVICE_SIZE = struct.calcsize(_LB4_SERVICE_FMT)  # 12


@dataclass(frozen=True)
class LbKey:
    address: int
    dport: int = 0
    slave: int = 0

    def pack(self) -> bytes:
        return struct.pack(_LB4_KEY_FMT, self.address, self.dport, self.slave)


@dataclass
class LbBackend:
    """lb4_service value (reference: common.h:433)."""

    target: int = 0  # backend IPv4 (or 0 in the master entry)
    port: int = 0
    count: int = 0  # only meaningful in the master entry
    rev_nat_index: int = 0
    weight: int = 0

    def pack(self) -> bytes:
        return struct.pack(
            _LB4_SERVICE_FMT, self.target, self.port, self.count,
            self.rev_nat_index, self.weight,
        )


class LbMap:
    """Host service table (reference: pkg/maps/lbmap)."""

    def __init__(self) -> None:
        # v4 and v6 services live in separate tables with separate
        # RevNAT registries, mirroring the reference's distinct
        # cilium_lb4_/cilium_lb6_ maps (bpf/lib/maps.h) — a numeric vip
        # alone cannot identify the family (::1 == 1).
        self.services: dict[LbKey, LbBackend] = {}
        self.revnat: dict[int, tuple[int, int]] = {}  # index -> (vip, port)
        self.services6: dict[LbKey, LbBackend] = {}
        self.revnat6: dict[int, tuple[int, int]] = {}

    @staticmethod
    def _upsert(services, revnat, vip, dport, backends, rev_nat_index):
        # Remove old slaves beyond the new count, and the old RevNAT entry
        # if the service's rev_nat_index changed.
        old = services.get(LbKey(vip, dport, 0))
        if old is not None:
            for s in range(len(backends) + 1, old.count + 1):
                services.pop(LbKey(vip, dport, s), None)
            if old.rev_nat_index and old.rev_nat_index != rev_nat_index:
                revnat.pop(old.rev_nat_index, None)
        services[LbKey(vip, dport, 0)] = LbBackend(
            count=len(backends), rev_nat_index=rev_nat_index
        )
        for i, (target, port) in enumerate(backends, start=1):
            services[LbKey(vip, dport, i)] = LbBackend(
                target=target, port=port, rev_nat_index=rev_nat_index
            )
        if rev_nat_index:
            revnat[rev_nat_index] = (vip, dport)

    def upsert_service(
        self, vip: int, dport: int, backends: list[tuple[int, int]],
        rev_nat_index: int = 0,
    ) -> None:
        """Install a v4 service with its backends; master entry at slave
        0, backends at slaves 1..n (reference: lbmap service layout)."""
        self._upsert(self.services, self.revnat, vip, dport, backends,
                     rev_nat_index)

    def upsert_service6(
        self, vip: int, dport: int, backends: list[tuple[int, int]],
        rev_nat_index: int = 0,
    ) -> None:
        """v6 twin (reference: cilium_lb6_services)."""
        self._upsert(self.services6, self.revnat6, vip, dport, backends,
                     rev_nat_index)

    @staticmethod
    def _delete(services, revnat, vip, dport) -> bool:
        master = services.pop(LbKey(vip, dport, 0), None)
        if master is None:
            return False
        for s in range(1, master.count + 1):
            services.pop(LbKey(vip, dport, s), None)
        if master.rev_nat_index:
            revnat.pop(master.rev_nat_index, None)
        return True

    def delete_service(self, vip: int, dport: int) -> bool:
        return self._delete(self.services, self.revnat, vip, dport)

    def delete_service6(self, vip: int, dport: int) -> bool:
        return self._delete(self.services6, self.revnat6, vip, dport)

    @staticmethod
    def _lookup(services, vip, dport) -> LbBackend | None:
        if dport:
            svc = services.get(LbKey(vip, dport, 0))
            if svc is not None and svc.count:
                return svc
        svc = services.get(LbKey(vip, 0, 0))
        if svc is not None and svc.count:
            return svc
        return None

    def lookup_service(self, vip: int, dport: int) -> LbBackend | None:
        """L4 first, then L3 wildcard-port (reference: lb.h:604-630)."""
        return self._lookup(self.services, vip, dport)

    def lookup_service6(self, vip: int, dport: int) -> LbBackend | None:
        return self._lookup(self.services6, vip, dport)

    @staticmethod
    def _select(services, vip, dport, flow_hash):
        key_port = dport
        svc = services.get(LbKey(vip, dport, 0)) if dport else None
        if svc is None or not svc.count:
            key_port = 0
            svc = services.get(LbKey(vip, 0, 0))
        if svc is None or not svc.count:
            return None
        slave = ((flow_hash & 0xFFFFFFFF) % svc.count) + 1
        return services.get(LbKey(vip, key_port, slave))

    def select_backend(self, vip: int, dport: int, flow_hash: int):
        """Host-side backend pick (reference: lb.h lb4_select_slave +
        lb4_lookup_slave): slave = hash % count + 1.  The hash is treated
        as a uint32 bit pattern so host and device picks agree."""
        return self._select(self.services, vip, dport, flow_hash)

    def select_backend6(self, vip: int, dport: int, flow_hash: int):
        return self._select(self.services6, vip, dport, flow_hash)

    def dump(self):
        return sorted(
            self.services.items(),
            key=lambda kv: (kv[0].address, kv[0].dport, kv[0].slave),
        )

    def to_device(self, max_backends: int | None = None) -> "DeviceLbMap":
        """Export as dense [S, max_backends] backend arrays per service.
        max_backends defaults to the widest service so no backend is ever
        silently dropped; an explicit value smaller than that is an error."""
        masters = [
            (k, v) for k, v in self.services.items() if k.slave == 0 and v.count
        ]
        widest = max((v.count for _, v in masters), default=1)
        if max_backends is None:
            max_backends = widest
        elif max_backends < widest:
            raise ValueError(
                f"max_backends {max_backends} < widest service {widest}"
            )
        s = max(len(masters), 1)
        vips = np.zeros((s,), np.int64)
        ports = np.zeros((s,), np.int64)
        counts = np.zeros((s,), np.int32)
        revnat = np.zeros((s,), np.int32)
        b_target = np.zeros((s, max_backends), np.int64)
        b_port = np.zeros((s, max_backends), np.int32)
        valid = np.zeros((s,), bool)
        for i, (k, master) in enumerate(masters):
            vips[i] = k.address
            ports[i] = k.dport
            counts[i] = min(master.count, max_backends)
            revnat[i] = master.rev_nat_index
            valid[i] = True
            for b in range(counts[i]):
                be = self.services.get(LbKey(k.address, k.dport, b + 1))
                if be is not None:
                    b_target[i, b] = be.target
                    b_port[i, b] = be.port
        return DeviceLbMap(
            vips=jnp.asarray(vips.astype(np.uint32).view(np.int32)),
            ports=jnp.asarray(ports.astype(np.int32)),
            counts=jnp.asarray(counts),
            revnat=jnp.asarray(revnat),
            b_target=jnp.asarray(b_target.astype(np.uint32).view(np.int32)),
            b_port=jnp.asarray(b_port),
            valid=jnp.asarray(valid),
        )


    def to_device6(self, max_backends: int | None = None) -> "DeviceLb6Map":
        """v6 export: vips/backends as four 32-bit word columns (same
        word order as ops/lpm.ipv6_to_words); reference: bpf/lib/lb.h
        lb6_lookup_service/lb6_select_slave — the v6 twins of the v4
        path with wider keys."""
        from .ctmap import CtKey6
        from ..ops.maplookup import u32_to_i32

        words = CtKey6.words
        masters = [
            (k, v)
            for k, v in self.services6.items() if k.slave == 0 and v.count
        ]
        widest = max((v.count for _, v in masters), default=1)
        if max_backends is None:
            max_backends = widest
        elif max_backends < widest:
            raise ValueError(
                f"max_backends {max_backends} < widest service {widest}"
            )
        s = max(len(masters), 1)
        vip_w = np.zeros((4, s), np.int64)
        ports = np.zeros((s,), np.int64)
        counts = np.zeros((s,), np.int32)
        revnat = np.zeros((s,), np.int32)
        bt_w = np.zeros((4, s, max_backends), np.int64)
        b_port = np.zeros((s, max_backends), np.int32)
        valid = np.zeros((s,), bool)
        for i, (k, master) in enumerate(masters):
            vip_w[:, i] = words(k.address)
            ports[i] = k.dport
            counts[i] = min(master.count, max_backends)
            revnat[i] = master.rev_nat_index
            valid[i] = True
            for b in range(counts[i]):
                be = self.services6.get(LbKey(k.address, k.dport, b + 1))
                if be is not None:
                    bt_w[:, i, b] = words(be.target)
                    b_port[i, b] = be.port
        as_i32 = u32_to_i32
        return DeviceLb6Map(
            vip_words=jnp.asarray(as_i32(vip_w)),
            ports=jnp.asarray(ports.astype(np.int32)),
            counts=jnp.asarray(counts),
            revnat=jnp.asarray(revnat),
            b_target_words=jnp.asarray(as_i32(bt_w)),
            b_port=jnp.asarray(b_port),
            valid=jnp.asarray(valid),
        )


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceLbMap:
    vips: jax.Array  # [S] int32
    ports: jax.Array  # [S] int32
    counts: jax.Array  # [S] int32
    revnat: jax.Array  # [S] int32
    b_target: jax.Array  # [S, B] int32
    b_port: jax.Array  # [S, B] int32
    valid: jax.Array  # [S] bool

    def tree_flatten(self):
        return (
            (self.vips, self.ports, self.counts, self.revnat,
             self.b_target, self.b_port, self.valid),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


def lb4_select_backend_batch(dlb: DeviceLbMap, vips, dports, flow_hashes):
    """Batched service lookup + backend selection.

    Args: [F] int32 arrays (vips as uint32 bit patterns).
    Returns (found [F] bool, target [F] int32, port [F] int32,
    rev_nat_index [F] int32).
    reference: bpf/lib/lb.h:604 (L4 then wildcard-port), :158 (hash pick).
    """
    vips = jnp.asarray(vips, jnp.int32)
    dports = jnp.asarray(dports, jnp.int32)
    flow_hashes = jnp.asarray(flow_hashes, jnp.int32)

    def service_match(port_query):
        m = (
            dlb.valid[None, :]
            & (dlb.vips[None, :] == vips[:, None])
            & (dlb.ports[None, :] == port_query[:, None])
        )  # [F, S]
        found = jnp.any(m, axis=1)
        idx = jnp.argmax(m, axis=1)
        return found, idx

    f_l4, i_l4 = service_match(dports)
    f_l3, i_l3 = service_match(jnp.zeros_like(dports))
    found = f_l4 | f_l3
    idx = jnp.where(f_l4, i_l4, i_l3)

    count = jnp.maximum(dlb.counts[idx], 1)
    # Hash is a uint32 bit pattern (negative int32 views reinterpreted),
    # matching the host path's `hash & 0xFFFFFFFF`.
    slave = (
        flow_hashes.astype(jnp.uint32) % count.astype(jnp.uint32)
    ).astype(jnp.int32)  # 0-based into backend arrays
    target = dlb.b_target[idx, slave]
    port = dlb.b_port[idx, slave]
    rev = dlb.revnat[idx]
    zero = jnp.zeros_like(target)
    return (
        found,
        jnp.where(found, target, zero),
        jnp.where(found, port, zero),
        jnp.where(found, rev, zero),
    )


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceLb6Map:
    vip_words: jax.Array  # [4, S] int32
    ports: jax.Array  # [S] int32
    counts: jax.Array  # [S] int32
    revnat: jax.Array  # [S] int32
    b_target_words: jax.Array  # [4, S, B] int32
    b_port: jax.Array  # [S, B] int32
    valid: jax.Array  # [S] bool

    def tree_flatten(self):
        return (
            (self.vip_words, self.ports, self.counts, self.revnat,
             self.b_target_words, self.b_port, self.valid),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


def lb6_select_backend_batch(dlb: "DeviceLb6Map", vip_words, dports,
                             flow_hashes):
    """v6 batched service lookup + backend selection: vip_words is a
    4-tuple of [F] int32 word arrays.  Returns (found, target_words
    4-tuple, port, rev_nat_index) — the v6 twin of
    lb4_select_backend_batch (reference: bpf/lib/lb.h lb6_*)."""
    vw = [jnp.asarray(w, jnp.int32) for w in vip_words]
    dports = jnp.asarray(dports, jnp.int32)
    flow_hashes = jnp.asarray(flow_hashes, jnp.int32)

    def service_match(port_query):
        m = dlb.valid[None, :] & (dlb.ports[None, :] == port_query[:, None])
        for w in range(4):
            m = m & (dlb.vip_words[w][None, :] == vw[w][:, None])
        found = jnp.any(m, axis=1)
        idx = jnp.argmax(m, axis=1)
        return found, idx

    f_l4, i_l4 = service_match(dports)
    f_l3, i_l3 = service_match(jnp.zeros_like(dports))
    found = f_l4 | f_l3
    idx = jnp.where(f_l4, i_l4, i_l3)

    count = jnp.maximum(dlb.counts[idx], 1)
    slave = (
        flow_hashes.astype(jnp.uint32) % count.astype(jnp.uint32)
    ).astype(jnp.int32)
    zero = jnp.zeros_like(idx, dtype=jnp.int32)
    target_words = tuple(
        jnp.where(found, dlb.b_target_words[w][idx, slave], zero)
        for w in range(4)
    )
    port = jnp.where(found, dlb.b_port[idx, slave], zero)
    rev = jnp.where(found, dlb.revnat[idx], zero)
    return found, target_words, port, rev
