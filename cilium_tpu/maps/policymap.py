"""Per-endpoint policy map: {identity, dport, proto, dir} -> {proxy_port}.

reference: pkg/maps/policymap/policymap.go (PolicyKey/PolicyEntry, Allow/
Delete/DumpToSlice) and the in-kernel lookup cascade bpf/lib/policy.h:47
__policy_can_access:

  1. {identity, dport, proto}  hit -> proxy_port (0 = allow, no redirect)
  2. {identity, 0, 0}          hit -> allow at L3 (no redirect)
  3. {0, dport, proto}         hit -> proxy_port (wildcard-identity L4)
  4. miss                      -> drop

The host table is authoritative and keeps the packed binary ABI (packed key
8 bytes, entry 24 bytes, checked by cilium_tpu.alignchecker); ``to_device``
exports the cascade as a DeviceTable for the batched verdict op.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.maplookup import DeviceTable, exact_lookup, pack_table

# Traffic directions (reference: pkg/maps/policymap/policymap.go Ingress=0x1?
# — the datapath encodes egress as a 1-bit flag in policy_key, common.h:184).
DIR_INGRESS = 0
DIR_EGRESS = 1

ALL_PORTS = 0

# Packed layouts (reference: bpf/lib/common.h:180-193).
_KEY_FMT = "<IHBB"  # sec_label, dport(be stored as-is), protocol, egress-bit
_ENTRY_FMT = "<HHHHQQ"  # proxy_port(be), pad[3], packets, bytes

KEY_SIZE = struct.calcsize(_KEY_FMT)  # 8
ENTRY_SIZE = struct.calcsize(_ENTRY_FMT)  # 24

MAX_ENTRIES = 65536


@dataclass(frozen=True)
class PolicyKey:
    """reference: policymap.go:64 PolicyKey."""

    identity: int
    dest_port: int = 0  # host byte-order here; packed as big-endian
    proto: int = 0
    direction: int = DIR_INGRESS

    def pack(self) -> bytes:
        be_port = ((self.dest_port & 0xFF) << 8) | (self.dest_port >> 8)
        return struct.pack(_KEY_FMT, self.identity, be_port, self.proto,
                           self.direction & 1)

    @staticmethod
    def unpack(b: bytes) -> "PolicyKey":
        identity, be_port, proto, egress = struct.unpack(_KEY_FMT, b)
        port = ((be_port & 0xFF) << 8) | (be_port >> 8)
        return PolicyKey(identity, port, proto, egress & 1)

    def __str__(self) -> str:
        d = "Egress" if self.direction == DIR_EGRESS else "Ingress"
        if self.dest_port:
            return f"{d}: {self.identity} {self.dest_port}/{self.proto}"
        return f"{d}: {self.identity}"


@dataclass
class PolicyEntry:
    """reference: policymap.go:73 PolicyEntry."""

    proxy_port: int = 0  # host byte-order; packed as big-endian
    packets: int = 0
    bytes: int = 0

    def pack(self) -> bytes:
        be_port = ((self.proxy_port & 0xFF) << 8) | (self.proxy_port >> 8)
        return struct.pack(_ENTRY_FMT, be_port, 0, 0, 0, self.packets, self.bytes)

    @staticmethod
    def unpack(b: bytes) -> "PolicyEntry":
        be_port, _, _, _, packets, nbytes = struct.unpack(_ENTRY_FMT, b)
        port = ((be_port & 0xFF) << 8) | (be_port >> 8)
        return PolicyEntry(port, packets, nbytes)


class PolicyMap:
    """Host-side authoritative policy map (reference: policymap.go)."""

    def __init__(self, endpoint_id: int = 0) -> None:
        self.endpoint_id = endpoint_id
        self.entries: dict[PolicyKey, PolicyEntry] = {}

    def allow(
        self,
        identity: int,
        dport: int = 0,
        proto: int = 0,
        direction: int = DIR_INGRESS,
        proxy_port: int = 0,
    ) -> None:
        """reference: policymap.go:164-186 Allow/AllowKey."""
        key = PolicyKey(identity, dport, proto, direction)
        existing = self.entries.get(key)
        if existing is not None:
            existing.proxy_port = proxy_port
        else:
            if len(self.entries) >= MAX_ENTRIES:
                raise OverflowError("policy map full")
            self.entries[key] = PolicyEntry(proxy_port=proxy_port)

    def delete(
        self, identity: int, dport: int = 0, proto: int = 0,
        direction: int = DIR_INGRESS,
    ) -> bool:
        """reference: policymap.go:188 DeleteKey."""
        return self.entries.pop(PolicyKey(identity, dport, proto, direction),
                                None) is not None

    def exists(self, identity: int, dport: int = 0, proto: int = 0,
               direction: int = DIR_INGRESS) -> bool:
        return PolicyKey(identity, dport, proto, direction) in self.entries

    def flush(self) -> None:
        self.entries.clear()

    def dump(self) -> list[tuple[PolicyKey, PolicyEntry]]:
        """Sorted dump (reference: policymap.go PolicyEntriesDump.Less:
        direction first, then identity)."""
        return sorted(
            self.entries.items(),
            key=lambda kv: (kv[0].direction, kv[0].identity, kv[0].dest_port),
        )

    def lookup(self, identity: int, dport: int, proto: int,
               direction: int = DIR_INGRESS,
               count_packets: bool = True) -> tuple[bool, int]:
        """Host-side reference cascade; returns (allowed, proxy_port)
        (reference: bpf/lib/policy.h:47).  ``count_packets=False`` makes
        the lookup a pure read (oracle use)."""
        for key in (
            PolicyKey(identity, dport, proto, direction),
            PolicyKey(identity, 0, 0, direction),
            PolicyKey(0, dport, proto, direction),
        ):
            e = self.entries.get(key)
            if e is not None:
                if count_packets:
                    e.packets += 1
                if key.dest_port == 0 and key.identity != 0:
                    return True, 0  # L3-only allow, never a redirect
                return True, e.proxy_port
        return False, 0

    def to_device(self, pad_to: int | None = None) -> "DevicePolicyMap":
        items = list(self.entries.items())
        n = len(items)
        if pad_to is None:
            # Pad to the next power of two (min 64) so repeated policy
            # updates reuse jit caches instead of recompiling per size.
            pad_to = 64
            while pad_to < n:
                pad_to *= 2
        keys = np.zeros((n, 4), np.int64)
        vals = np.zeros((n, 1), np.int64)
        for i, (k, e) in enumerate(items):
            keys[i] = (k.identity, k.dest_port, k.proto, k.direction)
            vals[i, 0] = e.proxy_port
        return DevicePolicyMap(
            table=pack_table(keys, vals, pad_to=pad_to)
        )


@jax.tree_util.register_pytree_node_class
@dataclass
class DevicePolicyMap:
    table: DeviceTable

    def tree_flatten(self):
        return ((self.table,), None)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


def policy_can_access_batch(
    dmap: DevicePolicyMap,
    identities,
    dports,
    protos,
    direction: int = DIR_INGRESS,
):
    """Batched __policy_can_access (reference: bpf/lib/policy.h:47-110).

    Args are [F] int32 arrays.  Returns (allowed [F] bool,
    proxy_port [F] int32).
    """
    identities = jnp.asarray(identities, jnp.int32)
    dports = jnp.asarray(dports, jnp.int32)
    protos = jnp.asarray(protos, jnp.int32)
    zeros = jnp.zeros_like(identities)
    dirs = jnp.full_like(identities, direction)

    # Step 1: exact L4 match.
    f1, v1 = exact_lookup(dmap.table, identities, dports, protos, dirs)
    # Step 2: L3-only (dport=0, proto=0) — allow without redirect.
    f2, _ = exact_lookup(dmap.table, identities, zeros, zeros, dirs)
    # Step 3: wildcard identity L4.
    f3, v3 = exact_lookup(dmap.table, zeros, dports, protos, dirs)

    allowed = f1 | f2 | f3
    proxy_port = jnp.where(
        f1, v1[:, 0], jnp.where(f2, 0, jnp.where(f3, v3[:, 0], 0))
    )
    return allowed, proxy_port
