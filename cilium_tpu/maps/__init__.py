"""Typed datapath maps — the array-native equivalent of pkg/maps/*.

Each map keeps an authoritative host-side table with the reference's packed
binary layout (reference: bpf/lib/common.h structs, verified by
cilium_tpu.alignchecker) plus a ``to_device()`` export packing entries into
column arrays for batched device lookups (cilium_tpu.ops.maplookup / lpm).
"""

from .policymap import (
    DIR_EGRESS,
    DIR_INGRESS,
    DevicePolicyMap,
    PolicyEntry,
    PolicyKey,
    PolicyMap,
    policy_can_access_batch,
)
from .ctmap import CtEntry, CtKey4, CtMap, TCP_CLOSING_LIFETIME, CT_DEFAULT_LIFETIME
from .lbmap import (
    DeviceLbMap,
    LbBackend,
    LbMap,
    lb4_select_backend_batch,
)
from .ipcache import IpcacheMap
from .lxcmap import EndpointInfo, LxcMap
from .metricsmap import MetricsMap
from .proxymap import ProxyMap

__all__ = [
    "CT_DEFAULT_LIFETIME",
    "CtEntry",
    "CtKey4",
    "CtMap",
    "DIR_EGRESS",
    "DIR_INGRESS",
    "DeviceLbMap",
    "DevicePolicyMap",
    "EndpointInfo",
    "IpcacheMap",
    "LbBackend",
    "LbMap",
    "LxcMap",
    "MetricsMap",
    "PolicyEntry",
    "PolicyKey",
    "PolicyMap",
    "ProxyMap",
    "TCP_CLOSING_LIFETIME",
    "lb4_select_backend_batch",
    "policy_can_access_batch",
]
