"""Correlation-ID rewrite cache.

reference: pkg/kafka/correlation_cache.go — the proxy rewrites each
forwarded request's correlation ID to a locally unique value so responses
can be matched back to their origin request, then restores the original ID
on the response path.
"""

from __future__ import annotations

import threading
from typing import Optional

from .request import RequestMessage


class CorrelationCache:
    def __init__(self) -> None:
        self._next = 1
        self._origins: dict[int, tuple[int, RequestMessage]] = {}
        self._mutex = threading.Lock()

    def handle_request(self, req: RequestMessage) -> int:
        """Assign a unique ID, remembering the original; returns the new
        ID (reference: correlation_cache.go HandleRequest)."""
        with self._mutex:
            new_id = self._next
            self._next += 1
            if self._next > 0x7FFFFFFF:
                self._next = 1
            self._origins[new_id] = (req.correlation_id, req)
        req.set_correlation_id(new_id)
        return new_id

    def correlate(self, response_id: int) -> Optional[RequestMessage]:
        """Find the origin request for a response (keeps the entry for
        duplicate responses until delete)."""
        with self._mutex:
            entry = self._origins.get(response_id)
            return entry[1] if entry else None

    def restore_response_id(self, response_id: int) -> Optional[int]:
        """Original correlation ID for a proxied response; removes the
        entry (reference: correlation_cache.go Delete on response)."""
        with self._mutex:
            entry = self._origins.pop(response_id, None)
            return entry[0] if entry else None

    def __len__(self) -> int:
        return len(self._origins)
