"""Kafka policy matching — the host oracle for the device ACL model.

reference: pkg/kafka/policy.go:200 MatchesRule + :142 ruleMatches.
"""

from __future__ import annotations

from ..policy.api import PortRuleKafka
from .request import (
    FIND_COORDINATOR_KEY,
    PARSED_TOPIC_KEYS,
    RequestMessage,
    TOPIC_API_KEYS,
)


def _rule_matches(req: RequestMessage, rule: PortRuleKafka) -> bool:
    """reference: policy.go:142 ruleMatches."""
    if not rule.check_api_key_role(req.api_key):
        return False
    api_version, wildcard = rule.get_api_version()
    if not wildcard and api_version != req.api_version:
        return False
    if rule.topic == "" and rule.client_id == "":
        return True
    if req.parsed and req.api_key in PARSED_TOPIC_KEYS:
        # Parsed request types check ClientID (policy.go:73-140).
        if rule.client_id and rule.client_id != req.client_id:
            return False
        return True
    if req.api_key == FIND_COORDINATOR_KEY:
        # ConsumerMetadataReq: unconditionally allowed (policy.go:181).
        return True
    # Header-only (nil request): a topic rule can never match a
    # topic-carrying API key (policy.go:54 matchNonTopicRequests).
    if rule.topic and req.api_key in TOPIC_API_KEYS:
        return False
    return True


def matches_rule(req: RequestMessage, rules: list[PortRuleKafka]) -> bool:
    """reference: policy.go:200 MatchesRule — a request is allowed if a
    topic-less matching rule allows it outright, or every distinct topic
    in the request is allowed by some matching rule naming it."""
    topics = set(req.get_topics())
    remaining = set(topics)
    for rule in rules:
        if rule.topic == "" or not topics:
            if _rule_matches(req, rule):
                return True
        elif rule.topic in remaining:
            if _rule_matches(req, rule):
                remaining.discard(rule.topic)
                if not remaining:
                    return True
    return False
