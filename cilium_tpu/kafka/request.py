"""Kafka wire-format request parsing and response construction.

reference: pkg/kafka/request.go — the reference parses requests with the
optiopay/kafka library; here the header and the topic lists of the six
topic-bearing request types the reference inspects (produce, fetch,
offsets, metadata, offsetcommit, offsetfetch — request.go:88-156) are
parsed directly from the wire format:

  frame   := length(int32) header body
  header  := api_key(int16) api_version(int16) correlation_id(int32)
             client_id(nullable_string)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional

# API keys (reference: pkg/policy/api/kafka.go:107-133).
PRODUCE_KEY = 0
FETCH_KEY = 1
OFFSETS_KEY = 2
METADATA_KEY = 3
OFFSET_COMMIT_KEY = 8
OFFSET_FETCH_KEY = 9
FIND_COORDINATOR_KEY = 10

# Request types whose topics the reference extracts (request.go:88).
PARSED_TOPIC_KEYS = frozenset(
    [PRODUCE_KEY, FETCH_KEY, OFFSETS_KEY, METADATA_KEY,
     OFFSET_COMMIT_KEY, OFFSET_FETCH_KEY]
)

# API keys carrying a topic in the request (reference: policy.go:27
# isTopicAPIKey) — single source of truth in policy.api.
from ..policy.api import KAFKA_TOPIC_API_KEYS as TOPIC_API_KEYS  # noqa: E402

ERROR_TOPIC_AUTHORIZATION_FAILED = 29


class KafkaParseError(ValueError):
    pass


class _Reader:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.off = 0

    def _need(self, n: int) -> None:
        if self.off + n > len(self.data):
            raise KafkaParseError(
                f"truncated at offset {self.off}, need {n} bytes"
            )

    def int8(self) -> int:
        self._need(1)
        v = self.data[self.off]
        self.off += 1
        return v

    def int16(self) -> int:
        self._need(2)
        v = struct.unpack_from(">h", self.data, self.off)[0]
        self.off += 2
        return v

    def int32(self) -> int:
        self._need(4)
        v = struct.unpack_from(">i", self.data, self.off)[0]
        self.off += 4
        return v

    def int64(self) -> int:
        self._need(8)
        v = struct.unpack_from(">q", self.data, self.off)[0]
        self.off += 8
        return v

    def string(self) -> Optional[str]:
        n = self.int16()
        if n < 0:
            return None
        self._need(n)
        v = self.data[self.off:self.off + n].decode("utf-8", "replace")
        self.off += n
        return v

    def bytes_(self) -> Optional[bytes]:
        n = self.int32()
        if n < 0:
            return None
        self._need(n)
        v = self.data[self.off:self.off + n]
        self.off += n
        return v

    def skip(self, n: int) -> None:
        self._need(n)
        self.off += n


@dataclass
class RequestMessage:
    """reference: pkg/kafka/request.go RequestMessage."""

    api_key: int
    api_version: int
    correlation_id: int
    client_id: str
    topics: list[str] = field(default_factory=list)
    parsed: bool = False  # body parsed (one of PARSED_TOPIC_KEYS)
    raw: bytes = b""  # full frame including the length prefix

    def get_topics(self) -> list[str]:
        return self.topics

    def set_correlation_id(self, cid: int) -> None:
        """Rewrite in the raw frame too (reference: request.go:66)."""
        self.correlation_id = cid
        if len(self.raw) >= 12:
            self.raw = (
                self.raw[:8] + struct.pack(">i", cid) + self.raw[12:]
            )

    def create_response(self, error_code: int = ERROR_TOPIC_AUTHORIZATION_FAILED
                        ) -> "ResponseMessage":
        """Build a deny response echoing the correlation ID
        (reference: request.go:158 CreateResponse)."""
        body = _error_response_body(self, error_code)
        payload = struct.pack(">i", self.correlation_id) + body
        return ResponseMessage(
            correlation_id=self.correlation_id,
            raw=struct.pack(">i", len(payload)) + payload,
        )


@dataclass
class ResponseMessage:
    correlation_id: int
    raw: bytes

    @staticmethod
    def parse_correlation_id(frame: bytes) -> int:
        """Peek the correlation ID of a response frame."""
        if len(frame) < 8:
            raise KafkaParseError("response frame too short")
        return struct.unpack_from(">i", frame, 4)[0]


def _parse_topics(r: _Reader, api_key: int, api_version: int) -> list[str]:
    """Extract topic names for the six inspected request types."""
    topics: list[str] = []

    if api_key == PRODUCE_KEY:
        if api_version >= 3:
            r.string()  # transactional_id
        r.int16()  # acks
        r.int32()  # timeout
        n = r.int32()
        for _ in range(max(n, 0)):
            topics.append(r.string() or "")
            # partitions array: [partition(int32) record_set(bytes)]
            pn = r.int32()
            for _ in range(max(pn, 0)):
                r.int32()
                rec = r.bytes_()
    elif api_key == FETCH_KEY:
        r.int32()  # replica_id
        r.int32()  # max_wait
        r.int32()  # min_bytes
        if api_version >= 3:
            r.int32()  # max_bytes
        if api_version >= 4:
            r.int8()  # isolation_level
        n = r.int32()
        for _ in range(max(n, 0)):
            topics.append(r.string() or "")
            pn = r.int32()
            for _ in range(max(pn, 0)):
                r.int32()  # partition
                r.int64()  # fetch_offset
                if api_version >= 5:
                    r.int64()  # log_start_offset
                r.int32()  # max_bytes
    elif api_key == OFFSETS_KEY:
        r.int32()  # replica_id
        if api_version >= 2:
            r.int8()  # isolation_level
        n = r.int32()
        for _ in range(max(n, 0)):
            topics.append(r.string() or "")
            pn = r.int32()
            for _ in range(max(pn, 0)):
                r.int32()  # partition
                r.int64()  # timestamp
                if api_version == 0:
                    r.int32()  # max_num_offsets
    elif api_key == METADATA_KEY:
        n = r.int32()
        for _ in range(max(n, 0)):  # -1 = all topics
            topics.append(r.string() or "")
    elif api_key == OFFSET_COMMIT_KEY:
        r.string()  # group_id
        if api_version >= 1:
            r.int32()  # generation_id
            r.string()  # member_id
        if api_version >= 2:
            r.int64()  # retention_time
        n = r.int32()
        for _ in range(max(n, 0)):
            topics.append(r.string() or "")
            pn = r.int32()
            for _ in range(max(pn, 0)):
                r.int32()  # partition
                r.int64()  # offset
                if api_version == 1:
                    r.int64()  # timestamp
                r.string()  # metadata
    elif api_key == OFFSET_FETCH_KEY:
        r.string()  # group_id
        n = r.int32()
        for _ in range(max(n, 0)):
            topics.append(r.string() or "")
            pn = r.int32()
            for _ in range(max(pn, 0)):
                r.int32()  # partition
    return topics


def parse_request(frame: bytes) -> RequestMessage:
    """Parse one length-prefixed request frame
    (reference: request.go:186 ReadRequest)."""
    if len(frame) < 4:
        raise KafkaParseError("frame shorter than length prefix")
    (length,) = struct.unpack_from(">i", frame, 0)
    if length < 8 or 4 + length > len(frame):
        raise KafkaParseError(f"bad frame length {length}")
    r = _Reader(frame[4:4 + length])
    api_key = r.int16()
    api_version = r.int16()
    correlation_id = r.int32()
    client_id = r.string() or ""
    msg = RequestMessage(
        api_key=api_key,
        api_version=api_version,
        correlation_id=correlation_id,
        client_id=client_id,
        raw=frame[:4 + length],
    )
    if api_key in PARSED_TOPIC_KEYS:
        try:
            msg.topics = _parse_topics(r, api_key, api_version)
            msg.parsed = True
        except KafkaParseError:
            # Header-only fallback, like the reference when the library
            # can't parse the body (policy.go matchNonTopicRequests).
            msg.topics = []
            msg.parsed = False
    return msg


def frame_length(buf: bytes) -> Optional[int]:
    """Total frame size (prefix included) if the length field is complete."""
    if len(buf) < 4:
        return None
    (length,) = struct.unpack_from(">i", buf, 0)
    if length < 0:
        raise KafkaParseError(f"negative frame length {length}")
    return 4 + length


def _error_response_body(req: RequestMessage, error_code: int) -> bytes:
    """Version-aware error response per API key (reference:
    request.go:158 CreateResponse family): every inspected topic gets the
    error code in a body shaped for the request's api_version, so clients
    receive a clean TOPIC_AUTHORIZATION_FAILED instead of a parse error."""
    w = bytearray()
    v = req.api_version

    def put16(x):
        w.extend(struct.pack(">h", x))

    def put32(x):
        w.extend(struct.pack(">i", x))

    def put64(x):
        w.extend(struct.pack(">q", x))

    def put_str(s):
        b = s.encode()
        put16(len(b))
        w.extend(b)

    if req.api_key == PRODUCE_KEY:
        put32(len(req.topics))
        for t in req.topics:
            put_str(t)
            put32(1)  # one partition entry
            put32(0)  # partition
            put16(error_code)
            put64(-1)  # base_offset
            if v >= 2:
                put64(-1)  # log_append_time
        if v >= 1:
            put32(0)  # throttle_time_ms (trailing for produce)
    elif req.api_key == FETCH_KEY:
        if v >= 1:
            put32(0)  # throttle_time_ms (leading for fetch)
        put32(len(req.topics))
        for t in req.topics:
            put_str(t)
            put32(1)
            put32(0)  # partition
            put16(error_code)
            put64(-1)  # high_watermark
            if v >= 4:
                put64(-1)  # last_stable_offset
                if v >= 5:
                    put64(-1)  # log_start_offset
                put32(0)  # aborted_transactions count
            put32(0)  # record set size
    elif req.api_key == OFFSETS_KEY:
        if v >= 2:
            put32(0)  # throttle_time_ms
        put32(len(req.topics))
        for t in req.topics:
            put_str(t)
            put32(1)
            put32(0)  # partition
            put16(error_code)
            if v == 0:
                put32(0)  # offsets array (empty)
            else:
                put64(-1)  # timestamp
                put64(-1)  # offset
    elif req.api_key == METADATA_KEY:
        if v >= 3:
            put32(0)  # throttle_time_ms
        put32(0)  # brokers
        if v >= 2:
            put_str("")  # cluster_id
        if v >= 1:
            put32(-1)  # controller_id
        put32(len(req.topics))
        for t in req.topics:
            put16(error_code)
            put_str(t)
            if v >= 1:
                w.extend(b"\x00")  # is_internal
            put32(0)  # partitions
    elif req.api_key == OFFSET_COMMIT_KEY:
        if v >= 3:
            put32(0)  # throttle_time_ms
        put32(len(req.topics))
        for t in req.topics:
            put_str(t)
            put32(1)
            put32(0)  # partition
            put16(error_code)
    elif req.api_key == OFFSET_FETCH_KEY:
        if v >= 3:
            put32(0)  # throttle_time_ms
        put32(len(req.topics))
        for t in req.topics:
            put_str(t)
            put32(1)
            put32(0)  # partition
            put64(-1)  # offset
            put_str("")  # metadata
            put16(error_code)
        if v >= 2:
            put16(error_code)  # top-level error
    else:
        # Uninspected request types get an empty body; clients treat the
        # missing payload as a broker error.
        pass
    return bytes(w)
