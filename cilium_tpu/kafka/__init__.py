"""Kafka protocol support: request parsing, policy matching, correlation
tracking, error response injection, and the batched TPU ACL model input.

reference: pkg/kafka — request frame parse (request.go:186 ReadRequest),
topic extraction per API key (request.go:88 GetTopics), policy matching
(policy.go:200 MatchesRule), correlation-ID rewrite cache
(correlation_cache.go), deny response injection (request.go:158).
"""

from .request import (
    KafkaParseError,
    RequestMessage,
    ResponseMessage,
    parse_request,
)
from .policy import matches_rule
from .correlation import CorrelationCache

__all__ = [
    "CorrelationCache",
    "KafkaParseError",
    "RequestMessage",
    "ResponseMessage",
    "matches_rule",
    "parse_request",
]
