"""Access log unix-socket server + client.

reference: pkg/envoy/accesslog_server.go:45 (server accepting protobuf
LogEntry frames from proxies over a unix socket, converting to
accesslog.LogRecord and feeding monitor + logger) and
proxylib/accesslog/client.go (sender).  Framing: 4-byte big-endian length
+ JSON record.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import Callable

from ..utils.logging import get_logger
from ..utils.sockutil import shutdown_close
from .record import LogRecord

log = get_logger("accesslog")


class AccessLogServer:
    """reference: accesslog_server.go:45 StartAccessLogServer."""

    def __init__(
        self,
        path: str,
        on_record: Callable[[LogRecord], None] | None = None,
    ) -> None:
        self.path = path
        self.on_record = on_record
        self.records: list[LogRecord] = []
        self._mutex = threading.Lock()
        if os.path.exists(path):
            os.unlink(path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(path)
        self._sock.listen(16)
        self._stop = threading.Event()
        threading.Thread(
            target=self._accept_loop, name="accesslog-server", daemon=True
        ).start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(None)
            while True:
                hdr = b""
                while len(hdr) < 4:
                    chunk = conn.recv(4 - len(hdr))
                    if not chunk:
                        return
                    hdr += chunk
                (n,) = struct.unpack(">I", hdr)
                if n > 16 * 1024 * 1024:
                    log.with_field("size", n).warning(
                        "oversized access log frame; closing"
                    )
                    return
                body = b""
                while len(body) < n:
                    chunk = conn.recv(n - len(body))
                    if not chunk:
                        return
                    body += chunk
                try:
                    rec = LogRecord.from_dict(json.loads(body.decode()))
                except (ValueError, TypeError) as e:
                    log.with_field("error", str(e)).warning(
                        "bad access log record"
                    )
                    continue
                self._handle(rec)
        except OSError:
            pass
        finally:
            shutdown_close(conn)

    def _handle(self, rec: LogRecord) -> None:
        with self._mutex:
            self.records.append(rec)
            if len(self.records) > 65536:
                self.records = self.records[-32768:]
        if self.on_record is not None:
            try:
                self.on_record(rec)
            except Exception:  # noqa: BLE001 — consumers never break intake
                pass

    def drain(self) -> list[LogRecord]:
        with self._mutex:
            out = self.records
            self.records = []
            return out

    def close(self) -> None:
        self._stop.set()
        # shutdown wakes the accept thread parked on the listener so
        # the fd tears down now, not at its next timeout tick.
        try:
            shutdown_close(self._sock)
        finally:
            if os.path.exists(self.path):
                os.unlink(self.path)


class AccessLogClient:
    """Sender side (reference: proxylib/accesslog/client.go).

    ``timeout`` bounds connect and sendall: access logging is
    best-effort by contract (a failed log() returns False and the
    verdict still flows), so a wedged collector — bound but not
    accepting, or accepting but never reading until the socket buffer
    fills — must cost ONE bounded wait, not hang the datapath caller
    under the client mutex forever."""

    def __init__(self, path: str, timeout: float = 5.0) -> None:
        self.path = path
        self.timeout = timeout
        self._sock: socket.socket | None = None
        self._mutex = threading.Lock()

    def _connect(self) -> socket.socket:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self.path)
        return s

    def log(self, rec: LogRecord) -> bool:
        """Send one record; reconnects once on failure (reference:
        client.go Log with reconnect)."""
        data = json.dumps(rec.to_dict()).encode()
        frame = struct.pack(">I", len(data)) + data
        with self._mutex:
            for _ in range(2):
                try:
                    if self._sock is None:
                        # lint: disable=R2 -- connect is bounded by the constructor timeout; dialing under the mutex is the one-socket serialization this client is built on
                        self._sock = self._connect()
                    # One socket serialized by design; the sendall is
                    # bounded by the constructor timeout, so a wedged
                    # collector fails this log() instead of wedging it.
                    self._sock.sendall(frame)  # lint: disable=R2 -- bounded by settimeout; serializing the shared socket is the point
                    return True
                except OSError:
                    shutdown_close(self._sock)
                    self._sock = None
        return False

    def close(self) -> None:
        with self._mutex:
            if self._sock is not None:
                shutdown_close(self._sock)
                self._sock = None
