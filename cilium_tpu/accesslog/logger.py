"""Access log plumbing: endpoint enrichment + fan-out.

reference: pkg/proxy/logger/logger.go:84 — fills in endpoint/identity
info on each record, then sends it to the monitor stream and the
structured log file (daemon/daemon.go:1653).
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Optional

from ..utils.metrics import ProxyVerdicts
from .record import LogRecord, VERDICT_FORWARDED


class AccessLogger:
    def __init__(
        self,
        endpoint_lookup: Callable[[int], object] | None = None,
        notify: Callable[[LogRecord], None] | None = None,
        logfile_path: str = "",
    ) -> None:
        self.endpoint_lookup = endpoint_lookup
        self.notify = notify
        self.logfile_path = logfile_path
        self._mutex = threading.Lock()

    def log(self, rec: LogRecord) -> None:
        """Enrich + fan out (reference: logger.go Log)."""
        self._fill_endpoint_info(rec)
        proto = (
            "http" if rec.http else "kafka" if rec.kafka
            else (rec.l7.proto if rec.l7 else "unknown")
        )
        verdict = (
            "forwarded" if rec.verdict == VERDICT_FORWARDED else "denied"
        )
        ProxyVerdicts.inc(proto, verdict)
        if self.notify is not None:
            self.notify(rec)
        if self.logfile_path:
            with self._mutex, open(self.logfile_path, "a") as f:
                f.write(json.dumps(rec.to_dict()) + "\n")

    def _fill_endpoint_info(self, rec: LogRecord) -> None:
        """reference: logger.go fillEndpointInfo."""
        if self.endpoint_lookup is None:
            return
        for info in (rec.source, rec.destination):
            if info.id and not info.labels:
                ep = self.endpoint_lookup(info.id)
                if ep is not None and getattr(ep, "security_identity", None):
                    info.identity = ep.security_identity.id
                    info.labels = ep.security_identity.labels.get_model()
                    info.ipv4 = getattr(ep, "ipv4", "")
