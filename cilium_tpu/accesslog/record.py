"""Canonical L7 access log record (reference: pkg/proxy/accesslog/record.go)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field, asdict
from typing import Optional

# Flow types (reference: record.go FlowType).
FLOW_TYPE_REQUEST = "Request"
FLOW_TYPE_RESPONSE = "Response"
FLOW_TYPE_SAMPLE = "Sample"

# Verdicts (reference: record.go FlowVerdict).
VERDICT_FORWARDED = "Forwarded"
VERDICT_DENIED = "Denied"
VERDICT_ERROR = "Error"

# Observation points (reference: record.go ObservationPoint).
OBS_POINT_INGRESS = "Ingress"
OBS_POINT_EGRESS = "Egress"


@dataclass
class EndpointInfo:
    """reference: record.go EndpointInfo."""

    id: int = 0
    identity: int = 0
    labels: list[str] = field(default_factory=list)
    ipv4: str = ""
    port: int = 0


@dataclass
class HttpLogEntry:
    """reference: record.go LogRecordHTTP."""

    code: int = 0
    method: str = ""
    url: str = ""
    protocol: str = "HTTP/1.1"
    headers: list[str] = field(default_factory=list)


@dataclass
class KafkaLogEntry:
    """reference: record.go LogRecordKafka."""

    error_code: int = 0
    api_version: int = 0
    api_key: str = ""
    correlation_id: int = 0
    topics: list[str] = field(default_factory=list)


@dataclass
class L7LogEntry:
    """Generic L7 entry (reference: record.go LogRecordL7)."""

    proto: str = ""
    fields: dict = field(default_factory=dict)


@dataclass
class LatencyInfo:
    """Verdict-path latency breakdown attached to slow-verdict
    exemplars by the sidecar tracer (sidecar/trace.py): end-to-end
    microseconds, the serving path (vec|oracle|host|shed), and the
    per-stage decomposition (queue/batch_form/device_submit/device/
    drain/send)."""

    total_us: float = 0.0
    path: str = ""
    stages_us: dict = field(default_factory=dict)


@dataclass
class LogRecord:
    """reference: record.go:140 LogRecord."""

    type: str = FLOW_TYPE_REQUEST
    timestamp: str = ""
    observation_point: str = OBS_POINT_INGRESS
    source: EndpointInfo = field(default_factory=EndpointInfo)
    destination: EndpointInfo = field(default_factory=EndpointInfo)
    verdict: str = VERDICT_FORWARDED
    info: str = ""
    transport_protocol: int = 6
    http: Optional[HttpLogEntry] = None
    kafka: Optional[KafkaLogEntry] = None
    l7: Optional[L7LogEntry] = None
    latency: Optional[LatencyInfo] = None

    def __post_init__(self) -> None:
        if not self.timestamp:
            self.timestamp = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            )

    def to_dict(self) -> dict:
        d = asdict(self)
        return {k: v for k, v in d.items() if v is not None}

    @staticmethod
    def from_dict(d: dict) -> "LogRecord":
        rec = LogRecord(
            type=d.get("type", FLOW_TYPE_REQUEST),
            timestamp=d.get("timestamp", ""),
            observation_point=d.get("observation_point", OBS_POINT_INGRESS),
            verdict=d.get("verdict", VERDICT_FORWARDED),
            info=d.get("info", ""),
            transport_protocol=d.get("transport_protocol", 6),
        )
        if "source" in d:
            rec.source = EndpointInfo(**d["source"])
        if "destination" in d:
            rec.destination = EndpointInfo(**d["destination"])
        if d.get("http"):
            rec.http = HttpLogEntry(**d["http"])
        if d.get("kafka"):
            rec.kafka = KafkaLogEntry(**d["kafka"])
        if d.get("l7"):
            rec.l7 = L7LogEntry(**d["l7"])
        if d.get("latency"):
            rec.latency = LatencyInfo(**d["latency"])
        return rec
