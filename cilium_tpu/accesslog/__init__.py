"""Access log records and the agent-side log server.

reference: pkg/proxy/accesslog/record.go (the canonical LogRecord with
HTTP/Kafka/L7 variants) + pkg/envoy/accesslog_server.go (unix-socket server
receiving per-request records from proxies, feeding the monitor and the
structured log file) + proxylib/accesslog/client.go (the sender side).
"""

from .record import (
    FLOW_TYPE_REQUEST,
    FLOW_TYPE_RESPONSE,
    FLOW_TYPE_SAMPLE,
    OBS_POINT_INGRESS,
    OBS_POINT_EGRESS,
    VERDICT_DENIED,
    VERDICT_ERROR,
    VERDICT_FORWARDED,
    EndpointInfo,
    HttpLogEntry,
    KafkaLogEntry,
    L7LogEntry,
    LogRecord,
)
from .server import AccessLogClient, AccessLogServer
from .logger import AccessLogger

__all__ = [
    "AccessLogClient",
    "AccessLogServer",
    "AccessLogger",
    "EndpointInfo",
    "FLOW_TYPE_REQUEST",
    "FLOW_TYPE_RESPONSE",
    "FLOW_TYPE_SAMPLE",
    "HttpLogEntry",
    "KafkaLogEntry",
    "L7LogEntry",
    "LogRecord",
    "OBS_POINT_EGRESS",
    "OBS_POINT_INGRESS",
    "VERDICT_DENIED",
    "VERDICT_ERROR",
    "VERDICT_FORWARDED",
]
