"""fqdn: toFQDNs rules -> generated CIDR rules with TTL-driven refresh.

reference: pkg/fqdn — a DNS poller periodically resolves every DNS name
referenced by a ``toFQDNs`` egress section (dnspoller.go), caches the
answers with their TTLs (cache.go DNSCache), and regenerates the owning
rules' ToCIDRSet with one generated /32 (or /128) per live IP; when the
answer set changes, policy regeneration is triggered so endpoints pick
up the new CIDR identities.

The resolver is injectable (tests use a fake; production wires a real
DNS client); answers below min_ttl are clamped up, mirroring the
reference's MinTTL handling.
"""

from __future__ import annotations

import ipaddress
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable

from .policy.api import CIDRRule
from .utils.controller import ControllerManager, ControllerParams

DNS_POLLER_INTERVAL = 5.0  # reference: dnspoller.go DNSPollerInterval
DEFAULT_MIN_TTL = 5.0

# resolver(name) -> (ips, ttl_seconds)
Resolver = Callable[[str], tuple[Iterable[str], float]]


@dataclass
class _CacheEntry:
    ips: tuple[str, ...]
    expires: float


class DnsCache:
    """Name -> live IPs with per-answer TTL (reference: cache.go DNSCache,
    folded to one entry per name — the poller re-resolves whole names)."""

    def __init__(self, clock=time.monotonic) -> None:
        self._entries: dict[str, _CacheEntry] = {}
        self._mutex = threading.Lock()
        self.clock = clock

    def update(self, name: str, ips: Iterable[str], ttl: float) -> None:
        with self._mutex:
            self._entries[name] = _CacheEntry(
                ips=tuple(sorted(set(ips))), expires=self.clock() + ttl
            )

    def lookup(self, name: str) -> tuple[str, ...]:
        with self._mutex:
            e = self._entries.get(name)
            if e is None or e.expires < self.clock():
                return ()
            return e.ips

    def lookup_stale(self, name: str) -> tuple[str, ...]:
        """Last known answer regardless of TTL (used for change
        detection across re-resolution, where ``lookup`` would already
        read () for the just-expired entry)."""
        with self._mutex:
            e = self._entries.get(name)
            return () if e is None else e.ips

    def expired(self, name: str) -> bool:
        with self._mutex:
            e = self._entries.get(name)
            return e is None or e.expires < self.clock()


class DnsPoller:
    """Resolve ToFQDNs names and regenerate rules' generated CIDR sets
    (reference: dnspoller.go LookupUpdateDNS + ruleGen semantics)."""

    def __init__(
        self,
        repo,
        resolver: Resolver,
        on_change: Callable[[], None] | None = None,
        min_ttl: float = DEFAULT_MIN_TTL,
        interval: float = DNS_POLLER_INTERVAL,
        controllers: ControllerManager | None = None,
        clock=time.monotonic,
    ) -> None:
        self.repo = repo
        self.resolver = resolver
        self.on_change = on_change
        self.min_ttl = min_ttl
        self.interval = interval
        self.cache = DnsCache(clock=clock)
        self._controllers = controllers or ControllerManager()
        self._own_controllers = controllers is None
        self._started = False

    def start(self) -> "DnsPoller":
        if not self._started:
            self._started = True
            self._controllers.update_controller(
                "dns-poller",
                ControllerParams(do_func=self.lookup_update_dns,
                                 run_interval=self.interval),
            )
        return self

    # -- one poll cycle ----------------------------------------------------

    def _names_in_use(self) -> set[str]:
        names: set[str] = set()
        with self.repo.mutex:
            for rule in self.repo.rules:
                for eg in rule.egress:
                    for f in eg.to_fqdns:
                        names.add(f.match_name)
        return names

    def lookup_update_dns(self) -> None:
        """Resolve every name whose cache TTL lapsed, then regenerate
        the rules if any answer set changed."""
        changed = False
        for name in sorted(self._names_in_use()):
            if not self.cache.expired(name):
                continue
            before = self.cache.lookup_stale(name)
            try:
                ips, ttl = self.resolver(name)
            except Exception:  # noqa: BLE001 — resolver failure keeps
                continue  # the previous answer until it expires
            self.cache.update(name, ips, max(float(ttl), self.min_ttl))
            if tuple(sorted(set(ips))) != before:
                changed = True
        if changed:
            self.regenerate_rules()
            if self.on_change is not None:
                self.on_change()

    def regenerate_rules(self) -> None:
        """Replace each ToFQDNs egress section's GENERATED CIDR entries
        with the current resolutions (user-written entries survive)."""
        with self.repo.mutex:
            for rule in self.repo.rules:
                for eg in rule.egress:
                    if not eg.to_fqdns:
                        continue
                    kept = [c for c in eg.to_cidr_set if not c.generated]
                    for f in eg.to_fqdns:
                        for ip in self.cache.lookup(f.match_name):
                            addr = ipaddress.ip_address(ip)
                            width = 32 if addr.version == 4 else 128
                            kept.append(
                                CIDRRule(cidr=f"{addr}/{width}",
                                         generated=True)
                            )
                    eg.to_cidr_set = kept
            self.repo.revision += 1

    # -- introspection -----------------------------------------------------

    def generated_cidrs(self) -> dict[str, tuple[str, ...]]:
        return {
            name: self.cache.lookup(name) for name in self._names_in_use()
        }

    def close(self) -> None:
        if self._own_controllers:
            self._controllers.remove_all()
        else:
            self._controllers.remove_controller("dns-poller")
