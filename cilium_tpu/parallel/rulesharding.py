"""Rule-axis model sharding: split a rule set across devices.

The reference scales per-endpoint policy by compiling per-identity rule
tables inside each Envoy worker (reference: envoy/cilium_network_policy.h:
50-76 — every worker holds the whole table).  On TPU the equivalent scale
limit is HBM: a policy's packed NFA transition tables (delta is O(S²·C))
and per-rule compare tensors grow with the rule count, and past a point
one chip cannot hold them.  Rule-axis sharding splits the RULES of one
policy across the mesh's ``RULE_AXIS``:

  - every shard compiles ITS OWN rule subset into its own tables (an NFA
    over fewer patterns has fewer states, so delta shrinks
    quadratically — sharding 2x cuts per-device table HBM ~4x);
  - shards are padded to a common (states, classes, patterns) shape and
    stacked along a leading shard dim, laid out with
    ``PartitionSpec(RULE_AXIS)`` so each device holds exactly one
    shard's tables;
  - evaluation runs under ``shard_map``: flows shard over FLOW_AXIS,
    every device evaluates its local rule subset, and per-rule-subset
    partial verdicts merge with an OR-reduce (``psum > 0``) over
    RULE_AXIS — one small [F] collective per batch, riding ICI.

The OR-reduce is exact, not approximate: every model's verdict is
``any(rule allows)`` over disjoint rule subsets (for Kafka the ORable
partials are (simple, cover); the ∀-topics combine happens after the
reduce — see models/kafka.py kafka_rule_hits/kafka_combine).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# jax.shard_map graduated from jax.experimental in 0.5; accept both so
# the mesh code runs on the container's pinned jax too.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover — depends on the installed jax
    from jax.experimental.shard_map import shard_map

from ..models.base import MAX_REMOTES, ConstVerdict, pack_remote_sets
from ..models.http import (
    HttpBatchModel,
    build_http_model,
    http_verdicts,
    http_verdicts_attr,
)
from ..models.kafka import (
    KafkaBatchModel,
    build_kafka_model,
    kafka_combine,
    kafka_rule_hits,
)
from ..models.dns import (
    DnsBatchModel,
    build_dns_model_from_rows,
    collect_dns_policy_rows,
    dns_row_arrays,
    dns_verdicts,
    dns_verdicts_attr,
)
from ..models.r2d2 import (
    MAX_CMD,
    R2d2BatchModel,
    _rule_bucket,
    build_r2d2_model_from_rows,
    collect_policy_rows,
    r2d2_verdicts,
    r2d2_verdicts_attr,
)
from ..ops.nfa import DeviceNfa, device_nfa
from ..regex import compile_patterns
from ..regex.tables import NfaTables
from .mesh import FLOW_AXIS, RULE_AXIS

P = jax.sharding.PartitionSpec

# Sentinel beating every real rule row in the cross-shard min-index
# reduction (rule counts are int32 row indices, far below this).
_NO_MATCH = np.iinfo(np.int32).max


def split_balanced(seq: list, k: int) -> list[list]:
    """Split seq into k contiguous, size-balanced chunks (first chunks
    one longer when len % k != 0).  Chunks may be empty when k > len."""
    n = len(seq)
    base, extra = divmod(n, k)
    out, i = [], 0
    for j in range(k):
        step = base + (1 if j < extra else 0)
        out.append(seq[i : i + step])
        i += step
    return out


def shard_offsets(n_rows: int, n_shards: int) -> jax.Array:
    """[n_shards] int32 global row index of each shard's FIRST rule row
    under split_balanced — the per-shard bias that turns a shard-local
    first-match argmax into a global row id (attribution contract:
    global index == the unsharded model's flattened row order == the
    host oracle's walk order)."""
    sizes = np.asarray(
        [len(s) for s in split_balanced(list(range(n_rows)), n_shards)],
        np.int32,
    )
    return jnp.asarray(
        np.concatenate(([0], np.cumsum(sizes)))[:-1].astype(np.int32)
    )


# --- table padding --------------------------------------------------------

def pad_tables(t: NfaTables, s: int, c: int, r: int) -> NfaTables:
    """Pad an NfaTables to (s states, c classes, r patterns).  Padding
    states have no transitions and are never set; padding classes are
    never produced by classmap; padding patterns never accept."""
    assert s >= t.n_states and c >= t.n_classes and r >= t.n_patterns
    delta = np.zeros((c, s, s), np.uint8)
    delta[: t.n_classes, : t.n_states, : t.n_states] = t.delta
    start = np.zeros((s,), bool)
    start[: t.n_states] = t.start
    accept = np.zeros((r, s), bool)
    accept[: t.n_patterns, : t.n_states] = t.accept
    accept_final = np.zeros((r, s), bool)
    accept_final[: t.n_patterns, : t.n_states] = t.accept_final
    matches_empty = np.zeros((r,), bool)
    matches_empty[: t.n_patterns] = t.matches_empty
    return NfaTables(
        n_states=s,
        n_classes=c,
        n_patterns=r,
        classmap=t.classmap,
        delta=delta,
        start=start,
        accept=accept,
        accept_final=accept_final,
        matches_empty=matches_empty,
        patterns=list(t.patterns),
    )


def _never_match_tables(n_patterns: int) -> NfaTables:
    """Tables with n_patterns patterns that accept nothing (used to give
    head-pattern-less shards a uniformly shaped head NFA)."""
    t = compile_patterns(["x"])
    t.accept[:] = False
    t.accept_final[:] = False
    t.matches_empty[:] = False
    return pad_tables(t, t.n_states, t.n_classes, max(n_patterns, 1))


def stack_nfas(tables: list[NfaTables]) -> DeviceNfa:
    """Pad a list of per-shard tables to a common shape and stack their
    device forms along a leading shard axis."""
    s = max(t.n_states for t in tables)
    c = max(t.n_classes for t in tables)
    r = max(t.n_patterns for t in tables)
    nfas = [device_nfa(pad_tables(t, s, c, r)) for t in tables]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *nfas)


def _stack_models(models: list):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *models)


# --- r2d2 -----------------------------------------------------------------

def build_sharded_r2d2_model(
    policy, ingress: bool, port: int, n_shards: int, bucket: bool = False
) -> ConstVerdict | R2d2BatchModel:
    """Compile the policy's rows into ``n_shards`` stacked shard models:
    every leaf gains a leading [n_shards] dim to lay out with
    PartitionSpec(RULE_AXIS).  Aux dims (states/classes/patterns) are
    padded to the max across shards so the stacked treedef is uniform.
    Padded rule rows are dead via never-accepting NFA pattern rows
    (file_ok is always False for them, independent of input bytes).
    ``bucket=True`` pads the per-shard rule axis to the power-of-two
    bucket (models/r2d2.MIN_RULE_BUCKET) so policy churn that stays in
    the bucket reuses the compiled mesh executable — the sharded twin
    of the single-chip shape-bucketed dispatch cache, keyed by
    (shard count, bucket) through the stacked leaf shapes."""
    rows = collect_policy_rows(policy, ingress, port)
    if isinstance(rows, ConstVerdict):
        return rows
    return build_sharded_r2d2_from_rows(rows, n_shards, bucket=bucket)


def build_sharded_r2d2_from_rows(
    rows: list, n_shards: int, bucket: bool = False
) -> R2d2BatchModel:
    """Rows-based half of build_sharded_r2d2_model (exposed for giant
    synthetic tables — the 100k-rule bench slice — where a full
    proxylib policy compile of the same rows would dominate)."""
    shards = split_balanced(rows, n_shards)
    r_max = max(len(s) for s in shards)
    if bucket:
        r_max = _rule_bucket(r_max)
    shard_tables = [
        compile_patterns([r[2] for r in s]) if s else _never_match_tables(1)
        for s in shards
    ]
    s_max = max(t.n_states for t in shard_tables)
    c_max = max(t.n_classes for t in shard_tables)
    models = []
    for s, t in zip(shards, shard_tables):
        packed = np.zeros((r_max, MAX_REMOTES), np.int32)
        any_remote = np.zeros((r_max,), bool)
        cmd_needle = np.zeros((r_max, MAX_CMD), np.uint8)
        cmd_len = np.zeros((r_max,), np.int32)
        cmd_any = np.zeros((r_max,), bool)
        if s:
            ids, anyr = pack_remote_sets([r[0] for r in s])
            packed[: len(s)] = ids
            any_remote[: len(s)] = anyr
            for i, (_, cmd, _f) in enumerate(s):
                b = cmd.encode()
                cmd_needle[i, : len(b)] = np.frombuffer(b, np.uint8)
                cmd_len[i] = len(b)
                cmd_any[i] = len(b) == 0
        models.append(
            R2d2BatchModel(
                nfa=device_nfa(pad_tables(t, s_max, c_max, r_max)),
                cmd_needle=jnp.asarray(cmd_needle),
                cmd_len=jnp.asarray(cmd_len),
                cmd_any=jnp.asarray(cmd_any),
                remote_ids=jnp.asarray(packed),
                any_remote=jnp.asarray(any_remote),
            )
        )
    return _stack_models(models)


# --- dns ------------------------------------------------------------------

def build_sharded_dns_from_rows(
    rows: list, n_shards: int, bucket: bool = False
) -> DnsBatchModel:
    """Shard (remote_set, DnsRule|None) rows across n_shards stacked
    models.  Aux dims unify across shards (needle width, NFA
    states/classes/patterns) so the stacked treedef is uniform;
    padding rows are dead (needle_len -1, never-accepting automaton
    slots, remote set {-1}) exactly like the single-chip padding."""
    shards = split_balanced(list(rows), n_shards)
    r_max = max(len(s) for s in shards)
    if bucket:
        r_max = _rule_bucket(r_max)
    # One needle width across shards so stacked leaves share shapes.
    width = max(
        (len(r.name.encode("latin-1", "replace"))
         for s in shards for _, r in s
         if r is not None and r.name),
        default=0,
    )
    width = max(8, (width + 7) // 8 * 8)
    per_shard = [
        dns_row_arrays(s, r_max, width=width) for s in shards
    ]
    tables = [
        compile_patterns(arr[6]) if any(arr[6]) else
        _never_match_tables(max(len(arr[6]), 1))
        for arr in per_shard
    ]
    s_max = max(t.n_states for t in tables)
    c_max = max(t.n_classes for t in tables)
    p_max = max(t.n_patterns for t in tables)
    models = []
    for arr, t in zip(per_shard, tables):
        needle, n_len, n_any, use_rx, packed, any_remote, _pats = arr
        models.append(
            DnsBatchModel(
                nfa=device_nfa(pad_tables(t, s_max, c_max, p_max)),
                name_needle=jnp.asarray(needle),
                name_len=jnp.asarray(n_len),
                name_any=jnp.asarray(n_any),
                use_rx=jnp.asarray(use_rx),
                remote_ids=jnp.asarray(packed),
                any_remote=jnp.asarray(any_remote),
            )
        )
    return _stack_models(models)


def mesh_dns_model(policy, ingress: bool, port: int, mesh):
    """Mesh-resident DNS name-policy model for the live serving path —
    the sharded twin of models/dns.build_dns_model: same port cascade,
    same flattened row order, single-chip fallback compiled alongside
    (the device-loss rung), ``match_kinds``/``invariant_rows`` from the
    fallback so attribution and the verdict-cache claim are identical
    on both rungs."""
    rows = collect_dns_policy_rows(policy, ingress, port)
    if isinstance(rows, ConstVerdict):
        return rows
    n_shards = mesh.shape[RULE_AXIS]
    fallback = build_dns_model_from_rows(rows, bucket=True)
    stacked = build_sharded_dns_from_rows(rows, n_shards, bucket=True)
    return ShardedVerdictModel(
        stacked, shard_offsets(len(rows), n_shards), mesh, "dns",
        fallback=fallback, match_kinds=fallback.match_kinds,
    )


# --- http -----------------------------------------------------------------

def build_sharded_http_model(
    rules_with_remotes: list, n_shards: int
) -> ConstVerdict | HttpBatchModel:
    """Shard (remote_set, PortRuleHTTP) rows across n_shards stacked
    models.  Every tier pads to cross-shard maxima: literal rows via the
    live mask, regex/head patterns via never-accepting table rows, rule
    dims via dead rules (no wildcard flag + no rows = method_ok False)."""
    from ..models.http import analyze_rules, lit_arrays

    if not rules_with_remotes:
        return ConstVerdict(False)
    shards = split_balanced(list(rules_with_remotes), n_shards)
    r_max = max(len(s) for s in shards)
    analyzed = [analyze_rules(s) for s in shards]

    def line_tab(patterns):
        return (
            compile_patterns(patterns) if patterns else _never_match_tables(1)
        )

    line_ts = [line_tab(a[2]) for a in analyzed]
    any_head = any(a[7] for a in analyzed)
    head_ts = [line_tab(a[7]) if any_head else None for a in analyzed]

    nm = max(max(len(a[0]) for a in analyzed), 1)
    npath = max(max(len(a[1]) for a in analyzed), 1)
    # Needle widths unified across shards so stacked models share shapes.
    lit_w = max(
        (
            len(lit)
            for a in analyzed
            for rows in (a[0], a[1])
            for lit, _, _ in rows
        ),
        default=0,
    )
    lit_w = max(8, (lit_w + 7) // 8 * 8)
    # Slot-usage flags are aux (static) — must agree across shards
    # (a[4] is each shard's line_slot list).
    has_m_rx = any(s == 0 for a in analyzed for s in a[4])
    has_p_rx = any(s == 1 for a in analyzed for s in a[4])
    pl_max = max(t.n_patterns for t in line_ts)
    ls = max(t.n_states for t in line_ts)
    lc = max(t.n_classes for t in line_ts)
    if any_head:
        p_max = max(t.n_patterns for t in head_ts)
        hs = max(t.n_states for t in head_ts)
        hc = max(t.n_classes for t in head_ts)

    models = []
    for shard, a, lt, ht in zip(shards, analyzed, line_ts, head_ts):
        (m_rows, p_rows, _line_pats, line_rule, line_slot, method_any,
         path_any, _head_pats, head_rule, head_count) = a
        n = len(shard)
        mn, ml, mp, mr, mlive = lit_arrays(m_rows, nm, width=lit_w)
        pn, pl_, pp, pr, plive = lit_arrays(p_rows, npath, width=lit_w)
        packed_ids = np.zeros((r_max, MAX_REMOTES), np.int32)
        any_remote = np.zeros((r_max,), bool)
        ma = np.zeros((r_max,), bool)
        pa = np.zeros((r_max,), bool)
        hcnt = np.zeros((r_max,), np.int32)
        if n:
            ids, anyr = pack_remote_sets([rs for rs, _ in shard])
            packed_ids[:n] = ids
            any_remote[:n] = anyr
            ma[:n] = method_any
            pa[:n] = path_any
            hcnt[:n] = np.asarray(head_count, np.int32)
        lr = np.zeros((pl_max,), np.int32)
        lsl = np.zeros((pl_max,), np.int32)
        lr[: len(line_rule)] = np.asarray(line_rule, np.int32)
        lsl[: len(line_slot)] = np.asarray(line_slot, np.int32)
        hr = np.zeros((max(p_max, 1) if any_head else 1,), np.int32)
        if any_head:
            hr[: len(head_rule)] = np.asarray(head_rule, np.int32)
        models.append(
            HttpBatchModel(
                m_needle=jnp.asarray(mn),
                m_len=jnp.asarray(ml),
                m_prefix=jnp.asarray(mp),
                m_rule=jnp.asarray(mr),
                m_live=jnp.asarray(mlive),
                p_needle=jnp.asarray(pn),
                p_len=jnp.asarray(pl_),
                p_prefix=jnp.asarray(pp),
                p_rule=jnp.asarray(pr),
                p_live=jnp.asarray(plive),
                method_any=jnp.asarray(ma),
                path_any=jnp.asarray(pa),
                line_nfa=device_nfa(pad_tables(lt, ls, lc, pl_max)),
                line_rule=jnp.asarray(lr),
                line_slot=jnp.asarray(lsl),
                head_nfa=(
                    device_nfa(pad_tables(ht, hs, hc, p_max))
                    if any_head
                    else None
                ),
                head_rule=jnp.asarray(hr),
                head_count=jnp.asarray(hcnt),
                remote_ids=jnp.asarray(packed_ids),
                any_remote=jnp.asarray(any_remote),
                n_rules=r_max,
                has_method_rx=has_m_rx,
                has_path_rx=has_p_rx,
            )
        )
    return _stack_models(models)


# --- kafka ----------------------------------------------------------------

def _pad_kafka_model(m: KafkaBatchModel, r: int) -> KafkaBatchModel:
    """Pad rule rows to r with dead rules (api_key_mask all-False fails
    key_ok; any_remote False with no ids fails remote_ok)."""
    cur = m.version.shape[0]
    if cur == r:
        return m

    def pad(x, fill=0):
        widths = [(0, r - cur)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, widths, constant_values=fill)

    return KafkaBatchModel(
        api_key_mask=pad(m.api_key_mask, False),
        version=pad(m.version),
        version_any=pad(m.version_any, False),
        client=pad(m.client),
        client_len=pad(m.client_len),
        client_any=pad(m.client_any, False),
        topic=pad(m.topic),
        topic_len=pad(m.topic_len),
        topic_any=pad(m.topic_any, False),
        is_topic_key=m.is_topic_key,
        remote_ids=pad(m.remote_ids),
        any_remote=pad(m.any_remote, False),
    )


def build_sharded_kafka_model(
    rules_with_remotes: list, n_shards: int
) -> ConstVerdict | KafkaBatchModel:
    if not rules_with_remotes:
        return ConstVerdict(False)
    shards = split_balanced(list(rules_with_remotes), n_shards)
    r_max = max(len(s) for s in shards)
    models = []
    for s in shards:
        if s:
            m = build_kafka_model(s)
        else:
            m = build_kafka_model(rules_with_remotes[:1])
            m = jax.tree_util.tree_map(lambda x: jnp.zeros_like(x), m)
        models.append(_pad_kafka_model(m, r_max))
    return _stack_models(models)


# --- sharded evaluation ---------------------------------------------------

def _local(model):
    """Drop the singleton shard dim a device sees under shard_map, and
    mark every leaf varying over FLOW_AXIS for the vma checker: model
    state mixes with flow-varying data inside lax.scan carries, whose
    input/output varying-axis sets must agree.  (On jax < 0.6 there is
    no vma checker and no lax.pcast — dropping the dim suffices.)"""
    if hasattr(jax.lax, "pcast"):
        mark = lambda x: jax.lax.pcast(x, FLOW_AXIS, to="varying")  # noqa: E731
    else:
        mark = lambda x: x  # noqa: E731
    return jax.tree_util.tree_map(lambda x: mark(x[0]), model)


def sharded_verdict_step(mesh, verdict_fn):
    """Jitted (stacked_model, data, lengths, remotes) -> (complete,
    msg_len, allow) over a (FLOW_AXIS, RULE_AXIS) mesh for models whose
    verdict is any-rule-allows (r2d2, http): flows shard, rules shard,
    allow OR-reduces over RULE_AXIS."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(RULE_AXIS), P(FLOW_AXIS), P(FLOW_AXIS), P(FLOW_AXIS)),
        out_specs=(P(FLOW_AXIS), P(FLOW_AXIS), P(FLOW_AXIS)),
    )
    def step(model, data, lengths, remotes):
        complete, msg_len, allow = verdict_fn(
            _local(model), data, lengths, remotes
        )
        allow = (
            jax.lax.psum(allow.astype(jnp.int32), RULE_AXIS) > 0
        )
        return complete, msg_len, allow

    return step


def sharded_kafka_step(mesh):
    """Jitted (stacked_model, batch, remotes) -> allow [F] bool.  The
    ORable partials (simple, cover) psum over RULE_AXIS; the ∀-topics
    combine runs on the merged partials (it does not distribute over
    rule subsets)."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(RULE_AXIS), P(FLOW_AXIS), P(FLOW_AXIS)),
        out_specs=P(FLOW_AXIS),
    )
    def step(model, batch, remotes):
        simple, cover = kafka_rule_hits(_local(model), batch, remotes)
        simple = jax.lax.psum(simple.astype(jnp.int32), RULE_AXIS) > 0
        cover = jax.lax.psum(cover.astype(jnp.int32), RULE_AXIS) > 0
        return kafka_combine(
            simple, cover, batch.topic_count, batch.overflow
        )

    return step


def sharded_verdict_step_attr(mesh, attr_fn):
    """Jitted (stacked_model, offsets, data, lengths, remotes) ->
    (complete, msg_len, allow, rule) over a (FLOW_AXIS, RULE_AXIS)
    mesh, with rule ids resolved GLOBALLY across rule shards in the
    same device round: each shard's ``attr_fn`` yields its local
    first-match argmax, the local index is biased by the shard's
    global row offset, and a cross-shard min-index reduction (pmin
    over RULE_AXIS) picks the host oracle's first match — no second
    hit-matrix pass, no extra readback."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(RULE_AXIS), P(RULE_AXIS),
            P(FLOW_AXIS), P(FLOW_AXIS), P(FLOW_AXIS),
        ),
        out_specs=(
            P(FLOW_AXIS), P(FLOW_AXIS), P(FLOW_AXIS), P(FLOW_AXIS),
        ),
    )
    def step(model, offsets, data, lengths, remotes):
        local, off = _local((model, offsets))
        complete, msg_len, allow_l, rule_l = attr_fn(
            local, data, lengths, remotes
        )
        cand = jnp.where(
            rule_l >= 0, rule_l + off, jnp.int32(_NO_MATCH)
        )
        cand = jax.lax.pmin(cand, RULE_AXIS)
        allow = jax.lax.psum(allow_l.astype(jnp.int32), RULE_AXIS) > 0
        rule = jnp.where(allow, cand, jnp.int32(-1))
        return complete, msg_len, allow, rule

    return step


# --- mesh-resident serving models -----------------------------------------
#
# Drop-in replacements for the single-chip batch models on the live
# dispatch path: same (data, lengths, remotes) -> (complete, msg_len,
# allow[, rule]) contract, tables resident sharded across the mesh.
# One jitted step per (mesh, family, attr) lives for the process: jit's
# own shape cache then keys executables by the stacked model's leaf
# shapes — i.e. by (shard count, rule bucket) — so policy churn whose
# rebuilt tables land in the same buckets re-uploads arrays without
# retracing a mesh executable.

_FAMILY_FNS = {
    "r2d2": (r2d2_verdicts, r2d2_verdicts_attr),
    "http": (http_verdicts, http_verdicts_attr),
    "dns": (dns_verdicts, dns_verdicts_attr),
}
_STEP_CACHE: dict = {}


def _mesh_step(mesh, family: str, attr: bool):
    key = (mesh, family, attr)
    step = _STEP_CACHE.get(key)
    if step is None:
        plain_fn, attr_fn = _FAMILY_FNS[family]
        step = (
            sharded_verdict_step_attr(mesh, attr_fn)
            if attr
            else sharded_verdict_step(mesh, plain_fn)
        )
        _STEP_CACHE[key] = step
    return step


def _pad_flow_axis(n: int, n_flow: int, *arrays):
    """Pad leading (flow) axes up to a multiple of the mesh's flow
    extent — shard_map requires exact divisibility.  The service's
    power-of-two buckets always divide, so this is a no-op on the
    dispatch path; ad-hoc callers (probes, tests) pay one jnp.pad."""
    pad = (-n) % n_flow
    if not pad:
        return 0, arrays
    out = tuple(
        jax.tree_util.tree_map(
            lambda x: jnp.pad(
                x, [(0, pad)] + [(0, 0)] * (jnp.ndim(x) - 1)
            ),
            a,
        )
        for a in arrays
    )
    return pad, out


@jax.tree_util.register_pytree_node_class
class ShardedVerdictModel:
    """A (flows, rules)-mesh-resident verdict model.

    ``stacked`` is the per-shard model pytree (leading [n_shards] dim,
    laid out with PartitionSpec(RULE_AXIS)); ``offsets`` the per-shard
    global row offsets the attributed step biases local argmaxes with.
    ``fallback`` is the SINGLE-CHIP executable compiled from the same
    rows — the degradation rung the service demotes to when a mesh
    device is lost (typed + counted; verdicts are bit-identical by the
    sharding parity contract).  ``fallback`` and ``match_kinds`` are
    host-side metadata, deliberately OUTSIDE the pytree (like
    R2d2BatchModel.match_kinds): the traced computation never reads
    them, and keeping them out of aux keeps churn relabels on the
    compiled executable."""

    def __init__(self, stacked, offsets, mesh, family: str,
                 fallback=None, match_kinds: tuple = ()):
        self.stacked = stacked
        self.offsets = offsets
        self.mesh = mesh
        self.family = family
        self.fallback = fallback
        self.match_kinds = match_kinds

    @property
    def n_shards(self) -> int:
        return int(self.offsets.shape[0])

    @property
    def remote_ids(self):
        """Stacked per-shard remote tables (epoch parity probes ravel
        these to draw candidate identities)."""
        return self.stacked.remote_ids

    def tree_flatten(self):
        return (self.stacked, self.offsets), (self.mesh, self.family)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(leaves[0], leaves[1], aux[0], aux[1])

    def dispatch_bare(self) -> "ShardedVerdictModel":
        """Shape-keyed dispatch-cache marker (see R2d2BatchModel): the
        service jits with the wrapper as an ARGUMENT, so same-bucketed
        churn rebuilds share one compiled mesh executable keyed by
        (shard count, rule bucket) through the stacked leaf shapes."""
        return self

    def __call__(self, data, lengths, remotes):
        n = data.shape[0]
        pad, (data, lengths, remotes) = _pad_flow_axis(
            n, self.mesh.shape[FLOW_AXIS], data, lengths, remotes
        )
        out = _mesh_step(self.mesh, self.family, attr=False)(
            self.stacked, data, lengths, remotes
        )
        return tuple(o[:n] for o in out) if pad else out

    def verdicts_attr(self, data, lengths, remotes):
        n = data.shape[0]
        pad, (data, lengths, remotes) = _pad_flow_axis(
            n, self.mesh.shape[FLOW_AXIS], data, lengths, remotes
        )
        out = _mesh_step(self.mesh, self.family, attr=True)(
            self.stacked, self.offsets, data, lengths, remotes
        )
        return tuple(o[:n] for o in out) if pad else out


@jax.tree_util.register_pytree_node_class
class ShardedKafkaModel:
    """Mesh twin of KafkaBatchModel's (batch, remotes) -> allow
    contract: the ORable (simple, cover) partials psum over RULE_AXIS,
    the ∀-topics combine runs on the merged partials."""

    def __init__(self, stacked, mesh, fallback=None):
        self.stacked = stacked
        self.mesh = mesh
        self.fallback = fallback

    def tree_flatten(self):
        return (self.stacked,), (self.mesh,)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(leaves[0], aux[0])

    def __call__(self, batch, remotes):
        key = (self.mesh, "kafka")
        step = _STEP_CACHE.get(key)
        if step is None:
            step = _STEP_CACHE[key] = sharded_kafka_step(self.mesh)
        n = remotes.shape[0]
        n_flow = self.mesh.shape[FLOW_AXIS]
        pad, padded = _pad_flow_axis(n, n_flow, batch, remotes)
        if pad:
            batch, remotes = padded
        allow = step(self.stacked, batch, remotes)
        return allow[:n] if pad else allow


# --- mesh-aware model builds (the live serving path's entry) --------------

def mesh_r2d2_model(policy, ingress: bool, port: int, mesh):
    """Mesh-resident r2d2 model for the live serving path: rule rows
    split-balanced and padded across the mesh's RULE_AXIS (bucketed so
    churn reuses compiled mesh executables), plus the single-chip
    fallback executable the service demotes to on device loss.
    Constant-verdict rule sets fold exactly as in the unsharded build.
    ``match_kinds`` comes from the fallback compile so the attribution
    legend is identical on both rungs."""
    rows = collect_policy_rows(policy, ingress, port)
    if isinstance(rows, ConstVerdict):
        return rows
    n_shards = mesh.shape[RULE_AXIS]
    fallback = build_r2d2_model_from_rows(rows, bucket=True)
    stacked = build_sharded_r2d2_model(
        policy, ingress, port, n_shards, bucket=True
    )
    return ShardedVerdictModel(
        stacked, shard_offsets(len(rows), n_shards), mesh, "r2d2",
        fallback=fallback, match_kinds=fallback.match_kinds,
    )


def mesh_http_model_from_rows(rows: list, mesh):
    """THE one assembly of a mesh-resident HTTP model from flattened
    (remote_set, PortRuleHTTP) rows — shared by the policy-cascade
    build below and models/builder.build_model_for_filter so the two
    wrapper constructions can never drift."""
    fallback = build_http_model(rows)
    if isinstance(fallback, ConstVerdict):
        return fallback
    n_shards = mesh.shape[RULE_AXIS]
    stacked = build_sharded_http_model(rows, n_shards)
    return ShardedVerdictModel(
        stacked, shard_offsets(len(rows), n_shards), mesh, "http",
        fallback=fallback,
        match_kinds=getattr(fallback, "match_kinds", ()),
    )


def mesh_http_model(policy, ingress: bool, port: int, mesh):
    """Mesh-resident HTTP model for (policy, direction, port) — the
    sharded twin of models/http.build_http_model_for_port, same port
    cascade and flattened row order."""
    from ..models.http import collect_http_rows

    rows = collect_http_rows(policy, ingress, port)
    if isinstance(rows, ConstVerdict):
        return rows
    return mesh_http_model_from_rows(rows, mesh)


def mesh_model_from_family_rows(family: str, rows: list, mesh):
    """Build a ShardedVerdictModel for ``family`` ("r2d2" | "dns" |
    "http") from already-flattened rule rows against an ARBITRARY mesh
    — the width-ladder's one assembly seam: the service's off-path
    reshape (and its parity probe) and the devicecheck reshape audit
    both rebuild through here, so a degraded-width rebuild can never
    drift from the full-width construction (same ``split_balanced``
    re-balance, same re-derived ``shard_offsets``, same pow2 rule
    buckets so the shape-keyed executable cache still hits)."""
    n_shards = mesh.shape[RULE_AXIS]
    if family == "r2d2":
        fallback = build_r2d2_model_from_rows(rows, bucket=True)
        stacked = build_sharded_r2d2_from_rows(rows, n_shards,
                                               bucket=True)
    elif family == "dns":
        fallback = build_dns_model_from_rows(rows, bucket=True)
        stacked = build_sharded_dns_from_rows(rows, n_shards,
                                              bucket=True)
    elif family == "http":
        fallback = build_http_model(rows)
        if isinstance(fallback, ConstVerdict):
            return fallback
        stacked = build_sharded_http_model(rows, n_shards)
    else:
        raise ValueError(f"unknown sharded family {family!r}")
    if isinstance(fallback, ConstVerdict):
        return fallback
    return ShardedVerdictModel(
        stacked, shard_offsets(len(rows), n_shards), mesh, family,
        fallback=fallback,
        match_kinds=getattr(fallback, "match_kinds", ()),
    )


def mesh_kafka_model(rules_with_remotes: list, mesh):
    """Mesh-resident kafka topic-ACL model from (remote_set, rule)
    rows."""
    fallback = build_kafka_model(rules_with_remotes)
    if isinstance(fallback, ConstVerdict):
        return fallback
    stacked = build_sharded_kafka_model(
        rules_with_remotes, mesh.shape[RULE_AXIS]
    )
    return ShardedKafkaModel(stacked, mesh, fallback=fallback)
