"""Device mesh construction and canonical shardings.

Axes:
  "flows" — data-parallel axis; every batch dimension (frames, CIDR lookup
            keys, policy-map lookup keys) shards here.
  "rules" — model-parallel axis for rule sets too large for one chip's HBM;
            1 by default.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FLOW_AXIS = "flows"
RULE_AXIS = "rules"


def flow_mesh(n_flow: int | None = None, n_rule: int = 1, devices=None) -> Mesh:
    """Build a (flows, rules) mesh over ``devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_flow is None:
        n_flow = len(devices) // n_rule
    devs = np.asarray(devices[: n_flow * n_rule]).reshape(n_flow, n_rule)
    return Mesh(devs, (FLOW_AXIS, RULE_AXIS))


def flow_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (flow/batch) axis across the flow axis."""
    return NamedSharding(mesh, P(FLOW_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
