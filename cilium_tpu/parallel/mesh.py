"""Device mesh construction and canonical shardings.

Axes:
  "flows" — data-parallel axis; every batch dimension (frames, CIDR lookup
            keys, policy-map lookup keys) shards here.
  "rules" — model-parallel axis for rule sets too large for one chip's HBM;
            1 by default.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FLOW_AXIS = "flows"
RULE_AXIS = "rules"


def flow_mesh(n_flow: int | None = None, n_rule: int = 1, devices=None) -> Mesh:
    """Build a (flows, rules) mesh over ``devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_flow is None:
        n_flow = len(devices) // n_rule
    devs = np.asarray(devices[: n_flow * n_rule]).reshape(n_flow, n_rule)
    return Mesh(devs, (FLOW_AXIS, RULE_AXIS))


def mesh_extents(mode: str, rule_shards: int = 0, flow_shards: int = 0,
                 n_devices: int = 0,
                 max_flow: int = 32) -> tuple[int, int] | None:
    """Pure extent resolution for ``serving_mesh`` — (n_flow, n_rule)
    or None — split out so >32-wide layouts are unit-testable without
    64 real devices.  The flow extent is floored to a power of two
    (every power-of-two dispatch bucket then divides it).  The
    ``max_flow`` cap applies only to the AUTO derivation (flow_shards
    == 0): an EXPLICIT ``mesh_flow_shards`` may exceed the smallest
    dispatch bucket — the service grows its minimum bucket to the flow
    extent instead (ROADMAP 5b), so >32-device pods shard the flow
    axis fully."""
    if mode == "off":
        return None
    n_rule = max(rule_shards, 1)
    n_flow = flow_shards or max(n_devices // n_rule, 1)
    n_flow = 1 << (n_flow.bit_length() - 1)
    if not flow_shards:
        n_flow = min(n_flow, max_flow)
    if n_flow * n_rule > n_devices:
        return None
    return n_flow, n_rule


def serving_mesh(mode: str, rule_shards: int = 0, flow_shards: int = 0,
                 devices=None, max_flow: int = 32) -> Mesh | None:
    """Resolve a (flows, rules) SERVING mesh from the DaemonConfig
    knobs (``mesh``/``mesh_rule_shards``/``mesh_flow_shards``), or
    None when multi-chip serving is off — THE one resolution shared by
    the sidecar service and the daemon-side engine factory.  'auto'
    requires more than one REAL accelerator device (virtual CPU
    devices share the host's cores — a collective there only adds
    overhead); 'on' forces a mesh at any device count.  Extent rules
    (pow2 flooring, the auto-only ``max_flow`` cap) live in
    ``mesh_extents``."""
    if mode == "off":
        return None
    if devices is None:
        devices = jax.devices()
    if mode != "on" and (
        len(devices) < 2 or devices[0].platform == "cpu"
    ):
        return None
    ext = mesh_extents(mode, rule_shards, flow_shards, len(devices),
                       max_flow=max_flow)
    if ext is None:
        return None
    n_flow, n_rule = ext
    return flow_mesh(n_flow=n_flow, n_rule=n_rule, devices=devices)


def reshape_mesh(survivors, rule_shards: int = 1,
                 max_flow: int = 32) -> Mesh | None:
    """Width-ladder rung: the widest bucketable (flows, rules) mesh
    over a SURVIVING device subset after a partial loss.  The rule
    extent is preserved when the survivors can still fill it (rule
    sharding exists for HBM capacity — halving it doubles per-device
    table memory) and halved only when they cannot; the flow extent is
    the power-of-two floor of what remains, capped at ``max_flow`` so
    every dispatch bucket still divides it.  None when fewer than two
    devices survive in a usable layout — the service then holds the
    single-chip fallback rung instead."""
    survivors = list(survivors)
    n = len(survivors)
    n_rule = max(rule_shards, 1)
    while n_rule > 1 and n_rule > n:
        n_rule = max(n_rule // 2, 1)
    n_flow = n // n_rule
    if n_flow < 1:
        return None
    n_flow = 1 << (n_flow.bit_length() - 1)
    if max_flow:
        n_flow = min(n_flow, max_flow)
    if n_flow * n_rule < 2:
        return None
    return flow_mesh(n_flow=n_flow, n_rule=n_rule, devices=survivors)


def flow_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (flow/batch) axis across the flow axis."""
    return NamedSharding(mesh, P(FLOW_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
