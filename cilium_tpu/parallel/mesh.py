"""Device mesh construction and canonical shardings.

Axes:
  "flows" — data-parallel axis; every batch dimension (frames, CIDR lookup
            keys, policy-map lookup keys) shards here.
  "rules" — model-parallel axis for rule sets too large for one chip's HBM;
            1 by default.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FLOW_AXIS = "flows"
RULE_AXIS = "rules"


def flow_mesh(n_flow: int | None = None, n_rule: int = 1, devices=None) -> Mesh:
    """Build a (flows, rules) mesh over ``devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
    if n_flow is None:
        n_flow = len(devices) // n_rule
    devs = np.asarray(devices[: n_flow * n_rule]).reshape(n_flow, n_rule)
    return Mesh(devs, (FLOW_AXIS, RULE_AXIS))


def serving_mesh(mode: str, rule_shards: int = 0, flow_shards: int = 0,
                 devices=None, max_flow: int = 32) -> Mesh | None:
    """Resolve a (flows, rules) SERVING mesh from the DaemonConfig
    knobs (``mesh``/``mesh_rule_shards``/``mesh_flow_shards``), or
    None when multi-chip serving is off — THE one resolution shared by
    the sidecar service and the daemon-side engine factory.  'auto'
    requires more than one REAL accelerator device (virtual CPU
    devices share the host's cores — a collective there only adds
    overhead); 'on' forces a mesh at any device count.  The flow
    extent is floored to a power of two (every power-of-two dispatch
    bucket then divides it) and capped at ``max_flow``."""
    if mode == "off":
        return None
    if devices is None:
        devices = jax.devices()
    if mode != "on" and (
        len(devices) < 2 or devices[0].platform == "cpu"
    ):
        return None
    n_rule = max(rule_shards, 1)
    n_flow = flow_shards or max(len(devices) // n_rule, 1)
    n_flow = min(1 << (n_flow.bit_length() - 1), max_flow)
    if n_flow * n_rule > len(devices):
        return None
    return flow_mesh(n_flow=n_flow, n_rule=n_rule, devices=devices)


def flow_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (flow/batch) axis across the flow axis."""
    return NamedSharding(mesh, P(FLOW_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
