"""Mesh/sharding helpers: data-parallel flow sharding over TPU meshes.

The reference scales by running one agent+datapath per node with cluster
state converging over a kvstore (reference: pkg/kvstore/store, SURVEY §2.10).
The TPU-native equivalent scales the verdict plane by sharding the *flow*
(batch) axis of every device op over an ICI mesh; rule tables are replicated
(they are small after byte-class compression) until they exceed chip HBM, at
which point the state axis shards too.
"""

from .mesh import (
    FLOW_AXIS,
    RULE_AXIS,
    flow_mesh,
    flow_sharding,
    mesh_extents,
    replicated,
    reshape_mesh,
)

__all__ = [
    "FLOW_AXIS", "RULE_AXIS", "flow_mesh", "flow_sharding",
    "mesh_extents", "replicated", "reshape_mesh",
]
