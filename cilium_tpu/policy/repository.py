"""Revisioned policy rule repository and resolution.

reference: pkg/policy/repository.go + pkg/policy/rule.go.  Rules are stored
in insertion order; every mutation bumps the revision.  Resolution walks all
rules whose EndpointSelector matches the destination (ingress) or source
(egress) labels and merges PortRules into an L4PolicyMap, preserving the
reference's merge semantics: wildcard L3 collapse, L7 parser conflicts,
FromRequires folding, and L3/L4-only rules wildcarding L7.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from ..labels import LabelArray
from .api import (
    EgressRule,
    EndpointSelector,
    IngressRule,
    L7Rules,
    PortRule,
    PortRuleKafka,
    PortRuleHTTP,
    PROTO_ANY,
    PROTO_TCP,
    PROTO_UDP,
    Rule,
    SelectorRequirement,
)
from .l3 import CIDRPolicy
from .l4 import (
    L4Filter,
    L4Policy,
    L4PolicyMap,
    PARSER_TYPE_HTTP,
    PARSER_TYPE_KAFKA,
    PARSER_TYPE_NONE,
    create_l4_egress_filter,
    create_l4_ingress_filter,
)
from .search import Decision, SearchContext


class PolicyMergeError(ValueError):
    """L7 merge conflict (reference: rule.go mergeL4Port errors)."""


@dataclass
class TraceState:
    """reference: repository.go:51."""

    rule_id: int = 0
    selected_rules: int = 0
    matched_rules: int = 0
    constrained_rules: int = 0

    def trace(self, repo: "Repository", ctx: SearchContext) -> None:
        ctx.policy_trace(
            "%d/%d rules selected\n", self.selected_rules, repo.num_rules()
        )
        if self.constrained_rules > 0:
            ctx.policy_trace(
                "Found unsatisfied FromRequires constraint\n"
            )
        elif self.matched_rules > 0:
            ctx.policy_trace("Found allow rule\n")
        else:
            ctx.policy_trace("Found no allow rule\n")


def _l7_rule_exists(existing: L7Rules, kind: str, rule) -> bool:
    if kind == "http":
        return any(r.key() == rule.key() for r in existing.http)
    if kind == "kafka":
        return any(r.key() == rule.key() for r in existing.kafka)
    return any(r.key() == rule.key() for r in existing.l7)


def _merge_l4_port(
    ctx: SearchContext,
    endpoints: list[EndpointSelector],
    existing: L4Filter,
    to_merge: L4Filter,
) -> None:
    """Merge to_merge into existing (reference: rule.go:36-111)."""
    if existing.allows_all_at_l3() or to_merge.allows_all_at_l3():
        from .api import WILDCARD_SELECTOR

        existing.endpoints = [WILDCARD_SELECTOR]
    else:
        existing.endpoints = existing.endpoints + list(endpoints)

    if to_merge.l7_parser != PARSER_TYPE_NONE:
        if existing.l7_parser == PARSER_TYPE_NONE:
            existing.l7_parser = to_merge.l7_parser
        elif to_merge.l7_parser != existing.l7_parser:
            ctx.policy_trace(
                "   Merge conflict: mismatching parsers %s/%s\n",
                to_merge.l7_parser,
                existing.l7_parser,
            )
            raise PolicyMergeError(
                f"cannot merge conflicting L7 parsers "
                f"({to_merge.l7_parser}/{existing.l7_parser})"
            )

    for sel, new_rules in to_merge.l7_rules_per_ep.items():
        ep = existing.l7_rules_per_ep.get(sel)
        if ep is None:
            existing.l7_rules_per_ep[sel] = new_rules
            continue
        if new_rules.http:
            if ep.kafka or ep.l7proto:
                raise PolicyMergeError("cannot merge conflicting L7 rule types")
            for nr in new_rules.http:
                if not _l7_rule_exists(ep, "http", nr):
                    ep.http.append(nr)
        elif new_rules.kafka:
            if ep.http or ep.l7proto:
                raise PolicyMergeError("cannot merge conflicting L7 rule types")
            for nr in new_rules.kafka:
                if not _l7_rule_exists(ep, "kafka", nr):
                    ep.kafka.append(nr)
        elif new_rules.l7proto:
            if ep.kafka or ep.http or (ep.l7proto and ep.l7proto != new_rules.l7proto):
                raise PolicyMergeError("cannot merge conflicting L7 rule types")
            if not ep.l7proto:
                ep.l7proto = new_rules.l7proto
            for nr in new_rules.l7:
                if not _l7_rule_exists(ep, "l7", nr):
                    ep.l7.append(nr)


def _expand_protocols(pp) -> list[str]:
    if pp.protocol != PROTO_ANY:
        return [pp.protocol]
    return [PROTO_TCP, PROTO_UDP]


class Repository:
    """Global revisioned rule store (reference: repository.go:31)."""

    def __init__(self) -> None:
        self.rules: list[Rule] = []
        self.revision: int = 1
        self.mutex = threading.RLock()

    # -- mutation ----------------------------------------------------------

    def add(self, r: Rule) -> int:
        """Sanitize + insert; returns new revision (reference:
        repository.go:529-542)."""
        r.sanitize()
        with self.mutex:
            return self.add_list([r])

    def add_list(self, rules: list[Rule]) -> int:
        with self.mutex:
            self.rules.extend(rules)
            self.revision += 1
            return self.revision

    def delete_by_labels(self, lbls: LabelArray) -> tuple[int, int]:
        """Delete rules whose labels contain lbls; returns (revision,
        n_deleted) (reference: repository.go:566-588)."""
        with self.mutex:
            kept = [r for r in self.rules if not r.labels.contains(lbls)]
            deleted = len(self.rules) - len(kept)
            if deleted > 0:
                self.rules = kept
                self.revision += 1
            return self.revision, deleted

    def bump_revision(self) -> None:
        with self.mutex:
            self.revision += 1

    # -- introspection -----------------------------------------------------

    def num_rules(self) -> int:
        return len(self.rules)

    def get_revision(self) -> int:
        return self.revision

    def is_empty(self) -> bool:
        return not self.rules

    def search(self, lbls: LabelArray) -> list[Rule]:
        """Rules whose labels contain lbls (reference: repository.go:495)."""
        return [r for r in self.rules if r.labels.contains(lbls)]

    def contains_all(self, needed: list[LabelArray]) -> bool:
        """Every needed label set must contain some rule's (non-empty)
        labels (reference: repository.go:510 ContainsAllRLocked)."""
        return all(
            any(r.labels and n.contains(r.labels) for r in self.rules)
            for n in needed
        )

    def get_rules_matching(self, lbls: LabelArray) -> tuple[bool, bool]:
        """Whether any rule's selector matches lbls with ingress/egress
        sections (reference: repository.go:624)."""
        ingress = egress = False
        for r in self.rules:
            if r.endpoint_selector.matches(lbls):
                if r.ingress:
                    ingress = True
                if r.egress:
                    egress = True
        return ingress, egress

    def get_json(self) -> str:
        from .serialize import rules_to_json

        return rules_to_json(self.rules)

    # -- label-level verdicts ---------------------------------------------

    def _can_reach_ingress(self, ctx: SearchContext) -> Decision:
        """reference: repository.go:80 + rule.go canReachIngress."""
        decision = Decision.UNDECIDED
        state = TraceState()
        for i, r in enumerate(self.rules):
            state.rule_id = i
            d = self._rule_can_reach_ingress(r, ctx, state)
            if d == Decision.DENIED:
                decision = Decision.DENIED
                break
            if d == Decision.ALLOWED:
                decision = Decision.ALLOWED
        state.trace(self, ctx)
        return decision

    def _rule_can_reach_ingress(
        self, r: Rule, ctx: SearchContext, state: TraceState
    ) -> Decision:
        if not r.endpoint_selector.matches(ctx.to_labels):
            return Decision.UNDECIDED
        state.selected_rules += 1
        # FromRequires takes precedence (reference: rule.go:358-379).
        for ing in r.ingress:
            for sel in ing.from_requires:
                if not sel.matches(ctx.from_labels):
                    state.constrained_rules += 1
                    return Decision.DENIED
        for ing in r.ingress:
            for sel in ing.get_source_endpoint_selectors():
                if sel.matches(ctx.from_labels):
                    if not ing.to_ports:
                        state.matched_rules += 1
                        return Decision.ALLOWED
        return Decision.UNDECIDED

    def _can_reach_egress(self, ctx: SearchContext) -> Decision:
        decision = Decision.UNDECIDED
        state = TraceState()
        for i, r in enumerate(self.rules):
            state.rule_id = i
            d = self._rule_can_reach_egress(r, ctx, state)
            if d == Decision.DENIED:
                decision = Decision.DENIED
                break
            if d == Decision.ALLOWED:
                decision = Decision.ALLOWED
        state.trace(self, ctx)
        return decision

    def _rule_can_reach_egress(
        self, r: Rule, ctx: SearchContext, state: TraceState
    ) -> Decision:
        if not r.endpoint_selector.matches(ctx.from_labels):
            return Decision.UNDECIDED
        state.selected_rules += 1
        for eg in r.egress:
            for sel in eg.to_requires:
                if not sel.matches(ctx.to_labels):
                    state.constrained_rules += 1
                    return Decision.DENIED
        for eg in r.egress:
            for sel in eg.get_destination_endpoint_selectors():
                if sel.matches(ctx.to_labels):
                    if not eg.to_ports:
                        state.matched_rules += 1
                        return Decision.ALLOWED
        return Decision.UNDECIDED

    def allows_ingress(self, ctx: SearchContext) -> Decision:
        """Full ingress verdict: labels first, then L4 if ports given
        (reference: repository.go:397-420)."""
        ctx.policy_trace("Tracing %s\n", str(ctx))
        decision = self._can_reach_ingress(ctx)
        ctx.policy_trace("Label verdict: %s\n", str(decision))
        if decision == Decision.ALLOWED:
            return decision
        if ctx.dports:
            l4 = self.resolve_l4_ingress_policy(ctx)
            if len(l4) > 0:
                decision = l4.ingress_covers_context(ctx)
        if decision != Decision.ALLOWED:
            decision = Decision.DENIED
        return decision

    def allows_egress(self, ctx: SearchContext) -> Decision:
        """reference: repository.go:422-446."""
        ctx.policy_trace("Tracing %s\n", str(ctx))
        decision = self._can_reach_egress(ctx)
        ctx.policy_trace("Label verdict: %s\n", str(decision))
        if decision == Decision.ALLOWED:
            return decision
        if ctx.dports:
            l4 = self.resolve_l4_egress_policy(ctx)
            if len(l4) > 0:
                decision = l4.egress_covers_context(ctx)
        if decision != Decision.ALLOWED:
            decision = Decision.DENIED
        return decision

    # -- L4 resolution -----------------------------------------------------

    def resolve_l4_ingress_policy(
        self,
        ctx: SearchContext,
        endpoints_with_l3_override: list[EndpointSelector] | None = None,
    ) -> L4PolicyMap:
        """reference: repository.go:245-283."""
        result = L4PolicyMap()
        ctx.policy_trace("Resolving ingress port policy\n")
        state = TraceState()

        # Flatten all FromRequires of rules selecting ctx.to into selector
        # requirements folded into every FromEndpoints (repository.go:252-267).
        requirements: list[SelectorRequirement] = []
        for r in self.rules:
            if r.endpoint_selector.matches(ctx.to_labels):
                for ing in r.ingress:
                    for req_sel in ing.from_requires:
                        requirements.extend(req_sel.to_requirements())

        for i, r in enumerate(self.rules):
            state.rule_id = i
            self._resolve_rule_l4_ingress(
                r, ctx, state, result, requirements,
                endpoints_with_l3_override or [],
            )

        self._wildcard_l3_l4_rules(ctx, True, result)
        state.trace(self, ctx)
        return result

    def _resolve_rule_l4_ingress(
        self,
        r: Rule,
        ctx: SearchContext,
        state: TraceState,
        res_map: L4PolicyMap,
        requirements: list[SelectorRequirement],
        endpoints_with_l3_override: list[EndpointSelector],
    ) -> None:
        if not r.endpoint_selector.matches(ctx.to_labels):
            return
        state.selected_rules += 1
        found = 0
        for ing in r.ingress:
            if not ing.to_ports:
                continue
            from_eps = [
                sel.with_requirements(requirements)
                for sel in ing.get_source_endpoint_selectors()
            ]
            # From-label filter when ctx.From given (reference: rule.go:156-161).
            if ctx.from_labels and from_eps:
                if not any(sel.matches(ctx.from_labels) for sel in from_eps):
                    continue
            for pr in ing.to_ports:
                for pp in pr.ports:
                    for proto in _expand_protocols(pp):
                        key = f"{int(pp.port, 0)}/{proto}"
                        new_f = create_l4_ingress_filter(
                            from_eps, endpoints_with_l3_override, pr, pp, proto,
                            r.labels,
                        )
                        existing = res_map.get(key)
                        if existing is None:
                            res_map[key] = new_f
                        else:
                            _merge_l4_port(ctx, from_eps, existing, new_f)
                            existing.derived_from_rules.append(r.labels)
                        found += 1
        if found:
            state.matched_rules += 1

    def resolve_l4_egress_policy(self, ctx: SearchContext) -> L4PolicyMap:
        """reference: repository.go:291-333."""
        result = L4PolicyMap()
        ctx.policy_trace("Resolving egress port policy\n")
        state = TraceState()

        requirements: list[SelectorRequirement] = []
        for r in self.rules:
            if r.endpoint_selector.matches(ctx.from_labels):
                for eg in r.egress:
                    for req_sel in eg.to_requires:
                        requirements.extend(req_sel.to_requirements())

        for i, r in enumerate(self.rules):
            state.rule_id = i
            self._resolve_rule_l4_egress(r, ctx, state, result, requirements)

        self._wildcard_l3_l4_rules(ctx, False, result)
        state.trace(self, ctx)
        return result

    def _resolve_rule_l4_egress(
        self,
        r: Rule,
        ctx: SearchContext,
        state: TraceState,
        res_map: L4PolicyMap,
        requirements: list[SelectorRequirement],
    ) -> None:
        if not r.endpoint_selector.matches(ctx.from_labels):
            return
        state.selected_rules += 1
        found = 0
        for eg in r.egress:
            if not eg.to_ports:
                continue
            to_eps = [
                sel.with_requirements(requirements)
                for sel in eg.get_destination_endpoint_selectors()
            ]
            if ctx.to_labels and to_eps:
                if not any(sel.matches(ctx.to_labels) for sel in to_eps):
                    continue
            for pr in eg.to_ports:
                for pp in pr.ports:
                    for proto in _expand_protocols(pp):
                        key = f"{int(pp.port, 0)}/{proto}"
                        new_f = create_l4_egress_filter(
                            to_eps, pr, pp, proto, r.labels
                        )
                        existing = res_map.get(key)
                        if existing is None:
                            res_map[key] = new_f
                        else:
                            _merge_l4_port(ctx, to_eps, existing, new_f)
                            existing.derived_from_rules.append(r.labels)
                        found += 1
        if found:
            state.matched_rules += 1

    # -- wildcard L3/L4 -> L7 (reference: repository.go:128-243) -----------

    def _wildcard_l3_l4_rules(
        self, ctx: SearchContext, ingress: bool, l4_policy: L4PolicyMap
    ) -> None:
        """Rules allowing traffic at L3-only or L3/L4-only wildcard the L7
        rules of any redirect filter on the same port, so broader allows are
        not narrowed by another rule's L7 restrictions."""
        for r in self.rules:
            if ingress:
                if not r.endpoint_selector.matches(ctx.to_labels):
                    continue
                sections = r.ingress
            else:
                if not r.endpoint_selector.matches(ctx.from_labels):
                    continue
                sections = r.egress
            for section in sections:
                if not section.is_label_based():
                    continue
                endpoints = (
                    section.get_source_endpoint_selectors()
                    if ingress
                    else section.get_destination_endpoint_selectors()
                )
                if not section.to_ports:
                    # L3-only rule wildcard-matches every port.
                    _wildcard_l3_l4_rule(PROTO_TCP, 0, endpoints, r.labels, l4_policy)
                    _wildcard_l3_l4_rule(PROTO_UDP, 0, endpoints, r.labels, l4_policy)
                else:
                    for pr in section.to_ports:
                        if pr.rules is None or pr.rules.is_empty():
                            for pp in pr.ports:
                                port = int(pp.port, 0)
                                _wildcard_l3_l4_rule(
                                    pp.protocol, port, endpoints, r.labels, l4_policy
                                )

    # -- CIDR resolution ---------------------------------------------------

    def resolve_cidr_policy(self, ctx: SearchContext) -> CIDRPolicy:
        """reference: repository.go:340 + rule.go resolveCIDRPolicy."""
        from .api import compute_resultant_cidr_set

        result = CIDRPolicy()
        ctx.policy_trace("Resolving L3 (CIDR) policy\n")
        for r in self.rules:
            if not r.endpoint_selector.matches(ctx.to_labels):
                continue
            for ing in r.ingress:
                all_cidrs = list(ing.from_cidr) + compute_resultant_cidr_set(
                    ing.from_cidr_set
                )
                # CIDR+L4 ingress handled by mergeL4Ingress (rule.go:315-318).
                if all_cidrs and ing.to_ports:
                    continue
                for c in all_cidrs:
                    result.ingress.insert(c, r.labels)
            for eg in r.egress:
                all_cidrs = list(eg.to_cidr) + compute_resultant_cidr_set(
                    eg.to_cidr_set
                )
                # Egress counts CIDR+L4 too, for prefix-length computation
                # (rule.go:330-340).
                for c in all_cidrs:
                    result.egress.insert(c, r.labels)
        return result


def _wildcard_l3_l4_rule(
    proto: str,
    port: int,
    endpoints: list[EndpointSelector],
    rule_labels: LabelArray,
    l4_policy: L4PolicyMap,
) -> None:
    """reference: repository.go:128-167."""
    for key, f in l4_policy.items():
        if proto != f.protocol or (port != 0 and port != f.port):
            continue
        if f.l7_parser == PARSER_TYPE_NONE:
            continue
        if f.l7_parser == PARSER_TYPE_HTTP:
            for sel in endpoints:
                f.l7_rules_per_ep[sel] = L7Rules(http=[PortRuleHTTP()])
        elif f.l7_parser == PARSER_TYPE_KAFKA:
            for sel in endpoints:
                rule = PortRuleKafka()
                rule.sanitize()
                f.l7_rules_per_ep[sel] = L7Rules(kafka=[rule])
        else:
            for sel in endpoints:
                f.l7_rules_per_ep[sel] = L7Rules(l7proto=f.l7_parser, l7=[])
        f.endpoints = f.endpoints + list(endpoints)
        f.derived_from_rules.append(rule_labels)
