"""Rule-row byte-invariance analysis — the verdict-cache contract.

The established-flow verdict cache (sidecar service + shim client)
short-circuits frames of a flow whose verdict provably cannot depend on
the frame's bytes.  That claim is STRUCTURAL and per-epoch static: it is
derived here from the compiled rule rows at table-build time, never from
observed traffic, so a cached verdict is exactly what a cold recompute
would produce — attribution included.

The analysis walks the same flattened first-match row order the device
argmax and the host oracle use (models/r2d2.collect_policy_rows /
models/http.collect_http_rows, proxylib policy.matches_at):

- **invariant ALLOW, rule k** — the FIRST row whose remote set admits
  this identity has no byte constraint (an always-match row: an L7 rule
  with no matchers, or a blank matcher).  Every frame then matches row
  k: rows before it cannot match (remote excluded), and first-match
  semantics stop the walk at k, so both the verdict AND the attributed
  rule are byte-invariant.
- **invariant DENY** — no row admits this identity at all.  (The cache
  tiers deliberately do not arm on deny: denied frames carry
  per-frame inject side effects — the ERROR/403 responses — whose
  framing the short-circuit would skip.)
- **None** — the first admitting row inspects bytes: no claim.  This is
  deliberately conservative: a later always-match row would make the
  VERDICT invariant but not the attribution, and the churn-soak parity
  gate re-validates cached (verdict, rule, epoch) against a cold
  recompute.

Rows are reduced to ``(remote_set_or_None, byte_free)`` pairs by the
model builders (``invariant_rows`` on the batch models, host-side aux
exactly like ``match_kinds`` — never device data, never pytree leaves).

Cross-restart note: every grant is stamped with the policy epoch it was
derived under, and the restart handoff snapshot carries that epoch
forward — a successor re-derives grants from its own recompiled rows
and confirms the (epoch, rule) pair matches before counting the grant
restored, while the shim's survival window serves a grant only while
its epoch equals the last service epoch the shim saw.  The invariance
claim therefore never outlives the rows it was computed from, even
across a kill -9 boundary.
"""

from __future__ import annotations

# Claim constants: what a cache tier may do with a flow.
ALLOW = True
DENY = False


def reduce_r2d2_rows(rows) -> tuple:
    """(remote_set_or_None, byte_free) per flattened r2d2 row.  A row is
    byte-free iff it constrains neither the command nor the file (the
    ``l7_matchers == [None]`` always-match shape, or a fully blank
    matcher — both match every framed message, host and device)."""
    return tuple(
        (remotes if remotes else None, not cmd and not file_rx)
        for remotes, cmd, file_rx in rows
    )


def reduce_dns_rows(rows) -> tuple:
    """(remote_set_or_None, byte_free) per flattened DNS row.  A row is
    byte-free iff it carries no name constraint (the matcherless
    always-match shape, or a DnsRule with none of matchName/
    matchPattern/matchRegex set).  This is SOUND only because the DNS
    engine's always-match rows admit any complete frame — the QNAME
    validity gate masks name-CONSTRAINED rows only (a malformed
    question can never satisfy a name rule, but a byte-free "allow
    these peers' DNS" row passes it, host and device alike) — so the
    verdict and the attributed first-match row really are independent
    of the frame's bytes, and a cached whole-frame short-circuit is
    exactly what a cold recompute would produce."""
    return tuple(
        (
            remotes if remotes else None,
            rule is None
            or not (rule.name or rule.pattern or rule.regex),
        )
        for remotes, rule in rows
    )


def reduce_http_rows(rows) -> tuple:
    """(remote_set_or_None, byte_free) per flattened HTTP row.  A row is
    byte-free iff the PortRuleHTTP carries no method/path/host/header
    constraint — the pure-L3/L4 "allow these peers on this port" shape."""
    return tuple(
        (
            remotes if remotes else None,
            not (r.method or r.path or r.host or r.headers),
        )
        for remotes, r in rows
    )


def invariant_verdict(inv_rows, remote_id: int):
    """Byte-invariance claim for one identity against reduced rows.

    Returns ``(ALLOW, rule_row)`` / ``(DENY, -1)`` / ``None`` (no
    claim).  ``inv_rows`` is the builders' ``invariant_rows`` tuple; the
    rule row index is the flattened first-match row — identical to the
    device argmax and the host ``matches_at`` walk by construction."""
    for i, (remotes, byte_free) in enumerate(inv_rows):
        if remotes is not None and remote_id not in remotes:
            continue  # this row can never match the identity
        if byte_free:
            return ALLOW, i  # first admitting row always matches
        return None  # first admitting row inspects bytes: no claim
    return DENY, -1  # no row admits the identity


def model_invariant_rows(model):
    """Resolve ``invariant_rows`` through a mesh wrapper: the sharded
    wrappers keep host-side aux on their single-chip ``fallback`` (same
    rows, same flattened order — the global-argmax contract)."""
    rows = getattr(model, "invariant_rows", None)
    if rows is None:
        fb = getattr(model, "fallback", None)
        rows = getattr(fb, "invariant_rows", None)
    return rows


_MISS = object()
MEMO_MAX = 1 << 16  # bound each engine's per-identity claim memo


class InvariantClaimEngine:
    """Mixin: the engine half of the verdict-cache contract — THE one
    definition behind every engine's ``verdict_invariant``
    (R2d2BatchEngine, BaseBatchEngine, DeviceAssistedEngine).

    ``verdict_invariant(remote_id)`` returns ``(allow, rule_row)``
    when every future frame's verdict (and attributed first-match
    row) against the engine's compiled table is independent of its
    bytes — ConstVerdict models, or a first-admitting rule row with
    no byte constraint — else ``None`` (no claim).  Per-epoch static:
    derived from the rule rows at build time, memoized per identity,
    and the memo dies with its engine on an epoch swap (the serving
    caches key on the epoch).  Models exposing no ``invariant_rows``
    make no claim structurally: kafka (per-frame error-response
    injection is framing-dependent) and cassandra/memcached (reply-
    intent queues make per-frame framing load-bearing); the HTTP
    judge path does claim (request heads are judged statelessly and
    replies pass untouched).  The memo is created lazily, so mixers
    need no ``__init__`` cooperation."""

    _invariant_memo: dict | None = None

    def verdict_invariant(self, remote_id: int):
        memo = self._invariant_memo
        if memo is None:
            memo = self._invariant_memo = {}
        return memoized_claim(
            getattr(self, "model", None), memo, remote_id
        )


def memoized_claim(model, memo: dict, remote_id: int):
    """Engine-side claim lookup (see ``InvariantClaimEngine``, the
    mixin the engine tiers inherit it through): bounded per-engine memo,
    ConstVerdict special-case, else the first-match walk over the
    model's (or its mesh fallback's) ``invariant_rows``; a model
    exposing no rows makes no claim.  The memo dies with its engine on
    an epoch swap — the serving caches key on the epoch."""
    claim = memo.get(remote_id, _MISS)
    if claim is not _MISS:
        return claim
    from ..models.base import ConstVerdict  # lazy: keep policy/ leaf-like

    if isinstance(model, ConstVerdict):
        claim = (bool(model.allow), -1)
    else:
        rows = model_invariant_rows(model)
        claim = (
            invariant_verdict(rows, remote_id) if rows is not None else None
        )
    if len(memo) < MEMO_MAX:
        memo[remote_id] = claim
    return claim
