"""Proxy redirect identifiers (reference: pkg/policy/proxyid.go)."""

from __future__ import annotations


def proxy_id(endpoint_id: int, ingress: bool, protocol: str, port: int) -> str:
    """``epID:direction:proto:port`` linking an L4Filter to its redirect
    (reference: proxyid.go:24)."""
    direction = "ingress" if ingress else "egress"
    return f"{endpoint_id}:{direction}:{protocol}:{port}"


def parse_proxy_id(pid: str) -> tuple[int, bool, str, int]:
    """reference: proxyid.go:33."""
    parts = pid.split(":")
    if len(parts) != 4:
        raise ValueError(f"invalid proxy ID {pid!r}")
    ep_id = int(parts[0])
    if parts[1] == "ingress":
        ingress = True
    elif parts[1] == "egress":
        ingress = False
    else:
        raise ValueError(f"invalid direction in proxy ID {pid!r}")
    return ep_id, ingress, parts[2], int(parts[3])
