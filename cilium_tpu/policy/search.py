"""Search context, decisions and policy tracing.

reference: pkg/policy/policy.go (SearchContext, Tracing), pkg/policy/api/
decision.go (Decision), pkg/policy/trace.
"""

from __future__ import annotations

import enum
import io
from dataclasses import dataclass, field
from typing import Optional

from ..labels import LabelArray


class Decision(enum.Enum):
    """reference: pkg/policy/api/decision.go."""

    UNDECIDED = 0
    ALLOWED = 1
    DENIED = 2

    def __str__(self) -> str:
        return {0: "undecided", 1: "allowed", 2: "denied"}[self.value]


class Tracing(enum.IntEnum):
    DISABLED = 0
    ENABLED = 1
    VERBOSE = 2


@dataclass
class DPort:
    """L4 destination-port context (models.Port analog)."""

    port: int
    protocol: str = "ANY"  # "TCP" | "UDP" | "ANY" | ""


@dataclass
class SearchContext:
    """From/To label sets + optional L4 ports for a policy question
    (reference: pkg/policy/policy.go:64)."""

    from_labels: LabelArray = field(default_factory=LabelArray)
    to_labels: LabelArray = field(default_factory=LabelArray)
    dports: list[DPort] = field(default_factory=list)
    trace: Tracing = Tracing.DISABLED
    depth: int = 0
    logging: Optional[io.StringIO] = None

    def policy_trace(self, fmt: str, *args) -> None:
        """reference: policy.go:39."""
        if self.trace != Tracing.DISABLED:
            self._log(fmt, *args)

    def policy_trace_verbose(self, fmt: str, *args) -> None:
        if self.trace == Tracing.VERBOSE:
            self._log(fmt, *args)

    def _log(self, fmt: str, *args) -> None:
        msg = (fmt % args) if args else fmt
        if self.logging is not None:
            self.logging.write(msg)

    def call_depth(self) -> str:
        return str(self.depth * 2)

    def __str__(self) -> str:
        return (
            f"From: {[str(l) for l in self.from_labels]} => "
            f"To: {[str(l) for l in self.to_labels]}"
            + (f" Ports: {[(p.port, p.protocol) for p in self.dports]}"
               if self.dports else "")
        )


def new_search_context(
    from_labels: LabelArray, to_labels: LabelArray, dports: list[DPort] | None = None
) -> SearchContext:
    return SearchContext(
        from_labels=from_labels, to_labels=to_labels, dports=dports or []
    )
