"""L4/L7 policy resolution result: L4Filter, L4PolicyMap, L4Policy.

reference: pkg/policy/l4.go.  The L4PolicyMap is keyed ``"port/PROTO"``; each
L4Filter carries the allowed peer selectors and the per-selector L7 rules
(L7DataMap) that the proxy layer compiles into device NFA tables.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..labels import LabelArray
from .api import (
    EndpointSelector,
    L7Rules,
    PortProtocol,
    PortRule,
    PROTO_TCP,
    WILDCARD_SELECTOR,
    proto_number,
)
from .search import Decision, SearchContext

# L7 parser types (reference: pkg/policy/l4.go:80-87).
PARSER_TYPE_NONE = ""
PARSER_TYPE_HTTP = "http"
PARSER_TYPE_KAFKA = "kafka"


def _copy_l7_rules(rules: L7Rules) -> L7Rules:
    return L7Rules(
        http=list(rules.http),
        kafka=list(rules.kafka),
        l7proto=rules.l7proto,
        l7=list(rules.l7),
    )


class L7DataMap(dict):
    """EndpointSelector -> L7Rules (reference: pkg/policy/l4.go:32)."""

    def add_rules_for_endpoints(
        self, rules: L7Rules, endpoints: list[EndpointSelector]
    ) -> None:
        """reference: l4.go:143-160 — no explicit endpoints means the
        wildcard selector carries the rules."""
        if len(rules) == 0:
            return
        # Each selector gets its own copy: merging appends to these lists,
        # and the rule AST stored in the Repository must never be mutated.
        if endpoints:
            for sel in endpoints:
                self[sel] = _copy_l7_rules(rules)
        else:
            self[WILDCARD_SELECTOR] = _copy_l7_rules(rules)

    def get_relevant_rules(self, identity_labels: Optional[LabelArray]) -> L7Rules:
        """Collect the L7 rules whose selector matches the remote identity
        (reference: l4.go:118-141)."""
        rules = L7Rules()
        if identity_labels is not None:
            for selector, ep_rules in self.items():
                if selector == WILDCARD_SELECTOR:
                    continue
                if selector.matches(identity_labels):
                    rules.http.extend(ep_rules.http)
                    rules.kafka.extend(ep_rules.kafka)
                    rules.l7proto = ep_rules.l7proto
                    rules.l7.extend(ep_rules.l7)
        wild = self.get(WILDCARD_SELECTOR)
        if wild is not None:
            rules.http.extend(wild.http)
            rules.kafka.extend(wild.kafka)
            rules.l7proto = wild.l7proto
            rules.l7.extend(wild.l7)
        return rules


@dataclass
class L4Filter:
    """One resolved port/proto entry (reference: pkg/policy/l4.go:89)."""

    port: int
    protocol: str
    u8_proto: int = 0
    endpoints: list[EndpointSelector] = field(default_factory=list)
    l7_parser: str = PARSER_TYPE_NONE
    l7_rules_per_ep: L7DataMap = field(default_factory=L7DataMap)
    ingress: bool = True
    derived_from_rules: list[LabelArray] = field(default_factory=list)

    def allows_all_at_l3(self) -> bool:
        """reference: l4.go:112."""
        if not self.endpoints:
            return True
        return any(sel.is_wildcard() for sel in self.endpoints)

    def is_redirect(self) -> bool:
        return self.l7_parser != PARSER_TYPE_NONE

    def matches_labels(self, lbls: LabelArray) -> bool:
        """reference: l4.go:258-274."""
        if self.allows_all_at_l3():
            return True
        if len(lbls) == 0:
            return False
        return any(sel.matches(lbls) for sel in self.endpoints)


def create_l4_filter(
    peer_endpoints: list[EndpointSelector],
    rule: PortRule,
    port: PortProtocol,
    protocol: str,
    rule_labels: LabelArray,
    ingress: bool,
) -> L4Filter:
    """reference: pkg/policy/l4.go:162-207."""
    p = int(port.port, 0)
    filter_endpoints = peer_endpoints
    if not peer_endpoints or any(s.is_wildcard() for s in peer_endpoints):
        filter_endpoints = [WILDCARD_SELECTOR]

    l4 = L4Filter(
        port=p,
        protocol=protocol,
        u8_proto=proto_number(protocol),
        endpoints=filter_endpoints,
        ingress=ingress,
        derived_from_rules=[rule_labels],
    )
    if protocol == PROTO_TCP and rule.rules is not None:
        if rule.rules.http:
            l4.l7_parser = PARSER_TYPE_HTTP
        elif rule.rules.kafka:
            l4.l7_parser = PARSER_TYPE_KAFKA
        elif rule.rules.l7proto:
            l4.l7_parser = rule.rules.l7proto
        if not rule.rules.is_empty():
            l4.l7_rules_per_ep.add_rules_for_endpoints(rule.rules, filter_endpoints)
    return l4


def create_l4_ingress_filter(
    from_endpoints: list[EndpointSelector],
    endpoints_with_l3_override: list[EndpointSelector],
    rule: PortRule,
    port: PortProtocol,
    protocol: str,
    rule_labels: LabelArray,
) -> L4Filter:
    """reference: l4.go:209-227 — L3-override selectors (host/world in
    allow-localhost modes) get their L7 rules wildcarded."""
    f = create_l4_filter(from_endpoints, rule, port, protocol, rule_labels, True)
    if rule.rules is not None and not rule.rules.is_empty():
        for sel in endpoints_with_l3_override:
            f.l7_rules_per_ep[sel] = L7Rules()
    return f


def create_l4_egress_filter(
    to_endpoints: list[EndpointSelector],
    rule: PortRule,
    port: PortProtocol,
    protocol: str,
    rule_labels: LabelArray,
) -> L4Filter:
    return create_l4_filter(to_endpoints, rule, port, protocol, rule_labels, False)


class L4PolicyMap(dict):
    """"port/PROTO" -> L4Filter (reference: pkg/policy/l4.go:276)."""

    def has_redirect(self) -> bool:
        return any(f.is_redirect() for f in self.values())

    def contains_all_l3_l4(
        self, lbls: LabelArray, dports
    ) -> Decision:
        """reference: l4.go:300-335."""
        if len(self) == 0:
            return Decision.ALLOWED
        if not dports:
            return Decision.DENIED
        for ctx in dports:
            proto = ctx.protocol
            if proto in ("", "ANY"):
                tcp = self.get(f"{ctx.port}/TCP")
                udp = self.get(f"{ctx.port}/UDP")
                tcp_ok = tcp is not None and tcp.matches_labels(lbls)
                udp_ok = udp is not None and udp.matches_labels(lbls)
                if not tcp_ok and not udp_ok:
                    return Decision.DENIED
            else:
                f = self.get(f"{ctx.port}/{proto}")
                if f is None or not f.matches_labels(lbls):
                    return Decision.DENIED
        return Decision.ALLOWED

    def ingress_covers_context(self, ctx: SearchContext) -> Decision:
        return self.contains_all_l3_l4(ctx.from_labels, ctx.dports)

    def egress_covers_context(self, ctx: SearchContext) -> Decision:
        return self.contains_all_l3_l4(ctx.to_labels, ctx.dports)


@dataclass
class L4Policy:
    """reference: pkg/policy/l4.go:337."""

    ingress: L4PolicyMap = field(default_factory=L4PolicyMap)
    egress: L4PolicyMap = field(default_factory=L4PolicyMap)
    revision: int = 0

    def has_redirect(self) -> bool:
        return self.ingress.has_redirect() or self.egress.has_redirect()

    def requires_conntrack(self) -> bool:
        return len(self.ingress) > 0 or len(self.egress) > 0
